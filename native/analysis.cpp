// Native analysis kernels for the host-side indexing path.
//
// Role: the reference's indexing hot loop runs in JIT-compiled Java inside
// Lucene (analyzer chains, term hashing). Here the write path is host-side
// (SURVEY.md §7.1: "the write path stays host-side (CPU: tokenize -> segment
// build -> WAL)"), so the tokenizer/hash inner loops are C++, bound via
// ctypes (utils/native.py) with a pure-Python fallback for parity testing.
//
// Fast paths are ASCII-exact replicas of the Python implementations; any
// input needing Unicode word-break semantics returns -1 and the caller
// falls back to Python (same result either way — tested in
// tests/test_native.py).
//
// Build: make -C native   (g++ -O3 -shared -fPIC)

#include <cstdint>
#include <cstring>

extern "C" {

// ---------------------------------------------------------------------------
// standard_tokenize_ascii: \w+ runs, lowercased in place into `out`.
// Token i spans out[starts[i]:ends[i]). Returns token count, or -1 if the
// text contains non-ASCII bytes (caller must use the Unicode path).
// ---------------------------------------------------------------------------
int standard_tokenize_ascii(const char *text, int len, char *out,
                            int32_t *starts, int32_t *ends, int max_tokens) {
    int n = 0;
    int i = 0;
    while (i < len) {
        unsigned char c = (unsigned char)text[i];
        if (c >= 0x80) return -1;  // Unicode: fall back to Python re
        bool word = (c >= 'a' && c <= 'z') || (c >= 'A' && c <= 'Z') ||
                    (c >= '0' && c <= '9') || c == '_';
        if (!word) { out[i] = (char)c; i++; continue; }
        if (n >= max_tokens) return n;
        int start = i;
        while (i < len) {
            unsigned char d = (unsigned char)text[i];
            if (d >= 0x80) return -1;
            bool w = (d >= 'a' && d <= 'z') || (d >= 'A' && d <= 'Z') ||
                     (d >= '0' && d <= '9') || d == '_';
            if (!w) break;
            out[i] = (d >= 'A' && d <= 'Z') ? (char)(d + 32) : (char)d;
            i++;
        }
        starts[n] = start;
        ends[n] = i;
        n++;
    }
    return n;
}

// ---------------------------------------------------------------------------
// whitespace_tokenize: \S+ runs (byte-exact for any input — UTF-8 bytes
// >= 0x80 are never ASCII whitespace).
// ---------------------------------------------------------------------------
int whitespace_tokenize(const char *text, int len, int32_t *starts,
                        int32_t *ends, int max_tokens) {
    int n = 0;
    int i = 0;
    while (i < len) {
        unsigned char c = (unsigned char)text[i];
        if (c == ' ' || c == '\t' || c == '\n' || c == '\r' || c == '\f' ||
            c == '\v') { i++; continue; }
        if (n >= max_tokens) return n;
        int start = i;
        while (i < len) {
            unsigned char d = (unsigned char)text[i];
            if (d == ' ' || d == '\t' || d == '\n' || d == '\r' || d == '\f' ||
                d == '\v') break;
            i++;
        }
        starts[n] = start;
        ends[n] = i;
        n++;
    }
    return n;
}

// ---------------------------------------------------------------------------
// MurmurHash3 x86_32 — identical to utils/murmur3.py (doc routing).
// ---------------------------------------------------------------------------
static inline uint32_t rotl32(uint32_t x, int8_t r) {
    return (x << r) | (x >> (32 - r));
}

static inline uint32_t fmix32(uint32_t h) {
    h ^= h >> 16; h *= 0x85ebca6b;
    h ^= h >> 13; h *= 0xc2b2ae35;
    h ^= h >> 16;
    return h;
}

int32_t murmur3_32(const char *data, int len, uint32_t seed) {
    const uint32_t c1 = 0xcc9e2d51, c2 = 0x1b873593;
    uint32_t h1 = seed;
    const int nblocks = len / 4;
    for (int i = 0; i < nblocks; i++) {
        uint32_t k1;
        memcpy(&k1, data + i * 4, 4);
        k1 *= c1; k1 = rotl32(k1, 15); k1 *= c2;
        h1 ^= k1; h1 = rotl32(h1, 13); h1 = h1 * 5 + 0xe6546b64;
    }
    const unsigned char *tail = (const unsigned char *)(data + nblocks * 4);
    uint32_t k1 = 0;
    switch (len & 3) {
        case 3: k1 ^= tail[2] << 16; [[fallthrough]];
        case 2: k1 ^= tail[1] << 8;  [[fallthrough]];
        case 1: k1 ^= tail[0];
                k1 *= c1; k1 = rotl32(k1, 15); k1 *= c2; h1 ^= k1;
    }
    h1 ^= (uint32_t)len;
    return (int32_t)fmix32(h1);
}

// batch variant: flat utf-8 buffer + offsets, one hash per string
void murmur3_batch(const char *buf, const int32_t *offsets, int n,
                   int32_t *out, uint32_t seed) {
    for (int i = 0; i < n; i++) {
        out[i] = murmur3_32(buf + offsets[i], offsets[i + 1] - offsets[i], seed);
    }
}

// shard routing: floorMod(hash, num_shards) per string
void shard_ids_batch(const char *buf, const int32_t *offsets, int n,
                     int32_t num_shards, int32_t *out) {
    for (int i = 0; i < n; i++) {
        int32_t h = murmur3_32(buf + offsets[i], offsets[i + 1] - offsets[i], 0);
        int32_t m = h % num_shards;
        out[i] = m < 0 ? m + num_shards : m;  // Python floor-mod parity
    }
}

}  // extern "C"
