"""Time merge-stage variants after the tile kernel on the real chip.

BENCH_r04 stage_breakdown: score_tiles 0.574ms, merge_topk 0.829ms of a
1.403ms p50. The merge is lax.top_k over n_tiles*k=640 candidates fused
in the same jit — this experiment isolates WHAT in the merge costs and
which replacement is fastest. Uses bench.py's corpus + marginal timing.
"""
import sys, time
sys.path.insert(0, "/root/repo")
import numpy as np
import bench
from bench import build_synthetic_corpus, measure_marginal, idf, K, WARMUP, log

import jax
import jax.numpy as jnp
from jax import lax
from elasticsearch_tpu.ops import pallas_scoring as psc

log(f"backend: {jax.default_backend()}")
corpus = build_synthetic_corpus()
nd_pad = corpus["nd_pad"]
geom = psc.tile_geometry(nd_pad)
frac = psc.compute_block_frac(corpus["block_docs"], corpus["block_tfs"],
                              corpus["norms"][0], corpus["avgdl"])
bmin, bmax = psc.block_min_max(corpus["block_docs"], corpus["block_tfs"], nd_pad)

rng = np.random.RandomState(3)
# same query construction as bench
term_sets = [list(rng.randint(50, 1000, bench.N_QUERY_TERMS))
             for _ in range(30)]

def kernel_query(terms, t_pad=4, cb=None):
    lanes = [psc.QueryLane(int(corpus["term_block_start"][t]),
                           int(corpus["n_blocks_per_term"][t]),
                           idf(int(corpus["term_df"][t])))
             for t in terms]
    return psc.build_tile_tables(lanes, bmin, bmax, geom, t_pad=t_pad, cb=cb)

kqueries = [kernel_query(ts) for ts in term_sets]
cb_run = max(kq[3] for kq in kqueries)
staged = [(jnp.asarray(rl), jnp.asarray(rh), jnp.asarray(w))
          for rl, rh, w, _ in kqueries]
dp, fp = psc.pad_segment_blocks(corpus["block_docs"], frac, nd_pad)
live_t = psc.build_live_t(corpus["live1"][:nd_pad].astype(np.float32), geom)
dev = {"docs": jnp.asarray(dp), "frac": jnp.asarray(fp),
       "live_t": jnp.asarray(live_t)}
log(f"staged; geom={geom} cb={cb_run}")

def score(rl, rh, w):
    return psc.score_tiles(dev["docs"], dev["frac"], dev["live_t"],
                           rl, rh, w, t_pad=4, cb=cb_run,
                           sub=geom.tile_sub, k=K)

def m_none(ts, td, th):
    return (ts,)

def m_topk(ts, td, th):
    return psc.merge_tile_topk(ts, td, th, K)

def m_max(ts, td, th):
    return (jnp.max(ts), jnp.sum(th).astype(jnp.int32))

def m_iter(ts, td, th):
    s = ts.reshape(-1); d = td.reshape(-1)
    outs_s, outs_d = [], []
    for _ in range(K):
        i = jnp.argmax(s)
        outs_s.append(s[i]); outs_d.append(d[i])
        s = s.at[i].set(-jnp.inf)
    return (jnp.stack(outs_s), jnp.stack(outs_d),
            jnp.sum(th).astype(jnp.int32))

def m_rank(ts, td, th):
    s = ts.reshape(-1); d = td.reshape(-1)
    n = s.shape[0]
    gt = (s[None, :] > s[:, None])
    idx = jnp.arange(n)
    tie = (s[None, :] == s[:, None]) & (idx[None, :] < idx[:, None])
    rank = jnp.sum((gt | tie).astype(jnp.float32), axis=1)  # 0 = best
    sel = (rank[None, :] == jnp.arange(K, dtype=rank.dtype)[:, None])
    self = sel.astype(jnp.float32)
    top_s = self @ s
    top_d = (self @ d.astype(jnp.float32)).astype(jnp.int32)
    return top_s, top_d, jnp.sum(th).astype(jnp.int32)

def m_approx(ts, td, th):
    s = ts.reshape(-1)
    top_s, top_i = lax.approx_max_k(s, K, recall_target=0.99)
    return top_s, td.reshape(-1)[top_i], jnp.sum(th).astype(jnp.int32)

def m_sortall(ts, td, th):
    # single variadic sort of (s, d) pairs; slice k — is top_k's sort the
    # cost, or its surrounding glue?
    s = ts.reshape(-1); d = td.reshape(-1)
    ss, dd = lax.sort((-s, d), num_keys=1)
    return -ss[:K], dd[:K], jnp.sum(th).astype(jnp.int32)

variants = {"topk": m_topk, "rank": m_rank, "none": m_none,
            "topk2": m_topk, "none2": m_none}
variants["topk2"] = lambda ts, td, th: psc.merge_tile_topk(ts, td, th, K)
variants["none2"] = lambda ts, td, th: (ts,)
# sustained warm-up: ramp device clocks/pipeline to steady state before
# ANY timed section (the first timed variant otherwise reads ~0.6ms high)
@jax.jit
def warm(docs, frac_a, live_a, rl, rh, w):
    ts, td, th = psc.score_tiles(docs, frac_a, live_a, rl, rh, w,
                                 t_pad=4, cb=cb_run, sub=geom.tile_sub, k=K)
    return psc.merge_tile_topk(ts, td, th, K)
out = None
t0 = time.perf_counter()
nwarm = 0
while time.perf_counter() - t0 < 4.0:
    for q in staged:
        out = warm(dev["docs"], dev["frac"], dev["live_t"], *q)
        nwarm += 1
np.asarray(out[0])
log(f"warmed up with {nwarm} queries in {time.perf_counter()-t0:.1f}s")
results = {}
first = True
for name, m in variants.items():
    @jax.jit
    def fused(docs, frac_a, live_a, rl, rh, w, _m=m):
        ts, td, th = psc.score_tiles(
            docs, frac_a, live_a, rl, rh, w,
            t_pad=4, cb=cb_run, sub=geom.tile_sub, k=K)
        return _m(ts, td, th)
    def run(q, _f=fused):
        rl, rh, w = q
        return _f(dev["docs"], dev["frac"], dev["live_t"], rl, rh, w)
    out = run(staged[0]); np.asarray(out[0])  # compile + first D2H
    pq = measure_marginal(run, staged[WARMUP:])
    results[name] = pq * 1000
    log(f"{name:8s}: {pq*1000:.3f} ms/query")
log("deltas vs none: " + ", ".join(
    f"{k}={results[k]-results['none']:+.3f}" for k in results if k != "none"))
