"""Benchmark: BM25 match-query latency on the flagship TPU query path.

Mirrors the Rally `pmc` match-query config from BASELINE.md: a synthetic
academic-scale corpus (1M docs, zipfian vocabulary, ~80 terms/doc), a
multi-term BM25 disjunction with top-10 collection, p50 service time
(the marginal-batch method cannot observe per-query tails, so no p99 is
claimed; a second independent p50 estimate bounds dispersion).

The primary path is the Pallas tile-scoring kernel
(elasticsearch_tpu/ops/pallas_scoring.py): doc-tiled scatter-free scoring
with fused per-tile top-k. For comparison the bench also measures the
legacy XLA scatter-add program (the r03 path that was 4x slower than
numpy on the chip) and a vectorized numpy implementation of the same
exhaustive scoring on the host CPU (the stand-in for the reference's CPU
execution; BASELINE.json's 32-vCPU Rally baseline is not reachable in
this image). vs_baseline = numpy_p50 / kernel_p50.

Extra configs (BASELINE.md table): bool must/should/filter, terms +
cardinality aggregation over a keyword column, rescore over top-1000.

Robustness (round-1 postmortem: the TPU tunnel backend hung/failed during
init and the bench died with a raw traceback — zero numbers captured):
the parent process NEVER imports jax. It runs the measurement in a child
process per backend attempt with a hard watchdog, retries the TPU backend
once, falls back to the CPU backend with the TPU diagnostics attached,
and ALWAYS prints exactly one JSON line on stdout, exit code 0.
"""

from __future__ import annotations

import json
import math
import os
import subprocess
import sys
import time

import numpy as np

N_DOCS = 1_000_000
AVG_DOC_LEN = 80
VOCAB = 50_000
BLOCK = 128
N_QUERY_TERMS = 3
K = 10
WARMUP = 5
ITERS = 50
# sustained pre-timing warm-up (~3.5s of device work): ramps the chip to
# steady state so the first timed section is not ~0.6ms/query high
WARM_QUERIES = int(os.environ.get("BENCH_WARM_QUERIES", "6000"))

TPU_ATTEMPT_TIMEOUT_S = int(os.environ.get("BENCH_TPU_TIMEOUT_S", "540"))
CPU_ATTEMPT_TIMEOUT_S = int(os.environ.get("BENCH_CPU_TIMEOUT_S", "600"))


def log(msg):
    print(f"[bench] {msg}", file=sys.stderr, flush=True)


def pctl(xs, p):
    return float(np.percentile(np.asarray(xs), p) * 1000)


def measure_marginal(fn, queries, b_small=10, b_big=60, reps=5):
    """Per-query device service time in seconds via marginal batch timing.

    Runs batches of b_small and b_big chained executions, each ending in one
    tiny D2H fetch (np.asarray of fn(...)[0]) that forces full completion,
    and returns (T_big - T_small) / (b_big - b_small). This cancels the axon
    tunnel's fixed per-sync overhead (~70ms after the first D2H) and is
    robust to its fire-and-forget block_until_ready. Minimum over `reps`
    repetitions cuts scheduler noise."""
    def batch_time(b):
        best = None
        for r in range(reps):
            t0 = time.perf_counter()
            out = None
            for i in range(b):
                out = fn(queries[(r * b + i) % len(queries)])
            np.asarray(out[0])
            dt = time.perf_counter() - t0
            best = dt if best is None else min(best, dt)
        return best
    t_small = batch_time(b_small)
    t_big = batch_time(b_big)
    return max((t_big - t_small) / (b_big - b_small), 1e-9)


# ----------------------------------------------------------------------
# Corpus
# ----------------------------------------------------------------------


def pack_postings(term_ids, docs, tfs, vocab, nd_pad):
    """Block-pack a (term, doc)-sorted flat posting list (vectorized —
    the same packing for the full corpus and for per-shard slices)."""
    term_start = np.searchsorted(term_ids, np.arange(vocab))
    term_end = np.searchsorted(term_ids, np.arange(vocab) + 1)
    term_df = (term_end - term_start).astype(np.int64)
    n_blocks_per_term = -(-term_df // BLOCK)
    total_blocks = max(int(n_blocks_per_term.sum()), 1)
    block_docs = np.full((total_blocks, BLOCK), nd_pad, dtype=np.int32)
    block_tfs = np.zeros((total_blocks, BLOCK), dtype=np.float32)
    term_block_start = np.concatenate(
        [[0], np.cumsum(n_blocks_per_term)[:-1]])
    within = np.arange(len(term_ids), dtype=np.int64) - term_start[term_ids]
    rows = term_block_start[term_ids] + within // BLOCK
    lanes = within % BLOCK
    block_docs[rows, lanes] = docs
    block_tfs[rows, lanes] = tfs.astype(np.float32)
    return (block_docs, block_tfs, term_block_start, n_blocks_per_term,
            term_df)


def build_synthetic_corpus(seed=7):
    """Directly build block-packed postings for a zipfian corpus (bypasses
    the host tokenizer — the bench targets the query path)."""
    rng = np.random.RandomState(seed)
    nd_pad = 1
    while nd_pad < N_DOCS:
        nd_pad *= 2
    doc_len = np.clip(
        rng.lognormal(np.log(AVG_DOC_LEN), 0.4, N_DOCS), 5, 500
    ).astype(np.int64)
    total_tokens = int(doc_len.sum())
    ranks = np.arange(1, VOCAB + 1)
    probs = 1.0 / ranks
    probs /= probs.sum()
    tokens = rng.choice(VOCAB, total_tokens, p=probs).astype(np.int32)
    doc_of_token = np.repeat(np.arange(N_DOCS, dtype=np.int32), doc_len)
    keys = tokens.astype(np.int64) * N_DOCS + doc_of_token
    uniq, counts = np.unique(keys, return_counts=True)
    term_ids = (uniq // N_DOCS).astype(np.int32)
    docs = (uniq % N_DOCS).astype(np.int32)
    tfs = counts.astype(np.float32)
    (block_docs, block_tfs, term_block_start, n_blocks_per_term,
     term_df) = pack_postings(term_ids, docs, tfs, VOCAB, nd_pad)
    norms = np.ones((1, nd_pad + 1), dtype=np.float32)
    norms[0, :N_DOCS] = doc_len.astype(np.float32)
    live1 = np.zeros(nd_pad + 1, dtype=bool)
    live1[:N_DOCS] = True
    avgdl = float(doc_len.mean())
    # a zipfian keyword column for the agg config (e.g. journal name):
    # 2000 distinct values, one per doc
    kranks = np.arange(1, 2001)
    kprobs = (1.0 / kranks) / (1.0 / kranks).sum()
    keyword_ord = rng.choice(2000, N_DOCS, p=kprobs).astype(np.int32)
    keyword_pad = np.full(nd_pad, 2000, np.int32)  # sentinel ord for padding
    keyword_pad[:N_DOCS] = keyword_ord
    # a numeric column for rescore (e.g. recency score)
    numeric = np.zeros(nd_pad, np.float32)
    numeric[:N_DOCS] = rng.rand(N_DOCS).astype(np.float32) * 10.0
    return {
        "block_docs": block_docs,
        "block_tfs": block_tfs,
        "norms": norms,
        "live1": live1,
        "term_block_start": term_block_start,
        "n_blocks_per_term": n_blocks_per_term,
        "term_df": term_df,
        "avgdl": avgdl,
        "nd_pad": nd_pad,
        "keyword_ord": keyword_pad,
        "numeric": numeric,
        # flat (term, doc)-sorted postings + per-doc lengths: the mesh
        # config re-packs doc-range slices of these into per-shard blocks
        "flat": (term_ids, docs, tfs),
        "doc_len": doc_len,
    }


def idf(df):
    return math.log(1 + (N_DOCS - df + 0.5) / (df + 0.5))


# ----------------------------------------------------------------------
# Legacy scatter program + numpy baseline (same exhaustive algorithm)
# ----------------------------------------------------------------------


def make_query_legacy(corpus, terms, qb_pad):
    blocks, weights, avgdls = [], [], []
    for t in terms:
        w = idf(int(corpus["term_df"][t]))
        start = int(corpus["term_block_start"][t])
        for bi in range(start, start + int(corpus["n_blocks_per_term"][t])):
            blocks.append(bi)
            weights.append(w)
            avgdls.append(corpus["avgdl"])
    n = qb_pad
    assert len(blocks) <= n, f"query needs {len(blocks)} blocks > pad {n}"
    pad = n - len(blocks)
    return (
        np.asarray(blocks + [0] * pad, np.int32),
        np.asarray(weights + [0.0] * pad, np.float32),
        np.zeros(n, np.int32),
        np.asarray(avgdls + [1.0] * pad, np.float32),
        np.asarray([True] * len(blocks) + [False] * pad),
    )


def numpy_reference_query(corpus, q, k=K):
    """Host-CPU scoring of the same query (vectorized numpy baseline)."""
    from elasticsearch_tpu.ops.scoring import B, K1

    q_blocks, q_weights, _, q_avgdl, q_valid = q
    docs = corpus["block_docs"][q_blocks]
    tfs = corpus["block_tfs"][q_blocks]
    doc_len = corpus["norms"][0][docs]
    denom = tfs + K1 * (1 - B + B * doc_len / q_avgdl[:, None])
    matched = (tfs > 0) & q_valid[:, None]
    contrib = np.where(matched, q_weights[:, None] * tfs * (K1 + 1) / denom, 0.0)
    nd1 = corpus["norms"].shape[1]
    scores = np.zeros(nd1, np.float32)
    np.add.at(scores, docs.ravel(), contrib.ravel())
    masked = np.where((scores > 0) & corpus["live1"], scores, -np.inf)
    top_idx = np.argpartition(-masked, k)[:k]
    top_idx = top_idx[np.argsort(-masked[top_idx])]
    return masked[top_idx], top_idx


# ----------------------------------------------------------------------
# Child measurement
# ----------------------------------------------------------------------


def run_measurement() -> dict:
    t_init = time.perf_counter()
    import jax

    if os.environ.get("BENCH_FORCE_CPU") == "1":
        # the env var alone is NOT enough: the axon site hook re-registers
        # the TPU tunnel backend regardless of JAX_PLATFORMS, so force the
        # platform through the config (same as tests/conftest.py)
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    from jax import lax

    devices = jax.devices()
    platform = devices[0].platform
    log(f"backend up: {platform} x{len(devices)} "
        f"in {time.perf_counter() - t_init:.1f}s")

    from elasticsearch_tpu.ops.scoring import B, K1
    from elasticsearch_tpu.ops import pallas_scoring as psc

    t0 = time.perf_counter()
    corpus = build_synthetic_corpus()
    nd_pad = corpus["nd_pad"]
    log(f"corpus built in {time.perf_counter() - t0:.1f}s "
        f"({corpus['block_docs'].shape[0]} blocks)")

    # ---------------- kernel staging (shard-open analog) ----------------
    t0 = time.perf_counter()
    geom = psc.tile_geometry(nd_pad)
    frac = psc.compute_block_frac(
        corpus["block_docs"], corpus["block_tfs"], corpus["norms"][0],
        corpus["avgdl"])
    bmin, bmax = psc.block_min_max(
        corpus["block_docs"], corpus["block_tfs"], nd_pad)
    dp, fp = psc.pad_segment_blocks(corpus["block_docs"], frac, nd_pad)
    live_t = psc.build_live_t(
        corpus["live1"][:nd_pad].astype(np.float32), geom)
    dev = {
        "docs": jnp.asarray(dp),
        "frac": jnp.asarray(fp),
        "live_t": jnp.asarray(live_t),
        # legacy path arrays
        "block_docs": jnp.asarray(corpus["block_docs"]),
        "block_tfs": jnp.asarray(corpus["block_tfs"]),
        "norms": jnp.asarray(corpus["norms"]),
        "live1": jnp.asarray(corpus["live1"]),
        "keyword_ord": jnp.asarray(corpus["keyword_ord"]),
        "numeric": jnp.asarray(corpus["numeric"]),
    }
    for v in dev.values():
        v.block_until_ready()
    hbm_bytes = sum(int(np.prod(v.shape)) * v.dtype.itemsize
                    for v in dev.values())
    stage_ms = (time.perf_counter() - t0) * 1000.0
    log(f"staged {hbm_bytes / 1e6:.0f} MB to device in "
        f"{stage_ms / 1000.0:.1f}s; geom={geom}")
    # bench stages the corpus directly (no Segment/IndexService in the
    # loop), so it registers with the device-memory accountant itself —
    # the report's staged_bytes_total / restage_amplification read the
    # same ledger production serves from (ISSUE 9, docs/OBSERVABILITY.md)
    from elasticsearch_tpu.common import memory as dm

    acct = dm.memory_accountant()
    _k = dict(reason="initial", duration_ms=stage_ms)
    acct.register("bench", "corpus", dm.KIND_POSTINGS_RAW, "k_postings",
                  int(dev["docs"].nbytes + dev["frac"].nbytes
                      + dev["block_docs"].nbytes
                      + dev["block_tfs"].nbytes), **_k)
    acct.register("bench", "corpus", dm.KIND_LIVE_MASK, "live",
                  int(dev["live_t"].nbytes + dev["live1"].nbytes), **_k)
    acct.register("bench", "corpus", dm.KIND_SCALE_NORM, "norms",
                  int(dev["norms"].nbytes), **_k)
    acct.register("bench", "corpus", dm.KIND_DOC_VALUES, "columns",
                  int(dev["keyword_ord"].nbytes + dev["numeric"].nbytes),
                  **_k)

    # ---------------- query mix ----------------
    rng = np.random.RandomState(3)
    term_sets = [list(rng.randint(50, 1000, N_QUERY_TERMS))
                 for _ in range(ITERS + WARMUP)]

    # the pallas kernel only lowers on real TPU; on the CPU fallback
    # backend measure the legacy XLA program as the primary path
    use_kernel = platform == "tpu"

    # legacy/numpy query pad: one shape bucket covering the whole run
    max_blocks = max(
        sum(int(corpus["n_blocks_per_term"][t]) for t in ts)
        for ts in term_sets)
    qb_pad = 1
    while qb_pad < max_blocks:
        qb_pad *= 2

    def kernel_query(terms, t_pad=4, cb=None):
        lanes = [psc.QueryLane(int(corpus["term_block_start"][t]),
                               int(corpus["n_blocks_per_term"][t]),
                               idf(int(corpus["term_df"][t])))
                 for t in terms]
        return psc.build_tile_tables(lanes, bmin, bmax, geom,
                                     t_pad=t_pad, cb=cb)

    kernel_metrics = None
    cb_run = None
    try:
        if not use_kernel:
            raise RuntimeError(f"pallas kernel not attempted on {platform}")
        # uniform CB bucket across the whole run -> one compiled program;
        # the tile tables themselves do not depend on cb, so build once
        kqueries = [kernel_query(ts) for ts in term_sets]
        cb_run = max(kq[3] for kq in kqueries)
        staged_kq = [(jnp.asarray(rl), jnp.asarray(rh), jnp.asarray(w))
                     for rl, rh, w, _ in kqueries]

        @jax.jit
        def _kernel_fused(docs, frac, live_t, rl, rh, w):
            # one program = one dispatch: the tile kernel + global merge
            # fuse under a single jit (two separate dispatches double the
            # per-call overhead and the marginal-timing jitter)
            ts_, td_, th_ = psc.score_tiles(
                docs, frac, live_t, rl, rh, w,
                t_pad=4, cb=cb_run, sub=geom.tile_sub, k=K)
            return psc.merge_tile_topk(ts_, td_, th_, K)

        def run_kernel(q):
            rl, rh, w = q
            return _kernel_fused(dev["docs"], dev["frac"], dev["live_t"],
                                 rl, rh, w)

        t0 = time.perf_counter()
        top_s, top_d, hits = run_kernel(staged_kq[0])
        top_s.block_until_ready()
        log(f"kernel first compile+run in {time.perf_counter() - t0:.1f}s "
            f"(cb={cb_run})")

        # Timing methodology (forced by the axon tunnel backend):
        # - block_until_ready does NOT wait for device completion here (a
        #   524k-element scatter "finished" in 40us), so naive per-call
        #   blocking under-reports arbitrarily.
        # - every np.asarray D2H pays a fixed ~70ms tunnel sync (and the
        #   first one permanently degrades later syncs the same way).
        # The only trustworthy estimator is MARGINAL BATCH time: run B and
        # then N*B chained executions, each batch ending in one tiny D2H
        # that forces full completion; the per-query device service time is
        # (T_big - T_small) / (extra queries), which cancels the fixed
        # dispatch+sync overhead exactly. measure_marginal() below also
        # repeats each batch and takes the minimum to cut scheduler noise.
        np.asarray(hits)  # deliberate first D2H: enter the degraded-sync
        # mode NOW so every timed section sees identical sync behavior

        # sustained warm-up to steady-state clocks/pipeline: without it
        # the FIRST timed section reads ~0.6 ms/query high regardless of
        # what it contains (round 4 reported "merge_topk 0.829ms" in the
        # stage breakdown — that was exactly this artifact hitting the
        # fused program, which was measured before score-only; verified
        # by reordering the sections in experiments/merge_variants.py:
        # whichever variant is timed first is slow, and the same program
        # re-timed later runs at ~0.58 ms)
        t0 = time.perf_counter()
        wout = None
        for i in range(WARM_QUERIES):
            wout = run_kernel(staged_kq[i % len(staged_kq)])
        if wout is not None:  # BENCH_WARM_QUERIES=0 skips the warm-up
            np.asarray(wout[0])
        log(f"steady-state warmup: {WARM_QUERIES} queries in "
            f"{time.perf_counter() - t0:.1f}s")

        timed = staged_kq[WARMUP:]
        per_query = measure_marginal(run_kernel, timed)

        def run_score_only(q):
            rl, rh, w = q
            return psc.score_tiles(
                dev["docs"], dev["frac"], dev["live_t"], rl, rh, w,
                t_pad=4, cb=cb_run, sub=geom.tile_sub, k=K)

        score_only = measure_marginal(run_score_only, timed)

        # per-phase attribution (ISSUE 8, docs/OBSERVABILITY.md): the
        # host-side plan/table-build cost per query — the production
        # path rebuilds the tile tables per request, so the staging rung
        # of the phase taxonomy has a real per-query price even though
        # the corpus itself stays resident
        t0 = time.perf_counter()
        n_stage = 0
        for ts in term_sets[WARMUP:]:
            kernel_query(ts, cb=cb_run)
            n_stage += 1
        table_build_ms = ((time.perf_counter() - t0)
                          / max(n_stage, 1) * 1000)

        kernel_metrics = {
            "stage_table_build": table_build_ms,
            "p50": per_query * 1000,
            # marginal estimates carry no per-query tail — a "p99" from
            # this method would be an artifact (round-4 VERDICT). Report
            # a SECOND independent p50 estimate as a dispersion proxy,
            # under a name that says what it is.
            "p50_2": measure_marginal(run_kernel, timed) * 1000,
            "stage_score_p50": score_only * 1000,
            # gate fetch happens after all timed sections
            "gate": (top_s, top_d),
        }
    except Exception as e:  # noqa: BLE001 — fall back to the legacy path
        import traceback

        traceback.print_exc(file=sys.stderr)
        log(f"kernel path unavailable ({type(e).__name__}: {e}); "
            f"falling back to legacy scatter program")

    # ---------------- extra configs (same marginal methodology) ----------
    def stamp_mem(*cfgs):
        """Stamp the device-memory ledger's view (ISSUE 9) onto each
        config dict AS IT COMPLETES: staged_bytes_total is the ledger's
        bench-index bytes at that point, restage_amplification the
        restaged/logically-changed ratio (non-null once the packed
        config re-stages the corpus)."""
        st = dm.memory_accountant().stats("bench")
        for cfg in cfgs:
            if isinstance(cfg, dict) and "error" not in cfg:
                cfg["staged_bytes_total"] = st["staged_bytes_total"]
                cfg["restage_amplification"] = st["restage_amplification"]

    extra_configs = None
    if kernel_metrics is not None:
        extra_configs = run_extra_configs(
            jax, jnp, lax, psc, corpus, dev, geom, bmin, bmax, cb_run, rng)
        stamp_mem(*extra_configs.values())
        # cross-query micro-batching sweep (ISSUE 5 acceptance config)
        try:
            extra_configs["batched_qps"] = run_batched_qps_config(
                jax, jnp, psc, corpus, dev, geom, frac, bmin, bmax)
        except Exception as e:  # noqa: BLE001 — recorded, never fatal
            import traceback

            traceback.print_exc(file=sys.stderr)
            extra_configs["batched_qps"] = {
                "error": f"{type(e).__name__}: {e}"}
        stamp_mem(extra_configs["batched_qps"])
        # the mesh-path config: distributed scoring on the tile kernel
        # (acceptance: within 2x of the single-chip pallas p50)
        try:
            extra_configs["mesh_pallas_packed"] = run_mesh_pallas_config(
                jax, jnp, lax, psc, corpus, term_sets)
        except Exception as e:  # noqa: BLE001 — recorded, never fatal
            import traceback

            traceback.print_exc(file=sys.stderr)
            extra_configs["mesh_pallas_packed"] = {
                "error": f"{type(e).__name__}: {e}"}
        stamp_mem(extra_configs["mesh_pallas_packed"])
        # ISSUE 6 acceptance configs: bit-packed postings codec and
        # block-max pruned scoring (each recall-gated vs the RAW oracle)
        try:
            packed_cfg, pruned_cfg = run_codec_pruning_configs(
                jax, jnp, psc, corpus, dev, geom, frac, bmin, bmax,
                cb_run, term_sets)
            extra_configs["packed_postings"] = packed_cfg
            extra_configs["pruned_scoring"] = pruned_cfg
        except Exception as e:  # noqa: BLE001 — recorded, never fatal
            import traceback

            traceback.print_exc(file=sys.stderr)
            extra_configs["packed_postings"] = {
                "error": f"{type(e).__name__}: {e}"}
            extra_configs["pruned_scoring"] = {
                "error": f"{type(e).__name__}: {e}"}
        stamp_mem(extra_configs["packed_postings"],
                  extra_configs["pruned_scoring"])
        # ISSUE 7 acceptance configs: dense-vector kNN on the MXU +
        # hybrid BM25 ∪ kNN ranking (recall-gated vs the numpy oracle)
        try:
            knn_cfg, hybrid_cfg = run_knn_configs(
                jax, jnp, psc, corpus, dev, geom, frac, bmin, bmax,
                term_sets)
            extra_configs["knn_top10"] = knn_cfg
            extra_configs["hybrid_rrf"] = hybrid_cfg
        except Exception as e:  # noqa: BLE001 — recorded, never fatal
            import traceback

            traceback.print_exc(file=sys.stderr)
            extra_configs["knn_top10"] = {
                "error": f"{type(e).__name__}: {e}"}
            extra_configs["hybrid_rrf"] = {
                "error": f"{type(e).__name__}: {e}"}
        stamp_mem(extra_configs["knn_top10"],
                  extra_configs["hybrid_rrf"])
        # ISSUE 10 acceptance config: serving capacity with the chaos
        # schemes running (BENCH_r10 — availability + qps under faults)
        try:
            extra_configs["fault_soak"] = run_fault_soak_config()
        except Exception as e:  # noqa: BLE001 — recorded, never fatal
            import traceback

            traceback.print_exc(file=sys.stderr)
            extra_configs["fault_soak"] = {
                "error": f"{type(e).__name__}: {e}"}
        stamp_mem(extra_configs["fault_soak"])
        # ISSUE 12 acceptance config: goodput/fairness at offered load
        # >> capacity with zipfian tenants (docs/OVERLOAD.md)
        try:
            extra_configs["overload_zipfian"] = \
                run_overload_zipfian_config()
        except Exception as e:  # noqa: BLE001 — recorded, never fatal
            import traceback

            traceback.print_exc(file=sys.stderr)
            extra_configs["overload_zipfian"] = {
                "error": f"{type(e).__name__}: {e}"}
        stamp_mem(extra_configs["overload_zipfian"])
        # ISSUE 14 acceptance config: cold-start stall elimination —
        # first-query latency cold vs compile-cache-warmed + drain p99
        # (docs/RESILIENCE.md "Rollout & drain")
        try:
            extra_configs["cold_start"] = run_cold_start_config()
        except Exception as e:  # noqa: BLE001 — recorded, never fatal
            import traceback

            traceback.print_exc(file=sys.stderr)
            extra_configs["cold_start"] = {
                "error": f"{type(e).__name__}: {e}"}
        stamp_mem(extra_configs["cold_start"])
        # ISSUE 20 acceptance config: ingest + search under sustained
        # delta device staging (docs/MESH.md "Slot allocator &
        # generations"). NO stamp_mem here: the config reports its own
        # windowed restage_amplification and the stamp would clobber it
        try:
            extra_configs["nrt_ingest"] = run_nrt_ingest_config()
        except Exception as e:  # noqa: BLE001 — recorded, never fatal
            import traceback

            traceback.print_exc(file=sys.stderr)
            extra_configs["nrt_ingest"] = {
                "error": f"{type(e).__name__}: {e}"}

    # ---------------- timings: legacy scatter path (r03) ----------------
    legacy_p50 = legacy_p50_2 = None
    try:
        n_legacy = (WARMUP + 10) if kernel_metrics else (WARMUP + ITERS // 2)

        @jax.jit
        def legacy_query(block_docs, block_tfs, norms, live1, q_blocks,
                         q_weights, q_norm_rows, q_avgdl, q_valid):
            docs = block_docs[q_blocks]
            tfs = block_tfs[q_blocks]
            nd1 = norms.shape[1]
            flat_idx = (q_norm_rows[:, None] * nd1 + docs).ravel()
            doc_len = norms.ravel()[flat_idx].reshape(docs.shape)
            denom = tfs + K1 * (1.0 - B + B * doc_len / q_avgdl[:, None])
            matched_blk = (tfs > 0.0) & q_valid[:, None]
            contrib = jnp.where(
                matched_blk, q_weights[:, None] * tfs * (K1 + 1.0) / denom,
                0.0)
            scores = jnp.zeros((nd1,), jnp.float32).at[docs].add(contrib)
            masked = jnp.where((scores > 0) & live1, scores, -jnp.inf)
            return lax.top_k(masked, K)

        lq = [tuple(jnp.asarray(x)
                    for x in make_query_legacy(corpus, ts, qb_pad))
              for ts in term_sets[:n_legacy]]

        def run_legacy(q):
            return legacy_query(dev["block_docs"], dev["block_tfs"],
                                dev["norms"], dev["live1"], *q)

        np.asarray(run_legacy(lq[0])[0])  # compile (+ first D2H on the
        # CPU-backend fallback path, where the kernel section didn't run)
        legacy_pq = measure_marginal(run_legacy, lq[WARMUP:] or lq)
        legacy_p50 = legacy_pq * 1000
        legacy_p50_2 = measure_marginal(run_legacy, lq[WARMUP:] or lq) * 1000
    except Exception as e:  # noqa: BLE001
        log(f"legacy path failed: {e}")

    # ---------------- correctness gate ------------------------------------
    tunnel_sync_ms = None
    if kernel_metrics is not None:
        try:
            top_s, top_d = kernel_metrics.pop("gate")
            q0 = make_query_legacy(corpus, term_sets[0], qb_pad)
            ref_s, ref_i = numpy_reference_query(corpus, q0)
            got_s = np.asarray(top_s)
            got_d = np.asarray(top_d)
            # tie-robust gate: sorted score values must match; the doc set
            # may legitimately differ on exact score ties. recall_at_10
            # reports the MEASURED intersection, not an assumption.
            np.testing.assert_allclose(got_s, ref_s, rtol=1e-3)
            recall = len(set(got_d.tolist()) & set(ref_i.tolist())) / K
            if recall < 1.0:
                kth = ref_s[-1]
                assert (got_s >= kth * (1 - 1e-3)).all(), \
                    "non-tie doc mismatch vs reference"
            kernel_metrics["recall"] = recall
            log(f"correctness gate passed (measured recall@10 = {recall})")

            # record the fixed per-sync tunnel cost: one execution + one
            # tiny D2H, minus the device time already measured marginally
            sync_lat = []
            for q in staged_kq[WARMUP: WARMUP + 3]:
                t0 = time.perf_counter()
                np.asarray(run_kernel(q)[0])
                sync_lat.append(time.perf_counter() - t0)
            tunnel_sync_ms = max(
                pctl(sync_lat, 50) - kernel_metrics["p50"], 0.0)
        except Exception as e:  # noqa: BLE001 — gate failure demotes the path
            import traceback

            traceback.print_exc(file=sys.stderr)
            log(f"kernel correctness gate FAILED ({type(e).__name__}: {e}); "
                f"falling back to legacy scatter numbers")
            kernel_metrics = None

    # ---------------- numpy baseline ----------------
    nq = [make_query_legacy(corpus, ts, qb_pad)
          for ts in term_sets[: WARMUP + 10]]
    cpu_lat = []
    for q in nq:
        t0 = time.perf_counter()
        numpy_reference_query(corpus, q)
        cpu_lat.append(time.perf_counter() - t0)
    cpu_p50 = pctl(cpu_lat[2:], 50)

    if kernel_metrics is None and legacy_p50 is None:
        raise RuntimeError("both kernel and legacy paths failed")

    if kernel_metrics is not None:
        p50, p50_2 = kernel_metrics["p50"], kernel_metrics["p50_2"]
        path = "pallas_tile_kernel"
        # HBM traffic for one kernel query: two cb-aligned posting windows
        # (docs + frac) per lane per tile + the live mask + tiny outputs
        bytes_per_query = (
            geom.n_tiles * 4 * (2 * cb_run) * BLOCK * (4 + 4)
            + geom.n_tiles * geom.tile_w * 4
            + geom.n_tiles * (2 * K + 1) * 4
        )
        stage = {
            "score_tiles_kernel": round(kernel_metrics["stage_score_p50"], 3),
            "merge_topk": round(
                max(kernel_metrics["p50"]
                    - kernel_metrics["stage_score_p50"], 0.0), 3),
        }
        # per-phase p50 attribution in the phase-taxonomy vocabulary
        # (docs/OBSERVABILITY.md): where one query's wall budget goes —
        # the item-1/item-5 tuning decisions (codec/pruning flips, ICI
        # serving loop) read this, not the raw stage numbers
        phase_attribution = {
            "plan_build": round(kernel_metrics["stage_table_build"], 3),
            "kernel": stage["score_tiles_kernel"],
            "merge": stage["merge_topk"],
        }
        recall = kernel_metrics["recall"]
        method = ("marginal batch timing: per-query device service time = "
                  "(T[60 chained queries] - T[10]) / 50, each batch ending in "
                  "one tiny D2H that forces completion; cancels the axon "
                  "tunnel's fixed ~70ms per-sync overhead (its "
                  "block_until_ready does not await completion, so naive "
                  "per-call timing is meaningless on this backend)")
        # ISSUE 6: the headline reports the best codec/pruning mode that
        # PASSED its recall gate (recall@10 == 1.0 vs the raw oracle) —
        # and says which mode produced it. Raw exhaustive remains the
        # floor: a failed gate or slower config can never claim it.
        headline_mode = {"config": "main", "postings_codec": "raw",
                         "pruning": False}
        if isinstance(extra_configs, dict):
            for cfg_name, mode in (
                    ("packed_postings",
                     {"postings_codec": "packed", "pruning": False}),
                    ("pruned_scoring",
                     {"postings_codec": "packed", "pruning": True})):
                cfg = extra_configs.get(cfg_name)
                if not isinstance(cfg, dict):
                    continue
                cfg_p50 = cfg.get("p50_ms")
                if (cfg.get("recall_at_10") == 1.0
                        and isinstance(cfg_p50, (int, float))
                        and cfg_p50 < p50):
                    p50 = cfg_p50
                    p50_2 = cfg_p50 + cfg.get("p50_spread_ms", 0.0)
                    headline_mode = dict(mode, config=cfg_name)
                    bq = cfg.get("bytes_per_query_mb_pruned",
                                 cfg.get("bytes_per_query_mb_packed"))
                    if bq is not None:
                        bytes_per_query = bq * 1e6
    else:
        p50, p50_2 = legacy_p50, legacy_p50_2
        path = "xla_scatter_fallback"
        nd1 = nd_pad + 1
        bytes_per_query = (
            qb_pad * BLOCK * 12 + nd1 * 13 + nd1 * 4)
        extra_configs = {"skipped": "kernel path unavailable"}
        stage = None
        phase_attribution = None
        recall = 1.0
        headline_mode = {"config": "main", "postings_codec": "raw",
                         "pruning": False}
        method = ("legacy XLA scatter program, marginal batch timing")

    # ISSUE 13 acceptance config: fused on-device aggregations — runs
    # on BOTH backends (the CPU fallback uses the XLA scatter front end
    # with the identical agg formulation), bucket-equality gated vs the
    # numpy oracle (docs/AGGS.md)
    try:
        agg_cfg = run_agg_fused_config(
            jax, jnp, lax, psc, corpus, dev, geom, bmin, bmax,
            cb_run, kernel_metrics is not None)
    except Exception as e:  # noqa: BLE001 — recorded, never fatal
        import traceback

        traceback.print_exc(file=sys.stderr)
        agg_cfg = {"error": f"{type(e).__name__}: {e}"}
    if not isinstance(extra_configs, dict):
        extra_configs = {}
    extra_configs["agg_fused"] = agg_cfg
    stamp_mem(agg_cfg)

    hbm_gbps = bytes_per_query / (p50 / 1000) / 1e9

    return {
        "metric": "bm25_match_top10_p50_latency_1M_docs",
        "value": round(p50, 3),
        "unit": "ms",
        "vs_baseline": round(cpu_p50 / p50, 2),
        "extra": {
            "backend": platform,
            "path": path,
            # which postings codec / pruning mode produced the headline
            # value (ISSUE 6): only recall-gated configs may claim it
            "headline_mode": headline_mode,
            # marginal batch timing cannot observe per-query tails; a
            # second independent estimate bounds run-to-run dispersion
            "p50_second_estimate_ms": round(p50_2, 3),
            "qps_per_chip": round(1000.0 / p50, 1),
            # cross-query micro-batching headline (q_batch=8 sweep point;
            # the full sweep is configs.batched_qps)
            "qps_per_chip_batched": (
                (extra_configs or {}).get("batched_qps", {})
                .get("q_batch_8", {}).get("qps_per_chip_batched")
                if isinstance(extra_configs, dict) else None),
            "bytes_per_query_mb_batched": (
                (extra_configs or {}).get("batched_qps", {})
                .get("q_batch_8", {}).get("bytes_per_query_mb_batched")
                if isinstance(extra_configs, dict) else None),
            # dense-vector plane headlines (ISSUE 7): exact kNN top-10
            # p50 on the MXU (recall-gated) and hybrid BM25 ∪ kNN RRF
            # throughput — None when the config errored or failed its
            # recall gate (configs.knn_top10 / configs.hybrid_rrf carry
            # the detail either way)
            "vector_top10_p50": (
                (extra_configs or {}).get("knn_top10", {}).get("p50_ms")
                if isinstance(extra_configs, dict)
                and (extra_configs.get("knn_top10", {})
                     .get("recall_at_10") == 1.0) else None),
            "hybrid_qps_per_chip": (
                (extra_configs or {}).get("hybrid_rrf", {})
                .get("qps_per_chip")
                if isinstance(extra_configs, dict)
                and (extra_configs.get("hybrid_rrf", {})
                     .get("fused_recall_at_10") == 1.0) else None),
            # device-plane chaos headline (ISSUE 10): serving capacity
            # with fault injection running — availability (zero-5xx as
            # a measured fraction) and qps/chip under the fault_soak
            # scheme mix (configs.fault_soak carries the detail)
            "availability_under_faults": (
                (extra_configs or {}).get("fault_soak", {})
                .get("availability_under_faults")
                if isinstance(extra_configs, dict) else None),
            "qps_under_faults_per_chip": (
                (extra_configs or {}).get("fault_soak", {})
                .get("qps_under_faults_per_chip")
                if isinstance(extra_configs, dict) else None),
            # NRT delta-staging headlines (ISSUE 20, docs/MESH.md "Slot
            # allocator & generations"): ingest + search throughput
            # under sustained incremental device staging, and the
            # append-window restage amplification (~1 = every refresh
            # rode the delta path; configs.nrt_ingest has the detail —
            # its restage_amplification is windowed over the append
            # legs, unlike the whole-run ratio below)
            "ingest_docs_per_s": (
                (extra_configs or {}).get("nrt_ingest", {})
                .get("ingest_docs_per_s")
                if isinstance(extra_configs, dict) else None),
            "search_p50_under_ingest_ms": (
                (extra_configs or {}).get("nrt_ingest", {})
                .get("search_p50_under_ingest_ms")
                if isinstance(extra_configs, dict) else None),
            "restage_amplification_nrt": (
                (extra_configs or {}).get("nrt_ingest", {})
                .get("restage_amplification")
                if isinstance(extra_configs, dict) else None),
            # overload-control headline (ISSUE 12, docs/OVERLOAD.md):
            # goodput, bounded admitted-p99, reject rate, and tenant
            # fairness at offered load >> capacity with zipfian tenants
            # (configs.overload_zipfian carries the detail)
            "goodput_qps_under_overload": (
                (extra_configs or {}).get("overload_zipfian", {})
                .get("goodput_qps_under_overload")
                if isinstance(extra_configs, dict) else None),
            "admitted_p99_ms": (
                (extra_configs or {}).get("overload_zipfian", {})
                .get("admitted_p99_ms")
                if isinstance(extra_configs, dict) else None),
            "reject_rate": (
                (extra_configs or {}).get("overload_zipfian", {})
                .get("reject_rate")
                if isinstance(extra_configs, dict) else None),
            "max_tenant_starvation_ratio": (
                (extra_configs or {}).get("overload_zipfian", {})
                .get("max_tenant_starvation_ratio")
                if isinstance(extra_configs, dict) else None),
            # fused on-device aggregations headline (ISSUE 13,
            # docs/AGGS.md): agg'd-query latency with the bucket
            # reductions fused into the scoring launch, what the host
            # round-trip used to cost on top, and the doc-value column
            # bytes per query (configs.agg_fused carries the detail +
            # the bucket-equality gate)
            "agg_p50_ms": agg_cfg.get("agg_p50_ms"),
            "agg_host_roundtrip_saved_ms": agg_cfg.get(
                "agg_host_roundtrip_saved_ms"),
            "bytes_per_query_mb_agg": agg_cfg.get(
                "bytes_per_query_mb_agg"),
            "cpu_numpy_p50_ms": round(cpu_p50, 3),
            "legacy_scatter_p50_ms": (round(legacy_p50, 3)
                                      if legacy_p50 else None),
            "tunnel_sync_ms_after_first_d2h": (
                round(tunnel_sync_ms, 3) if tunnel_sync_ms is not None
                else None),
            "stage_breakdown_ms": stage,
            # where one query's p50 goes, in the phase-taxonomy
            # vocabulary of docs/OBSERVABILITY.md (staging vs kernel vs
            # merge) — the ROADMAP item-1/item-5 decisions read this
            "phase_attribution_p50_ms": phase_attribution,
            "n_docs": N_DOCS,
            "recall_at_10": recall,
            # device-memory ledger view (ISSUE 9): exact bytes the bench
            # corpus holds staged, and restaged/logically-changed — the
            # ROADMAP item-3 number (non-null once the packed config
            # re-staged the corpus in a second layout)
            "staged_bytes_total": (
                dm.memory_accountant().stats("bench")
                ["staged_bytes_total"]),
            "restage_amplification": (
                dm.memory_accountant().stats("bench")
                ["restage_amplification"]),
            "hbm_gb_per_s_estimate": round(hbm_gbps, 1),
            "bytes_per_query_mb": round(bytes_per_query / 1e6, 2),
            "corpus_hbm_mb": round(hbm_bytes / 1e6, 1),
            "tile_geometry": {"n_tiles": geom.n_tiles, "tile_w": geom.tile_w,
                              "cb": cb_run},
            "configs": extra_configs,
            "method": method,
        },
    }


def run_extra_configs(jax, jnp, lax, psc, corpus, dev, geom, bmin, bmax,
                      cb_run, rng):
    """The remaining BASELINE.md configs, each a small timed program.
    Failures are reported per-config, never fatal."""
    import numpy as np

    out = {}
    # Estimator note (BENCH_r05 rescore_top1000 diagnosis: p50 1.625 vs
    # second estimate 2.406 ms): between configs the device idles while
    # the host stages the next config's arrays, so clocks ramp down and
    # the next marginal estimate reads HIGH — the same artifact the main
    # path's 6000-query warm-up removes, re-entering here config by
    # config. Marginal-batch noise is one-sided (preemption, ramp-down
    # and sync jitter only ADD time; nothing executes faster than the
    # device), so the MINIMUM of several estimates after a short re-warm
    # is the trustworthy p50; the spread field bounds dispersion.
    out["estimator_note"] = (
        "p50_ms is the min of 3 marginal estimates after a 200-query "
        "re-warm (marginal noise is one-sided: idle clock ramp-down "
        "between configs inflates estimates, nothing deflates them); "
        "p50_spread_ms = max - min of the 3")

    def time_it(fn, warm=2):
        """fn() must return the (device-array, ...) outputs of one query.
        Marginal batch timing — see measure_marginal and estimator_note."""
        for _ in range(warm):
            fn()
        # short sustained re-warm to steady-state clocks: the host-side
        # staging between configs idles the device long enough for the
        # first estimate to read high otherwise
        o = None
        for _ in range(200):
            o = fn()
        np.asarray(o[0])
        ests = sorted(measure_marginal(lambda _q: fn(), [None])
                      for _ in range(3))
        return ests[0] * 1000, (ests[-1] - ests[0]) * 1000

    def lanes_for(terms):
        return [psc.QueryLane(int(corpus["term_block_start"][t]),
                              int(corpus["n_blocks_per_term"][t]),
                              idf(int(corpus["term_df"][t])))
                for t in terms]

    # ---- config 2: bool must + should + filter ----
    try:
        must_t = int(rng.randint(50, 200))
        should_ts = [int(x) for x in rng.randint(200, 2000, 2)]
        rl_m, rh_m, w_m, _ = psc.build_tile_tables(
            lanes_for([must_t]), bmin, bmax, geom, t_pad=4, cb=cb_run)
        rl_a, rh_a, w_a, _ = psc.build_tile_tables(
            lanes_for([must_t] + should_ts), bmin, bmax, geom, t_pad=4,
            cb=cb_run)
        args_m = (jnp.asarray(rl_m), jnp.asarray(rh_m), jnp.asarray(w_m))
        args_a = (jnp.asarray(rl_a), jnp.asarray(rh_a), jnp.asarray(w_a))
        lo, hi = 2.0, 8.0

        @jax.jit
        def bool_query(docs, frac, live_t, rlm, rhm, wm, rla, rha, wa,
                       numeric):
            # dense scores for all clauses; dense counts for the must lane
            all_s = psc.score_tiles(docs, frac, live_t, rla, rha, wa,
                                    t_pad=4, cb=cb_run, sub=geom.tile_sub,
                                    dense=True)[0]
            must_s, must_c = psc.score_tiles(
                docs, frac, live_t, rlm, rhm, wm, t_pad=4, cb=cb_run,
                sub=geom.tile_sub, dense=True, with_counts=True)
            scores = psc.dense_to_flat(all_s, geom.tile_sub)
            mustc = psc.dense_to_flat(must_c, geom.tile_sub)
            filt = (numeric >= lo) & (numeric <= hi)
            masked = jnp.where((mustc > 0) & filt, scores, -jnp.inf)
            # hierarchical top-k: per-row then global
            m2 = masked.reshape(1024, -1)
            s_r, i_r = lax.top_k(m2, K)
            flat_i = (jnp.arange(1024, dtype=jnp.int32)[:, None] * m2.shape[1] + i_r).reshape(-1)
            s_f, i_f = lax.top_k(s_r.reshape(-1), K)
            return s_f, flat_i[i_f], jnp.sum(masked > -jnp.inf)

        def run_bool():
            return bool_query(dev["docs"], dev["frac"], dev["live_t"],
                              *args_m, *args_a, dev["numeric"])
        p50b, spreadb = time_it(run_bool)
        out["bool_must_should_filter"] = {"p50_ms": round(p50b, 3),
                                          "p50_spread_ms": round(spreadb, 3)}
    except Exception as e:  # noqa: BLE001
        out["bool_must_should_filter"] = {"error": f"{type(e).__name__}: {e}"}

    # ---- config 3: terms + cardinality agg over keyword column ----
    try:
        terms = [int(x) for x in rng.randint(50, 500, 2)]
        rl, rh, w, _ = psc.build_tile_tables(
            lanes_for(terms), bmin, bmax, geom, t_pad=4, cb=cb_run)
        args = (jnp.asarray(rl), jnp.asarray(rh), jnp.asarray(w))

        from elasticsearch_tpu.ops import pallas_aggs as pag

        @jax.jit
        def agg_query(docs, frac, live_t, rl, rh, w, kw):
            ds = psc.score_tiles(docs, frac, live_t, rl, rh, w,
                                 t_pad=4, cb=cb_run, sub=geom.tile_sub,
                                 dense=True)[0]
            scores = psc.dense_to_flat(ds, geom.tile_sub)
            contrib = jnp.where(scores > 0, jnp.float32(1.0),
                                jnp.float32(0.0))
            # terms agg: pallas segment-sum over keyword ordinals (the
            # scatter-free BucketsAggregator.collect analog)
            (counts,) = pag.segment_aggregate(kw, contrib, n_ords=2000)
            top_counts, top_ords = lax.top_k(counts, 10)
            # cardinality: count of distinct matched ordinals (exact here;
            # the engine's HLL++ kernel is ops/aggs.py)
            card = jnp.sum(counts > 0)
            return top_counts, top_ords, card

        def run_agg():
            return agg_query(dev["docs"], dev["frac"], dev["live_t"],
                             *args, dev["keyword_ord"])
        p50a, spreada = time_it(run_agg)
        out["terms_cardinality_agg"] = {"p50_ms": round(p50a, 3),
                                        "p50_spread_ms": round(spreada, 3)}
    except Exception as e:  # noqa: BLE001
        out["terms_cardinality_agg"] = {"error": f"{type(e).__name__}: {e}"}

    # ---- config 5: DMA double-buffering (tiles_per_step=2) ----
    try:
        terms = [int(x) for x in rng.randint(50, 1000, 3)]
        rl5, rh5, w5, _ = psc.build_tile_tables(
            lanes_for(terms), bmin, bmax, geom, t_pad=4, cb=cb_run)
        args5 = (jnp.asarray(rl5), jnp.asarray(rh5), jnp.asarray(w5))

        @jax.jit
        def tps2_query(docs, frac, live_t, rl, rh, w):
            ts_, td_, th_ = psc.score_tiles(
                docs, frac, live_t, rl, rh, w,
                t_pad=4, cb=cb_run, sub=geom.tile_sub, k=K,
                tiles_per_step=2)
            return psc.merge_tile_topk(ts_, td_, th_, K)

        def run_tps2():
            return tps2_query(dev["docs"], dev["frac"], dev["live_t"],
                              *args5)
        p50t, spreadt = time_it(run_tps2)
        out["pallas_tiles_per_step2"] = {
            "p50_ms": round(p50t, 3),
            "p50_spread_ms": round(spreadt, 3),
            "note": ("grid coarsened to 2 tiles/step: posting-window DMAs "
                     "for the second tile issue while the first computes, "
                     "halving the fixed per-step cost the kernel comment "
                     "names as dominant; compare against the main p50 to "
                     "decide the search.pallas.tiles_per_step default"),
        }
    except Exception as e:  # noqa: BLE001
        out["pallas_tiles_per_step2"] = {"error": f"{type(e).__name__}: {e}"}

    # ---- config 4: rescore over top-1000 ----
    try:
        terms = [int(x) for x in rng.randint(50, 1000, 3)]
        rl, rh, w, _ = psc.build_tile_tables(
            lanes_for(terms), bmin, bmax, geom, t_pad=4, cb=cb_run)
        args = (jnp.asarray(rl), jnp.asarray(rh), jnp.asarray(w))

        @jax.jit
        def rescore_query(docs, frac, live_t, rl, rh, w, numeric):
            ds = psc.score_tiles(docs, frac, live_t, rl, rh, w,
                                 t_pad=4, cb=cb_run, sub=geom.tile_sub,
                                 dense=True)[0]
            scores = psc.dense_to_flat(ds, geom.tile_sub)
            masked = jnp.where(scores > 0, scores, -jnp.inf)
            # exact top-1000 window (a per-row hierarchical cut would clip
            # rows holding >4 of the true top-1000)
            s1k, window = lax.top_k(masked, 1000)
            # function_score rescore: query_weight*s + rescore_weight*fn
            fn = jnp.log1p(numeric[window])
            rescored = s1k * 1.0 + fn * 0.5
            return lax.top_k(rescored, K)

        def run_rescore():
            return rescore_query(dev["docs"], dev["frac"], dev["live_t"],
                                 *args, dev["numeric"])
        p50r, spreadr = time_it(run_rescore)
        out["rescore_top1000"] = {
            "p50_ms": round(p50r, 3),
            "p50_spread_ms": round(spreadr, 3),
            "note": ("r05 showed 1.625 vs 2.406 ms estimates here: the "
                     "second estimate ran after the device idled through "
                     "host-side staging (clock ramp-down); see "
                     "estimator_note — min-of-3 after re-warm is the "
                     "trustworthy figure"),
        }
    except Exception as e:  # noqa: BLE001
        out["rescore_top1000"] = {"error": f"{type(e).__name__}: {e}"}

    return out


def run_batched_qps_config(jax, jnp, psc, corpus, dev, geom, frac,
                           bmin, bmax):
    """Cross-query micro-batching sweep (ISSUE 5): q_batch in {1,4,8,16}
    on the 1M-doc corpus, one batched ``score_tiles`` launch per batch
    over UNION tables + the per-query fused top-k, every member
    recall-gated against the numpy oracle.

    Query mix: 3 terms per query drawn ZIPFIAN from a 1000-term hot
    query vocabulary — the production property the batching exploits
    (concurrent queries share hot terms, so the union lane count grows
    sublinearly in Q and the shared posting-window DMA amortizes). The
    estimator is the min-of-3 marginal method of estimator_note (the
    r05 rescore_top1000 one-sided-spread fix applies here too: these
    numbers gate an acceptance criterion and must not be
    ramp-down-noise-dominated)."""
    import numpy as np

    rng = np.random.RandomState(11)
    # zipf over a hot query vocabulary (rank 50..1049 of the corpus
    # zipf, i.e. realistic mid-frequency search terms)
    qvocab = np.arange(50, 1050)
    ranks = np.arange(1, len(qvocab) + 1, dtype=np.float64)
    probs = (1.0 / ranks) / (1.0 / ranks).sum()

    def draw_query():
        return list(np.unique(rng.choice(qvocab, 3, p=probs)))

    def lanes_for(terms):
        return [psc.QueryLane(int(corpus["term_block_start"][t]),
                              int(corpus["n_blocks_per_term"][t]),
                              idf(int(corpus["term_df"][t])))
                for t in terms]

    def time_min3(fn):
        """min-of-3 marginal estimate after a sustained re-warm (see
        estimator_note: marginal noise is one-sided)."""
        for _ in range(2):
            fn()
        o = None
        for _ in range(200):
            o = fn()
        np.asarray(o[0])
        ests = sorted(measure_marginal(lambda _q: fn(), [None])
                      for _ in range(3))
        return ests[0] * 1000, (ests[-1] - ests[0]) * 1000

    out = {"query_mix": ("3 zipfian terms per query from a 1000-term "
                         "hot vocabulary; batches drawn independently")}
    nd_pad = corpus["nd_pad"]
    base_qps = None
    for q_batch in (1, 4, 8, 16):
        n_batches = 8
        batches = [[draw_query() for _ in range(q_batch)]
                   for _ in range(n_batches)]
        staged, t_pad_run, cb_run = [], 8, 8
        tables = []
        for batch in batches:
            rl, rh, w, cbr = psc.build_tile_tables_batched(
                [lanes_for(ts) for ts in batch], bmin, bmax, geom)
            tables.append((rl, rh, w))
            t_pad_run = max(t_pad_run, rl.shape[1])
            cb_run = max(cb_run, cbr)
        # one shape bucket per q_batch: pad every batch's tables to the
        # run-wide (t_pad, cb) so the sweep compiles once per Q
        for rl, rh, w in tables:
            if rl.shape[1] < t_pad_run:
                pad = t_pad_run - rl.shape[1]
                rl = np.pad(rl, ((0, 0), (0, pad)))
                rh = np.pad(rh, ((0, 0), (0, pad)))
                w = np.pad(w, ((0, 0), (0, pad)))
            staged.append((jnp.asarray(rl), jnp.asarray(rh),
                           jnp.asarray(w)))

        @jax.jit
        def _batched_fused(docs, frac_d, live_t, rl, rh, w,
                           t_pad=t_pad_run, cb=cb_run, qb=q_batch):
            ts_, td_, th_ = psc.score_tiles(
                docs, frac_d, live_t, rl, rh, w,
                t_pad=t_pad, cb=cb, sub=geom.tile_sub, k=K, q_batch=qb)
            return psc.merge_tile_topk_batched(ts_, td_, th_, K)

        cycle = {"i": 0}

        def run_batch():
            q = staged[cycle["i"] % len(staged)]
            cycle["i"] += 1
            return _batched_fused(dev["docs"], dev["frac"], dev["live_t"],
                                  *q)

        # recall gate: EVERY member of the first batch vs the numpy
        # oracle (acceptance requires 1.0 across the batch)
        top_s, top_d, _hits = run_batch()
        top_s = np.asarray(top_s)
        top_d = np.asarray(top_d)
        recall_min = 1.0
        for q, terms in enumerate(batches[0]):
            ref = psc.reference_scores(
                corpus["block_docs"], frac, lanes_for(terms), nd_pad)
            ref = np.where(corpus["live1"][:nd_pad], ref[:nd_pad], 0.0)
            expect_i = np.argpartition(-ref, K)[:K]
            expect_i = expect_i[np.argsort(-ref[expect_i])]
            np.testing.assert_allclose(
                top_s[q], ref[expect_i], rtol=1e-3)
            recall = len(set(top_d[q].tolist())
                         & set(expect_i.tolist())) / K
            recall_min = min(recall_min, recall)
        cycle["i"] = 0
        p50_launch, spread = time_min3(run_batch)
        per_query = p50_launch / q_batch
        qps = q_batch * 1000.0 / p50_launch
        # HBM traffic per launch: the union posting windows (shared by
        # the whole batch) + live mask + per-query top-k outputs
        launch_bytes = (
            geom.n_tiles * t_pad_run * (2 * cb_run) * BLOCK * (4 + 4)
            + geom.n_tiles * geom.tile_w * 4
            + geom.n_tiles * q_batch * (2 * K + 1) * 4
        )
        entry = {
            "p50_ms_per_launch": round(p50_launch, 3),
            "p50_spread_ms": round(spread, 3),
            "p50_ms_per_query": round(per_query, 4),
            "qps_per_chip_batched": round(qps, 1),
            "union_t_pad": t_pad_run,
            "cb": cb_run,
            "bytes_per_query_mb_batched": round(
                launch_bytes / q_batch / 1e6, 2),
            "recall_at_10": recall_min,
        }
        if q_batch == 1:
            base_qps = qps
        elif base_qps:
            entry["qps_speedup_vs_q1"] = round(qps / base_qps, 2)
        out[f"q_batch_{q_batch}"] = entry
        log(f"batched_qps q={q_batch}: {p50_launch:.3f} ms/launch "
            f"({per_query:.3f} ms/query, {qps:.0f} qps, "
            f"t_pad={t_pad_run}, recall={recall_min})")
    return out


def run_knn_configs(jax, jnp, psc, corpus, dev, geom, frac, bmin, bmax,
                    term_sets):
    """ISSUE 7 acceptance configs — the dense-vector plane on the MXU:

    - ``knn_top10``: exhaustive exact kNN over a 1M x d=128 bf16
      embedding corpus (cosine), one ``knn_score_tiles`` MXU launch +
      fused per-tile top-10. Recall@10 gated against the exact f32
      numpy oracle over the same bf16-rounded vectors; min-of-3
      marginal estimator (r05 methodology). Headline:
      ``vector_top10_p50``.
    - ``hybrid_rrf``: BM25 top-10 (tile kernel) + kNN top-10 (MXU)
      fused by reciprocal-rank fusion — the latency is both device
      launches chained (marginal) plus the measured host fusion cost.
      Gated on the fused id list matching the oracle-side fusion.
      Headline: ``hybrid_qps_per_chip``.
    """
    import numpy as np

    import ml_dtypes

    from elasticsearch_tpu.ops import pallas_knn as pkn

    D = 128
    METRIC = "cosine"
    RRF_C = 60
    nd_pad = corpus["nd_pad"]
    rng = np.random.RandomState(23)

    t0 = time.perf_counter()
    # 1M x 128 embeddings, generated + bf16-rounded in chunks to bound
    # peak host memory (standard_normal materializes f64)
    vecs = np.empty((N_DOCS, D), np.float32)
    for lo in range(0, N_DOCS, 100_000):
        hi = min(lo + 100_000, N_DOCS)
        chunk = rng.standard_normal((hi - lo, D)).astype(np.float32)
        vecs[lo:hi] = chunk.astype(ml_dtypes.bfloat16).astype(np.float32)
    geom_k = pkn.knn_geometry(nd_pad, pkn.pad_dims(D))
    d_pad = pkn.pad_dims(D)
    emb_host = np.zeros((geom_k.nd_pad, d_pad), ml_dtypes.bfloat16)
    emb_host[:N_DOCS, :D] = vecs.astype(ml_dtypes.bfloat16)
    inv_norms = np.zeros(geom_k.nd_pad, np.float32)
    norms = np.sqrt(np.einsum("ij,ij->i", vecs, vecs))
    inv_norms[:N_DOCS] = np.where(norms > 0, 1.0 / norms, 0.0)
    scale_host = inv_norms.reshape(-1, 1)
    mask_host = np.zeros((geom_k.nd_pad, 1), np.float32)
    mask_host[:N_DOCS] = 1.0
    emb_d = jnp.asarray(emb_host)
    scale_d = jnp.asarray(scale_host)
    mask_d = jnp.asarray(mask_host)
    log(f"knn corpus staged in {time.perf_counter() - t0:.1f}s "
        f"({emb_host.nbytes / 1e6:.0f} MB bf16, tile_sub="
        f"{geom_k.tile_sub}, n_tiles={geom_k.n_tiles})")
    from elasticsearch_tpu.common import memory as dm

    acct = dm.memory_accountant()
    knn_ms = (time.perf_counter() - t0) * 1000.0
    acct.register("bench", "knn_corpus", dm.KIND_EMBEDDINGS, "emb",
                  int(emb_host.nbytes), duration_ms=knn_ms)
    acct.register("bench", "knn_corpus", dm.KIND_SCALE_NORM, "scale",
                  int(scale_host.nbytes), duration_ms=knn_ms)
    acct.register("bench", "knn_corpus", dm.KIND_LIVE_MASK, "mask",
                  int(mask_host.nbytes), duration_ms=knn_ms)

    # query mix: a random doc's embedding + gaussian noise — neighbors
    # exist (recall is meaningful) without being degenerate self-matches
    def draw_qvec():
        base = vecs[rng.randint(N_DOCS)]
        return (base + 0.25 * rng.standard_normal(D).astype(np.float32))

    n_queries = WARMUP + 24
    qvecs = [draw_qvec() for _ in range(n_queries)]
    staged_q = [jnp.asarray(pkn.normalize_query(q, METRIC, d_pad)
                            .reshape(1, d_pad)) for q in qvecs]

    @jax.jit
    def knn_query(qrow):
        ts, td = pkn.knn_score_tiles(
            emb_d, scale_d, mask_d, qrow,
            sub=geom_k.tile_sub, k=K, q_batch=1)
        return pkn.merge_knn_topk(ts, td, K)

    def oracle_knn(q):
        s = vecs @ pkn.normalize_query(q, METRIC, d_pad)[:D]
        s = s * inv_norms[:N_DOCS] * np.float32(0.5) + np.float32(0.5)
        idx = np.argpartition(-s, K)[:K]
        return idx[np.argsort(-s[idx], kind="stable")], s

    def time_min3(fn, arg_cycle):
        """min-of-3 marginal estimate after a sustained re-warm (the
        r05 estimator: marginal noise is one-sided)."""
        cycle = {"i": 0}

        def call(_q=None):
            a = arg_cycle[cycle["i"] % len(arg_cycle)]
            cycle["i"] += 1
            return fn(a)

        o = None
        for _ in range(200):
            o = call()
        np.asarray(o[0])
        ests = sorted(measure_marginal(call, [None]) for _ in range(3))
        return ests[0] * 1000, (ests[-1] - ests[0]) * 1000

    # ---- knn_top10 ----
    top_s, top_d = knn_query(staged_q[0])
    top_s, top_d = np.asarray(top_s)[0], np.asarray(top_d)[0]
    recall_min, err_max = 1.0, 0.0
    for i in range(8):
        got_s, got_d = (np.asarray(o) for o in knn_query(staged_q[i]))
        ref_i, ref_s = oracle_knn(qvecs[i])
        recall = len(set(got_d[0].tolist()) & set(ref_i.tolist())) / K
        recall_min = min(recall_min, recall)
        err_max = max(err_max, float(np.max(np.abs(
            np.sort(got_s[0]) - np.sort(ref_s[ref_i])))))
    p50k, spreadk = time_min3(knn_query, staged_q[WARMUP:])
    # HBM per query: the bf16 embedding stream + scale/mask columns +
    # tiny per-tile candidate outputs
    knn_bytes = (geom_k.nd_pad * d_pad * 2 + geom_k.nd_pad * 2 * 4
                 + geom_k.n_tiles * K * 2 * 4)
    knn_cfg = {
        "p50_ms": round(p50k, 3),
        "p50_spread_ms": round(spreadk, 3),
        "qps_per_chip": round(1000.0 / p50k, 1),
        "recall_at_10": recall_min,
        "max_abs_score_err": round(err_max, 8),
        "n_docs": N_DOCS,
        "dims": D,
        "metric": METRIC,
        "storage": "bf16",
        "tile_sub": geom_k.tile_sub,
        "bytes_per_query_mb": round(knn_bytes / 1e6, 2),
        "hbm_gb_per_s_estimate": round(
            knn_bytes / (p50k / 1000) / 1e9, 1),
        "note": ("exhaustive exact kNN on the MXU (no ANN graph): one "
                 "tiled [W, d] @ [d, Q] matmul per doc tile with fused "
                 "per-tile top-10; recall gated vs the exact f32 numpy "
                 "oracle over the same bf16-rounded vectors"),
    }
    log(f"knn_top10: {p50k:.3f} ms, recall={recall_min}")

    # ---- hybrid_rrf: BM25 launch + kNN launch + host RRF fusion ----
    qb_pad = 8
    t_pad_run = cb_run = None
    bm25_staged = []
    for ts_ in term_sets[:n_queries]:
        lanes = [psc.QueryLane(int(corpus["term_block_start"][t]),
                               int(corpus["n_blocks_per_term"][t]),
                               idf(int(corpus["term_df"][t])))
                 for t in ts_]
        rl, rh, w, cbr = psc.build_tile_tables(lanes, bmin, bmax, geom)
        t_pad_run = max(t_pad_run or 8, rl.shape[1])
        cb_run = max(cb_run or 8, cbr)
        bm25_staged.append((rl, rh, w))
    bm25_dev = []
    for rl, rh, w in bm25_staged:
        if rl.shape[1] < t_pad_run:
            pad = t_pad_run - rl.shape[1]
            rl = np.pad(rl, ((0, 0), (0, pad)))
            rh = np.pad(rh, ((0, 0), (0, pad)))
            w = np.pad(w, ((0, 0), (0, pad)))
        bm25_dev.append((jnp.asarray(rl), jnp.asarray(rh), jnp.asarray(w)))

    @jax.jit
    def hybrid_query(rl, rh, w, qrow):
        ts_, td_, th_ = psc.score_tiles(
            dev["docs"], dev["frac"], dev["live_t"], rl, rh, w,
            t_pad=t_pad_run, cb=cb_run, sub=geom.tile_sub, k=K)
        bs, bd, _ = psc.merge_tile_topk(ts_, td_, th_, K)
        kts, ktd = pkn.knn_score_tiles(
            emb_d, scale_d, mask_d, qrow,
            sub=geom_k.tile_sub, k=K, q_batch=1)
        ks_, kd_ = pkn.merge_knn_topk(kts, ktd, K)
        return bs, bd, ks_[0], kd_[0]

    def rrf_fuse(bm25_docs, knn_docs):
        scores = {}
        for r, d_ in enumerate(bm25_docs):
            if d_ >= 0:
                scores[int(d_)] = scores.get(int(d_), 0.0) \
                    + 1.0 / (RRF_C + r + 1)
        for r, d_ in enumerate(knn_docs):
            if d_ >= 0:
                scores[int(d_)] = scores.get(int(d_), 0.0) \
                    + 1.0 / (RRF_C + r + 1)
        return [d_ for d_, _s in sorted(scores.items(),
                                        key=lambda kv: (-kv[1], kv[0]))][:K]

    # gate: kernel-side fusion must equal oracle-side fusion
    hybrid_recall = 1.0
    for i in range(4):
        outs = hybrid_query(*bm25_dev[i], staged_q[i])
        _bs, bd, _ks, kd = (np.asarray(o) for o in outs)
        q0 = make_query_legacy(corpus, term_sets[i], qb_pad)
        _ref_s, ref_bm = numpy_reference_query(corpus, q0)
        ref_knn, _ = oracle_knn(qvecs[i])
        got = rrf_fuse(bd, kd)
        want = rrf_fuse(ref_bm, ref_knn)
        hybrid_recall = min(hybrid_recall,
                            len(set(got) & set(want)) / K)

    def hybrid_call(i):
        rl, rh, w = bm25_dev[i % len(bm25_dev)]
        return hybrid_query(rl, rh, w, staged_q[i % len(staged_q)])

    cyc = {"i": 0}

    def hybrid_fn(_arg):
        cyc["i"] += 1
        return hybrid_call(cyc["i"])

    p50h, spreadh = time_min3(hybrid_fn, [None])
    # host fusion cost (numpy over 2*K candidates) measured separately:
    # the marginal estimator must stay device-only (one D2H per batch)
    outs = [np.asarray(o) for o in hybrid_call(0)]
    t0 = time.perf_counter()
    for _ in range(200):
        rrf_fuse(outs[1], outs[3])
    fuse_ms = (time.perf_counter() - t0) / 200 * 1000
    p50_total = p50h + fuse_ms
    hybrid_cfg = {
        "p50_ms": round(p50_total, 3),
        "p50_spread_ms": round(spreadh, 3),
        "device_p50_ms": round(p50h, 3),
        "host_fusion_ms": round(fuse_ms, 4),
        "qps_per_chip": round(1000.0 / p50_total, 1),
        "fused_recall_at_10": hybrid_recall,
        "rank_constant": RRF_C,
        "window": K,
        "note": ("BM25 tile-kernel launch + kNN MXU launch chained on "
                 "device, RRF-fused host-side over 2*10 candidates; "
                 "gated on the fused id list matching oracle-side "
                 "fusion of the two exact reference rankings"),
    }
    log(f"hybrid_rrf: {p50_total:.3f} ms ({p50h:.3f} device + "
        f"{fuse_ms:.4f} fuse), fused_recall={hybrid_recall}")
    return knn_cfg, hybrid_cfg


def run_fault_soak_config():
    """ISSUE 10 config: serving capacity WITH chaos running.

    A packed multi-shard IndexService corpus answers a zipfian query
    stream twice — clean, then with the device fault-injection schemes
    active (transient staging faults absorbed by the bounded retry,
    kernel-launch faults driving quarantine + single-flight probes, an
    eviction storm forcing restages) — and reports:

    - ``availability_under_faults``: fraction of under-fault searches
      that returned a complete answer (no exception, no failed shards)
      — the zero-5xx invariant as a measured number;
    - ``qps_under_faults_per_chip`` vs the clean ``qps_per_chip``: what
      the retry/demotion/restage machinery costs in throughput;
    - ``ledger_leak_free`` / ``healed_plane``: after scheme removal +
      one healing query the per-kind device ledger returns exactly to
      its pre-fault snapshot and the fast plane serves again.
    """
    import numpy as np

    from elasticsearch_tpu.common.memory import memory_accountant
    from elasticsearch_tpu.common.settings import Settings
    from elasticsearch_tpu.index.index_service import IndexService
    from elasticsearch_tpu.testing.disruption import (
        EvictionStormScheme,
        KernelLaunchFailScheme,
        SearchDelayScheme,
        StagingFailScheme,
        clear_search_disruptions,
    )

    N_DOCS_SOAK = 6000
    N_QUERIES = 120
    rng = np.random.RandomState(10)
    vocab = [f"w{i}" for i in range(24)]
    idx = IndexService("bench_fault_soak", Settings({
        "index.number_of_shards": 4,
        "index.search.mesh": True,
        "index.search.mesh.plane": "pallas",
        "index.search.plane_quarantine.cooldown": "100ms",
        "index.refresh_interval": -1,
    }), mapping={"properties": {
        "body": {"type": "text", "analyzer": "whitespace"}}})
    try:
        for d in range(N_DOCS_SOAK):
            toks = [vocab[min(int(rng.zipf(1.4)) - 1, len(vocab) - 1)]
                    for _ in range(3 + int(rng.randint(6)))]
            idx.index_doc(str(d), {"body": " ".join(toks)})
        idx.refresh()

        def q():
            terms = " ".join(
                vocab[min(int(rng.zipf(1.4)) - 1, len(vocab) - 1)]
                for _ in range(1 + int(rng.randint(2))))
            return {"query": {"match": {"body": terms}}, "size": 10}

        queries = [q() for _ in range(N_QUERIES)]
        # warm both rungs + compiles off the clock
        idx.search(dict(queries[0]))
        idx._search_uncached(dict(queries[0]), skip_mesh=True)
        t0 = time.perf_counter()
        for body in queries:
            idx.search(dict(body))
        clean_s = time.perf_counter() - t0
        plane_clean = idx.search(dict(queries[0]))["_plane"]
        idx._search_uncached(dict(queries[0]), skip_mesh=True)
        snap = memory_accountant().staged_bytes_by_kind(
            "bench_fault_soak")
        schemes = [
            StagingFailScheme(kinds=["postings"], transient=True,
                              times=6, indices=["bench_fault_soak"]),
            KernelLaunchFailScheme(rungs=("mesh_pallas", "batched"),
                                   times=3,
                                   indices=["bench_fault_soak"]),
            EvictionStormScheme(period=10,
                                indices=["bench_fault_soak"]),
            SearchDelayScheme(0.0005, indices=["bench_fault_soak"]),
        ]
        for s in schemes:
            s.install()
        ok = 0
        t0 = time.perf_counter()
        try:
            for body in queries:
                try:
                    r = idx.search(dict(body))
                    if not r["_shards"]["failed"]:
                        ok += 1
                except Exception:  # noqa: BLE001 — availability metric
                    pass
        finally:
            fault_s = time.perf_counter() - t0
            hits = {type(s).__name__: s.hits for s in schemes}
            for s in schemes:
                s.remove()
        time.sleep(0.15)  # quarantine cooldown
        healed = idx.search(dict(queries[0]))
        idx._search_uncached(dict(queries[0]), skip_mesh=True)
        after = memory_accountant().staged_bytes_by_kind(
            "bench_fault_soak")
        mem = memory_accountant().stats("bench_fault_soak")
        return {
            "availability_under_faults": round(ok / N_QUERIES, 4),
            "qps_under_faults_per_chip": round(N_QUERIES / fault_s, 1),
            "qps_per_chip": round(N_QUERIES / clean_s, 1),
            "qps_retention": round(clean_s / fault_s, 3),
            "plane_clean": plane_clean,
            "healed_plane": healed["_plane"],
            "ledger_leak_free": after == snap,
            "scheme_hits": hits,
            "staging_retries_total": mem["staging_retries_total"],
            "staging_faults_transient_total":
                mem["staging_faults_transient_total"],
            "staging_faults_deterministic_total":
                mem["staging_faults_deterministic_total"],
            "n_docs": N_DOCS_SOAK,
            "n_queries": N_QUERIES,
            "note": ("zipfian search stream over a packed 4-shard "
                     "corpus with device fault injection running "
                     "(transient staging faults, kernel-launch faults, "
                     "eviction storm, 0.5ms shard delay) — the "
                     "ROADMAP item-5 aggregate-QPS target's fault leg"),
        }
    finally:
        clear_search_disruptions()
        idx.close()


def run_nrt_ingest_config():
    """ISSUE 20 config: ingest + search under sustained delta staging
    (docs/MESH.md "Slot allocator & generations").

    A packed 3-shard mesh corpus takes a sustained interleaved
    ingest/refresh/search stream — every refresh window is a pure
    append, so the delta staging path carries each one as a
    copy-on-write successor generation; between passes a synchronous
    compaction pass re-densifies the generation (the background
    single-flight pass, run on the clock's edge for determinism) —
    then a delete+refresh leg exercises the tombstone path. Reports:

    - ``ingest_docs_per_s``: docs through index_doc+refresh per second
      of ingest time (search time excluded);
    - ``search_p50_under_ingest_ms``: p50 search latency measured
      INSIDE the ingest windows — min of 3 per-pass medians (the
      fault_soak min-of-3 estimator convention: marginal noise is
      one-sided);
    - ``restage_amplification``: restaged/logically-changed bytes over
      the append windows only (compaction restages excluded — reported
      separately) — the ISSUE 20 headline, ~1 when every window rode
      the delta path, ~n_slots when each refresh rebuilt the full
      generation.
    """
    import numpy as np

    from elasticsearch_tpu.common.memory import memory_accountant
    from elasticsearch_tpu.common.settings import Settings
    from elasticsearch_tpu.index.index_service import IndexService

    NAME = "bench_nrt_ingest"
    N_BASE = 2400
    PASSES = 3               # min-of-3: one p50 estimate per pass
    DOCS_PER_WINDOW = 120    # one append window (refresh) per pass
    SEARCHES_PER_WINDOW = 12
    N_DELETES = 60
    rng = np.random.RandomState(20)
    vocab = [f"w{i}" for i in range(24)]
    idx = IndexService(NAME, Settings({
        "index.number_of_shards": 3,
        "index.search.mesh": True,
        "index.search.mesh.plane": "pallas",
        "index.search.mesh.max_slots_per_device": 16,
        "index.staging.delta.enabled": True,
        # deterministic windows: no background compaction mid-measure
        "index.staging.compact.threshold": 0.0,
        "index.refresh_interval": -1,
    }), mapping={"properties": {
        "body": {"type": "text", "analyzer": "whitespace"}}})

    def doc():
        toks = [vocab[min(int(rng.zipf(1.4)) - 1, len(vocab) - 1)]
                for _ in range(3 + int(rng.randint(6)))]
        return {"body": " ".join(toks)}

    def q():
        terms = " ".join(
            vocab[min(int(rng.zipf(1.4)) - 1, len(vocab) - 1)]
            for _ in range(1 + int(rng.randint(2))))
        return {"query": {"match": {"body": terms}}, "size": 10}

    try:
        for d in range(N_BASE):
            idx.index_doc(str(d), doc())
        idx.refresh()
        # warm both rungs + compiles off the clock
        idx.search(q())
        idx._search_uncached(q(), skip_mesh=True)
        acc = memory_accountant()
        next_id = N_BASE
        ingest_s = 0.0
        restaged = logical = compaction_bytes = 0
        pass_p50s = []
        for p in range(PASSES):
            s0 = acc.stats(NAME)
            lat = []
            t0 = time.perf_counter()
            for _ in range(DOCS_PER_WINDOW):
                idx.index_doc(str(next_id), doc())
                next_id += 1
            idx.refresh()
            ingest_s += time.perf_counter() - t0
            for _ in range(SEARCHES_PER_WINDOW):
                body = q()
                t0 = time.perf_counter()
                idx.search(body)
                lat.append((time.perf_counter() - t0) * 1000)
            pass_p50s.append(float(np.percentile(lat, 50)))
            s1 = acc.stats(NAME)
            restaged += (s1["restaged_bytes_total"]
                         - s0["restaged_bytes_total"])
            logical += (s1["bytes_logically_changed_total"]
                        - s0["bytes_logically_changed_total"])
            # between passes: the compaction pass re-densifies the
            # generation (fresh slot headroom) so the NEXT window's
            # append fits the free slots — run synchronously here, off
            # the ingest clock and outside the amp snapshots, standing
            # in for the background single-flight thread
            if p < PASSES - 1:
                c0 = acc.stats(NAME)["restaged_bytes_total"]
                idx.compact_now()
                idx.search(q())  # restage on the spot, not next window
                compaction_bytes += (acc.stats(NAME)
                                     ["restaged_bytes_total"] - c0)
        amp = round(restaged / logical, 3) if logical else None
        # delete leg: tombstones restage only live-mask bytes
        for d in range(N_DELETES):
            idx.delete_doc(str(d * 7))
        idx.refresh()
        idx.search(q())
        planes = idx.search_stats()["planes"]
        n_appended = PASSES * DOCS_PER_WINDOW
        return {
            "ingest_docs_per_s": round(n_appended / ingest_s, 1),
            "search_p50_under_ingest_ms": round(min(pass_p50s), 3),
            "search_p50_spread_ms": round(
                max(pass_p50s) - min(pass_p50s), 3),
            "restage_amplification": amp,
            "restaged_bytes_append_windows": restaged,
            "logical_bytes_append_windows": logical,
            "compaction_restaged_bytes": compaction_bytes,
            "delta_restage_total": planes["delta_restage_total"],
            "tombstone_update_total": planes["tombstone_update_total"],
            "compaction_runs_total": planes["compaction_runs_total"],
            "n_docs_base": N_BASE,
            "n_docs_appended": n_appended,
            "n_deletes": N_DELETES,
            "note": ("interleaved ingest/refresh/search over a packed "
                     "3-shard mesh corpus — every refresh window is a "
                     "pure append carried by the delta staging path "
                     "(restage_amplification ~1 when no window fell "
                     "back to a full generation rebuild), a synchronous "
                     "compaction pass re-densifies between windows "
                     "(bytes reported separately), then a "
                     "delete+refresh leg drives the tombstone path; "
                     "p50 is the min of 3 per-pass medians per the "
                     "fault_soak estimator convention"),
        }
    finally:
        idx.close()


def run_cold_start_config():
    """ISSUE 14 config: what does a restart cost the first query, and
    what does the rollout plane save (docs/RESILIENCE.md "Rollout &
    drain")?

    Three headline numbers, all measured on this backend (a future TPU
    run quantifies the real 2–27 s stall elimination):

    - ``first_query_cold_ms``: restart with NO persistent cache and NO
      warming — compiled-program caches cleared, the first query pays
      trace + XLA compile on its own path;
    - ``first_query_warmed_ms``: restart WITH the persistent
      compilation cache + variant-registry warming — programs warm in
      the background off the clock, the first query pays only its
      serving latency (``query_path_first_compiles`` proves it paid no
      compile);
    - ``drain_p99_ms``: p99 time for a drain to quiesce the index
      under concurrent in-flight searches (begin_drain →
      await_drained over repeated cycles).
    """
    import shutil
    import tempfile
    import threading

    import numpy as np

    from elasticsearch_tpu.common import compile_cache as cc
    from elasticsearch_tpu.common.settings import Settings
    from elasticsearch_tpu.index.index_service import IndexService
    from elasticsearch_tpu.parallel.plan_exec import (
        clear_compiled_programs,
    )
    from elasticsearch_tpu.testing.disruption import SearchDelayScheme

    root = tempfile.mkdtemp(prefix="estpu-coldstart-")
    N_DOCS = 4000
    rng = np.random.RandomState(14)
    vocab = [f"w{i}" for i in range(24)]
    settings = Settings({
        "index.number_of_shards": 4,
        "index.search.mesh": True,
        "index.search.mesh.plane": "pallas",
        "index.refresh_interval": -1,
    })
    mapping = {"properties": {
        "body": {"type": "text", "analyzer": "whitespace"}}}
    data_path = os.path.join(root, "index")

    def mk():
        return IndexService("bench_cold_start", settings,
                            mapping=mapping, data_path=data_path)

    probe = {"query": {"match": {"body": "w0 w1"}}, "size": 10}

    def timed_query(svc):
        t0 = time.perf_counter()
        svc.search(dict(probe))
        return (time.perf_counter() - t0) * 1000.0

    prev_registry = cc.variant_registry()
    try:
        cc.configure_compile_cache(None)
        registry_path = os.path.join(root, "variants.json")
        cc.set_variant_registry(cc.VariantRegistry(registry_path))
        svc = mk()
        for d in range(N_DOCS):
            toks = [vocab[min(int(rng.zipf(1.4)) - 1, len(vocab) - 1)]
                    for _ in range(3 + int(rng.randint(5)))]
            svc.index_doc(str(d), {"body": " ".join(toks)})
        svc.refresh()
        svc.flush()

        # ---- cold restart: no cache, no warming ----
        clear_compiled_programs()
        first_query_cold_ms = timed_query(svc)

        # ---- populate the persistent cache (the "previous process") --
        cache_dir = os.path.join(root, "jax_cache")
        cache_on = cc.configure_compile_cache(cache_dir)
        clear_compiled_programs()
        svc.search(dict(probe))  # compiles + serializes to disk
        svc.close()

        # ---- warmed restart: cache + registry + background warming --
        clear_compiled_programs()
        cc.set_variant_registry(cc.VariantRegistry(registry_path))
        svc = mk()
        t_warm0 = time.perf_counter()
        warmed = svc.warm_compile_variants()
        warm_ms = (time.perf_counter() - t_warm0) * 1000.0
        qp0 = cc.compile_stats().stats()[
            "query_path_first_compile_total"]
        first_query_warmed_ms = timed_query(svc)
        query_path_first_compiles = (
            cc.compile_stats().stats()["query_path_first_compile_total"]
            - qp0)

        # ---- drain p99 under concurrent in-flight searches ----
        adm = svc.admission
        delay = SearchDelayScheme(0.004,
                                  indices=["bench_cold_start"]).install()
        drain_ms = []
        try:
            for _ in range(20):
                stop = threading.Barrier(3)

                def inflight():
                    stop.wait(timeout=5)
                    try:
                        svc.search(dict(probe))
                    except Exception:  # noqa: BLE001 — drain may refuse
                        pass

                threads = [threading.Thread(target=inflight)
                           for _ in range(2)]
                for t in threads:
                    t.start()
                stop.wait(timeout=5)
                time.sleep(0.002)  # searches admitted + executing
                t0 = time.perf_counter()
                adm.begin_drain()
                drained = adm.await_drained(10.0)
                drain_ms.append(time.perf_counter() - t0)  # seconds;
                # pctl() scales to ms
                adm.end_drain()
                for t in threads:
                    t.join()
                if not drained:
                    break
        finally:
            delay.remove()
        svc.close()
        return {
            "n_docs": N_DOCS,
            "cache_enabled": bool(cache_on),
            "variants_recorded": len(cc.variant_registry().programs),
            "warm_specs_replayed": warmed,
            "warm_background_ms": round(warm_ms, 3),
            # headline keys (BENCH_rNN)
            "first_query_cold_ms": round(first_query_cold_ms, 3),
            "first_query_warmed_ms": round(first_query_warmed_ms, 3),
            "cold_start_stall_saved_ms": round(
                first_query_cold_ms - first_query_warmed_ms, 3),
            "query_path_first_compiles": query_path_first_compiles,
            "drain_p99_ms": round(pctl(drain_ms, 99), 3) if drain_ms
            else None,
            "drain_p50_ms": round(pctl(drain_ms, 50), 3) if drain_ms
            else None,
            "drain_cycles": len(drain_ms),
        }
    finally:
        cc.configure_compile_cache(None)
        cc.set_variant_registry(prev_registry)
        shutil.rmtree(root, ignore_errors=True)


def run_overload_zipfian_config():
    """ISSUE 12 config: goodput + fairness at offered load ≫ capacity.

    A packed multi-shard IndexService with a TIGHT admission shape
    (2 concurrency slots, queue 8 — docs/OVERLOAD.md) answers a burst
    from 16 client threads whose tenants are zipfian-assigned, so one
    hot tenant dominates the offered load. Reports:

    - ``saturated_capacity_qps``: completed/sec with exactly
      max_concurrent clients (no rejects) — best of 3 runs, the
      fault_soak min-of-3 estimator convention;
    - ``goodput_qps_under_overload``: admitted completions/sec while
      offered load exceeds capacity (``offered_capacity_ratio``);
      the acceptance bar is goodput within 10% of saturated capacity;
    - ``admitted_p99_ms``: p99 latency of ADMITTED queries under
      overload (bounded queueing — the queue depth caps the wait);
    - ``reject_rate``: rejected/offered — every one a clean 429 with
      Retry-After (``zero_5xx`` asserts nothing else escaped);
    - ``max_tenant_starvation_ratio``: max over active tenants of
      (demand-capped fair share) / (achieved admission share) — 1.0 is
      perfectly fair, and the no-starvation bar is <= 2 (every tenant
      gets at least half its fair share).
    """
    import threading

    import numpy as np

    from elasticsearch_tpu.common.errors import (
        EsRejectedExecutionException,
    )
    from elasticsearch_tpu.common.settings import Settings
    from elasticsearch_tpu.index.index_service import IndexService

    N_DOCS_OV = 4000
    N_THREADS = 16
    N_PER_THREAD = 30
    N_TENANTS = 8
    rng = np.random.RandomState(12)
    vocab = [f"w{i}" for i in range(24)]
    idx = IndexService("bench_overload", Settings({
        "index.number_of_shards": 4,
        "index.search.mesh": True,
        "index.search.mesh.plane": "pallas",
        "index.refresh_interval": -1,
        "search.admission.max_concurrent": 2,
        "search.queue.size": 8,
        # brownout step 1 (forced pruning) is excluded from this
        # config's measurement: on the interpret/CPU smoke backend the
        # pruned kernel is SLOWER than exhaustive (inverting the trade
        # it exists for), which would corrupt the goodput number. The
        # hardware tuning pass (ROADMAP item 1) re-enables it by
        # dropping this threshold; steps 2-4 still measure.
        "search.admission.brownout.pruned_threshold": 10.0,
        # adaptive-window widening is capped at the base window here:
        # with max_concurrent=2 a wider collection window cannot form a
        # bigger batch (batch size <= in-flight), so widening would be
        # pure added latency in THIS shape; wide-slot hardware configs
        # measure the real trade (docs/OVERLOAD.md)
        "search.batch.max_window_ms": 0.2,
    }), mapping={"properties": {
        "body": {"type": "text", "analyzer": "whitespace"}}})
    try:
        from elasticsearch_tpu.search.telemetry import set_opaque_id

        for d in range(N_DOCS_OV):
            toks = [vocab[min(int(rng.zipf(1.4)) - 1, len(vocab) - 1)]
                    for _ in range(3 + int(rng.randint(6)))]
            idx.index_doc(str(d), {"body": " ".join(toks)})
        idx.refresh()

        def q():
            terms = " ".join(
                vocab[min(int(rng.zipf(1.4)) - 1, len(vocab) - 1)]
                for _ in range(1 + int(rng.randint(2))))
            return {"query": {"match": {"body": terms}}, "size": 10}

        idx.search(dict(q()))  # warm compiles off the clock
        idx._search_uncached(dict(q()), skip_mesh=True)
        clean_queries = [q() for _ in range(40)]
        for body in clean_queries:
            idx.search(dict(body))  # warm every shape variant
        clean_lat = []  # seconds (pctl scales to ms)
        for body in clean_queries:
            t0 = time.perf_counter()
            idx.search(dict(body))
            clean_lat.append(time.perf_counter() - t0)

        # --- overload burst: zipfian tenants, offered >> capacity.
        # Clients honor Retry-After (capped for bench speed) and retry
        # a bounded number of times — a rejected closed-loop client
        # that never backs off would just exhaust its workload in the
        # first milliseconds of queue-full and read as "starved".
        tenant_of = [f"tenant{min(int(rng.zipf(1.3)) - 1, N_TENANTS - 1)}"
                     for _ in range(N_THREADS)]
        thread_queries = [[q() for _ in range(N_PER_THREAD)]
                          for _ in range(N_THREADS)]
        lock = threading.Lock()

        def client(tid, start, stats):
            tenant = tenant_of[tid]
            set_opaque_id(tenant)
            start.wait()
            counts, per_tenant, admitted_lat = stats
            for body in thread_queries[tid]:
                # clients honor Retry-After (capped for bench speed),
                # bounded retries: a rejected closed-loop client that
                # never backs off would exhaust its workload in the
                # first milliseconds of queue-full and read "starved"
                for _attempt in range(5):
                    if counts is not None:
                        with lock:
                            counts["offered"] += 1
                            t_bucket = per_tenant.setdefault(
                                tenant, {"offered": 0, "admitted": 0,
                                         "rejected": 0})
                            t_bucket["offered"] += 1
                    t0 = time.perf_counter()
                    try:
                        r = idx.search(dict(body))
                        lat = time.perf_counter() - t0  # seconds
                        if counts is not None:
                            with lock:
                                counts["admitted"] += 1
                                t_bucket["admitted"] += 1
                                admitted_lat.append(lat)
                                if r["_shards"]["failed"]:
                                    counts["errors"] += 1
                        break
                    except EsRejectedExecutionException as e:
                        if counts is not None:
                            with lock:
                                counts["rejected"] += 1
                                t_bucket["rejected"] += 1
                        time.sleep(min(getattr(e, "retry_after_s", 1.0),
                                       0.02))
                    except Exception:  # noqa: BLE001 — zero-5xx metric
                        if counts is not None:
                            with lock:
                                counts["errors"] += 1
                        break

        def run_burst(stats=(None, None, None)):
            start = threading.Barrier(N_THREADS + 1)
            threads = [threading.Thread(target=client,
                                        args=(t, start, stats))
                       for t in range(N_THREADS)]
            for t in threads:
                t.start()
            start.wait()
            t0 = time.perf_counter()
            for t in threads:
                t.join()
            return time.perf_counter() - t0

        # unmeasured pre-burst: compiles every batched-launch variant
        # the measured mix will hit (first-compile stalls are a
        # COLD-START cost — 2-27s in this image, ROADMAP item 4's
        # compilation cache — not steady-state overload behavior)
        run_burst()
        # saturated capacity: the SAME client load with the queue bound
        # lifted (explicit override, then cleared) so nothing rejects —
        # isolates what overflow handling costs vs pure queueing under
        # identical thread pressure; best of 3 (min-of-3 convention)
        idx.admission.set_cluster_overrides(
            Settings({"search.queue.size": 1_000_000}))
        capacity = 0.0
        for _ in range(3):
            sat = ({"offered": 0, "admitted": 0, "rejected": 0,
                    "errors": 0}, {}, [])
            sat_wall = run_burst(sat)
            capacity = max(capacity, sat[0]["admitted"] / sat_wall)
        idx.admission.set_cluster_overrides(Settings({}))
        # measured overload burst against the tight queue
        counts = {"offered": 0, "admitted": 0, "rejected": 0,
                  "errors": 0}
        per_tenant = {}
        admitted_lat = []
        wall = run_burst((counts, per_tenant, admitted_lat))
        set_opaque_id(None)

        goodput = counts["admitted"] / wall
        # closed-loop clients: each thread always has one request
        # outstanding, so the offered CONCURRENCY (threads vs slots) is
        # the honest overload ratio — completed-rate ratios would be
        # throttled by admission itself
        offered_ratio = N_THREADS / 2.0
        # demand-capped fairness: a tenant that offered less than its
        # fair share cannot be "starved" below what it asked for
        active = [t for t, b in per_tenant.items() if b["offered"]]
        starvation = 1.0
        if counts["admitted"] and active:
            fair = 1.0 / len(active)
            for t in active:
                b = per_tenant[t]
                entitled = min(fair, b["offered"] / counts["offered"])
                share = b["admitted"] / counts["admitted"]
                ratio = (entitled / share) if share > 0 else 99.0
                starvation = max(starvation, ratio)
        adm = idx.admission.stats_dict()
        return {
            "saturated_capacity_qps": round(capacity, 1),
            "goodput_qps_under_overload": round(goodput, 1),
            "goodput_retention": round(goodput / capacity, 3),
            "offered_capacity_ratio": round(offered_ratio, 2),
            "admitted_p99_ms": round(pctl(admitted_lat, 99), 3),
            "admitted_p50_ms": round(pctl(admitted_lat, 50), 3),
            "clean_p99_ms": round(pctl(clean_lat, 99), 3),
            "reject_rate": round(counts["rejected"]
                                 / max(counts["offered"], 1), 4),
            "max_tenant_starvation_ratio": round(starvation, 3),
            "zero_5xx": counts["errors"] == 0,
            "offered": counts["offered"],
            "admitted": counts["admitted"],
            "rejected": counts["rejected"],
            "active_tenants": len(active),
            "retry_after_s": adm["retry_after_s"],
            "brownout": adm["brownout"],
            "n_docs": N_DOCS_OV,
            "note": ("16 zipfian-tenant client threads against a "
                     "2-slot/8-deep admission shape on a packed 4-shard "
                     "corpus — the ROADMAP item-5 overload invariant: "
                     "goodput near saturated capacity, bounded admitted "
                     "p99, no tenant below half its fair share, every "
                     "non-admitted query a clean 429 (docs/OVERLOAD.md)"),
        }
    finally:
        idx.close()


def run_codec_pruning_configs(jax, jnp, psc, corpus, dev, geom, frac,
                              bmin, bmax, cb_run, term_sets):
    """ISSUE 6 configs on the 1M corpus, same query mix as the headline:

    - ``packed_postings``: the bit-packed postings codec — one i32 word
      per posting, decoded in-kernel — exhaustive scoring. Halves the
      posting-window HBM bytes the kernel is bandwidth-bound on.
    - ``pruned_scoring``: block-max pruned top-k over the packed corpus
      (probe pass seeds the threshold, rest tiles skip when their summed
      block-max bound cannot beat it; the threshold never leaves the
      device — no per-query D2H sync).

    Both recall-gate EVERY measured aspect against the RAW numpy oracle
    (quantization is lossy by ~2.7e-4 absolute; the gate is what decides
    whether the codec/pruning mode may claim the headline)."""
    import numpy as np

    out_packed, out_pruned = {}, {}
    nd_pad = corpus["nd_pad"]
    n_gate = 8  # queries recall-gated per config

    def lanes_for(terms):
        return [psc.QueryLane(int(corpus["term_block_start"][t]),
                              int(corpus["n_blocks_per_term"][t]),
                              idf(int(corpus["term_df"][t])))
                for t in terms]

    def time_min3(fn):
        for _ in range(2):
            fn()
        o = None
        for _ in range(200):
            o = fn()
        np.asarray(o[0])
        ests = sorted(measure_marginal(lambda _q: fn(), [None])
                      for _ in range(3))
        return ests[0] * 1000, (ests[-1] - ests[0]) * 1000

    def recall_gate(top_s, top_d, terms):
        """Measured (recall@10, max score error) vs the RAW oracle.

        Never raises: the gate's job is to MEASURE — a failed gate
        demotes the config from headline contention (recall < 1.0),
        it must not crash the config into an error dict. The score
        tolerance carries an ABSOLUTE term: quantization error is
        absolute (~(k1+1)/2^13), so a relative-only check would flag
        legitimately low-scoring queries."""
        qb_pad = 1
        nb = sum(int(corpus["n_blocks_per_term"][t]) for t in terms)
        while qb_pad < nb:
            qb_pad *= 2
        ref_s, ref_i = numpy_reference_query(
            corpus, make_query_legacy(corpus, terms, qb_pad))
        got_s = np.asarray(top_s).reshape(-1)
        got_d = np.asarray(top_d).reshape(-1)
        err = float(np.abs(got_s - ref_s).max())
        tol = 2e-3 * float(np.abs(ref_s).max()) + 4 * psc.PACK_FRAC_SCALE
        recall = len(set(got_d.tolist()) & set(ref_i.tolist())) / K
        if err > tol:
            recall = min(recall, 0.0)  # scores off the rails: fail gate
        return recall, err

    # ---- staging: the packed corpus (one word per posting) ----
    t0 = time.perf_counter()
    pk = psc.pack_segment_blocks(corpus["block_docs"], frac, nd_pad)
    dev_pk = jnp.asarray(pk)
    dev_pk.block_until_ready()
    stage_s = time.perf_counter() - t0
    raw_bytes = int(dev["docs"].size * 4 + dev["frac"].size * 4)
    packed_bytes = int(pk.nbytes)
    log(f"packed staging: {packed_bytes / 1e6:.0f} MB (raw "
        f"{raw_bytes / 1e6:.0f} MB) in {stage_s:.1f}s")
    # the packed layout re-stages the SAME logical corpus — a
    # geometry_change restage in the device-memory ledger, so the
    # report's restage_amplification reflects a real restage cycle
    from elasticsearch_tpu.common import memory as dm

    dm.memory_accountant().register(
        "bench", "corpus", dm.KIND_POSTINGS_PACKED, "k_packed",
        packed_bytes, reason="geometry_change",
        duration_ms=stage_s * 1000.0)

    timed_terms = term_sets[WARMUP:]
    tables = []
    for ts in timed_terms:
        rl, rh, w, _ = psc.build_tile_tables(
            lanes_for(ts), bmin, bmax, geom, t_pad=4, cb=cb_run)
        tables.append((rl, rh, w))
    staged_kq = [(jnp.asarray(rl), jnp.asarray(rh), jnp.asarray(w))
                 for rl, rh, w in tables]

    # ---- config: packed_postings (exhaustive, packed codec) ----
    try:
        @jax.jit
        def _packed_fused(pkc, live_t, rl, rh, w):
            ts_, td_, th_ = psc.score_tiles(
                pkc, None, live_t, rl, rh, w,
                t_pad=4, cb=cb_run, sub=geom.tile_sub, k=K,
                codec="packed")
            return psc.merge_tile_topk(ts_, td_, th_, K)

        cycle = {"i": 0}

        def run_packed():
            q = staged_kq[cycle["i"] % len(staged_kq)]
            cycle["i"] += 1
            return _packed_fused(dev_pk, dev["live_t"], *q)

        recall_min, err_max = 1.0, 0.0
        for i in range(n_gate):
            top_s, top_d, _h = _packed_fused(dev_pk, dev["live_t"],
                                             *staged_kq[i])
            recall, err = recall_gate(top_s, top_d, timed_terms[i])
            recall_min = min(recall_min, recall)
            err_max = max(err_max, err)
        cycle["i"] = 0
        p50p, spreadp = time_min3(run_packed)
        # posting windows stream as ONE word (4 B) instead of 8 B
        bytes_packed = (
            geom.n_tiles * 4 * (2 * cb_run) * BLOCK * 4
            + geom.n_tiles * geom.tile_w * 4
            + geom.n_tiles * (2 * K + 1) * 4)
        out_packed = {
            "p50_ms": round(p50p, 3),
            "p50_spread_ms": round(spreadp, 3),
            "recall_at_10": recall_min,
            "max_score_abs_err_vs_raw": round(err_max, 6),
            "bytes_per_query_mb_packed": round(bytes_packed / 1e6, 2),
            "postings_bytes_staged_mb": round(packed_bytes / 1e6, 1),
            "postings_bytes_staged_raw_mb": round(raw_bytes / 1e6, 1),
            "stage_seconds": round(stage_s, 2),
            "note": ("bit-packed postings decoded in-kernel: half the "
                     "posting-window HBM bytes and half the staged "
                     "posting bytes; recall measured vs the RAW oracle "
                     "(frac quantized to 12 bits over (0, k1+1))"),
        }
        log(f"packed_postings: {p50p:.3f} ms, recall={recall_min}")
    except Exception as e:  # noqa: BLE001
        import traceback

        traceback.print_exc(file=sys.stderr)
        out_packed = {"error": f"{type(e).__name__}: {e}"}

    # ---- config: pruned_scoring (block-max pruning over packed) ----
    try:
        probe = 8
        bfmax = psc.block_frac_max(
            psc.dequantize_frac(psc.quantize_frac(frac)))
        plans = []
        for (rl, rh, w) in tables:
            plan = psc.plan_pruned_tiles(rl, rh, w, bfmax,
                                         probe_tiles=probe)
            assert plan is not None, "corpus too small to prune"
            plans.append(plan)
        staged_pr = [
            tuple(jnp.asarray(x) for x in (
                p["rl_probe"], p["rh_probe"], p["tid_probe"],
                p["rl_rest"], p["rh_rest"], p["tid_rest"],
                p["bounds_rest"], t[2]))
            for p, t in zip(plans, tables)]

        def run_pruned_q(q):
            (rlp, rhp, tidp, rlr, rhr, tidr, br, w) = q
            return psc.score_tiles_pruned(
                dev_pk, None, dev["live_t"], rlp, rhp, tidp,
                rlr, rhr, tidr, br, w,
                t_pad=4, cb=cb_run, sub=geom.tile_sub, k=K,
                codec="packed")

        cycle = {"i": 0}

        def run_pruned():
            q = staged_pr[cycle["i"] % len(staged_pr)]
            cycle["i"] += 1
            return run_pruned_q(q)

        recall_min, err_max = 1.0, 0.0
        scored_total = 0
        tiles_total = 0
        for i in range(n_gate):
            top_s, top_d, _h, scored = run_pruned_q(staged_pr[i])
            recall, err = recall_gate(top_s, top_d, timed_terms[i])
            recall_min = min(recall_min, recall)
            err_max = max(err_max, err)
            scored_total += int(scored)
            tiles_total += geom.n_tiles
        pruned_fraction = 1.0 - scored_total / max(tiles_total, 1)
        cycle["i"] = 0
        p50r, spreadr = time_min3(run_pruned)
        scored_avg = scored_total / n_gate
        # only SCORED tiles stream their posting windows + live slabs
        bytes_pruned = scored_avg * (
            4 * (2 * cb_run) * BLOCK * 4 + geom.tile_w * 4) \
            + geom.n_tiles * (2 * K + 1) * 4 * 2
        out_pruned = {
            "p50_ms": round(p50r, 3),
            "p50_spread_ms": round(spreadr, 3),
            "recall_at_10": recall_min,
            "max_score_abs_err_vs_raw": round(err_max, 6),
            "probe_tiles": probe,
            "tiles_scored_avg": round(scored_avg, 1),
            "tiles_total": geom.n_tiles,
            "tiles_pruned_fraction": round(pruned_fraction, 3),
            "tiles_pruned_total": tiles_total - scored_total,
            "bytes_per_query_mb_pruned": round(bytes_pruned / 1e6, 2),
            "note": ("block-max pruned top-k over the packed corpus: "
                     "the probe pass scores the 8 highest-bound tiles, "
                     "the rest run only if their bound beats the "
                     "running k-th score (threshold computed on-device "
                     "— no per-query host sync); under pruning hit "
                     "totals are a lower bound (WAND semantics)"),
        }
        log(f"pruned_scoring: {p50r:.3f} ms, recall={recall_min}, "
            f"scored {scored_avg:.1f}/{geom.n_tiles} tiles")
    except Exception as e:  # noqa: BLE001
        import traceback

        traceback.print_exc(file=sys.stderr)
        out_pruned = {"error": f"{type(e).__name__}: {e}"}

    return out_packed, out_pruned


def run_mesh_pallas_config(jax, jnp, lax, psc, corpus, term_sets,
                           n_shards=4):
    """The packed mesh plane on this chip: the 1M corpus split into
    n_shards doc-range shards, every shard scored BY THE TILE KERNEL
    inside ONE shard_map program with all shards packed as slots on the
    single device, candidates merged in-program — the mesh data plane of
    parallel/plan_exec.py in bench form (same slot unroll, same per-slot
    kernel invocation, same all_gather+top_k merge). This is the path a
    multi-chip pod runs per device; acceptance: p50 within 2x of the
    single-chip pallas p50 with recall@10 = 1.0 (it replaces the 6.9 ms
    scatter formulation distributed queries were pinned to)."""
    from jax.sharding import Mesh, PartitionSpec as PS

    from elasticsearch_tpu.parallel.compat import shard_map

    term_ids, docs, tfs = corpus["flat"]
    doc_len = corpus["doc_len"]
    shard_size = N_DOCS // n_shards
    nd_pad_s = 1
    while nd_pad_s < shard_size:
        nd_pad_s *= 2
    geom = psc.tile_geometry(nd_pad_s)
    sub, n_tiles = geom.tile_sub, geom.n_tiles
    shards = []
    max_rows = 0
    t0 = time.perf_counter()
    for s in range(n_shards):
        lo = s * shard_size
        hi = (s + 1) * shard_size if s < n_shards - 1 else N_DOCS
        m = (docs >= lo) & (docs < hi)
        bd, bt, tbs, nbt, _df = pack_postings(
            term_ids[m], docs[m] - lo, tfs[m], VOCAB, nd_pad_s)
        norms_s = np.ones(nd_pad_s + 1, np.float32)
        norms_s[: hi - lo] = doc_len[lo:hi].astype(np.float32)
        # per-posting norm factors with the CORPUS avgdl: scores must
        # equal the single-index kernel's exactly for the recall gate
        frac = psc.compute_block_frac(bd, bt, norms_s, corpus["avgdl"])
        bmin, bmax = psc.block_min_max(bd, bt, nd_pad_s)
        dp, fp = psc.pad_segment_blocks(bd, frac, nd_pad_s)
        live = np.zeros(nd_pad_s, np.float32)
        live[: hi - lo] = 1.0
        shards.append({"dp": dp, "fp": fp, "tbs": tbs, "nbt": nbt,
                       "bmin": bmin, "bmax": bmax,
                       "live_t": psc.build_live_t(live, geom),
                       "live1": live.astype(bool), "lo": lo})
        max_rows = max(max_rows, dp.shape[0])
    k_docs = np.full((n_shards, max_rows, BLOCK), nd_pad_s, np.int32)
    k_frac = np.zeros((n_shards, max_rows, BLOCK), np.float32)
    for i, sh in enumerate(shards):
        k_docs[i, : sh["dp"].shape[0]] = sh["dp"]
        k_frac[i, : sh["fp"].shape[0]] = sh["fp"]
    live_t = np.stack([sh["live_t"] for sh in shards])
    live1 = np.stack([sh["live1"] for sh in shards])
    log(f"mesh config: {n_shards} shards packed "
        f"(nd_pad_s={nd_pad_s}, n_tiles={n_tiles}) built in "
        f"{time.perf_counter() - t0:.1f}s")

    def shard_tables(terms, cb=None):
        per = []
        need_cb = 8
        for sh in shards:
            lanes = [psc.QueryLane(int(sh["tbs"][t]), int(sh["nbt"][t]),
                                   idf(int(corpus["term_df"][t])))
                     for t in terms]
            rl, rh, w, cbr = psc.build_tile_tables(
                lanes, sh["bmin"], sh["bmax"], geom, t_pad=4, cb=cb)
            per.append((rl, rh, w))
            need_cb = max(need_cb, cbr)
        return (np.stack([p[0] for p in per]),
                np.stack([p[1] for p in per]),
                np.stack([p[2] for p in per]), need_cb)

    queries = [shard_tables(ts) for ts in term_sets]
    cb_run = max(q[3] for q in queries)
    staged_q = [(jnp.asarray(rl), jnp.asarray(rh), jnp.asarray(w))
                for rl, rh, w, _ in queries]

    mesh = Mesh(np.asarray(jax.devices()[:1]), ("shards",))
    spd = n_shards

    def per_device(kd, kf, lt, lv, rl, rh, w):
        cand_s, cand_d = [], []
        for i in range(spd):
            ds = psc.score_tiles(
                kd[i], kf[i], lt[i], rl[i], rh[i], w[i],
                t_pad=4, cb=cb_run, sub=sub, dense=True)[0]
            scores = psc.dense_to_flat(ds, sub)
            masked = jnp.where((scores > 0) & lv[i], scores, -jnp.inf)
            s_i, d_i = lax.top_k(masked, K)
            cand_s.append(s_i)
            cand_d.append(d_i + jnp.int32(i * shard_size))
        all_s = lax.all_gather(jnp.concatenate(cand_s), "shards").reshape(-1)
        all_d = lax.all_gather(jnp.concatenate(cand_d), "shards").reshape(-1)
        top_s, ti = lax.top_k(all_s, K)
        return top_s[None], all_d[ti][None]

    mapped = shard_map(per_device, mesh=mesh,
                       in_specs=(PS("shards"),) * 7,
                       out_specs=(PS("shards"),) * 2, check_vma=False)

    @jax.jit
    def run_prog(kd, kf, lt, lv, rl, rh, w):
        o = mapped(kd, kf, lt, lv, rl, rh, w)
        return o[0][0], o[1][0]

    sharding = jax.sharding.NamedSharding(mesh, PS("shards"))
    dev_kd = jax.device_put(k_docs, sharding)
    dev_kf = jax.device_put(k_frac, sharding)
    dev_lt = jax.device_put(live_t, sharding)
    dev_lv = jax.device_put(live1, sharding)
    for v in (dev_kd, dev_kf, dev_lt, dev_lv):
        v.block_until_ready()

    def run_mesh(q):
        return run_prog(dev_kd, dev_kf, dev_lt, dev_lv, *q)

    t0 = time.perf_counter()
    top_s, top_d = run_mesh(staged_q[0])
    np.asarray(top_s)
    log(f"mesh program first compile+run in {time.perf_counter() - t0:.1f}s "
        f"(cb={cb_run})")
    # re-warm + marginal timing (same estimator as the main path)
    wout = None
    for i in range(400):
        wout = run_mesh(staged_q[i % len(staged_q)])
    np.asarray(wout[0])
    timed = staged_q[WARMUP:]
    ests = sorted(measure_marginal(run_mesh, timed) for _ in range(3))
    # recall gate vs the full-corpus numpy oracle (shard-local doc ids
    # were offset back to global in-program)
    qb_pad = 1
    nb = sum(int(corpus["n_blocks_per_term"][t]) for t in term_sets[0])
    while qb_pad < nb:
        qb_pad *= 2
    ref_s, ref_i = numpy_reference_query(
        corpus, make_query_legacy(corpus, term_sets[0], qb_pad))
    got_s, got_d = (np.asarray(x) for x in run_mesh(staged_q[0]))
    np.testing.assert_allclose(got_s, ref_s, rtol=1e-3)
    recall = len(set(got_d.tolist()) & set(ref_i.tolist())) / K
    return {
        "p50_ms": round(ests[0] * 1000, 3),
        "p50_spread_ms": round((ests[-1] - ests[0]) * 1000, 3),
        "recall_at_10": recall,
        "n_shards": n_shards,
        "devices": 1,
        "slots_per_device": spd,
        "note": ("the mesh data plane scoring with the tile kernel: "
                 "n_shards segments packed as slots on this one chip, "
                 "scored per slot by score_tiles inside shard_map and "
                 "merged in-program — distributed queries no longer pay "
                 "the scatter formulation"),
    }


# ----------------------------------------------------------------------
# Parent process driver (never imports jax)
# ----------------------------------------------------------------------


def run_agg_fused_config(jax, jnp, lax, psc, corpus, dev, geom, bmin,
                         bmax, cb_run, use_kernel):
    """ISSUE 13 acceptance config (docs/AGGS.md): fused on-device
    aggregations — terms(10 buckets over the zipfian 2000-value keyword
    column) + date_histogram (hourly week rolled to 7 day buckets) over
    the 1M corpus, WITH fusion (bucket counts reduced in the SAME
    program/launch that scores, only tiny accumulators cross to the
    host) and WITHOUT (the old path: the dense score vector D2H's and
    the host re-reads the columns). Bucket-equality gated vs the numpy
    oracle. Runs on both backends: the scoring front end is the tile
    kernel on TPU and the legacy XLA scatter program on the CPU
    fallback (the agg formulation — precomputed int32 code columns +
    int32 scatter counts — is identical)."""
    import numpy as np

    from elasticsearch_tpu.common import memory as dm
    from elasticsearch_tpu.ops.scoring import B, K1

    nd_pad = corpus["nd_pad"]
    nd1 = nd_pad + 1
    live1 = corpus["live1"]
    # doc-value code columns, precomputed host-side with the oracle's
    # exact arithmetic (the production staging contract,
    # search/fused_aggs.py): ordinal codes for terms, day-bucket codes
    # for the date_histogram; -1 = no value / padding doc
    n_kw = 2000
    kw_codes = np.full(nd1, -1, np.int32)
    kw_raw = corpus["keyword_ord"]
    kw_codes[:nd_pad] = np.where(
        (kw_raw < n_kw) & live1[:nd_pad], kw_raw, -1)
    epoch = 1_500_000_000_000
    day_ms = 86_400_000.0
    ts = epoch + (np.arange(nd_pad, dtype=np.int64) % 168) * 3_600_000
    b = np.floor(ts / day_ms).astype(np.int64)
    b_min = int(b.min())
    n_dh = int(b.max()) - b_min + 1
    dh_codes = np.full(nd1, -1, np.int32)
    dh_codes[:nd_pad] = np.where(live1[:nd_pad],
                                 (b - b_min).astype(np.int32), -1)
    dev_kw = jnp.asarray(kw_codes)
    dev_dh = jnp.asarray(dh_codes)
    dv_bytes = int(dev_kw.nbytes + dev_dh.nbytes)
    acct = dm.memory_accountant()
    acct.register("bench", "corpus", dm.KIND_DOC_VALUES, "agg_codes",
                  dv_bytes, reason="initial")

    def bucket_counts(codes, mask, nb):
        sel = mask & (codes >= 0)
        safe = jnp.where(sel, codes, 0)
        return jnp.zeros((nb,), jnp.int32).at[safe].add(
            sel.astype(jnp.int32))

    rng = np.random.RandomState(23)
    terms = [int(x) for x in rng.randint(50, 500, 3)]
    if use_kernel:
        lanes = [psc.QueryLane(int(corpus["term_block_start"][t]),
                               int(corpus["n_blocks_per_term"][t]),
                               idf(int(corpus["term_df"][t])))
                 for t in terms]
        rl, rh, w, _cb = psc.build_tile_tables(lanes, bmin, bmax, geom,
                                               t_pad=4, cb=cb_run)
        args = (jnp.asarray(rl), jnp.asarray(rh), jnp.asarray(w))

        @jax.jit
        def _scores1(rl_, rh_, w_):
            ds = psc.score_tiles(dev["docs"], dev["frac"], dev["live_t"],
                                 rl_, rh_, w_, t_pad=4, cb=cb_run,
                                 sub=geom.tile_sub, dense=True)[0]
            s = psc.dense_to_flat(ds, geom.tile_sub)[:nd_pad]
            return jnp.concatenate([s, jnp.zeros(1, jnp.float32)])

        path = "pallas_tile_kernel"
    else:
        n_blocks = sum(int(corpus["n_blocks_per_term"][t]) for t in terms)
        qb_pad = 1
        while qb_pad < n_blocks:
            qb_pad *= 2
        q = tuple(jnp.asarray(x)
                  for x in make_query_legacy(corpus, terms, qb_pad))
        args = q

        @jax.jit
        def _scores1(q_blocks, q_weights, q_norm_rows, q_avgdl, q_valid):
            docs = dev["block_docs"][q_blocks]
            tfs = dev["block_tfs"][q_blocks]
            flat_idx = (q_norm_rows[:, None] * nd1 + docs).ravel()
            doc_len = dev["norms"].ravel()[flat_idx].reshape(docs.shape)
            denom = tfs + K1 * (1.0 - B + B * doc_len / q_avgdl[:, None])
            matched_blk = (tfs > 0.0) & q_valid[:, None]
            contrib = jnp.where(
                matched_blk,
                q_weights[:, None] * tfs * (K1 + 1.0) / denom, 0.0)
            scores = jnp.zeros((nd1,), jnp.float32).at[docs].add(contrib)
            return jnp.where(dev["live1"], scores, 0.0)

        path = "xla_scatter"

    @jax.jit
    def fused(*a):
        # ONE program: score + rank + both bucket reductions on device;
        # only the top-k and the tiny count vectors cross to the host
        scores = _scores1(*a)
        mask = scores > 0.0
        top_s, top_d = lax.top_k(jnp.where(mask, scores, -jnp.inf), K)
        kw_counts = bucket_counts(dev_kw, mask, n_kw)
        top_kw_c, top_kw_o = lax.top_k(kw_counts, 10)
        dh_counts = bucket_counts(dev_dh, mask, n_dh)
        return top_s, top_d, top_kw_c, top_kw_o, dh_counts

    @jax.jit
    def score_only(*a):
        scores = _scores1(*a)
        top_s, top_d = lax.top_k(
            jnp.where(scores > 0.0, scores, -jnp.inf), K)
        return top_s, top_d, scores

    def host_roundtrip():
        # the pre-fusion path: rank on device, ship the DENSE score
        # vector to the host, re-read the columns there
        top_s, top_d, scores = score_only(*args)
        m = np.asarray(scores) > 0.0
        kw_counts = np.zeros(n_kw, np.int64)
        sel = m & (kw_codes >= 0)
        np.add.at(kw_counts, kw_codes[sel], 1)
        order = np.argsort(-kw_counts, kind="stable")[:10]
        dh = np.zeros(n_dh, np.int64)
        sel2 = m & (dh_codes >= 0)
        np.add.at(dh, dh_codes[sel2], 1)
        return np.asarray(top_s), kw_counts[order], order, dh

    # --- bucket-equality gate vs the numpy oracle ---
    matched = np.zeros(nd1, bool)
    for t in terms:
        start = int(corpus["term_block_start"][t])
        cnt = int(corpus["n_blocks_per_term"][t])
        blk = corpus["block_docs"][start: start + cnt]
        tfs = corpus["block_tfs"][start: start + cnt]
        matched[blk[tfs > 0]] = True
    matched &= live1
    oracle_kw = np.zeros(n_kw, np.int64)
    np.add.at(oracle_kw, kw_codes[matched & (kw_codes >= 0)], 1)
    oracle_dh = np.zeros(n_dh, np.int64)
    np.add.at(oracle_dh, dh_codes[matched & (dh_codes >= 0)], 1)
    out_f = fused(*args)
    got_kw_c, got_kw_o = np.asarray(out_f[2]), np.asarray(out_f[3])
    got_dh = np.asarray(out_f[4]).astype(np.int64)
    oracle_top = np.sort(oracle_kw)[::-1][:10]
    equality = (bool(np.array_equal(np.sort(got_kw_c)[::-1].astype(
        np.int64), oracle_top))
        and bool(np.array_equal(
            oracle_kw[got_kw_o].astype(np.int64),
            got_kw_c.astype(np.int64)))
        and bool(np.array_equal(got_dh, oracle_dh)))

    def wall_p50(fn, reps=9):
        lat = []
        for _ in range(reps):
            t0 = time.perf_counter()
            out = fn()
            np.asarray(out[0])
            lat.append(time.perf_counter() - t0)
        return pctl(lat[2:], 50)  # pctl converts seconds -> ms

    fused_p50 = wall_p50(lambda: fused(*args))
    host_p50 = wall_p50(host_roundtrip)
    return {
        "agg_p50_ms": round(fused_p50, 3),
        "agg_host_p50_ms": round(host_p50, 3),
        "agg_host_roundtrip_saved_ms": round(host_p50 - fused_p50, 3),
        # doc-value column bytes one fused query streams on device (the
        # second corpus read the host path performs host-side instead)
        "bytes_per_query_mb_agg": round(dv_bytes / 1e6, 3),
        "bucket_equality": equality,
        "terms_buckets": 10,
        "date_histogram_buckets": n_dh,
        "matched_docs": int(matched.sum()),
        "path": path,
        "method": ("wall-clock p50 over 7 timed reps (both variants end "
                   "in a host materialization, so marginal device "
                   "timing would hide exactly the round-trip this "
                   "config measures)"),
        "note": ("on the CPU fallback backend saved_ms can go negative: "
                 "XLA-CPU lowers the in-program bucket scatter to a "
                 "serial loop while the 'round-trip' D2H is an "
                 "in-process memcpy — the gate here is bucket equality; "
                 "the latency delta is the TPU run's headline, where "
                 "the dense-vector D2H pays the real tunnel sync"),
    }


def child_main():
    try:
        result = run_measurement()
        print(json.dumps(result), flush=True)
        return 0
    except Exception as e:  # noqa: BLE001 — diagnostics belong in the JSON
        import traceback

        traceback.print_exc(file=sys.stderr)
        print(json.dumps({"child_error": f"{type(e).__name__}: {e}"}),
              flush=True)
        return 1


def run_child(backend_env: dict, timeout_s: int):
    env = dict(os.environ)
    env.update(backend_env)
    env["BENCH_CHILD"] = "1"
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__)],
            env=env, timeout=timeout_s, capture_output=True, text=True,
            cwd=os.path.dirname(os.path.abspath(__file__)),
        )
    except subprocess.TimeoutExpired:
        return None, f"timeout after {timeout_s}s (backend init or staging hang)"
    sys.stderr.write(proc.stderr[-4000:])
    for line in reversed(proc.stdout.strip().splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                parsed = json.loads(line)
            except json.JSONDecodeError:
                continue
            if "child_error" in parsed:
                return None, parsed["child_error"]
            return parsed, None
    return None, (f"child exited rc={proc.returncode} without a JSON line; "
                  f"stderr tail: {proc.stderr[-500:]!r}")


def main():
    attempts = []
    for i in range(2):
        log(f"TPU attempt {i + 1}")
        result, diag = run_child({}, TPU_ATTEMPT_TIMEOUT_S)
        if result is not None:
            print(json.dumps(result), flush=True)
            return
        attempts.append(f"default-backend attempt {i + 1}: {diag}")
        log(attempts[-1])
    log("falling back to CPU backend")
    result, diag = run_child({"JAX_PLATFORMS": "cpu", "BENCH_FORCE_CPU": "1"},
                             CPU_ATTEMPT_TIMEOUT_S)
    if result is not None:
        result["extra"]["tpu_unavailable"] = attempts
        print(json.dumps(result), flush=True)
        return
    attempts.append(f"cpu fallback: {diag}")
    print(json.dumps({
        "metric": "bm25_match_top10_p50_latency_1M_docs",
        "value": -1,
        "unit": "ms",
        "vs_baseline": 0,
        "extra": {"error": "all backend attempts failed",
                  "attempts": attempts},
    }), flush=True)


if __name__ == "__main__":
    if os.environ.get("BENCH_CHILD") == "1":
        sys.exit(child_main())
    main()
