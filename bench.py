"""Benchmark: BM25 match-query latency on the flagship TPU query path.

Mirrors the Rally `pmc` match-query config from BASELINE.md: a synthetic
academic-scale corpus (1M docs, zipfian vocabulary, ~80 terms/doc), a
multi-term BM25 disjunction with top-10 collection, p50/p99 service time.

vs_baseline: speedup of the TPU program's p50 over an equivalent
vectorized numpy implementation of the same exhaustive scoring on the host
CPU (the stand-in for the reference's CPU execution; BASELINE.json's
32-vCPU Rally baseline is not reachable in this image).

Robustness (round-1 postmortem: the TPU tunnel backend hung/failed during
init and the bench died with a raw traceback — zero numbers captured):
the parent process NEVER imports jax. It runs the measurement in a child
process per backend attempt with a hard watchdog, retries the TPU backend
once, falls back to the CPU backend with the TPU diagnostics attached,
and ALWAYS prints exactly one JSON line on stdout, exit code 0.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

import numpy as np

N_DOCS = 1_000_000
AVG_DOC_LEN = 80
VOCAB = 50_000
BLOCK = 128
N_QUERY_TERMS = 3
K = 10
WARMUP = 5
ITERS = 50

TPU_ATTEMPT_TIMEOUT_S = int(os.environ.get("BENCH_TPU_TIMEOUT_S", "540"))
CPU_ATTEMPT_TIMEOUT_S = int(os.environ.get("BENCH_CPU_TIMEOUT_S", "600"))


def build_synthetic_corpus(seed=7):
    """Directly build block-packed postings for a zipfian corpus (bypasses
    the host tokenizer — the bench targets the query path)."""
    rng = np.random.RandomState(seed)
    nd_pad = 1
    while nd_pad < N_DOCS:
        nd_pad *= 2
    # per-doc lengths ~ lognormal around AVG_DOC_LEN
    doc_len = np.clip(
        rng.lognormal(np.log(AVG_DOC_LEN), 0.4, N_DOCS), 5, 500
    ).astype(np.int64)
    total_tokens = int(doc_len.sum())
    # zipfian term ids
    ranks = np.arange(1, VOCAB + 1)
    probs = 1.0 / ranks
    probs /= probs.sum()
    tokens = rng.choice(VOCAB, total_tokens, p=probs).astype(np.int32)
    doc_of_token = np.repeat(np.arange(N_DOCS, dtype=np.int32), doc_len)
    # (term, doc) -> tf
    keys = tokens.astype(np.int64) * N_DOCS + doc_of_token
    uniq, counts = np.unique(keys, return_counts=True)
    term_ids = (uniq // N_DOCS).astype(np.int32)
    docs = (uniq % N_DOCS).astype(np.int32)
    tfs = counts.astype(np.float32)
    # postings already sorted by (term, doc); block-pack
    term_start = np.searchsorted(term_ids, np.arange(VOCAB))
    term_end = np.searchsorted(term_ids, np.arange(VOCAB) + 1)
    term_df = (term_end - term_start).astype(np.int64)
    n_blocks_per_term = -(-term_df // BLOCK)
    total_blocks = int(n_blocks_per_term.sum())
    block_docs = np.full((total_blocks, BLOCK), nd_pad, dtype=np.int32)
    block_tfs = np.zeros((total_blocks, BLOCK), dtype=np.float32)
    term_block_start = np.concatenate(
        [[0], np.cumsum(n_blocks_per_term)[:-1]])
    # vectorized block packing: posting j of term t lands in
    # (term_block_start[t] + j // BLOCK, j % BLOCK)
    within = np.arange(len(term_ids), dtype=np.int64) - term_start[term_ids]
    rows = term_block_start[term_ids] + within // BLOCK
    lanes = within % BLOCK
    block_docs[rows, lanes] = docs
    block_tfs[rows, lanes] = tfs
    norms = np.ones((1, nd_pad + 1), dtype=np.float32)
    norms[0, :N_DOCS] = doc_len.astype(np.float32)
    live1 = np.zeros(nd_pad + 1, dtype=bool)
    live1[:N_DOCS] = True
    avgdl = float(doc_len.mean())
    return {
        "block_docs": block_docs,
        "block_tfs": block_tfs,
        "norms": norms,
        "live1": live1,
        "term_block_start": term_block_start,
        "n_blocks_per_term": n_blocks_per_term,
        "term_df": term_df,
        "avgdl": avgdl,
        "nd_pad": nd_pad,
    }


def make_query(corpus, terms, qb_pad):
    import math

    blocks, weights, avgdls = [], [], []
    for t in terms:
        df = int(corpus["term_df"][t])
        idf = math.log(1 + (N_DOCS - df + 0.5) / (df + 0.5))
        start = int(corpus["term_block_start"][t])
        for bi in range(start, start + int(corpus["n_blocks_per_term"][t])):
            blocks.append(bi)
            weights.append(idf)
            avgdls.append(corpus["avgdl"])
    n = qb_pad
    assert len(blocks) <= n, f"query needs {len(blocks)} blocks > pad {n}"
    pad = n - len(blocks)
    return (
        np.asarray(blocks + [0] * pad, np.int32),
        np.asarray(weights + [0.0] * pad, np.float32),
        np.zeros(n, np.int32),
        np.asarray(avgdls + [1.0] * pad, np.float32),
        np.asarray([True] * len(blocks) + [False] * pad),
    )


def numpy_reference_query(corpus, q):
    """Host-CPU scoring of the same query (vectorized numpy baseline)."""
    from elasticsearch_tpu.ops.scoring import B, K1

    q_blocks, q_weights, _, q_avgdl, q_valid = q
    docs = corpus["block_docs"][q_blocks]
    tfs = corpus["block_tfs"][q_blocks]
    doc_len = corpus["norms"][0][docs]
    denom = tfs + K1 * (1 - B + B * doc_len / q_avgdl[:, None])
    matched = (tfs > 0) & q_valid[:, None]
    contrib = np.where(matched, q_weights[:, None] * tfs * (K1 + 1) / denom, 0.0)
    nd1 = corpus["norms"].shape[1]
    scores = np.zeros(nd1, np.float32)
    np.add.at(scores, docs.ravel(), contrib.ravel())
    counts = np.zeros(nd1, np.float32)
    np.add.at(counts, docs.ravel(), matched.ravel().astype(np.float32))
    masked = np.where((counts > 0) & corpus["live1"], scores, -np.inf)
    top_idx = np.argpartition(-masked, K)[:K]
    top_idx = top_idx[np.argsort(-masked[top_idx])]
    return masked[top_idx], top_idx


def log(msg):
    print(f"[bench] {msg}", file=sys.stderr, flush=True)


def run_measurement() -> dict:
    """Child-process body: init backend, stage, measure. Raises on error."""
    t_init = time.perf_counter()
    import jax

    if os.environ.get("BENCH_FORCE_CPU") == "1":
        # the env var alone is NOT enough: the axon site hook re-registers
        # the TPU tunnel backend regardless of JAX_PLATFORMS, so force the
        # platform through the config (same as tests/conftest.py)
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    from jax import lax

    # fail fast + loud if the backend can't come up: this is the exact
    # spot that silently hung in round 1
    devices = jax.devices()
    platform = devices[0].platform
    log(f"backend up: {platform} x{len(devices)} "
        f"in {time.perf_counter() - t_init:.1f}s")

    from elasticsearch_tpu.ops.scoring import B, K1

    t0 = time.perf_counter()
    corpus = build_synthetic_corpus()
    log(f"corpus built in {time.perf_counter() - t0:.1f}s "
        f"({corpus['block_docs'].shape[0]} blocks)")

    @jax.jit
    def query_phase(block_docs, block_tfs, norms, live1, q_blocks, q_weights,
                    q_norm_rows, q_avgdl, q_valid):
        docs = block_docs[q_blocks]
        tfs = block_tfs[q_blocks]
        nd1 = norms.shape[1]
        flat_idx = (q_norm_rows[:, None] * nd1 + docs).ravel()
        doc_len = norms.ravel()[flat_idx].reshape(docs.shape)
        denom = tfs + K1 * (1.0 - B + B * doc_len / q_avgdl[:, None])
        matched_blk = (tfs > 0.0) & q_valid[:, None]
        contrib = jnp.where(
            matched_blk, q_weights[:, None] * tfs * (K1 + 1.0) / denom, 0.0
        )
        # single scatter: BM25 contributions are strictly positive, so
        # scores > 0 is exactly "matched" for a disjunction
        scores = jnp.zeros((nd1,), jnp.float32).at[docs].add(contrib)
        masked = jnp.where((scores > 0) & live1, scores, -jnp.inf)
        return lax.top_k(masked, K)

    # stage corpus to HBM once (shard-open staging)
    t0 = time.perf_counter()
    dev = {
        "block_docs": jnp.asarray(corpus["block_docs"]),
        "block_tfs": jnp.asarray(corpus["block_tfs"]),
        "norms": jnp.asarray(corpus["norms"]),
        "live1": jnp.asarray(corpus["live1"]),
    }
    for v in dev.values():
        v.block_until_ready()
    hbm_bytes = sum(int(np.prod(v.shape)) * v.dtype.itemsize
                    for v in dev.values())
    log(f"staged {hbm_bytes / 1e6:.0f} MB to device in "
        f"{time.perf_counter() - t0:.1f}s")

    # query mix: mid-frequency terms (zipf ranks 50..1000), like pmc terms.
    # All queries pad to ONE fixed shape so a single compiled program serves
    # the whole run (shape bucketing; SURVEY.md §7.3).
    rng = np.random.RandomState(3)
    term_sets = [list(rng.randint(50, 1000, N_QUERY_TERMS))
                 for _ in range(ITERS + WARMUP)]
    max_blocks = max(
        sum(int(corpus["n_blocks_per_term"][t]) for t in ts) for ts in term_sets
    )
    qb_pad = 1
    while qb_pad < max_blocks:
        qb_pad *= 2
    queries = [make_query(corpus, ts, qb_pad) for ts in term_sets]
    # pre-stage all query args (the engine stages per-query args while the
    # previous query executes; here we exclude that host->HBM copy the same
    # way Rally excludes client-side serialization)
    staged_queries = [tuple(jnp.asarray(x) for x in q) for q in queries]

    # correctness gate vs numpy reference (recall@10 == 1.0)
    q0 = queries[0]
    t0 = time.perf_counter()
    ts_, ti = query_phase(dev["block_docs"], dev["block_tfs"], dev["norms"],
                          dev["live1"], *staged_queries[0])
    ts_.block_until_ready()
    log(f"first compile+run in {time.perf_counter() - t0:.1f}s")
    ref_s, ref_i = numpy_reference_query(corpus, q0)
    assert set(np.asarray(ti).tolist()) == set(ref_i.tolist()), \
        "recall@10 != 1.0"
    np.testing.assert_allclose(np.asarray(ts_), ref_s, rtol=1e-4)

    # --- device timing ---
    def run_q(q):
        return query_phase(dev["block_docs"], dev["block_tfs"], dev["norms"],
                           dev["live1"], *q)

    # warmup (compile once — fixed shapes)
    for q in staged_queries[:WARMUP]:
        np.asarray(run_q(q)[0])

    # (a) pipelined: amortized per-query device time. The queue hides the
    # dispatch round-trip of the remote-execution tunnel, like a loaded
    # server hides per-request dispatch under concurrency (Rally's
    # multi-client throughput measurement).
    BATCH = 10
    batch_lat = []
    timed = staged_queries[WARMUP:]
    for start in range(0, len(timed) - BATCH + 1, BATCH):
        batch = timed[start: start + BATCH]
        t0 = time.perf_counter()
        outs = [run_q(q) for q in batch]
        np.asarray(outs[-1][0])
        for o in outs[:-1]:
            o[0].block_until_ready()
        batch_lat.append((time.perf_counter() - t0) / BATCH)
    batch_lat = np.asarray(batch_lat)
    p50 = float(np.percentile(batch_lat, 50) * 1000)
    p99 = float(np.percentile(batch_lat, 99) * 1000)
    qps = 1000.0 / p50

    # (b) blocking single-query service time (includes the tunnel dispatch
    # round-trip — an artifact of the remote-chip dev setup, recorded for
    # transparency)
    blocking = []
    for q in staged_queries[WARMUP: WARMUP + 10]:
        t0 = time.perf_counter()
        np.asarray(run_q(q)[0])
        blocking.append(time.perf_counter() - t0)
    blocking_p50 = float(np.percentile(np.asarray(blocking), 50) * 1000)

    # --- CPU numpy baseline timing (same exhaustive algorithm) ---
    cpu_lat = []
    for q in queries[: WARMUP + 10]:
        t0 = time.perf_counter()
        numpy_reference_query(corpus, q)
        cpu_lat.append(time.perf_counter() - t0)
    cpu_p50 = float(np.percentile(np.asarray(cpu_lat[2:]), 50) * 1000)

    # HBM traffic estimate for one query: gathered posting blocks
    # (docs+tfs), the norms gather, the score scatter + mask + top_k scan
    nd1 = corpus["nd_pad"] + 1
    bytes_per_query = (
        qb_pad * BLOCK * (4 + 4)        # block_docs + block_tfs gather
        + qb_pad * BLOCK * 4            # norms gather
        + nd1 * 4 * 3                   # scores init + scatter + mask
        + nd1 * 1                       # live mask read
        + nd1 * 4                       # top_k scan read
    )
    hbm_gbps = bytes_per_query / (p50 / 1000) / 1e9

    return {
        "metric": "bm25_match_top10_p50_latency_1M_docs",
        "value": round(p50, 3),
        "unit": "ms",
        "vs_baseline": round(cpu_p50 / p50, 2),
        "extra": {
            "backend": platform,
            "p99_ms": round(p99, 3),
            "qps_per_chip": round(qps, 1),
            "cpu_numpy_p50_ms": round(cpu_p50, 3),
            "blocking_p50_ms_incl_tunnel_rtt": round(blocking_p50, 3),
            "n_docs": N_DOCS,
            "recall_at_10": 1.0,
            "hbm_gb_per_s_estimate": round(hbm_gbps, 1),
            "corpus_hbm_mb": round(hbm_bytes / 1e6, 1),
            "method": "chained back-to-back execution (amortized device "
                      "service time); single fixed-shape compiled program",
        },
    }


def child_main():
    try:
        result = run_measurement()
        print(json.dumps(result), flush=True)
        return 0
    except Exception as e:  # noqa: BLE001 — diagnostics belong in the JSON
        import traceback

        traceback.print_exc(file=sys.stderr)
        print(json.dumps({"child_error": f"{type(e).__name__}: {e}"}),
              flush=True)
        return 1


def run_child(backend_env: dict, timeout_s: int):
    """Run the measurement in a child process; returns (json_or_None,
    diagnostic_str_or_None)."""
    env = dict(os.environ)
    env.update(backend_env)
    env["BENCH_CHILD"] = "1"
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__)],
            env=env, timeout=timeout_s, capture_output=True, text=True,
            cwd=os.path.dirname(os.path.abspath(__file__)),
        )
    except subprocess.TimeoutExpired:
        return None, f"timeout after {timeout_s}s (backend init or staging hang)"
    sys.stderr.write(proc.stderr[-4000:])
    for line in reversed(proc.stdout.strip().splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                parsed = json.loads(line)
            except json.JSONDecodeError:
                continue
            if "child_error" in parsed:
                return None, parsed["child_error"]
            return parsed, None
    return None, (f"child exited rc={proc.returncode} without a JSON line; "
                  f"stderr tail: {proc.stderr[-500:]!r}")


def main():
    attempts = []
    # attempt 1+2: whatever backend the environment pins (the TPU tunnel
    # under the driver; transient UNAVAILABLE errors got round 1 zero
    # numbers, so retry once before falling back)
    for i in range(2):
        log(f"TPU attempt {i + 1}")
        result, diag = run_child({}, TPU_ATTEMPT_TIMEOUT_S)
        if result is not None:
            print(json.dumps(result), flush=True)
            return
        attempts.append(f"default-backend attempt {i + 1}: {diag}")
        log(attempts[-1])
    # fallback: CPU backend so the round still records a number; the
    # vs_baseline of the XLA-CPU program vs the numpy baseline is still
    # meaningful, and the JSON carries the TPU failure diagnostics
    log("falling back to CPU backend")
    result, diag = run_child({"JAX_PLATFORMS": "cpu", "BENCH_FORCE_CPU": "1"},
                             CPU_ATTEMPT_TIMEOUT_S)
    if result is not None:
        result["extra"]["tpu_unavailable"] = attempts
        print(json.dumps(result), flush=True)
        return
    attempts.append(f"cpu fallback: {diag}")
    print(json.dumps({
        "metric": "bm25_match_top10_p50_latency_1M_docs",
        "value": -1,
        "unit": "ms",
        "vs_baseline": 0,
        "extra": {"error": "all backend attempts failed",
                  "attempts": attempts},
    }), flush=True)


if __name__ == "__main__":
    if os.environ.get("BENCH_CHILD") == "1":
        sys.exit(child_main())
    main()
