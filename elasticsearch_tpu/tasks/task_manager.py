"""Task management: running-operation registry with cancellation.

Role model: ``TaskManager`` (core/.../tasks/TaskManager.java:52,
register:82, unregister:141) + ``CancellableTask``; the `_tasks` API lists
and cancels. Parent/child task hierarchies collapse on a single node but
the id scheme (node_id:task_number) is preserved for the clustered path.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Optional

from elasticsearch_tpu.common.errors import (
    ResourceNotFoundException,
    TaskCancelledException,
)


class Task:
    def __init__(self, task_id: int, node_id: str, action: str, description: str,
                 cancellable: bool = True, parent: Optional[str] = None,
                 headers: Optional[Dict[str, str]] = None):
        self.task_id = task_id
        self.node_id = node_id
        self.action = action
        self.description = description
        self.cancellable = cancellable
        self.parent = parent
        # task headers (TaskManager.register copies X-Opaque-Id from the
        # request thread context): joins a running/slow task back to the
        # client that issued it (docs/OBSERVABILITY.md)
        self.headers = {k: v for k, v in (headers or {}).items()
                        if v is not None}
        self.start_time = time.time()
        self._cancelled = threading.Event()
        self.cancel_reason: Optional[str] = None
        # mutable progress status (BulkByScrollTask-style)
        self.status: Dict = {}

    @property
    def id_string(self) -> str:
        return f"{self.node_id}:{self.task_id}"

    def cancel(self, reason: str = "by user request") -> None:
        self.cancel_reason = reason
        self._cancelled.set()

    @property
    def cancelled(self) -> bool:
        return self._cancelled.is_set()

    def ensure_not_cancelled(self) -> None:
        if self.cancelled:
            raise TaskCancelledException(f"task cancelled [{self.cancel_reason}]")

    def to_dict(self) -> dict:
        return {
            "node": self.node_id,
            "id": self.task_id,
            "type": "transport",
            "action": self.action,
            "description": self.description,
            "start_time_in_millis": int(self.start_time * 1000),
            "running_time_in_nanos": int((time.time() - self.start_time) * 1e9),
            "cancellable": self.cancellable,
            "status": self.status or None,
            "headers": dict(self.headers),
            **({"parent_task_id": self.parent} if self.parent else {}),
        }


class TaskManager:
    def __init__(self, node_id: str):
        self.node_id = node_id
        self._tasks: Dict[int, Task] = {}
        self._counter = 0
        self._lock = threading.Lock()

    def register(self, action: str, description: str, cancellable: bool = True,
                 parent: Optional[str] = None,
                 headers: Optional[Dict[str, str]] = None) -> Task:
        if headers is None:
            # default: lift the request's X-Opaque-Id off the REST
            # thread context so every registered task carries it
            from elasticsearch_tpu.search.telemetry import get_opaque_id

            oid = get_opaque_id()
            headers = {"X-Opaque-Id": oid} if oid else None
        with self._lock:
            self._counter += 1
            task = Task(self._counter, self.node_id, action, description,
                        cancellable, parent, headers=headers)
            self._tasks[self._counter] = task
            return task

    def unregister(self, task: Task) -> None:
        with self._lock:
            self._tasks.pop(task.task_id, None)

    def get(self, task_id: str) -> Task:
        num = int(task_id.split(":")[-1])
        task = self._tasks.get(num)
        if task is None:
            raise ResourceNotFoundException(f"task [{task_id}] isn't running and hasn't stored its results")
        return task

    def cancel(self, task_id: str, reason: str = "by user request") -> Task:
        task = self.get(task_id)
        if not task.cancellable:
            raise ResourceNotFoundException(f"task [{task_id}] is not cancellable")
        task.cancel(reason)
        return task

    def list_tasks(self, actions: Optional[str] = None) -> dict:
        import fnmatch

        with self._lock:
            tasks = {
                t.id_string: t.to_dict()
                for t in self._tasks.values()
                if actions is None or any(
                    fnmatch.fnmatchcase(t.action, pat)
                    for pat in str(actions).split(",")
                )
            }
        return {"nodes": {self.node_id: {"tasks": tasks}}}
