"""Text analysis: tokenizers, token filters, char filters, analyzers.

Role model: the reference's per-index ``AnalysisRegistry`` /
``IndexAnalyzers`` / ``CustomAnalyzer``
(core/.../index/analysis/AnalysisRegistry.java, CustomAnalyzer.java) plus
the common analyzers shipped in ``modules/analysis-common``. An analyzer is
char_filters -> tokenizer -> token_filters; the registry builds named
analyzers from index settings (``index.analysis.analyzer.<name>.*``).

All analysis is host-side (strings never reach the TPU); tokens become term
ids before staging.
"""

from __future__ import annotations

import re
import unicodedata
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

from elasticsearch_tpu.common.errors import IllegalArgumentException

# ---------------------------------------------------------------------------
# Tokenizers: text -> [ (token, start_offset, end_offset) ]
# ---------------------------------------------------------------------------

Token = tuple  # (text, start, end)

# Unicode-aware word pattern: letters/digits runs (approximates Lucene's
# StandardTokenizer UAX#29 word-break behavior for alphanumeric text).
_STANDARD_RE = re.compile(r"\w+", re.UNICODE)
_WHITESPACE_RE = re.compile(r"\S+")
_LETTER_RE = re.compile(r"[^\W\d_]+", re.UNICODE)


def standard_tokenizer(text: str) -> List[Token]:
    return [(m.group(), m.start(), m.end()) for m in _STANDARD_RE.finditer(text)]


def whitespace_tokenizer(text: str) -> List[Token]:
    return [(m.group(), m.start(), m.end()) for m in _WHITESPACE_RE.finditer(text)]


def letter_tokenizer(text: str) -> List[Token]:
    return [(m.group(), m.start(), m.end()) for m in _LETTER_RE.finditer(text)]


def keyword_tokenizer(text: str) -> List[Token]:
    return [(text, 0, len(text))] if text else []


def _ngram_tokens(text: str, min_gram: int, max_gram: int, edge: bool) -> List[Token]:
    out = []
    n = len(text)
    starts = [0] if edge else range(n)
    for i in starts:
        for g in range(min_gram, max_gram + 1):
            if i + g <= n:
                out.append((text[i : i + g], i, i + g))
    return out


def make_ngram_tokenizer(min_gram: int = 1, max_gram: int = 2, edge: bool = False):
    def tok(text: str) -> List[Token]:
        return _ngram_tokens(text, min_gram, max_gram, edge)

    return tok


def make_pattern_tokenizer(pattern: str = r"\W+"):
    rx = re.compile(pattern)

    def tok(text: str) -> List[Token]:
        out, pos = [], 0
        for m in rx.finditer(text):
            if m.start() > pos:
                out.append((text[pos : m.start()], pos, m.start()))
            pos = m.end()
        if pos < len(text):
            out.append((text[pos:], pos, len(text)))
        return out

    return tok


TOKENIZERS: Dict[str, Callable] = {
    "standard": standard_tokenizer,
    "whitespace": whitespace_tokenizer,
    "letter": letter_tokenizer,
    "keyword": keyword_tokenizer,
    "lowercase": lambda t: [
        (tok.lower(), s, e) for tok, s, e in letter_tokenizer(t)
    ],
}

# ---------------------------------------------------------------------------
# Token filters: [tokens] -> [tokens]; a None/"" token is dropped.
# ---------------------------------------------------------------------------

# Lucene's default English stopword set (EnglishAnalyzer.ENGLISH_STOP_WORDS_SET).
ENGLISH_STOP_WORDS = frozenset(
    """a an and are as at be but by for if in into is it no not of on or such
    that the their then there these they this to was will with""".split()
)


def lowercase_filter(tokens):
    return [(t.lower(), s, e) for t, s, e in tokens]


def uppercase_filter(tokens):
    return [(t.upper(), s, e) for t, s, e in tokens]


def asciifolding_filter(tokens):
    def fold(t):
        return "".join(
            c for c in unicodedata.normalize("NFKD", t) if not unicodedata.combining(c)
        )

    return [(fold(t), s, e) for t, s, e in tokens]


def make_stop_filter(stopwords=ENGLISH_STOP_WORDS):
    sw = frozenset(w.lower() for w in stopwords)

    def f(tokens):
        return [tok for tok in tokens if tok[0].lower() not in sw]

    return f


def make_length_filter(min_len=0, max_len=2**31 - 1):
    def f(tokens):
        return [tok for tok in tokens if min_len <= len(tok[0]) <= max_len]

    return f


def unique_filter(tokens):
    seen, out = set(), []
    for tok in tokens:
        if tok[0] not in seen:
            seen.add(tok[0])
            out.append(tok)
    return out


def reverse_filter(tokens):
    return [(t[::-1], s, e) for t, s, e in tokens]


def trim_filter(tokens):
    return [(t.strip(), s, e) for t, s, e in tokens if t.strip()]


def make_truncate_filter(length=10):
    def f(tokens):
        return [(t[:length], s, e) for t, s, e in tokens]

    return f


def make_shingle_filter(min_size=2, max_size=2, sep=" ", output_unigrams=True):
    def f(tokens):
        out = list(tokens) if output_unigrams else []
        words = [t for t, _, _ in tokens]
        for n in range(min_size, max_size + 1):
            for i in range(len(words) - n + 1):
                text = sep.join(words[i : i + n])
                out.append((text, tokens[i][1], tokens[i + n - 1][2]))
        return out

    return f


_PORTER_STEP1 = [
    ("sses", "ss"),
    ("ies", "i"),
    ("ss", "ss"),
    ("s", ""),
]


def porter_light_stem(word: str) -> str:
    """A light English stemmer (Porter step-1-ish + common suffixes).

    Stands in for Lucene's PorterStemFilter; exact Porter parity is not a
    conformance surface (scores differ, recall behavior is similar).
    """
    w = word
    if len(w) > 3:
        for suf, rep in _PORTER_STEP1:
            if w.endswith(suf):
                w = w[: -len(suf)] + rep
                break
    for suf in ("ingly", "edly", "ing", "ed", "ly"):
        if len(w) > len(suf) + 2 and w.endswith(suf):
            w = w[: -len(suf)]
            if suf in ("ing", "ed") and len(w) >= 2 and w[-1] == w[-2] and w[-1] not in "lsz":
                w = w[:-1]
            break
    return w


def stemmer_filter(tokens):
    return [(porter_light_stem(t), s, e) for t, s, e in tokens]


TOKEN_FILTERS: Dict[str, Callable] = {
    "lowercase": lowercase_filter,
    "uppercase": uppercase_filter,
    "asciifolding": asciifolding_filter,
    "stop": make_stop_filter(),
    "unique": unique_filter,
    "reverse": reverse_filter,
    "trim": trim_filter,
    "stemmer": stemmer_filter,
    "porter_stem": stemmer_filter,
    "shingle": make_shingle_filter(),
}

# ---------------------------------------------------------------------------
# Char filters: text -> text
# ---------------------------------------------------------------------------

_HTML_RE = re.compile(r"<[^>]*>")


def html_strip_char_filter(text: str) -> str:
    return _HTML_RE.sub(" ", text)


def make_mapping_char_filter(mappings: List[str]):
    pairs = []
    for m in mappings:
        if "=>" not in m:
            raise IllegalArgumentException(f"Invalid mapping rule : [{m}]")
        a, b = m.split("=>", 1)
        pairs.append((a.strip(), b.strip()))

    def f(text: str) -> str:
        for a, b in pairs:
            text = text.replace(a, b)
        return text

    return f


def make_pattern_replace_char_filter(pattern: str, replacement: str = ""):
    rx = re.compile(pattern)

    def f(text: str) -> str:
        return rx.sub(replacement, text)

    return f


CHAR_FILTERS: Dict[str, Callable] = {
    "html_strip": html_strip_char_filter,
}

# AnalysisPlugin extension points (filled by PluginsService): merged into
# every new AnalysisRegistry ahead of settings-defined custom components
EXTRA_ANALYZERS: Dict[str, "Analyzer"] = {}
EXTRA_TOKENIZERS: Dict[str, Callable] = {}
EXTRA_TOKEN_FILTERS: Dict[str, Callable] = {}
EXTRA_CHAR_FILTERS: Dict[str, Callable] = {}

# ---------------------------------------------------------------------------
# Analyzer = char_filters + tokenizer + filters
# ---------------------------------------------------------------------------


@dataclass
class Analyzer:
    name: str
    tokenizer: Callable[[str], List[Token]]
    token_filters: List[Callable] = field(default_factory=list)
    char_filters: List[Callable] = field(default_factory=list)
    # positions increment per token; a filter removing tokens leaves gaps in
    # the reference; we renumber contiguously (phrase slop semantics differ
    # only around removed stopwords).

    def analyze(self, text: str) -> List[str]:
        return [t for t, _, _ in self.analyze_tokens(text)]

    def analyze_tokens(self, text: str) -> List[Token]:
        if not isinstance(text, str):
            text = str(text)
        for cf in self.char_filters:
            text = cf(text)
        tokens = None
        # native fast path: standard tokenizer + leading lowercase filter is
        # the dominant indexing combination (C++ does both in one pass)
        if (self.tokenizer is standard_tokenizer and self.token_filters
                and self.token_filters[0] is lowercase_filter):
            from elasticsearch_tpu.utils import native

            fast = native.standard_tokenize_fast(text)
            if fast is not None:
                tokens = fast
                for f in self.token_filters[1:]:
                    tokens = f(tokens)
        if tokens is None:
            tokens = self.tokenizer(text)
            for f in self.token_filters:
                tokens = f(tokens)
        return [tok for tok in tokens if tok[0]]


def _builtin_analyzers() -> Dict[str, Analyzer]:
    return {
        "standard": Analyzer("standard", standard_tokenizer, [lowercase_filter]),
        "simple": Analyzer("simple", letter_tokenizer, [lowercase_filter]),
        "whitespace": Analyzer("whitespace", whitespace_tokenizer),
        "keyword": Analyzer("keyword", keyword_tokenizer),
        "stop": Analyzer("stop", letter_tokenizer, [lowercase_filter, make_stop_filter()]),
        "english": Analyzer(
            "english",
            standard_tokenizer,
            [lowercase_filter, make_stop_filter(), stemmer_filter],
        ),
        # analysis-common SnowballAnalyzer (default English): same
        # pipeline as "english" here — our stemmer approximates both
        "snowball": Analyzer(
            "snowball",
            standard_tokenizer,
            [lowercase_filter, make_stop_filter(), stemmer_filter],
        ),
    }


class AnalysisRegistry:
    """Builds an index's named analyzers from its settings.

    Settings shape (same as the reference):
      index.analysis.char_filter.<name>.type: mapping|pattern_replace|html_strip
      index.analysis.tokenizer.<name>.type: ngram|edge_ngram|pattern|standard|...
      index.analysis.filter.<name>.type: stop|length|truncate|shingle|...
      index.analysis.analyzer.<name>.type: custom
      index.analysis.analyzer.<name>.tokenizer: <tokenizer-name>
      index.analysis.analyzer.<name>.filter: [f1, f2]
      index.analysis.analyzer.<name>.char_filter: [c1]
    """

    def __init__(self, index_settings=None):
        from elasticsearch_tpu.common.settings import Settings

        self.settings = index_settings or Settings.EMPTY
        self.analyzers: Dict[str, Analyzer] = _builtin_analyzers()
        self._tokenizers = dict(TOKENIZERS)
        self._filters = dict(TOKEN_FILTERS)
        self._char_filters = dict(CHAR_FILTERS)
        # AnalysisPlugin extension points (plugins/__init__.py)
        self.analyzers.update(EXTRA_ANALYZERS)
        self._tokenizers.update(EXTRA_TOKENIZERS)
        self._filters.update(EXTRA_TOKEN_FILTERS)
        self._char_filters.update(EXTRA_CHAR_FILTERS)
        self._build_custom()

    def _component_names(self, kind: str) -> List[str]:
        prefix = f"index.analysis.{kind}."
        names = set()
        for key in self.settings.keys():
            if key.startswith(prefix):
                names.add(key[len(prefix) :].split(".")[0])
        return sorted(names)

    def _build_custom(self) -> None:
        s = self.settings
        for name in self._component_names("char_filter"):
            p = f"index.analysis.char_filter.{name}"
            typ = s.get_str(f"{p}.type")
            if typ == "mapping":
                self._char_filters[name] = make_mapping_char_filter(
                    s.get_list(f"{p}.mappings", [])
                )
            elif typ == "pattern_replace":
                self._char_filters[name] = make_pattern_replace_char_filter(
                    s.get_str(f"{p}.pattern", ""), s.get_str(f"{p}.replacement", "")
                )
            elif typ == "html_strip":
                self._char_filters[name] = html_strip_char_filter
            else:
                raise IllegalArgumentException(f"Unknown char_filter type [{typ}] for [{name}]")

        for name in self._component_names("tokenizer"):
            p = f"index.analysis.tokenizer.{name}"
            typ = s.get_str(f"{p}.type")
            if typ in ("ngram", "nGram"):
                self._tokenizers[name] = make_ngram_tokenizer(
                    s.get_int(f"{p}.min_gram", 1), s.get_int(f"{p}.max_gram", 2), False
                )
            elif typ in ("edge_ngram", "edgeNGram"):
                self._tokenizers[name] = make_ngram_tokenizer(
                    s.get_int(f"{p}.min_gram", 1), s.get_int(f"{p}.max_gram", 2), True
                )
            elif typ == "pattern":
                self._tokenizers[name] = make_pattern_tokenizer(
                    s.get_str(f"{p}.pattern", r"\W+")
                )
            elif typ in self._tokenizers:
                self._tokenizers[name] = self._tokenizers[typ]
            else:
                raise IllegalArgumentException(f"Unknown tokenizer type [{typ}] for [{name}]")

        for name in self._component_names("filter"):
            p = f"index.analysis.filter.{name}"
            typ = s.get_str(f"{p}.type")
            if typ == "stop":
                words = s.get_list(f"{p}.stopwords", None)
                self._filters[name] = make_stop_filter(
                    ENGLISH_STOP_WORDS if words in (None, ["_english_"]) else words
                )
            elif typ == "length":
                self._filters[name] = make_length_filter(
                    s.get_int(f"{p}.min", 0), s.get_int(f"{p}.max", 2**31 - 1)
                )
            elif typ == "truncate":
                self._filters[name] = make_truncate_filter(s.get_int(f"{p}.length", 10))
            elif typ == "shingle":
                self._filters[name] = make_shingle_filter(
                    s.get_int(f"{p}.min_shingle_size", 2),
                    s.get_int(f"{p}.max_shingle_size", 2),
                    s.get_str(f"{p}.token_separator", " "),
                    s.get_bool(f"{p}.output_unigrams", True),
                )
            elif typ in self._filters:
                self._filters[name] = self._filters[typ]
            else:
                raise IllegalArgumentException(f"Unknown filter type [{typ}] for [{name}]")

        for name in self._component_names("analyzer"):
            p = f"index.analysis.analyzer.{name}"
            typ = s.get_str(f"{p}.type", "custom")
            if typ != "custom" and typ in self.analyzers:
                self.analyzers[name] = self.analyzers[typ]
                continue
            tok_name = s.get_str(f"{p}.tokenizer", "standard")
            if tok_name not in self._tokenizers:
                raise IllegalArgumentException(
                    f"analyzer [{name}] must specify a known tokenizer, got [{tok_name}]"
                )
            filters = []
            for fn in s.get_list(f"{p}.filter", []):
                if fn not in self._filters:
                    raise IllegalArgumentException(f"Unknown filter [{fn}] for analyzer [{name}]")
                filters.append(self._filters[fn])
            char_filters = []
            for cn in s.get_list(f"{p}.char_filter", []):
                if cn not in self._char_filters:
                    raise IllegalArgumentException(
                        f"Unknown char_filter [{cn}] for analyzer [{name}]"
                    )
                char_filters.append(self._char_filters[cn])
            self.analyzers[name] = Analyzer(name, self._tokenizers[tok_name], filters, char_filters)

    def get(self, name: str) -> Analyzer:
        a = self.analyzers.get(name)
        if a is None:
            raise IllegalArgumentException(f"failed to find analyzer [{name}]")
        return a

    def default(self) -> Analyzer:
        return self.analyzers.get("default") or self.analyzers["standard"]
