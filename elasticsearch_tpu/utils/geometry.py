"""Planar geometry for geo_shape fields and queries.

Role model: the reference's geo_shape support — shape builders in
``common/geo/builders/`` (GeoJSON + WKT parsing: ShapeParser /
GeoWKTParser) and the spatial-relation query strategies
(``index/query/GeoShapeQueryBuilder.java``: INTERSECTS / DISJOINT /
WITHIN / CONTAINS over Lucene spatial prefix trees).

TPU-first inversion: instead of a quadtree term index, shapes stay
host-side as geometry objects with a dense numpy bbox table per segment;
query evaluation is a vectorized bbox prefilter over all docs followed by
exact planar predicates on the candidates (the same grid-approximation
tier the reference's prefix tree quantizes to). Coordinates are lon/lat
degrees on a planar approximation; circles become 32-gons
(the reference's recursive-prefix-tree circles are likewise polygonal at
tree precision).
"""

from __future__ import annotations

import math
from typing import List, Optional, Sequence, Tuple

from elasticsearch_tpu.common.errors import (
    IllegalArgumentException,
    MapperParsingException,
)

EARTH_RADIUS_M = 6371008.7714
CIRCLE_SIDES = 32


# ---------------------------------------------------------------------------
# primitives (planar, lon/lat degrees)
# ---------------------------------------------------------------------------


def _seg_intersect(p1, p2, p3, p4) -> bool:
    """Proper + collinear-overlap segment intersection."""

    def orient(a, b, c):
        v = (b[0] - a[0]) * (c[1] - a[1]) - (b[1] - a[1]) * (c[0] - a[0])
        if abs(v) < 1e-12:
            return 0
        return 1 if v > 0 else -1

    def on_seg(a, b, c):
        return (min(a[0], b[0]) - 1e-12 <= c[0] <= max(a[0], b[0]) + 1e-12
                and min(a[1], b[1]) - 1e-12 <= c[1] <= max(a[1], b[1]) + 1e-12)

    o1, o2 = orient(p1, p2, p3), orient(p1, p2, p4)
    o3, o4 = orient(p3, p4, p1), orient(p3, p4, p2)
    if o1 != o2 and o3 != o4:
        return True
    if o1 == 0 and on_seg(p1, p2, p3):
        return True
    if o2 == 0 and on_seg(p1, p2, p4):
        return True
    if o3 == 0 and on_seg(p3, p4, p1):
        return True
    if o4 == 0 and on_seg(p3, p4, p2):
        return True
    return False


def _point_in_ring(pt, ring: Sequence[Tuple[float, float]]) -> bool:
    """Ray casting; boundary counts as inside (tolerance 1e-12)."""
    x, y = pt
    inside = False
    n = len(ring)
    for i in range(n - 1):
        x1, y1 = ring[i]
        x2, y2 = ring[i + 1]
        # boundary check
        if _seg_intersect((x1, y1), (x2, y2), (x, y), (x, y)):
            return True
        if (y1 > y) != (y2 > y):
            xin = (x2 - x1) * (y - y1) / (y2 - y1) + x1
            if x < xin:
                inside = not inside
    return inside


# ---------------------------------------------------------------------------
# shapes
# ---------------------------------------------------------------------------


class Shape:
    kind = "shape"

    def bbox(self) -> Tuple[float, float, float, float]:
        """(min_lon, min_lat, max_lon, max_lat)."""
        raise NotImplementedError

    # decomposition every shape provides: points / segments / rings
    def points(self) -> List[Tuple[float, float]]:
        return []

    def segments(self) -> List[Tuple[Tuple[float, float], Tuple[float, float]]]:
        return []

    def rings(self) -> List["Polygon"]:
        """Filled areas as simple polygons (shells with holes)."""
        return []

    def contains_point(self, pt) -> bool:
        return any(poly._contains_point(pt) for poly in self.rings())

    # -- relations ----------------------------------------------------

    def intersects(self, other: "Shape") -> bool:
        ba, bb = self.bbox(), other.bbox()
        if ba[0] > bb[2] or bb[0] > ba[2] or ba[1] > bb[3] or bb[1] > ba[3]:
            return False
        # any point of one inside the other's area
        pts_a, pts_b = self.points(), other.points()
        segs_a, segs_b = self.segments(), other.segments()
        for pt in pts_a:
            if other.contains_point(pt):
                return True
        for pt in pts_b:
            if self.contains_point(pt):
                return True
        # point-on-point / point-on-edge (points and lines have no filled
        # area, so contains_point can't see them)
        for pa in pts_a:
            for pb in pts_b:
                if abs(pa[0] - pb[0]) < 1e-12 and abs(pa[1] - pb[1]) < 1e-12:
                    return True
        for pt in pts_a:
            for sb in segs_b:
                if _seg_intersect(sb[0], sb[1], pt, pt):
                    return True
        for pt in pts_b:
            for sa in segs_a:
                if _seg_intersect(sa[0], sa[1], pt, pt):
                    return True
        # any edge crossing
        for sa in segs_a:
            for sb in segs_b:
                if _seg_intersect(sa[0], sa[1], sb[0], sb[1]):
                    return True
        # area containment without vertex containment is covered by the
        # point checks above (first vertex of the contained shape)
        return False

    def within(self, other: "Shape") -> bool:
        """Every point of self inside other's filled area: all vertices
        AND all edge midpoints inside (grid-precision approximation of
        full boundary containment, adequate at the reference's
        prefix-tree quantization)."""
        pts = self.points()
        if not pts:
            return False
        for pt in pts:
            if not other.contains_point(pt):
                return False
        for sa in self.segments():
            mid = ((sa[0][0] + sa[1][0]) / 2.0, (sa[0][1] + sa[1][1]) / 2.0)
            if not other.contains_point(mid):
                return False
        return True

    def contains(self, other: "Shape") -> bool:
        return other.within(self)

    def disjoint(self, other: "Shape") -> bool:
        return not self.intersects(other)

    def relate(self, other: "Shape", relation: str) -> bool:
        if relation == "intersects":
            return self.intersects(other)
        if relation == "disjoint":
            return self.disjoint(other)
        if relation == "within":
            return self.within(other)
        if relation == "contains":
            return self.contains(other)
        raise IllegalArgumentException(f"Unknown shape relation [{relation}]")


class Point(Shape):
    kind = "point"

    def __init__(self, lon: float, lat: float):
        self.lon, self.lat = float(lon), float(lat)

    def bbox(self):
        return (self.lon, self.lat, self.lon, self.lat)

    def points(self):
        return [(self.lon, self.lat)]


class MultiPoint(Shape):
    kind = "multipoint"

    def __init__(self, pts):
        self.pts = [(float(x), float(y)) for x, y in pts]
        if not self.pts:
            raise MapperParsingException("multipoint requires coordinates")

    def bbox(self):
        xs = [p[0] for p in self.pts]
        ys = [p[1] for p in self.pts]
        return (min(xs), min(ys), max(xs), max(ys))

    def points(self):
        return list(self.pts)


class LineString(Shape):
    kind = "linestring"

    def __init__(self, pts):
        self.pts = [(float(x), float(y)) for x, y in pts]
        if len(self.pts) < 2:
            raise MapperParsingException(
                "linestring requires at least 2 points")

    def bbox(self):
        xs = [p[0] for p in self.pts]
        ys = [p[1] for p in self.pts]
        return (min(xs), min(ys), max(xs), max(ys))

    def points(self):
        return list(self.pts)

    def segments(self):
        return list(zip(self.pts[:-1], self.pts[1:]))


class MultiLineString(Shape):
    kind = "multilinestring"

    def __init__(self, lines):
        self.lines = [LineString(l) for l in lines]

    def bbox(self):
        bs = [l.bbox() for l in self.lines]
        return (min(b[0] for b in bs), min(b[1] for b in bs),
                max(b[2] for b in bs), max(b[3] for b in bs))

    def points(self):
        return [p for l in self.lines for p in l.points()]

    def segments(self):
        return [s for l in self.lines for s in l.segments()]


class Polygon(Shape):
    kind = "polygon"

    def __init__(self, shell, holes=()):
        self.shell = [(float(x), float(y)) for x, y in shell]
        if len(self.shell) < 4:
            raise MapperParsingException(
                "polygon shell requires at least 4 points (closed ring)")
        if self.shell[0] != self.shell[-1]:
            raise MapperParsingException("polygon ring must be closed")
        self.holes = [[(float(x), float(y)) for x, y in h] for h in holes]
        for h in self.holes:
            if len(h) < 4 or h[0] != h[-1]:
                raise MapperParsingException("polygon hole must be a closed ring")

    def bbox(self):
        xs = [p[0] for p in self.shell]
        ys = [p[1] for p in self.shell]
        return (min(xs), min(ys), max(xs), max(ys))

    def points(self):
        return self.shell[:-1]

    def segments(self):
        segs = list(zip(self.shell[:-1], self.shell[1:]))
        for h in self.holes:
            segs.extend(zip(h[:-1], h[1:]))
        return segs

    def rings(self):
        return [self]

    def _contains_point(self, pt) -> bool:
        if not _point_in_ring(pt, self.shell):
            return False
        for h in self.holes:
            # inside a hole = outside, unless on the hole's boundary
            if _point_in_ring(pt, h):
                on_boundary = any(
                    _seg_intersect(a, b, pt, pt)
                    for a, b in zip(h[:-1], h[1:]))
                if not on_boundary:
                    return False
        return True


class MultiPolygon(Shape):
    kind = "multipolygon"

    def __init__(self, polys):
        self.polys = [p if isinstance(p, Polygon) else Polygon(p[0], p[1:])
                      for p in polys]

    def bbox(self):
        bs = [p.bbox() for p in self.polys]
        return (min(b[0] for b in bs), min(b[1] for b in bs),
                max(b[2] for b in bs), max(b[3] for b in bs))

    def points(self):
        return [pt for p in self.polys for pt in p.points()]

    def segments(self):
        return [s for p in self.polys for s in p.segments()]

    def rings(self):
        return list(self.polys)


def envelope(top_left, bottom_right) -> Polygon:
    """GeoJSON-style envelope: [[minLon, maxLat], [maxLon, minLat]]."""
    min_lon, max_lat = float(top_left[0]), float(top_left[1])
    max_lon, min_lat = float(bottom_right[0]), float(bottom_right[1])
    return Polygon([(min_lon, min_lat), (max_lon, min_lat),
                    (max_lon, max_lat), (min_lon, max_lat),
                    (min_lon, min_lat)])


def circle(center, radius_m: float) -> Polygon:
    """Circle approximated as a CIRCLE_SIDES-gon (planar degrees)."""
    lon, lat = float(center[0]), float(center[1])
    dlat = math.degrees(radius_m / EARTH_RADIUS_M)
    dlon = dlat / max(math.cos(math.radians(lat)), 1e-6)
    pts = []
    for i in range(CIRCLE_SIDES):
        a = 2.0 * math.pi * i / CIRCLE_SIDES
        pts.append((lon + dlon * math.cos(a), lat + dlat * math.sin(a)))
    pts.append(pts[0])
    return Polygon(pts)


class GeometryCollection(Shape):
    kind = "geometrycollection"

    def __init__(self, shapes: List[Shape]):
        self.shapes = shapes
        if not shapes:
            raise MapperParsingException("geometrycollection requires shapes")

    def bbox(self):
        bs = [s.bbox() for s in self.shapes]
        return (min(b[0] for b in bs), min(b[1] for b in bs),
                max(b[2] for b in bs), max(b[3] for b in bs))

    def points(self):
        return [p for s in self.shapes for p in s.points()]

    def segments(self):
        return [seg for s in self.shapes for seg in s.segments()]

    def rings(self):
        return [r for s in self.shapes for r in s.rings()]


# ---------------------------------------------------------------------------
# parsing: GeoJSON + WKT
# ---------------------------------------------------------------------------

_DISTANCE_UNITS = {
    "m": 1.0, "meters": 1.0, "km": 1000.0, "kilometers": 1000.0,
    "mi": 1609.344, "miles": 1609.344, "yd": 0.9144, "ft": 0.3048,
    "in": 0.0254, "cm": 0.01, "mm": 0.001, "nmi": 1852.0, "nm": 1852.0,
}


def _parse_radius(value) -> float:
    if isinstance(value, (int, float)):
        return float(value)
    s = str(value).strip().lower()
    for unit in sorted(_DISTANCE_UNITS, key=len, reverse=True):
        if s.endswith(unit):
            return float(s[: -len(unit)]) * _DISTANCE_UNITS[unit]
    return float(s)


def parse_geojson(obj: dict) -> Shape:
    if not isinstance(obj, dict) or "type" not in obj:
        raise MapperParsingException(f"failed to parse geo_shape [{obj!r}]")
    t = str(obj["type"]).lower()
    coords = obj.get("coordinates")
    try:
        if t == "point":
            return Point(coords[0], coords[1])
        if t == "multipoint":
            return MultiPoint(coords)
        if t == "linestring":
            return LineString(coords)
        if t == "multilinestring":
            return MultiLineString(coords)
        if t == "polygon":
            return Polygon(coords[0], coords[1:])
        if t == "multipolygon":
            return MultiPolygon([(p[0], *p[1:]) for p in coords])
        if t == "envelope":
            return envelope(coords[0], coords[1])
        if t == "circle":
            if "radius" not in obj:
                raise MapperParsingException(
                    "circle geo_shape requires a [radius]")
            return circle(coords, _parse_radius(obj["radius"]))
        if t == "geometrycollection":
            return GeometryCollection(
                [parse_geojson(g) for g in obj.get("geometries", [])])
    except MapperParsingException:
        raise
    except Exception as e:
        raise MapperParsingException(
            f"failed to parse geo_shape [{t}]: {e}") from e
    raise MapperParsingException(f"unknown geo_shape type [{obj['type']}]")


def _wkt_coords(body: str) -> List[Tuple[float, float]]:
    out = []
    for pair in body.split(","):
        parts = pair.split()
        out.append((float(parts[0]), float(parts[1])))
    return out


def parse_wkt(text: str) -> Shape:
    """WKT subset: POINT, LINESTRING, POLYGON, MULTIPOINT, MULTILINESTRING,
    MULTIPOLYGON, ENVELOPE (BBOX), GEOMETRYCOLLECTION
    (common/geo/parsers/GeoWKTParser.java)."""
    s = text.strip()
    m = s.upper()
    try:
        if m.startswith("POINT"):
            inner = s[s.index("(") + 1: s.rindex(")")]
            return Point(*(_wkt_coords(inner)[0]))
        if m.startswith("MULTIPOINT"):
            inner = s[s.index("(") + 1: s.rindex(")")].replace("(", "").replace(")", "")
            return MultiPoint(_wkt_coords(inner))
        if m.startswith("LINESTRING"):
            inner = s[s.index("(") + 1: s.rindex(")")]
            return LineString(_wkt_coords(inner))
        if m.startswith("MULTILINESTRING"):
            inner = s[s.index("(") + 1: s.rindex(")")]
            lines = [_wkt_coords(part) for part in _split_rings(inner)]
            return MultiLineString(lines)
        if m.startswith("MULTIPOLYGON"):
            inner = s[s.index("(") + 1: s.rindex(")")]
            polys = []
            for poly_body in _split_groups(inner):
                rings = [_wkt_coords(r) for r in _split_rings(poly_body)]
                polys.append((rings[0], *rings[1:]))
            return MultiPolygon(polys)
        if m.startswith("POLYGON"):
            inner = s[s.index("(") + 1: s.rindex(")")]
            rings = [_wkt_coords(r) for r in _split_rings(inner)]
            return Polygon(rings[0], rings[1:])
        if m.startswith("ENVELOPE") or m.startswith("BBOX"):
            inner = s[s.index("(") + 1: s.rindex(")")]
            # ENVELOPE(minLon, maxLon, maxLat, minLat) — WKT order
            a = [float(x) for x in inner.split(",")]
            return envelope((a[0], a[2]), (a[1], a[3]))
        if m.startswith("GEOMETRYCOLLECTION"):
            inner = s[s.index("(") + 1: s.rindex(")")]
            return GeometryCollection(
                [parse_wkt(part) for part in _split_top_level(inner)])
    except MapperParsingException:
        raise
    except Exception as e:
        raise MapperParsingException(f"failed to parse WKT [{text}]: {e}") from e
    raise MapperParsingException(f"unknown WKT shape [{text}]")


def _split_rings(body: str) -> List[str]:
    """Split '(...),(...)' into ring bodies."""
    out, depth, start = [], 0, None
    for i, c in enumerate(body):
        if c == "(":
            if depth == 0:
                start = i + 1
            depth += 1
        elif c == ")":
            depth -= 1
            if depth == 0:
                out.append(body[start:i])
    return out


def _split_groups(body: str) -> List[str]:
    """Split '((..),(..)),((..))' into polygon bodies (depth-1 groups)."""
    out, depth, start = [], 0, None
    for i, c in enumerate(body):
        if c == "(":
            depth += 1
            if depth == 1:
                start = i + 1
        elif c == ")":
            if depth == 1:
                out.append(body[start:i])
            depth -= 1
    return out


def _split_top_level(body: str) -> List[str]:
    """Split a GEOMETRYCOLLECTION body on top-level commas."""
    out, depth, start = [], 0, 0
    for i, c in enumerate(body):
        if c == "(":
            depth += 1
        elif c == ")":
            depth -= 1
        elif c == "," and depth == 0:
            out.append(body[start:i])
            start = i + 1
    out.append(body[start:])
    return [p for p in (x.strip() for x in out) if p]


def parse_shape(value) -> Shape:
    if isinstance(value, str):
        return parse_wkt(value)
    return parse_geojson(value)
