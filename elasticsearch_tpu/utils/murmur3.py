"""MurmurHash3 x86 32-bit — the document routing hash.

Role model: ``Murmur3HashFunction``
(core/src/main/java/org/elasticsearch/cluster/routing/Murmur3HashFunction.java)
which hashes the routing key (UTF-16 code units in Java; we hash UTF-8
bytes, which only changes *which* shard a given id lands on, not the
uniformity) and ``OperationRouting.generateShardId``
(cluster/routing/OperationRouting.java:232): shard = floorMod(hash, num_shards).
"""

from __future__ import annotations

_M32 = 0xFFFFFFFF


def _rotl32(x: int, r: int) -> int:
    return ((x << r) | (x >> (32 - r))) & _M32


def _fmix32(h: int) -> int:
    h ^= h >> 16
    h = (h * 0x85EBCA6B) & _M32
    h ^= h >> 13
    h = (h * 0xC2B2AE35) & _M32
    h ^= h >> 16
    return h


def murmur3_32(data: bytes, seed: int = 0) -> int:
    """MurmurHash3_x86_32, returns signed 32-bit int (Java parity)."""
    c1, c2 = 0xCC9E2D51, 0x1B873593
    h1 = seed & _M32
    nblocks = len(data) // 4
    for i in range(nblocks):
        k1 = int.from_bytes(data[i * 4 : i * 4 + 4], "little")
        k1 = (k1 * c1) & _M32
        k1 = _rotl32(k1, 15)
        k1 = (k1 * c2) & _M32
        h1 ^= k1
        h1 = _rotl32(h1, 13)
        h1 = (h1 * 5 + 0xE6546B64) & _M32
    tail = data[nblocks * 4 :]
    k1 = 0
    if len(tail) >= 3:
        k1 ^= tail[2] << 16
    if len(tail) >= 2:
        k1 ^= tail[1] << 8
    if len(tail) >= 1:
        k1 ^= tail[0]
        k1 = (k1 * c1) & _M32
        k1 = _rotl32(k1, 15)
        k1 = (k1 * c2) & _M32
        h1 ^= k1
    h1 ^= len(data)
    h1 = _fmix32(h1)
    return h1 - (1 << 32) if h1 >= (1 << 31) else h1


def encode_id(doc_id: str) -> bytes:
    """The reference's binary _id term encoding
    (index/mapper/Uid.java:232 encodeId): positive-numeric ids pack two
    digits per nibble-pair behind a 0xfe marker, URL-base64 ids decode
    to their raw bytes (0xfd escape when ambiguous), everything else is
    0xff + UTF-8. The slice partition hash runs over THESE bytes."""
    if not doc_id:
        raise ValueError("Ids can't be empty")
    if doc_id.isascii() and doc_id.isdigit():
        out = bytearray([0xFE])
        for i in range(0, len(doc_id), 2):
            b1 = ord(doc_id[i]) - ord("0")
            b2 = (ord(doc_id[i + 1]) - ord("0")
                  if i + 1 < len(doc_id) else 0x0F)
            out.append((b1 << 4) | b2)
        return bytes(out)
    if _is_url_base64_without_padding(doc_id):
        import base64

        raw = base64.urlsafe_b64decode(doc_id + "=" * (-len(doc_id) % 4))
        if raw and raw[0] >= 0xFD:
            return bytes([0xFD]) + raw
        return raw
    return bytes([0xFF]) + doc_id.encode("utf-8")


def _is_url_base64_without_padding(doc_id: str) -> bool:
    n = len(doc_id)
    if n % 4 == 1:
        return False
    if n % 4 == 2 and doc_id[-1] not in "AQgw":
        return False
    if n % 4 == 3 and doc_id[-1] not in "AEIMQUYcgkosw048":
        return False
    return all(c.isascii() and (c.isalnum() or c in "-_") for c in doc_id)


def hash_slice_id(doc_id: str) -> int:
    """The slice partition hash (search/slice/TermsSliceQuery.java:80):
    murmur3_x86_32 over the ENCODED _id term bytes (Uid.encodeId) with
    the FIXED seed 7919 (StringHelper's default seed is
    startup-time-random, so the query pins its own). floorMod against
    slice ``max`` picks the slice."""
    return murmur3_32(encode_id(doc_id), seed=7919)


def hash_routing(routing: str) -> int:
    # the reference hashes the routing string's UTF-16LE char bytes, NOT
    # UTF-8 (Murmur3HashFunction.hash(String): bytesToHash[i*2]=(byte)c,
    # [i*2+1]=(byte)(c>>>8)) — matching it exactly keeps doc->shard
    # placement identical to an Elasticsearch cluster's
    return murmur3_32(routing.encode("utf-16-le"))


def shard_id_for(routing: str, num_shards: int, partition_size: int = 1,
                 partition_offset: int = 0) -> int:
    """floorMod(murmur3(routing) [+ offset], num_shards).

    ``partition_size`` mirrors ``index.routing_partition_size``
    (OperationRouting.java:244): a custom-routed doc may land on any of
    ``partition_size`` shards offset by a hash of its ``_id``.
    """
    h = hash_routing(routing)
    if partition_size > 1:
        h += partition_offset % partition_size
    return h % num_shards  # Python % is floorMod
