"""Geohash encoding (common/geo/GeoHashUtils in the reference)."""

from __future__ import annotations

_BASE32 = "0123456789bcdefghjkmnpqrstuvwxyz"


def encode(lat: float, lon: float, precision: int = 5) -> str:
    lat_lo, lat_hi = -90.0, 90.0
    lon_lo, lon_hi = -180.0, 180.0
    out = []
    bit = 0
    ch = 0
    even = True
    while len(out) < precision:
        if even:
            mid = (lon_lo + lon_hi) / 2
            if lon >= mid:
                ch = (ch << 1) | 1
                lon_lo = mid
            else:
                ch <<= 1
                lon_hi = mid
        else:
            mid = (lat_lo + lat_hi) / 2
            if lat >= mid:
                ch = (ch << 1) | 1
                lat_lo = mid
            else:
                ch <<= 1
                lat_hi = mid
        even = not even
        bit += 1
        if bit == 5:
            out.append(_BASE32[ch])
            bit = 0
            ch = 0
    return "".join(out)


def decode(geohash: str):
    """-> (lat, lon) of the cell center."""
    lat_lo, lat_hi = -90.0, 90.0
    lon_lo, lon_hi = -180.0, 180.0
    even = True
    for c in geohash:
        cd = _BASE32.index(c)
        for shift in range(4, -1, -1):
            bit = (cd >> shift) & 1
            if even:
                mid = (lon_lo + lon_hi) / 2
                if bit:
                    lon_lo = mid
                else:
                    lon_hi = mid
            else:
                mid = (lat_lo + lat_hi) / 2
                if bit:
                    lat_lo = mid
                else:
                    lat_hi = mid
            even = not even
    return ((lat_lo + lat_hi) / 2, (lon_lo + lon_hi) / 2)
