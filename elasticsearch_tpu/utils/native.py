"""ctypes bindings to the native analysis library (native/analysis.cpp).

Loads ``native/libestpu_native.so``; builds it with make/g++ on first use
if the toolchain is available. Every entry point has a pure-Python
fallback, and the native fast paths are ASCII-exact replicas (verified in
tests/test_native.py), so behavior is identical either way.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from typing import List, Optional, Tuple

import numpy as np

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))), "native")
_LIB_PATH = os.path.join(_NATIVE_DIR, "libestpu_native.so")

_lib = None
_load_attempted = False


def _try_load():
    global _lib, _load_attempted
    if _load_attempted:
        return _lib
    _load_attempted = True
    if not os.path.exists(_LIB_PATH) and os.path.exists(
        os.path.join(_NATIVE_DIR, "Makefile")
    ):
        try:
            subprocess.run(
                ["make", "-C", _NATIVE_DIR], check=True,
                capture_output=True, timeout=120,
            )
        except (subprocess.SubprocessError, FileNotFoundError, OSError):
            return None
    if not os.path.exists(_LIB_PATH):
        return None
    try:
        lib = ctypes.CDLL(_LIB_PATH)
    except OSError:
        return None
    lib.standard_tokenize_ascii.restype = ctypes.c_int
    lib.standard_tokenize_ascii.argtypes = [
        ctypes.c_char_p, ctypes.c_int, ctypes.c_char_p,
        ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_int32),
        ctypes.c_int,
    ]
    lib.whitespace_tokenize.restype = ctypes.c_int
    lib.whitespace_tokenize.argtypes = [
        ctypes.c_char_p, ctypes.c_int,
        ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_int32),
        ctypes.c_int,
    ]
    lib.murmur3_32.restype = ctypes.c_int32
    lib.murmur3_32.argtypes = [ctypes.c_char_p, ctypes.c_int, ctypes.c_uint32]
    lib.shard_ids_batch.restype = None
    lib.shard_ids_batch.argtypes = [
        ctypes.c_char_p, ctypes.POINTER(ctypes.c_int32), ctypes.c_int,
        ctypes.c_int32, ctypes.POINTER(ctypes.c_int32),
    ]
    _lib = lib
    return _lib


def available() -> bool:
    return _try_load() is not None


_MAX_TOKENS = 65536


def standard_tokenize_fast(text: str) -> Optional[List[Tuple[str, int, int]]]:
    """Lowercased \\w+ tokens with offsets, or None if the native path
    can't handle the input (non-ASCII) / isn't available."""
    lib = _try_load()
    if lib is None:
        return None
    raw = text.encode("utf-8", errors="surrogatepass")
    if len(raw) != len(text):  # non-ASCII
        return None
    out = ctypes.create_string_buffer(len(raw) or 1)
    starts = (ctypes.c_int32 * _MAX_TOKENS)()
    ends = (ctypes.c_int32 * _MAX_TOKENS)()
    n = lib.standard_tokenize_ascii(raw, len(raw), out, starts, ends, _MAX_TOKENS)
    if n < 0:
        return None
    lowered = out.raw[: len(raw)].decode("ascii", errors="replace")
    return [(lowered[starts[i]: ends[i]], starts[i], ends[i]) for i in range(n)]


def whitespace_tokenize_fast(text: str) -> Optional[List[Tuple[str, int, int]]]:
    lib = _try_load()
    if lib is None:
        return None
    raw = text.encode("utf-8")
    if len(raw) != len(text):
        return None  # byte offsets would diverge from str offsets
    starts = (ctypes.c_int32 * _MAX_TOKENS)()
    ends = (ctypes.c_int32 * _MAX_TOKENS)()
    n = lib.whitespace_tokenize(raw, len(raw), starts, ends, _MAX_TOKENS)
    return [(text[starts[i]: ends[i]], starts[i], ends[i]) for i in range(n)]


def murmur3_32_fast(data: bytes, seed: int = 0) -> Optional[int]:
    lib = _try_load()
    if lib is None:
        return None
    return int(lib.murmur3_32(data, len(data), seed))


def shard_ids_batch(routings: List[str], num_shards: int) -> Optional[np.ndarray]:
    """Vectorized doc->shard routing for bulk indexing."""
    lib = _try_load()
    if lib is None:
        return None
    # UTF-16LE: the reference's Murmur3HashFunction hashes the routing
    # string's char bytes little-endian (see utils/murmur3.hash_routing)
    encoded = [r.encode("utf-16-le") for r in routings]
    buf = b"".join(encoded)
    offsets = np.zeros(len(encoded) + 1, dtype=np.int32)
    np.cumsum([len(e) for e in encoded], out=offsets[1:])
    out = np.zeros(len(encoded), dtype=np.int32)
    lib.shard_ids_batch(
        buf, offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        len(encoded), num_shards,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
    )
    return out
