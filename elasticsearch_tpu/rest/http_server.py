"""HTTP server: the port-9200 front door.

Role model: ``Netty4HttpServerTransport`` (modules/transport-netty4/).
The reference's event-loop server maps to a threading HTTP server here —
the HTTP layer is control-plane I/O, never the perf path (queries spend
their time in compiled TPU programs; SURVEY.md §7.1). Content negotiation:
JSON bodies in/out; cat API emits text/plain.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional
from urllib.parse import parse_qsl, urlparse

from elasticsearch_tpu.rest.controller import RestController


class _Handler(BaseHTTPRequestHandler):
    controller: RestController = None  # set by serve()
    protocol_version = "HTTP/1.1"

    def _handle(self, method: str) -> None:
        parsed = urlparse(self.path)
        query = dict(parse_qsl(parsed.query, keep_blank_values=True))
        length = int(self.headers.get("Content-Length") or 0)
        body = self.rfile.read(length) if length else b""
        status, payload = self.controller.dispatch(
            method, parsed.path, query, body,
            content_type=self.headers.get("Content-Type"),
            headers=dict(self.headers.items()))
        from elasticsearch_tpu.common.deprecation import (
            collect_warnings,
            warning_header_value,
        )

        warnings = collect_warnings()
        if isinstance(payload, str):
            data = payload.encode("utf-8")
            ctype = "text/plain; charset=UTF-8"
        else:
            from elasticsearch_tpu.common.xcontent import (
                response_format,
                serialize,
            )

            fmt = response_format(query, self.headers.get("Accept"))
            data, ctype = serialize(payload, fmt, pretty="pretty" in query)
        self.send_response(status)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(data)))
        # echo the client's correlation id back (reference behavior:
        # X-Opaque-Id is a passthrough header — docs/OBSERVABILITY.md)
        opaque = self.headers.get("X-Opaque-Id")
        if opaque:
            self.send_header("X-Opaque-Id", opaque)
        # dispatch-collected response headers (rest/controller.py):
        # Retry-After on 429 rejections (docs/OVERLOAD.md)
        from elasticsearch_tpu.rest.controller import (
            collect_response_headers,
        )

        for name, value in collect_response_headers().items():
            self.send_header(name, value)
        for w in warnings:
            self.send_header("Warning", warning_header_value(w))
        self.end_headers()
        if method != "HEAD":
            self.wfile.write(data)

    def do_GET(self):
        self._handle("GET")

    def do_POST(self):
        self._handle("POST")

    def do_PUT(self):
        self._handle("PUT")

    def do_DELETE(self):
        self._handle("DELETE")

    def do_HEAD(self):
        self._handle("HEAD")

    def log_message(self, fmt, *args):  # quiet by default
        pass


class HttpServer:
    def __init__(self, node, host: str = "127.0.0.1", port: int = 9200):
        self.node = node
        self.controller = RestController(node)
        node.rest_controller = self.controller
        handler = type("BoundHandler", (_Handler,), {"controller": self.controller})
        self.server = ThreadingHTTPServer((host, port), handler)
        self.port = self.server.server_address[1]
        # the sniffer reads this from /_nodes/http (publish_address).
        # Wildcard binds fall back to loopback: hostname resolution can
        # yield 127.0.1.1 (Debian /etc/hosts) or stale-DNS addresses the
        # machine doesn't own, which would poison a sniffing client's
        # host list; multi-host deployments should bind a concrete
        # address (http.publish_host in the reference)
        publish_host = host if host not in ("", "0.0.0.0", "::") \
            else "127.0.0.1"
        node.http_publish_address = f"{publish_host}:{self.port}"
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self._thread = threading.Thread(target=self.server.serve_forever, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self.server.shutdown()
        self.server.server_close()
