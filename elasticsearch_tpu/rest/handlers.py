"""All REST handlers (the reference registers 105 in ActionModule:332).

Grouped like the reference: document CRUD, search family, index admin,
cluster admin, cat API, ingest, snapshots, tasks, scripts. Handlers are
(node, request) -> (status, payload). The cat API returns text tables
(rest/action/cat/RestTable) unless ?format=json.
"""

from __future__ import annotations

import time
from typing import List, Tuple

from elasticsearch_tpu.common.errors import (
    ActionRequestValidationException,
    IllegalArgumentException,
    VersionConflictEngineException,
)
from elasticsearch_tpu.version import __version__


def register_all(c) -> None:
    r = c.register
    # --- root ---
    r("GET", "/", _root)
    r("HEAD", "/", lambda n, q: (200, {}))

    # --- document CRUD ---
    r("PUT", "/{index}/_doc/{id}", _index_doc)
    r("POST", "/{index}/_doc/{id}", _index_doc)
    r("POST", "/{index}/_doc", _index_doc_auto_id)
    r("POST", "/{index}/{type}", _index_doc_auto_id)
    r("GET", "/{index}/_doc/{id}", _get_doc)
    r("HEAD", "/{index}/_doc/{id}", _head_doc)
    r("DELETE", "/{index}/_doc/{id}", _delete_doc)
    r("POST", "/{index}/_update/{id}", _update_doc)
    r("GET", "/{index}/_source/{id}", _get_source)
    # 6.x typed forms
    r("PUT", "/{index}/{type}/{id}", _index_doc)
    r("POST", "/{index}/{type}/{id}", _index_doc)
    r("GET", "/{index}/{type}/{id}", _get_doc)
    r("HEAD", "/{index}/{type}/{id}", _head_doc)
    r("DELETE", "/{index}/{type}/{id}", _delete_doc)
    r("POST", "/{index}/{type}/{id}/_update", _update_doc)
    r("PUT", "/{index}/{type}/{id}/_create", _create_doc)
    r("POST", "/{index}/{type}/{id}/_create", _create_doc)
    r("PUT", "/{index}/_create/{id}", _create_doc)
    r("POST", "/{index}/_create/{id}", _create_doc)
    r("GET", "/{index}/{type}/{id}/_explain", _explain)
    r("POST", "/{index}/{type}/{id}/_explain", _explain)
    r("GET", "/{index}/{type}/{id}/_source", _get_source)
    r("POST", "/_mget", _mget)
    r("POST", "/{index}/_mget", _mget)
    r("POST", "/{index}/{type}/_mget", _mget)
    # explicit literal route: "/{index}/_doc/{id}" is MORE specific than
    # "/{index}/{type}/_mget", so type "_doc" would otherwise index the
    # mget body as a document with _id "_mget"
    r("POST", "/{index}/_doc/_mget", _mget)
    r("GET", "/_mget", _mget)
    r("GET", "/{index}/{type}/_mget", _mget)
    r("GET", "/{index}/_doc/_mget", _mget)

    # --- bulk ---
    r("POST", "/_bulk", _bulk)
    r("PUT", "/_bulk", _bulk)
    r("POST", "/{index}/_bulk", _bulk)

    # --- search family (typed 6.x forms included) ---
    r("GET", "/{index}/{type}/_search", _search)
    r("POST", "/{index}/{type}/_search", _search)
    r("GET", "/{index}/{type}/_count", _count)
    r("POST", "/{index}/{type}/_count", _count)
    r("GET", "/_search", _search)
    r("POST", "/_search", _search)
    r("GET", "/{index}/_search", _search)
    r("POST", "/{index}/_search", _search)
    r("POST", "/_search/scroll", _scroll)
    r("GET", "/_search/scroll", _scroll)
    r("POST", "/_search/scroll/{scroll_id}", _scroll)
    r("GET", "/_search/scroll/{scroll_id}", _scroll)
    r("DELETE", "/_search/scroll", _clear_scroll)
    r("DELETE", "/_search/scroll/{scroll_id}", _clear_scroll)
    r("POST", "/_msearch", _msearch)
    r("GET", "/_msearch", _msearch)
    r("POST", "/{index}/_msearch", _msearch)
    r("GET", "/_count", _count)
    r("POST", "/_count", _count)
    r("GET", "/{index}/_count", _count)
    r("POST", "/{index}/_count", _count)
    r("GET", "/{index}/_validate/query", _validate_query)
    r("POST", "/{index}/_validate/query", _validate_query)
    r("GET", "/_field_caps", _field_caps)
    r("POST", "/_field_caps", _field_caps)
    r("GET", "/{index}/_field_caps", _field_caps)
    r("POST", "/{index}/_field_caps", _field_caps)
    r("GET", "/{index}/_explain/{id}", _explain)
    r("POST", "/{index}/_explain/{id}", _explain)

    # --- templates / termvectors / rollover / shrink / hot_threads ---
    r("GET", "/_search/template", _search_template)
    r("POST", "/_search/template", _search_template)
    r("GET", "/{index}/_search/template", _search_template)
    r("POST", "/{index}/_search/template", _search_template)
    r("GET", "/_render/template", _render_template)
    r("POST", "/_render/template", _render_template)
    r("GET", "/{index}/_termvectors/{id}", _termvectors)
    r("POST", "/{index}/_termvectors/{id}", _termvectors)
    r("GET", "/{index}/{type}/{id}/_termvectors", _termvectors)
    r("POST", "/{index}/_rollover", _rollover)
    r("POST", "/{index}/_rollover/{new_index}", _rollover)
    r("POST", "/{index}/_shrink/{target}", _shrink)
    r("PUT", "/{index}/_shrink/{target}", _shrink)
    r("GET", "/_nodes/hot_threads", lambda n, q: (200, n.hot_threads()))
    r("GET", "/_nodes/{node_id}/hot_threads", lambda n, q: (200, n.hot_threads()))
    # zero-downtime rollout (ISSUE 14, docs/RESILIENCE.md "Rollout &
    # drain"): enter/abort the draining state — the operator's (or the
    # orchestrator's preStop hook's) API for a graceful restart
    r("POST", "/_nodes/_local/_drain", lambda n, q: (200, n.drain()))
    r("DELETE", "/_nodes/_local/_drain", lambda n, q: (200, n.undrain()))

    # --- reindex family ---
    r("POST", "/_reindex", _reindex)
    r("POST", "/{index}/_update_by_query", _update_by_query)
    r("POST", "/{index}/_delete_by_query", _delete_by_query)

    # --- index admin ---
    r("PUT", "/{index}", _create_index)
    r("DELETE", "/{index}", _delete_index)
    r("GET", "/{index}", _get_index)
    r("HEAD", "/{index}", _head_index)
    r("POST", "/{index}/_open", lambda n, q: (200, n.open_index(q.param("index"))))
    r("POST", "/{index}/_close", lambda n, q: (200, n.close_index(q.param("index"))))
    r("POST", "/{index}/_refresh", _refresh)
    r("GET", "/{index}/_refresh", _refresh)
    r("POST", "/_refresh", _refresh)
    r("POST", "/{index}/_flush", _flush)
    r("GET", "/{index}/_flush", _flush)
    r("POST", "/_flush", _flush)
    # synced flush: durability already implies a sync point here, so it
    # degrades to a flush with the sync-shaped response
    r("POST", "/{index}/_flush/synced", _flush_synced)
    r("POST", "/_flush/synced", _flush_synced)
    r("GET", "/{index}/_flush/synced", _flush_synced)
    r("POST", "/{index}/_forcemerge", _forcemerge)
    r("POST", "/_forcemerge", _forcemerge)
    r("GET", "/{index}/_stats", _index_stats)
    r("GET", "/_stats", _index_stats)
    r("GET", "/{index}/_stats/{metric}", _index_stats)
    r("GET", "/_stats/{metric}", _index_stats)
    r("GET", "/{index}/_segments", _segments)
    r("GET", "/_segments", _segments)
    r("PUT", "/{index}/_mapping", _put_mapping)
    r("PUT", "/{index}/_mapping/{type}", _put_mapping)
    r("POST", "/{index}/_mapping", _put_mapping)
    r("GET", "/{index}/_mapping", _get_mapping)
    r("GET", "/_mapping", _get_mapping)
    r("GET", "/{index}/_mapping/{type}", _get_mapping)
    r("PUT", "/{index}/_settings", _put_index_settings)
    r("PUT", "/_settings", _put_index_settings)
    r("GET", "/{index}/_settings", _get_index_settings)
    r("GET", "/_settings", _get_index_settings)
    r("GET", "/{index}/_settings/{setting}", _get_index_settings)
    r("GET", "/_settings/{setting}", _get_index_settings)
    r("GET", "/_analyze", _analyze)
    r("POST", "/_analyze", _analyze)
    r("GET", "/{index}/_analyze", _analyze)
    r("POST", "/{index}/_analyze", _analyze)
    r("POST", "/_aliases", _update_aliases)
    r("GET", "/_alias", _get_alias)
    r("GET", "/_alias/{name}", _get_alias)
    r("GET", "/{index}/_alias", _get_alias)
    r("GET", "/{index}/_alias/{name}", _get_alias)
    r("PUT", "/{index}/_alias/{name}", _put_alias)
    r("DELETE", "/{index}/_alias/{name}", _delete_alias)
    r("HEAD", "/_alias/{name}", _head_alias)
    r("HEAD", "/{index}/_alias/{name}", _head_alias)
    r("PUT", "/_template/{name}", _put_template)
    r("GET", "/_template", _get_template)
    r("GET", "/_template/{name}", _get_template)
    r("DELETE", "/_template/{name}", _delete_template)
    r("HEAD", "/_template/{name}", _head_template)
    r("POST", "/{index}/_cache/clear", _clear_cache)
    r("POST", "/_cache/clear", _clear_cache)

    # --- cluster admin ---
    r("GET", "/_cluster/health", lambda n, q: (200, n.health()))
    r("GET", "/_cluster/health/{index}", lambda n, q: (200, n.health()))
    r("GET", "/_cluster/state", _cluster_state)
    r("GET", "/_cluster/state/{metrics}", _cluster_state)
    r("GET", "/_cluster/stats", lambda n, q: (200, n.cluster_stats()))
    r("GET", "/_cluster/settings", _get_cluster_settings)
    r("PUT", "/_cluster/settings", lambda n, q: (200, n.put_cluster_settings(q.json_body({}))))
    r("POST", "/_cluster/reroute", lambda n, q: (200, n.reroute(
        q.json_body({}) or {},
        dry_run=q.bool_param("dry_run", False),
        explain=q.bool_param("explain", False))))
    r("GET", "/_cluster/allocation/explain", _allocation_explain)
    r("GET", "/_nodes", lambda n, q: (200, n.node_info()))
    r("GET", "/_nodes/stats", lambda n, q: (200, n.node_stats()))
    r("GET", "/_nodes/stats/{metric}", lambda n, q: (200, n.node_stats()))
    r("GET", "/_nodes/stats/{metric}/{index_metric}",
      lambda n, q: (200, n.node_stats()))
    r("GET", "/_nodes/{node_id}", lambda n, q: (200, n.node_info()))
    r("GET", "/_nodes/{node_id}/stats", lambda n, q: (200, n.node_stats()))
    r("GET", "/_nodes/{node_id}/stats/{metric}",
      lambda n, q: (200, n.node_stats()))
    r("GET", "/_nodes/{node_id}/stats/{metric}/{index_metric}",
      lambda n, q: (200, n.node_stats()))
    r("GET", "/_remote/info", lambda n, q: (200, n.remote_clusters.info()))

    # --- tasks ---
    r("GET", "/_tasks", lambda n, q: (200, n.tasks.list_tasks(q.param("actions"))))
    r("GET", "/_tasks/{task_id}", _get_task)
    r("POST", "/_tasks/{task_id}/_cancel", _cancel_task)

    # --- scripts ---
    r("PUT", "/_scripts/{id}", lambda n, q: (200, n.put_stored_script(
        q.param("id"), q.json_body({}))))
    r("GET", "/_scripts/{id}", lambda n, q: (200, n.get_stored_script(q.param("id"))))
    r("DELETE", "/_scripts/{id}", _delete_script)

    # --- ingest ---
    r("PUT", "/_ingest/pipeline/{id}", lambda n, q: (200, n.ingest.put_pipeline(
        q.param("id"), q.json_body({}))))
    r("GET", "/_ingest/pipeline", lambda n, q: (200, n.ingest.get_pipeline()))
    r("GET", "/_ingest/pipeline/{id}", lambda n, q: (200, n.ingest.get_pipeline(q.param("id"))))
    r("DELETE", "/_ingest/pipeline/{id}", lambda n, q: (200, n.ingest.delete_pipeline(q.param("id"))))
    r("POST", "/_ingest/pipeline/_simulate", lambda n, q: (200, n.ingest.simulate(q.json_body({}))))
    r("GET", "/_ingest/pipeline/_simulate", lambda n, q: (200, n.ingest.simulate(q.json_body({}))))
    r("POST", "/_ingest/pipeline/{id}/_simulate", _simulate_pipeline_by_id)

    # --- snapshots ---
    r("PUT", "/_snapshot/{repo}", lambda n, q: (200, n.snapshots.put_repository(
        q.param("repo"), q.json_body({}))))
    r("POST", "/_snapshot/{repo}", lambda n, q: (200, n.snapshots.put_repository(
        q.param("repo"), q.json_body({}))))
    r("GET", "/_snapshot", lambda n, q: (200, n.snapshots.get_repository()))
    r("GET", "/_snapshot/{repo}", lambda n, q: (200, n.snapshots.get_repository(q.param("repo"))))
    r("DELETE", "/_snapshot/{repo}", lambda n, q: (200, n.snapshots.delete_repository(q.param("repo"))))
    r("PUT", "/_snapshot/{repo}/{snapshot}", lambda n, q: (200, n.snapshots.create_snapshot(
        q.param("repo"), q.param("snapshot"), q.json_body({}),
        wait_for_completion=q.bool_param("wait_for_completion", True))))
    r("GET", "/_snapshot/{repo}/_status", lambda n, q: (200, n.snapshots.snapshot_status(
        q.param("repo"))))
    r("GET", "/_snapshot/{repo}/{snapshot}/_status", lambda n, q: (200, n.snapshots.snapshot_status(
        q.param("repo"), q.param("snapshot"))))
    r("GET", "/_snapshot/{repo}/{snapshot}", lambda n, q: (200, n.snapshots.get_snapshot(
        q.param("repo"), q.param("snapshot"))))
    r("DELETE", "/_snapshot/{repo}/{snapshot}", lambda n, q: (200, n.snapshots.delete_snapshot(
        q.param("repo"), q.param("snapshot"))))
    r("POST", "/_snapshot/{repo}/{snapshot}/_restore", lambda n, q: (200, n.snapshots.restore_snapshot(
        q.param("repo"), q.param("snapshot"), q.json_body({}))))
    # repository verification probe (ISSUE 16): write/read/delete a
    # probe blob and report the nodes that could see it
    r("POST", "/_snapshot/{repo}/_verify", lambda n, q: (200, n.snapshots.verify_repository(
        q.param("repo"))))

    # --- cat API (rest/action/cat/, 22 handlers in the reference) ---
    r("GET", "/_cat", _cat_help)
    r("GET", "/_cat/indices", _cat_indices)
    r("GET", "/_cat/indices/{index}", _cat_indices)
    r("GET", "/_cat/health", _cat_health)
    r("GET", "/_cat/nodes", _cat_nodes)
    r("GET", "/_cat/shards", _cat_shards)
    r("GET", "/_cat/shards/{index}", _cat_shards)
    r("GET", "/_cat/staging", _cat_staging)
    r("GET", "/_cat/count", _cat_count)
    r("GET", "/_cat/count/{index}", _cat_count)
    r("GET", "/_cat/aliases", _cat_aliases)
    r("GET", "/_cat/aliases/{name}", _cat_aliases)
    r("GET", "/_cat/templates", _cat_templates)
    r("GET", "/_cat/templates/{name}", _cat_templates)
    r("GET", "/_cat/master", _cat_master)
    r("GET", "/_cat/segments", _cat_segments)
    r("GET", "/_cat/plugins", lambda n, q: _cat_table(
        q,
        [[n.node_id, n.node_name, p["name"], p["version"], "-"]
         for p in n.plugins_service.info()],
        ["id", "name", "component", "version", "description"]))
    r("GET", "/_cat/tasks", _cat_tasks)
    r("GET", "/_cat/pending_tasks", lambda n, q: _cat_table(
        q, [], ["insertOrder", "timeInQueue", "priority", "source"]))
    r("GET", "/_cat/allocation", _cat_allocation)
    r("GET", "/_cat/recovery", _cat_recovery)
    r("GET", "/_cat/thread_pool", _cat_thread_pool)
    r("GET", "/_cat/fielddata", lambda n, q: _cat_table(
        q, [], ["id", "host", "ip", "node", "field", "size"]))
    r("GET", "/_cat/fielddata/{fields}", lambda n, q: _cat_table(
        q, [], ["id", "host", "ip", "node", "field", "size"]))
    r("GET", "/_cat/nodeattrs", lambda n, q: _cat_table(
        q, [], ["node", "id", "pid", "host", "ip", "port", "attr", "value"]))
    r("GET", "/_cat/repositories", _cat_repositories)
    r("GET", "/_cat/snapshots/{repo}", _cat_snapshots)


# ---------------------------------------------------------------------------
# Root / info
# ---------------------------------------------------------------------------


def _root(node, req):
    return 200, {
        "name": node.node_name,
        "cluster_name": node.cluster_service.state.cluster_name,
        "cluster_uuid": node.node_id,
        "version": {
            "number": __version__,
            "lucene_version": "tpu-block-packed-1",
            "build_flavor": "tpu",
        },
        "tagline": "You Know, for Search (on TPUs)",
    }


# ---------------------------------------------------------------------------
# Document CRUD
# ---------------------------------------------------------------------------


_DEPRECATION = None


def _typed_api_warning(req) -> None:
    """Custom type names in document API paths are deprecated
    (6.x single-type enforcement, DeprecationLogger usage in
    RestIndexAction et al.)."""
    global _DEPRECATION
    t = req.param("type")
    if t is not None and t != "_doc":
        if _DEPRECATION is None:
            from elasticsearch_tpu.common.deprecation import DeprecationLogger

            _DEPRECATION = DeprecationLogger("rest.typed_api")
        _DEPRECATION.deprecated(
            "specifying a custom type in document API paths is deprecated; "
            "use /{index}/_doc/{id} instead")


def _doc_type_of(node, index):
    svc = node.indices.get(index)
    return getattr(svc, "doc_type", "_doc") if svc is not None else "_doc"


def _echo_type(req, r, node=None):
    """6.x typed-path compatibility: document API responses echo the
    type from the request path (custom types are deprecated but legal);
    type `_all` resolves to the index's actual type."""
    if isinstance(r, dict):
        t = req.param("type")
        if (t is None or t == "_all") and node is not None:
            t = _doc_type_of(node, req.param("index"))
        r["_type"] = t or "_doc"
    return r


def _write_shards_header(node, req, r):
    """Single-doc write responses carry the replication-group header
    (ReplicationResponse.ShardInfo): total = 1 primary + replicas."""
    if isinstance(r, dict) and "_shards" not in r:
        try:
            svc = node.index_service(req.param("index"))
            total = 1 + svc.num_replicas
        except Exception:  # noqa: BLE001 — header is best-effort
            total = 1
        r["_shards"] = {"total": total, "successful": 1, "failed": 0}
    return r


def _forced_refresh(req, r):
    """refresh=true responses carry forced_refresh
    (TransportWriteAction.WriteResponse.setForcedRefresh)."""
    if isinstance(r, dict) and req.param("refresh") in ("", "true", True):
        r["forced_refresh"] = True
    return r


def _validate_type_param(req):
    """MapperService.validateTypeName: type names can't start with '_'
    (only the canonical _doc is allowed)."""
    t = req.param("type")
    if t is not None and t.startswith("_") and t != "_doc":
        raise IllegalArgumentException(
            f"Document mapping type name can't start with '_', "
            f"found: [{t}]")


def _record_doc_type(node, req):
    """6.x first-write-wins type naming: indexing through a typed path
    onto an index whose type is still the default records the custom
    name, so later responses echo it (even via untyped/_all paths)."""
    t = req.param("type")
    if t in (None, "_doc", "_all"):
        return
    try:
        svc = node.index_service(req.param("index"))
    except Exception:
        return
    if svc.doc_type == "_doc":
        svc.doc_type = t


def _parent_routing(node, req):
    """Legacy ``_parent`` metadata field (ParentFieldMapper): the
    ``parent`` param acts as the routing value, and a parent-mapped type
    REQUIRES parent/routing on every single-doc op
    (RoutingMissingException). Returns (effective_routing, parent)."""
    from elasticsearch_tpu.common.errors import RoutingMissingException

    routing = req.param("routing")
    parent = req.param("parent")
    eff = routing if routing is not None else parent
    if eff is None:
        svc = node.indices.get(req.param("index"))
        if (svc is not None
                and svc.mapper_service.parent_type is not None):
            raise RoutingMissingException(
                svc.doc_type or "_doc", req.param("id") or "")
    return eff, parent


def _record_parent(node, req, doc_id, parent):
    if parent is None or doc_id is None:
        return
    svc = node.indices.get(req.param("index"))
    if svc is not None:
        svc.parents[str(doc_id)] = str(parent)


def _index_doc(node, req, force_create: bool = False):
    _validate_type_param(req)
    _typed_api_warning(req)
    body = req.json_body()
    if body is None:
        raise ActionRequestValidationException(
            "request body is required")
    kw = {}
    if req.param("version") is not None:
        kw["version"] = int(req.param("version"))
        kw["version_type"] = req.param("version_type", "internal")
    if force_create or req.param("op_type") == "create":
        kw["op_type"] = "create"
    routing, parent = _parent_routing(node, req)
    r = node.index_doc(req.param("index"), req.param("id"), body,
                       routing=routing, refresh=req.param("refresh"),
                       pipeline=req.param("pipeline"),
                       wait_for_active_shards=req.param("wait_for_active_shards"),
                       parent=parent, **kw)
    _record_parent(node, req, r.get("_id"), parent)
    _record_doc_type(node, req)
    _echo_type(req, _forced_refresh(req, _write_shards_header(node, req, r)))
    return (201 if r.get("result") == "created" else 200), r


def _create_doc(node, req):
    return _index_doc(node, req, force_create=True)


def _index_doc_auto_id(node, req):
    t = req.param("type")
    if t is not None:
        # the POST /{index}/{type} route would otherwise swallow typoed
        # or unregistered /{index}/_endpoint POSTs as documents: type
        # names may not start with '_' (MapperService.validateTypeName)
        _validate_type_param(req)
        _typed_api_warning(req)
    body = req.json_body()
    if body is None:
        raise ActionRequestValidationException("Validation Failed: 1: source is missing;")
    routing, parent = _parent_routing(node, req)
    r = node.index_doc(req.param("index"), None, body,
                       routing=routing, refresh=req.param("refresh"),
                       pipeline=req.param("pipeline"),
                       wait_for_active_shards=req.param("wait_for_active_shards"),
                       parent=parent)
    _record_parent(node, req, r.get("_id"), parent)
    _record_doc_type(node, req)
    _echo_type(req, _forced_refresh(req, _write_shards_header(node, req, r)))
    return 201, r


def _apply_source_filtering(req, r):
    """_source=false / _source=a,b / _source_include(s) / _source_exclude(s)
    on single-doc GETs (FetchSourceContext.parseFromRestRequest) — same
    filter_source the search fetch phase uses, so dotted paths and
    wildcards behave identically on both surfaces."""
    if not isinstance(r, dict) or "_source" not in r:
        return r
    from elasticsearch_tpu.search.service import filter_source

    src_param = req.param("_source")
    includes = req.param("_source_includes") or req.param("_source_include")
    excludes = req.param("_source_excludes") or req.param("_source_exclude")
    if src_param is None and includes is None and excludes is None:
        return r
    if src_param is not None and src_param.lower() == "false":
        del r["_source"]
        return r
    if src_param is not None and src_param.lower() != "true":
        includes = src_param
    inc = [f.strip() for f in includes.split(",")] if includes else None
    exc = [f.strip() for f in excludes.split(",")] if excludes else None
    r["_source"] = filter_source(r["_source"], inc, exc)
    return r


def _realtime_params(req):
    rt = req.param("realtime")
    return {
        "realtime": not (rt is not None and rt.lower() == "false"),
        "refresh": req.param("refresh"),
    }


def _get_doc(node, req):
    _typed_api_warning(req)
    routing, _parent = _parent_routing(node, req)
    r = node.get_doc(req.param("index"), req.param("id"),
                     routing, **_realtime_params(req))
    if r["found"] and req.param("version") is not None:
        # GetRequest version check: reading a stale version conflicts
        try:
            want = int(req.param("version"))
        except ValueError:
            raise IllegalArgumentException(
                f"failed to parse version [{req.param('version')}]") from None
        have = r.get("_version")
        # reads conflict on ANY mismatch for every version_type
        # (VersionType.isVersionConflictForReads: only equality passes)
        ok = (want == have)
        if not ok:
            raise VersionConflictEngineException(
                req.param("id"), have, want)
    stored = req.param("stored_fields")
    if r["found"] and stored is not None:
        wanted = [f for f in str(stored).split(",") if f]
        src = r.get("_source") or {}
        svc = node.index_service(req.param("index"))
        fields = {}
        for f in wanted:
            if f == "_source":
                continue
            if f == "_parent":
                p = svc.parents.get(str(req.param("id")))
                if p is not None:
                    r["_parent"] = p
                continue
            if f == "_routing":
                continue  # node.get_doc already set the stored value
            ft = svc.mapper_service.field_type(f)
            if (ft is None or not ft.params.get("store", False)
                    or f not in src):
                continue
            v = src[f]
            fields[f] = v if isinstance(v, list) else [v]
        if fields:
            r["fields"] = fields
        if "_source" not in wanted:
            r.pop("_source", None)
    _echo_type(req, _apply_source_filtering(req, r), node)
    return (200 if r["found"] else 404), r


def _head_doc(node, req):
    routing, _parent = _parent_routing(node, req)
    r = node.get_doc(req.param("index"), req.param("id"),
                     routing, **_realtime_params(req))
    return (200 if r["found"] else 404), {}


def _get_source(node, req):
    routing, _parent = _parent_routing(node, req)
    r = node.get_doc(req.param("index"), req.param("id"),
                     routing, **_realtime_params(req))
    if not r["found"]:
        return 404, {}
    _apply_source_filtering(req, r)
    return 200, r.get("_source", {})


def _delete_doc(node, req):
    _typed_api_warning(req)
    kw = {}
    if req.param("version") is not None:
        kw["version"] = int(req.param("version"))
        kw["version_type"] = req.param("version_type", "internal")
    routing, _parent = _parent_routing(node, req)
    r = node.delete_doc(req.param("index"), req.param("id"),
                        routing=routing,
                        refresh=req.param("refresh"), **kw)
    _echo_type(req, _forced_refresh(req, _write_shards_header(node, req, r)))
    return (200 if r.get("found") else 404), r


def _update_doc(node, req):
    _typed_api_warning(req)
    routing, parent = _parent_routing(node, req)
    version = req.param("version")
    if version is not None and req.param(
            "version_type", "internal") != "internal":
        # UpdateRequest.validate(): only internal versioning applies
        raise ActionRequestValidationException(
            "Validation Failed: 1: version type [force/external] is not "
            "supported by the update API;")
    r = node.update_doc(req.param("index"), req.param("id"), req.json_body({}),
                        routing=routing, refresh=req.param("refresh"),
                        version=int(version) if version is not None else None)
    _record_parent(node, req, r.get("_id"), parent)
    _echo_type(req, _forced_refresh(req, _write_shards_header(node, req, r)))
    src_param = req.param("_source")
    want_get = (req.param("fields")
                or (src_param is not None and src_param.lower() != "false"))
    if want_get and r.get("result") != "noop":
        from elasticsearch_tpu.search.service import filter_source

        g = node.get_doc(req.param("index"), req.param("id"),
                         req.param("routing"))
        if g.get("found"):
            src = g["_source"]
            if src_param and src_param.lower() != "true":
                src = filter_source(src, src_param.split(","), None)
            get_sec = {"found": True, "_source": src}
            if req.param("fields"):
                want = req.param("fields").split(",")
                get_sec["fields"] = {f: [g["_source"][f]]
                                     for f in want if f in g["_source"]}
            r["get"] = get_sec
    return 200, r


def _mget(node, req):
    rp = _realtime_params(req)
    stored = req.param("stored_fields")
    return 200, node.mget(req.json_body({}), req.param("index"),
                          req.param("type"), realtime=rp["realtime"],
                          refresh=rp["refresh"],
                          stored_fields=([f for f in str(stored).split(",")
                                          if f] if stored else None))


def _bulk(node, req):
    lines = req.ndjson_lines()
    if not lines:
        raise ActionRequestValidationException("request body is required")
    default_index = req.param("index")
    ops = []
    i = 0
    while i < len(lines):
        action_line = lines[i]
        if not action_line:
            # an empty {} action object (BulkRequest.add: the parser
            # expects the action FIELD_NAME immediately)
            raise IllegalArgumentException(
                f"Malformed action/metadata line [{i + 1}], expected "
                f"FIELD_NAME but found [END_OBJECT]")
        ((action, meta),) = action_line.items()
        meta = dict(meta or {})
        meta.setdefault("_index", default_index)
        i += 1
        if action in ("index", "create", "update"):
            if i >= len(lines):
                raise ActionRequestValidationException(
                    "Validation Failed: 1: no requests added;"
                )
            ops.append((action, meta, lines[i]))
            i += 1
        else:
            ops.append((action, meta, None))
    resp = node.bulk(ops, refresh=req.param("refresh"), pipeline=req.param("pipeline"))
    return 200, resp


# ---------------------------------------------------------------------------
# Search family
# ---------------------------------------------------------------------------


def _search_body(req):
    body = req.json_body({}) or {}
    # URI search: ?q=...&size=...&from=...&sort=f:asc
    q = req.param("q")
    if q is not None:
        qs = {"query": q}
        for name, key in (("df", "default_field"),
                          ("default_operator", "default_operator"),
                          ("analyzer", "analyzer")):
            if req.param(name) is not None:
                qs[key] = req.param(name)
        if req.param("lenient") is not None:
            qs["lenient"] = req.bool_param("lenient")
        body["query"] = {"query_string": qs}
    for p in ("size", "from"):
        if req.param(p) is not None:
            body[p] = int(req.param(p))
    # query-phase fault-tolerance params (RestSearchAction): a deadline
    # on the query phase and the partial-results degradation policy
    if req.param("timeout") is not None:
        body["timeout"] = req.param("timeout")
    if req.param("allow_partial_search_results") is not None:
        body["allow_partial_search_results"] = req.bool_param(
            "allow_partial_search_results")
    if req.param("track_total_hits") is not None:
        # boolean OR the reference's integer-threshold form; an explicit
        # false is the default behavior, so the key is simply not set
        # (setting it would needlessly demote the request off the
        # batchable fast path)
        raw = req.param("track_total_hits")
        try:
            body["track_total_hits"] = int(raw)
        except (TypeError, ValueError):
            if req.bool_param("track_total_hits"):
                body["track_total_hits"] = True
    if req.param("sort") is not None:
        sort = []
        for part in req.param("sort").split(","):
            if ":" in part:
                f, o = part.split(":", 1)
                sort.append({f: o})
            else:
                sort.append(part)
        body["sort"] = sort
    if req.param("_source") is not None:
        v = req.param("_source")
        body["_source"] = False if v == "false" else (True if v == "true" else v.split(","))
    return body


def _search(node, req):
    body = _search_body(req)
    resp = node.search(req.param("index", "_all"), body,
                       scroll=req.param("scroll"))
    _echo_hit_types(node, resp)
    _render_total_hits(resp, body)
    return 200, resp


def _render_total_hits(resp, body) -> None:
    """track_total_hits-style REST surfacing of inexact totals: the 6.x
    response keeps ``hits.total`` a bare int, but block-max pruned
    scoring (docs/PRUNING.md) and hybrid kNN fusion (docs/VECTOR.md)
    report LOWER BOUNDS — previously visible only through the
    response-internal ``_pruned``/``_total_relation`` markers. Whenever
    the total is inexact, or the request explicitly asked with
    ``track_total_hits``, it renders as the modern object form
    ``{"value": N, "relation": "eq"|"gte"}``. Passing
    ``track_total_hits: true`` (or the reference's integer-threshold
    form — totals here are exact whenever the count ran exhaustively,
    so any positive threshold is satisfied) also forces the EXACT
    total: the key is outside the pruned fast path's allowed body keys,
    so such requests execute exhaustively by construction."""
    hits = (resp or {}).get("hits")
    if not isinstance(hits, dict) or not isinstance(hits.get("total"), int):
        return
    relation = "eq"
    pruned = resp.get("_pruned")
    if isinstance(pruned, dict) and pruned.get("total_relation"):
        relation = str(pruned["total_relation"])
    elif resp.get("_total_relation") == "gte":
        relation = "gte"
    tth = (body or {}).get("track_total_hits")
    opted_in = tth is True or (isinstance(tth, int)
                               and not isinstance(tth, bool) and tth > 0)
    if relation != "eq" or opted_in:
        hits["total"] = {"value": hits["total"], "relation": relation}


def _echo_hit_types(node, resp):
    """Hits echo their index's 6.x type name (custom types deprecated)."""
    for hit in (resp.get("hits", {}) or {}).get("hits", []):
        if isinstance(hit, dict) and hit.get("_type") == "_doc":
            hit["_type"] = _doc_type_of(node, hit.get("_index"))


def _scroll(node, req):
    body = req.json_body({}) or {}
    scroll_id = body.get("scroll_id") or req.param("scroll_id")
    return 200, node.scroll(scroll_id, body.get("scroll") or req.param("scroll"))


def _clear_scroll(node, req):
    body = req.json_body({}) or {}
    ids = body.get("scroll_id") or req.param("scroll_id") or ["_all"]
    if isinstance(ids, str):
        ids = [i for i in ids.split(",") if i]
    r = node.clear_scroll(ids)
    # clearing ids none of which existed is a 404 (RestClearScrollAction
    # maps num_freed == 0 to NOT_FOUND); _all always acknowledges
    status = 200 if (r.get("num_freed", 0) > 0 or ids == ["_all"]) else 404
    return status, r


def _msearch(node, req):
    lines = req.ndjson_lines()
    searches = []
    i = 0
    while i + 1 <= len(lines):
        header = lines[i] if isinstance(lines[i], dict) else {}
        body = lines[i + 1] if i + 1 < len(lines) else {}
        header.setdefault("index", req.param("index", "_all"))
        searches.append((header, body))
        i += 2
    resp = node.msearch(searches)
    # the same inexact-total rendering as _search, per entry (a pruned
    # or hybrid member's gte lower bound must not present as exact)
    for (header, body), entry in zip(searches,
                                     resp.get("responses") or []):
        if isinstance(entry, dict):
            _render_total_hits(entry, body)
    return 200, resp


def _count(node, req):
    body = _search_body(req)
    body["size"] = 0
    resp = node.search(req.param("index", "_all"), body)
    return 200, {"count": resp["hits"]["total"], "_shards": resp["_shards"]}


def _validate_query(node, req):
    from elasticsearch_tpu.search.query_dsl import parse_query

    body = req.json_body({}) or {}
    try:
        parse_query(body.get("query"))
        return 200, {"valid": True, "_shards": {"total": 1, "successful": 1, "failed": 0}}
    except Exception as e:
        resp = {"valid": False, "_shards": {"total": 1, "successful": 1, "failed": 0}}
        if req.bool_param("explain"):
            resp["explanations"] = [{"index": req.param("index"), "valid": False,
                                     "error": str(e)}]
        return 200, resp


def _field_caps(node, req):
    from elasticsearch_tpu.mapper.field_types import NUMERIC_TYPES

    fields_param = req.param("fields") or (req.json_body({}) or {}).get("fields", "*")
    if isinstance(fields_param, str):
        fields_param = fields_param.split(",")
    out = {}
    # cross-cluster field caps: alias:index groups resolve on the remote
    pairs, _ = node._resolve_search_groups(req.param("index", "_all"))
    for _prefix, svc in pairs:
        for pattern in fields_param:
            for fname in svc.mapper_service.mapper.simple_match_to_fields(pattern):
                ft = svc.mapper_service.field_type(fname)
                t = ft.type_name
                entry = out.setdefault(fname, {}).setdefault(t, {
                    "type": t,
                    "searchable": bool(ft.index),
                    "aggregatable": bool(ft.doc_values) or t == "text" and ft.fielddata,
                })
    return 200, {"fields": out}


def _explain(node, req):
    body = req.json_body({}) or {}
    if body and "query" not in body:
        # a bare query object at the top level is a parse error
        # (RestExplainAction expects the "query" element)
        raise ActionRequestValidationException(
            "Validation Failed: 1: query is missing;")
    svc = node.index_service(req.param("index"))
    doc_id = req.param("id")
    inner = body.get("query")
    if inner is None and req.param("q") is not None:
        # URI-search form: ?q= with df/default_operator/analyzer/lenient
        inner = {"query_string": {
            "query": req.param("q"),
            **({"default_field": req.param("df")} if req.param("df")
               else {}),
            **({"default_operator": req.param("default_operator")}
               if req.param("default_operator") else {}),
            **({"analyzer": req.param("analyzer")}
               if req.param("analyzer") else {}),
            **({"lenient": req.bool_param("lenient")}
               if req.param("lenient") is not None else {}),
        }}
    q = dict(body)
    q["query"] = {"bool": {"must": [inner or {"match_all": {}}],
                           "filter": [{"ids": {"values": [doc_id]}}]}}
    q["size"] = 1
    resp = svc.search(q)
    matched = resp["hits"]["total"] > 0
    score = resp["hits"]["hits"][0]["_score"] if matched else 0.0
    details = _bm25_explanation_details(
        svc, doc_id, body.get("query")) if matched else []
    out = {
        "_index": svc.name,
        "_id": doc_id,
        "matched": matched,
        "explanation": {
            "value": score,
            "description": ("sum of:" if details else
                            "score via the fused TPU query program"),
            "details": details,
        },
    }
    # the `get` section carries the (filtered) source when any _source
    # param was given (RestExplainAction -> GetResult); reuses the same
    # FetchSourceContext param parsing as single-doc GETs
    if any(req.param(p) is not None for p in (
            "_source", "_source_include", "_source_includes",
            "_source_exclude", "_source_excludes")):
        g = svc.get_doc(doc_id, routing=req.param("routing"))
        if g.found:
            get_out = {"found": True, "_source": dict(g.source)}
            _apply_source_filtering(req, get_out)
            out["get"] = get_out
    _echo_type(req, out)
    return 200, out


def _bm25_explanation_details(svc, doc_id, query_body):
    """Per-term BM25 breakdown (BM25Similarity.explain's tree: boost *
    idf * tfNorm with their inputs) for queries that expand to term
    lanes; other query shapes keep the summary-level explanation."""
    import math

    from elasticsearch_tpu.ops.scoring import B, K1, bm25_idf
    from elasticsearch_tpu.search.query_dsl import (
        ShardQueryContext,
        parse_query,
    )

    try:
        qb = parse_query(query_body)
    except Exception:  # noqa: BLE001 — summary fallback
        return []
    shard = svc.shards[svc._route(doc_id)]
    ctx = ShardQueryContext(svc.mapper_service, engine=shard.engine)
    lanes = qb.explain_terms(ctx)
    if not lanes:
        return []
    entry = shard.engine.version_map.get(doc_id)
    if entry is None or entry.segment is None:
        return []
    segment = next((s for s in shard.engine.searchable_segments()
                    if s.name == entry.segment), None)
    if segment is None:
        return []
    local = entry.local_doc
    details = []
    for field, token, boost in lanes:
        tid = segment.term_id(field, token)
        if tid < 0:
            continue
        start = int(segment.term_block_start[tid])
        count = int(segment.term_block_count[tid])
        blk = segment.block_docs[start:start + count]
        sel = blk == local
        if not sel.any():
            continue
        freq = float(segment.block_tfs[start:start + count][sel][0])
        row = segment.field_norm_idx.get(field, 0)
        dl = float(segment.norms[row][local])
        avgdl = segment.field_avgdl(field)
        st = segment.field_stats.get(field, {})
        n_docs = int(st.get("doc_count", segment.num_docs))
        df = int(segment.term_doc_freq[tid])
        idf = bm25_idf(df, n_docs)
        tf_norm = freq * (K1 + 1) / (freq + K1 * (1 - B + B * dl / avgdl))
        details.append({
            "value": boost * idf * tf_norm,
            "description": f"weight({field}:{token} in {local}) "
                           f"[PerFieldSimilarity], result of:",
            "details": [{
                "value": boost * idf * tf_norm,
                "description": f"score(doc={local}, freq={freq}), "
                               f"product of:",
                "details": [
                    {"value": boost, "description": "boost", "details": []},
                    {"value": idf,
                     "description": "idf, computed as log(1 + (N - n + 0.5)"
                                    " / (n + 0.5)) from:",
                     "details": [
                         {"value": df,
                          "description": "n, number of documents containing "
                                         "term", "details": []},
                         {"value": n_docs,
                          "description": "N, total number of documents with "
                                         "field", "details": []}]},
                    {"value": tf_norm,
                     "description": "tfNorm, computed as (freq * (k1 + 1)) /"
                                    " (freq + k1 * (1 - b + b * dl / avgdl))"
                                    " from:",
                     "details": [
                         {"value": freq, "description": "termFreq",
                          "details": []},
                         {"value": K1, "description": "parameter k1",
                          "details": []},
                         {"value": B, "description": "parameter b",
                          "details": []},
                         {"value": avgdl,
                          "description": "avgFieldLength", "details": []},
                         {"value": dl, "description": "fieldLength",
                          "details": []}]},
                ],
            }],
        })
    return details


def _search_template(node, req):
    from elasticsearch_tpu.search.templates import resolve_template

    body = req.json_body({}) or {}
    rendered = resolve_template(node, body)
    return 200, node.search(req.param("index", "_all"), rendered)


def _render_template(node, req):
    from elasticsearch_tpu.search.templates import resolve_template

    return 200, {"template_output": resolve_template(node, req.json_body({}) or {})}


def _termvectors(node, req):
    _typed_api_warning(req)
    body = req.json_body({}) or {}
    fields = body.get("fields") or (
        req.param("fields").split(",") if req.param("fields") else None
    )
    return 200, node.termvectors(req.param("index"), req.param("id"), fields)


def _rollover(node, req):
    body = req.json_body({}) or {}
    if req.param("new_index"):
        body["new_index"] = req.param("new_index")
    if req.bool_param("dry_run"):
        body["dry_run"] = True
    return 200, node.rollover(req.param("index"), body)


def _shrink(node, req):
    return 200, node.shrink_index(req.param("index"), req.param("target"),
                                  req.json_body({}))


def _reindex(node, req):
    from elasticsearch_tpu.index.reindex import reindex

    return 200, reindex(node, req.json_body({}))


def _update_by_query(node, req):
    from elasticsearch_tpu.index.reindex import update_by_query

    return 200, update_by_query(node, req.param("index"), req.json_body({}))


def _delete_by_query(node, req):
    from elasticsearch_tpu.index.reindex import delete_by_query

    return 200, delete_by_query(node, req.param("index"), req.json_body({}))


# ---------------------------------------------------------------------------
# Index admin
# ---------------------------------------------------------------------------


def _create_index(node, req):
    return 200, node.create_index(req.param("index"), req.json_body({}))


def _delete_index(node, req):
    return 200, node.delete_index(
        req.param("index"),
        ignore_unavailable=req.bool_param("ignore_unavailable"),
        allow_no_indices=req.bool_param("allow_no_indices", True))


def _get_index(node, req):
    state = node.cluster_service.state
    out = {}
    expr = req.param("index")
    if req.bool_param("ignore_unavailable"):
        from elasticsearch_tpu.common.errors import IndexNotFoundException

        names = []
        for part in str(expr).split(","):
            try:
                names.extend(state.resolve_index_names(part))
            except IndexNotFoundException:
                continue  # ignore_unavailable skips only missing parts
    else:
        names = state.resolve_index_names(expr)
    for name in names:
        md = state.indices[name]
        out[name] = md.to_dict()
    return 200, out


def _head_index(node, req):
    state = node.cluster_service.state
    try:
        state.resolve_index_names(req.param("index"))
        return 200, {}
    except Exception:
        return 404, {}


def _refresh(node, req):
    names = node.cluster_service.state.resolve_index_names(req.param("index", "_all"))
    for name in names:
        node.indices[name].refresh()
    n = sum(node.indices[x].num_shards for x in names)
    return 200, {"_shards": {"total": n, "successful": n, "failed": 0}}


def _flush(node, req):
    names = node.cluster_service.state.resolve_index_names(req.param("index", "_all"))
    for name in names:
        node.indices[name].flush()
    n = sum(node.indices[x].num_shards for x in names)
    return 200, {"_shards": {"total": n, "successful": n, "failed": 0}}


def _flush_synced(node, req):
    """Synced flush (SyncedFlushService): every flush here commits a
    durable sync point, so the response reports all shards successful in
    the reference's per-index shape."""
    names = node.cluster_service.state.resolve_index_names(
        req.param("index", "_all"))
    out = {"_shards": {"total": 0, "successful": 0, "failed": 0}}
    for name in names:
        node.indices[name].flush()
        n = node.indices[name].num_shards
        out["_shards"]["total"] += n
        out["_shards"]["successful"] += n
        out[name] = {"total": n, "successful": n, "failed": 0}
    return 200, out


def _forcemerge(node, req):
    names = node.cluster_service.state.resolve_index_names(req.param("index", "_all"))
    for name in names:
        node.indices[name].force_merge()
    n = sum(node.indices[x].num_shards for x in names)
    return 200, {"_shards": {"total": n, "successful": n, "failed": 0}}


_STATS_METRICS = {
    "docs": "docs", "store": "store", "indexing": "indexing", "get": "get",
    "search": "search", "merge": "merges", "refresh": "refresh",
    "flush": "flush", "warmer": "warmer", "query_cache": "query_cache",
    "fielddata": "fielddata", "completion": "completion",
    "segments": "segments", "translog": "translog", "recovery": "recovery",
    "request_cache": "request_cache", "suggest": "search",
}


def _filter_named(entries, param):
    """groups=/types= filtering: comma lists, _all, and * wildcards
    (the reference's CommonStatsFlags groups/types patterns)."""
    import fnmatch

    if not param or not entries:
        return None
    wanted = param if isinstance(param, list) else str(param).split(",")
    if "_all" in wanted:
        return dict(entries)
    return {k: v for k, v in entries.items()
            if any(fnmatch.fnmatchcase(k, w) for w in wanted if w)}


def _sum_stats(dicts):
    """Element-wise numeric merge of section dicts (nested)."""
    out = {}
    for d in dicts:
        for k, v in d.items():
            if isinstance(v, dict):
                out[k] = _sum_stats([out.get(k, {}), v])
            elif isinstance(v, (int, float)):
                out[k] = out.get(k, 0) + v
            else:
                out[k] = v
    return out


def _index_stats(node, req):
    names = node.cluster_service.state.resolve_index_names(
        req.param("index", "_all"))
    metric_param = req.param("metric")
    sections = None
    if metric_param and metric_param != "_all":
        parts = (metric_param if isinstance(metric_param, list)
                 else str(metric_param).split(","))
        sections = set()
        for m in parts:
            if not m or m == "_all":
                sections = None
                break
            if m not in _STATS_METRICS:
                import difflib

                near = difflib.get_close_matches(m, _STATS_METRICS, n=3)
                hint = (" -> did you mean " + (
                    f"[{near[0]}]" if len(near) == 1
                    else "any of [" + ", ".join(near) + "]") + "?") \
                    if near else ""
                raise IllegalArgumentException(
                    f"request [{req.path}] contains unrecognized metric: "
                    f"[{m}]{hint}")
            sections.add(_STATS_METRICS[m])
    level = req.param("level", "indices")
    if level not in ("cluster", "indices", "shards"):
        raise IllegalArgumentException(
            f"level parameter must be one of [cluster] or [indices] or "
            f"[shards] but was [{level}]")
    groups_param = req.param("groups")
    types_param = req.param("types")

    def shape(stats_pair):
        """Apply metric/groups/types filters to a {primaries,total} pair."""
        out = {}
        for side in ("primaries", "total"):
            src_side = stats_pair[side]
            side_out = {}
            for key, val in src_side.items():
                if sections is not None and key not in sections:
                    continue
                val = dict(val) if isinstance(val, dict) else val
                if key == "search" and isinstance(val, dict):
                    g = val.pop("groups", None)
                    kept = _filter_named(g, groups_param)
                    if kept:
                        val["groups"] = kept
                if key == "indexing" and isinstance(val, dict):
                    t = val.pop("types", None)
                    kept = _filter_named(t, types_param)
                    if kept:
                        val["types"] = kept
                side_out[key] = val
            out[side] = side_out
        return out

    state = node.cluster_service.state
    indices = {}
    shards_total = shards_ok = 0
    for name in names:
        if name not in node.indices:
            continue
        md = state.indices.get(name)
        replicas = md.num_replicas if md is not None else 0
        svc = node.indices[name]
        # the reference's stats header counts ALL copies in `total`
        # (including unassigned replicas: rest-api-spec
        # indices.stats/10_index.yml expects 18 for 9 primaries + 9
        # unassigned replicas with successful 9) — total here is NOT
        # successful + failed
        shards_total += svc.num_shards * (1 + replicas)
        shards_ok += svc.num_shards
        raw = svc.stats()
        if req.bool_param("include_segment_file_sizes"):
            for side in ("primaries", "total"):
                seg = raw[side].get("segments")
                if seg is not None:
                    seg["file_sizes"] = {"postings": {
                        "size_in_bytes": seg.get("memory_in_bytes", 0),
                        "description": "block-packed postings arrays"}}
        entry = shape(raw)
        if level == "shards":
            def shard_entry(s):
                out = {k: v for k, v in s.items()
                       if sections is None or k in sections
                       or k in ("routing", "commit", "seq_no")}
                return out
            entry["shards"] = {str(sid): [shard_entry(s)]
                               for sid, s in raw["shards"].items()}
        indices[name] = entry
    all_stats = {
        "primaries": _sum_stats([i["primaries"] for i in indices.values()]),
        "total": _sum_stats([i["total"] for i in indices.values()]),
    }
    resp = {
        "_shards": {"total": shards_total, "successful": shards_ok,
                    "failed": 0},
        "_all": all_stats,
    }
    if level != "cluster":
        resp["indices"] = indices
    return 200, resp


def _segments(node, req):
    names = node.cluster_service.state.resolve_index_names(
        req.param("index", "_all"))
    indices = {}
    total = 0
    for name in names:
        svc = node.indices[name]
        shards = {}
        for sid, shard in svc.shards.items():
            shards[str(sid)] = [{
                "segments": {s.name: s.stats()
                             for s in shard.engine.segments},
            }]
            total += 1
        indices[name] = {"shards": shards}
    return 200, {"indices": indices,
                 "_shards": {"total": total, "successful": total,
                             "failed": 0}}


def _put_mapping(node, req):
    svc = node.index_service(req.param("index"))
    body = req.json_body({}) or {}
    if "properties" not in body and len(body) == 1:
        body = next(iter(body.values()))  # typed form {"_doc": {...}}
    svc.put_mapping(body)
    node._maybe_update_mapping_meta(svc.name)
    return 200, {"acknowledged": True}


def _get_mapping(node, req):
    state = node.cluster_service.state
    want_type = req.param("type")
    out = {}
    for name in state.resolve_index_names(req.param("index", "_all")):
        svc = node.indices[name]
        dt = getattr(svc, "doc_type", "_doc")
        if want_type and want_type not in (dt, "_all"):
            continue
        out[name] = {"mappings": {dt: svc.mapping_dict()}}
    if want_type and not out:
        from elasticsearch_tpu.common.errors import (
            ResourceNotFoundException,
        )
        raise ResourceNotFoundException(f"type[[{want_type}]] missing")
    return 200, out


def _flat_field_mappings(props: dict, prefix: str = "") -> dict:
    """Flatten a properties tree to {full_path: leaf_params} (object
    containers themselves are not fields)."""
    out = {}
    for name, params in (props or {}).items():
        path = f"{prefix}{name}"
        child = (params or {}).get("properties")
        if child and "type" not in (params or {}):
            out.update(_flat_field_mappings(child, path + "."))
            continue
        if child:
            out.update(_flat_field_mappings(child, path + "."))
        out[path] = {k: v for k, v in (params or {}).items()
                     if k != "properties"}
        for sub, sub_params in ((params or {}).get("fields") or {}).items():
            out[f"{path}.{sub}"] = dict(sub_params or {})
    return out


def _get_field_mapping(node, req):
    """GET /_mapping/field/{fields} (TransportGetFieldMappingsAction):
    per-index, per-type field mapping extracts with full_name + the
    field's mapping subtree; wildcards match the full path."""
    import fnmatch as _fn

    state = node.cluster_service.state
    fields = [f for f in str(req.param("fields", "")).split(",") if f]
    want_types = [t for t in str(req.param("type") or "").split(",") if t]
    include_defaults = req.bool_param("include_defaults", False)
    out = {}
    matched_type = not want_types
    for name in state.resolve_index_names(req.param("index", "_all")):
        svc = node.indices[name]
        dt = getattr(svc, "doc_type", "_doc") or "_doc"
        if want_types and not any(
                _fn.fnmatchcase(dt, t) for t in want_types):
            continue
        matched_type = True
        flat = _flat_field_mappings(
            svc.mapping_dict().get("properties") or {})
        per_field = {}
        for pattern in fields:
            for path, params in flat.items():
                if path == pattern or _fn.fnmatchcase(path, pattern):
                    leaf = path.rsplit(".", 1)[-1]
                    params = dict(params)
                    if (include_defaults and params.get("type") == "text"
                            and "analyzer" not in params):
                        params["analyzer"] = "default"
                    per_field[path] = {"full_name": path,
                                       "mapping": {leaf: params}}
        if per_field:
            out[name] = {"mappings": {dt: per_field}}
        elif want_types or req.param("index") is not None:
            # index+type resolved but no field matched: empty marker —
            # unless NOTHING matched anywhere, which renders {}
            out[name] = {"mappings": {dt: {}}}
    if want_types and not matched_type:
        raise ResourceNotFoundException(
            f"type[[{','.join(want_types)}]] missing")
    if not any(per for v in out.values()
               for per in v["mappings"].values()):
        return 200, {}  # no field matched anywhere (reference shape)
    return 200, out


def _put_index_settings(node, req):
    return 200, node.update_index_settings(req.param("index", "_all"),
                                           req.json_body({}) or {})


def _settings_values_as_strings(obj):
    """The reference renders every setting value as a string
    (Settings#toXContent); booleans lowercase."""
    if isinstance(obj, dict):
        return {k: _settings_values_as_strings(v) for k, v in obj.items()}
    if isinstance(obj, list):
        return [_settings_values_as_strings(v) for v in obj]
    if isinstance(obj, bool):
        return "true" if obj else "false"
    return str(obj)


def _render_settings(settings, flat: bool):
    """Settings -> response dict: index.-prefixed, flat or nested,
    string-valued."""
    from elasticsearch_tpu.common.settings import Settings

    if isinstance(settings, dict):
        settings = Settings.from_dict(settings)
    settings = settings.with_index_prefix()
    if flat:
        return _settings_values_as_strings(settings.as_dict())
    return _settings_values_as_strings(settings.as_nested_dict())


def _get_index_settings(node, req):
    import fnmatch

    state = node.cluster_service.state
    flat = req.bool_param("flat_settings")
    name_filter = req.param("setting")
    out = {}
    for name in state.resolve_index_names(req.param("index", "_all")):
        md = state.indices[name]
        settings = md.settings.as_dict()
        settings.setdefault("index.number_of_shards", md.num_shards)
        settings.setdefault("index.number_of_replicas", md.num_replicas)
        settings.setdefault(
            "index.uuid",
            node.indices[name].uuid if name in node.indices else name)
        if name_filter and name_filter != "_all":
            pats = [p for p in str(name_filter).split(",") if p]
            settings = {k: v for k, v in settings.items()
                        if any(fnmatch.fnmatchcase(k, p) for p in pats)}
        out[name] = {"settings": _render_settings(settings, flat)}
    return 200, out


def _analyze(node, req):
    from elasticsearch_tpu.analysis.analyzers import AnalysisRegistry

    body = req.json_body({}) or {}
    text = body.get("text") or req.param("text")
    if text is None:
        raise ActionRequestValidationException("Validation Failed: 1: text is missing;")
    texts = text if isinstance(text, list) else [text]
    index = req.param("index")
    if index is not None:
        registry = node.index_service(index).analyzers
    else:
        registry = AnalysisRegistry()
    analyzer_name = body.get("analyzer") or req.param("analyzer")
    field = body.get("field")
    if analyzer_name is None and field is not None and index is not None:
        ft = node.index_service(index).mapper_service.field_type(field)
        analyzer_name = getattr(ft, "analyzer", None) or "standard"
    analyzer = registry.get(analyzer_name or "standard")
    tokens = []
    for t in texts:
        for pos, (tok, start, end) in enumerate(analyzer.analyze_tokens(t)):
            tokens.append({
                "token": tok,
                "start_offset": start,
                "end_offset": end,
                "type": "<ALPHANUM>",
                "position": pos,
            })
    return 200, {"tokens": tokens}


def _update_aliases(node, req):
    body = req.json_body({}) or {}
    return 200, node.update_aliases(body.get("actions", []))


def _get_alias(node, req):
    state = node.cluster_service.state
    name_filter = req.param("name")
    out = {}
    for idx in state.resolve_index_names(req.param("index", "_all")):
        aliases = state.indices[idx].aliases
        if name_filter and name_filter != "_all":
            import fnmatch

            patterns = [p for p in str(name_filter).split(",") if p]
            aliases = {a: v for a, v in aliases.items()
                       if any(fnmatch.fnmatchcase(a, p) for p in patterns)}
            if not aliases:
                continue
        out[idx] = {"aliases": aliases}
    if name_filter and name_filter != "_all":
        # a NAMED (non-wildcard) pattern matching nothing -> 404, but the
        # body still carries whatever did match (GetAliasesResponse)
        found = {a for v in out.values() for a in v["aliases"]}
        import fnmatch as _fn
        missing = [p for p in str(name_filter).split(",")
                   if p and "*" not in p and p not in found]
        if missing:
            return 404, {**out, "error": f"aliases {missing} missing",
                         "status": 404}
    return 200, out


def _put_alias(node, req):
    spec = req.json_body({}) or {}
    return 200, node.update_aliases([{"add": {
        "index": req.param("index"), "alias": req.param("name"), **spec}}])


def _delete_alias(node, req):
    return 200, node.update_aliases([{"remove": {
        "index": req.param("index"), "alias": req.param("name")}}])


def _head_alias(node, req):
    state = node.cluster_service.state
    index = req.param("index")
    names = (state.resolve_index_names(index) if index else
             list(state.indices))
    for n in names:
        md = state.indices.get(n)
        if md is not None and req.param("name") in md.aliases:
            return 200, {}
    return 404, {}


def _put_template(node, req):
    name = req.param("name")
    if req.bool_param("create") and \
            name in node.cluster_service.state.templates:
        raise IllegalArgumentException(
            f"index_template [{name}] already exists")
    return 200, node.put_template(name, req.json_body({}) or {})


def _get_template(node, req):
    import fnmatch

    templates = node.cluster_service.state.templates
    name = req.param("name")
    flat = req.bool_param("flat_settings")

    def render(t):
        t = dict(t)
        if "settings" in t:
            t["settings"] = _render_settings(t["settings"] or {}, flat)
        if t.get("aliases"):
            # AliasMetaData normalizes `routing` into index_routing +
            # search_routing on output
            out = {}
            for a, spec in t["aliases"].items():
                spec = dict(spec or {})
                routing = spec.pop("routing", None)
                if routing is not None:
                    spec.setdefault("index_routing", routing)
                    spec.setdefault("search_routing", routing)
                out[a] = spec
            t["aliases"] = out
        return t

    if name:
        matched = {k: render(v) for k, v in templates.items()
                   if fnmatch.fnmatchcase(k, name)}
        if not matched:
            return 404, {"error": f"index_template [{name}] missing", "status": 404}
        return 200, matched
    return 200, {k: render(v) for k, v in templates.items()}


def _delete_template(node, req):
    return 200, node.delete_template(req.param("name"))


def _head_template(node, req):
    return (200 if req.param("name") in node.cluster_service.state.templates else 404), {}


def _clear_cache(node, req):
    for svc in node.resolve_search_indices(req.param("index", "_all")):
        for shard in svc.shards.values():
            for seg in shard.engine.segments:
                seg.dev_cache.clear()
    return 200, {"_shards": {"total": 0, "successful": 0, "failed": 0}}


# ---------------------------------------------------------------------------
# Cluster admin
# ---------------------------------------------------------------------------


def _cluster_state(node, req):
    return 200, node.cluster_service.state.to_dict()


def _get_cluster_settings(node, req):
    state = node.cluster_service.state
    return 200, {
        "persistent": state.persistent_settings.as_nested_dict(),
        "transient": state.transient_settings.as_nested_dict(),
    }


def _allocation_explain(node, req):
    # corruption markers (ISSUE 16): a quarantined copy is unusable for
    # allocation, so explain surfaces every marked (index, shard) — the
    # operator-visible trail for a RED last-copy corruption
    corrupted = []
    for name, svc in node.indices.items():
        for sid, shard in svc.shards.items():
            for marker in shard.engine.store.corruption_markers():
                corrupted.append({
                    "index": name, "shard": sid,
                    "marker": marker.get("marker", "corrupted"),
                    "site": marker.get("site", "load"),
                    "reason": marker.get("reason", ""),
                })
    out = {
        "note": "single-node cluster: all primaries allocated locally",
        "can_allocate": "yes",
    }
    if corrupted:
        out["can_allocate"] = "no"
        out["note"] = ("corrupted store copies are unusable for "
                       "allocation until re-recovered from a healthy "
                       "copy (docs/RESILIENCE.md \"Data integrity\")")
        out["corrupted_copies"] = corrupted
    return 200, out


def _get_task(node, req):
    task = node.tasks.get(req.param("task_id"))
    return 200, {"completed": False, "task": task.to_dict()}


def _cancel_task(node, req):
    task = node.tasks.cancel(req.param("task_id"))
    return 200, {"nodes": {node.node_id: {"tasks": {task.id_string: task.to_dict()}}}}


def _delete_script(node, req):
    node.get_stored_script(req.param("id"))  # 404 if missing

    def update(state):
        new = state.copy()
        new.stored_scripts.pop(req.param("id"), None)
        return new

    node.cluster_service.submit_state_update_task("delete-script", update)
    return 200, {"acknowledged": True}


def _simulate_pipeline_by_id(node, req):
    body = req.json_body({}) or {}
    body["id"] = req.param("id")
    return 200, node.ingest.simulate(body)


# ---------------------------------------------------------------------------
# cat API
# ---------------------------------------------------------------------------


def _cat_table(req, rows: List[List], headers: List[str]) -> Tuple[int, object]:
    if req.bool_param("help"):
        # RestTable help: one line per column — name | alias | description
        w = max(len(h) for h in headers)
        return 200, "".join(f"{h.ljust(w)} | - | {h}\n" for h in headers)
    # s: sort by column(s), `name` or `name:desc`, comma list
    sort_spec = req.param("s")
    if sort_spec:
        keys = sort_spec if isinstance(sort_spec, list) \
            else str(sort_spec).split(",")
        for key in reversed([k for k in keys if k]):
            name, _, direction = key.partition(":")
            if name not in headers:
                raise IllegalArgumentException(
                    f"Unable to sort by unknown sort key `{name}`")
            i = headers.index(name)

            def sort_key(row, _i=i):
                v = row[_i]
                try:
                    return (0, float(v), "")
                except (TypeError, ValueError):
                    return (1, 0.0, str(v))
            rows = sorted(rows, key=sort_key, reverse=direction == "desc")
    # h: select/reorder columns
    h_spec = req.param("h")
    if h_spec:
        wanted = h_spec if isinstance(h_spec, list) \
            else str(h_spec).split(",")
        wanted = [w for w in wanted if w]
        idx = []
        for name in wanted:
            if name not in headers:
                raise IllegalArgumentException(
                    f"Field [{name}] not found in the cat table")
            idx.append(headers.index(name))
        headers = [headers[i] for i in idx]
        rows = [[row[i] for i in idx] for row in rows]
    if req.param("format") == "json":
        return 200, [dict(zip(headers, row)) for row in rows]
    verbose = req.bool_param("v")
    cols = [[str(c) for c in row] for row in rows]
    if verbose:
        cols = [headers] + cols
    if not cols:
        return 200, ""
    widths = [max(len(r[i]) for r in cols) for i in range(len(headers))]
    lines = [" ".join(c.ljust(w) for c, w in zip(row, widths))
             for row in cols]
    return 200, "\n".join(lines) + "\n"


def _cat_help(node, req):
    paths = sorted({r.pattern for r in node.rest_controller.routes
                    if r.pattern.startswith("/_cat")})
    return 200, "\n".join(f"{p}" for p in paths) + "\n"


def _cat_indices(node, req):
    state = node.cluster_service.state
    rows = []
    names = state.resolve_index_names(req.param("index", "_all"))
    for name in names:
        md = state.indices[name]
        svc = node.indices.get(name)
        health = "green" if md.num_replicas == 0 else "yellow"
        deleted = 0
        store = 0
        if svc is not None:
            for shard in svc.shards.values():
                for seg in shard.engine.segments:
                    deleted += seg.num_docs - seg.live_doc_count
                store += shard.stats()["segments"]["memory_in_bytes"]
        rows.append([
            health, md.state, name, svc.uuid if svc else "-",
            md.num_shards, md.num_replicas,
            svc.num_docs if svc else 0, deleted,
            f"{store}b", f"{store}b",
        ])
    return _cat_table(req, rows, [
        "health", "status", "index", "uuid", "pri", "rep", "docs.count",
        "docs.deleted", "store.size", "pri.store.size",
    ])


def _cat_health(node, req):
    h = node.health()
    if req.param("ts") in ("false", False, "0"):
        rows = [[h["cluster_name"], h["status"], h["number_of_nodes"],
                 h["number_of_data_nodes"], h["active_shards"],
                 h["active_primary_shards"], h["relocating_shards"],
                 h["initializing_shards"], h["unassigned_shards"], 0, "-",
                 f"{h['active_shards_percent_as_number']:.1f}%"]]
        return _cat_table(req, rows, [
            "cluster", "status", "node.total", "node.data", "shards", "pri",
            "relo", "init", "unassign", "pending_tasks",
            "max_task_wait_time", "active_shards_percent"])
    rows = [[int(time.time()), time.strftime("%H:%M:%S"), h["cluster_name"],
             h["status"], h["number_of_nodes"], h["number_of_data_nodes"],
             h["active_shards"], h["active_primary_shards"],
             h["relocating_shards"], h["initializing_shards"],
             h["unassigned_shards"], 0, "-",
             f"{h['active_shards_percent_as_number']:.1f}%"]]
    return _cat_table(req, rows, [
        "epoch", "timestamp", "cluster", "status", "node.total", "node.data",
        "shards", "pri", "relo", "init", "unassign", "pending_tasks",
        "max_task_wait_time", "active_shards_percent",
    ])


def _cat_nodes(node, req):
    rows = [["127.0.0.1", 0, 0, "mdi", "*", node.node_name]]
    return _cat_table(req, rows, ["ip", "heap.percent", "cpu", "node.role",
                                  "master", "name"])


def _cat_shards(node, req):
    state = node.cluster_service.state
    rows = []
    for name in state.resolve_index_names(req.param("index", "_all")):
        svc = node.indices.get(name)
        if svc is None:
            continue
        for sid, shard in svc.shards.items():
            store = shard.stats()["segments"]["memory_in_bytes"]
            # integrity column (ISSUE 16): newest corruption marker name,
            # or "-" for a healthy copy — operators see quarantined
            # copies directly in _cat/shards
            markers = shard.engine.store.corruption_markers()
            integrity = markers[0].get("marker", "corrupted") \
                if markers else "-"
            rows.append([name, sid, "p", shard.state, shard.num_docs,
                         f"{store}b", "127.0.0.1", node.node_name,
                         integrity])
    return _cat_table(req, rows, ["index", "shard", "prirep", "state", "docs",
                                  "store", "ip", "node", "integrity"])


def _cat_staging(node, req):
    """_cat/staging (ISSUE 9 + 20, docs/OBSERVABILITY.md): the
    at-a-glance per-(index, segment/plane, kind) view of the
    device-memory ledger — what is staged in HBM right now, how big,
    how hot, and whether the budget breaker may evict it — plus the
    mesh generation's slot occupancy: per-device free slot capacity
    (``free/dev`` on the generation's scope rows) and per-slot
    tombstone density (``tombs`` on its slot rows), so operators can
    see when the ISSUE-20 background compaction will trigger."""
    from elasticsearch_tpu.common.memory import memory_accountant

    # mesh slot occupancy, keyed by the generation scope the ledger
    # rows carry in their segment column (e.g. "mesh#3")
    scope_meta: dict = {}
    for name in node.cluster_service.state.resolve_index_names("_all"):
        svc = node.indices.get(name)
        ms = getattr(svc, "_mesh_search", None) if svc else None
        stats = ms.staging_slot_stats() if ms is not None else None
        if not stats:
            continue
        scope = ms._executor.scope if ms._executor is not None else None
        if scope is None:
            continue
        scope_meta[(name, scope)] = stats["free_slots_per_device"]
    rows = []
    for row in memory_accountant().table():
        free_dev = scope_meta.get((row["index"], row["segment"]))
        # kind rows under a mesh scope show the generation's headroom;
        # the scope summary columns stay "-" for host-plane
        # (per-segment) rows, which have no slot allocator
        rows.append([
            row["index"], row["segment"], row["kind"],
            f"{row['bytes']}b", row["tables"], row["stage_count"],
            "-" if row["idle_s"] is None else f"{row['idle_s']:.1f}s",
            "*" if row["evictable"] else "-",
            "-" if free_dev is None else f"{free_dev}",
            "-",
        ])
    # one summary row per staged slot (ISSUE 20): slot → segment →
    # live/total docs → tombstone density, the compaction trigger's
    # exact inputs
    for (name, scope), free_dev in sorted(scope_meta.items()):
        svc = node.indices.get(name)
        stats = svc._mesh_search.staging_slot_stats() if svc else None
        if not stats:
            continue
        for s in stats["slots"]:
            rows.append([
                name, f"{scope}/slot{s['slot']}", "slot",
                f"{s['live']}/{s['docs']}d", 1, "-", "-", "-",
                f"{free_dev}", f"{s['tombstone_density']}",
            ])
    return _cat_table(req, rows, [
        "index", "segment", "kind", "bytes", "tables", "stage_count",
        "idle", "evictable", "free_slots_per_dev", "tombstone_density",
    ])


def _cat_count(node, req):
    total = sum(
        node.indices[n].num_docs
        for n in node.cluster_service.state.resolve_index_names(req.param("index", "_all"))
        if n in node.indices
    )
    rows = [[int(time.time()), time.strftime("%H:%M:%S"), total]]
    return _cat_table(req, rows, ["epoch", "timestamp", "count"])


def _cat_aliases(node, req):
    rows = []
    for name, md in node.cluster_service.state.indices.items():
        for alias, spec in md.aliases.items():
            spec = spec or {}
            routing = spec.get("routing")
            rows.append([
                alias, name,
                "*" if spec.get("filter") else "-",
                spec.get("index_routing") or routing or "-",
                spec.get("search_routing") or routing or "-",
            ])
    return _cat_table(req, rows, ["alias", "index", "filter", "routing.index",
                                  "routing.search"])


def _cat_templates(node, req):
    import fnmatch

    pat = req.param("name")
    rows = []
    for name, t in node.cluster_service.state.templates.items():
        if pat and not fnmatch.fnmatchcase(name, pat):
            continue
        rows.append([name, "[" + ", ".join(t.get("index_patterns", [])) + "]",
                     t.get("order", 0), t.get("version", "")])
    return _cat_table(req, rows, ["name", "index_patterns", "order", "version"])


def _cat_master(node, req):
    rows = [[node.node_id, "127.0.0.1", "127.0.0.1", node.node_name]]
    return _cat_table(req, rows, ["id", "host", "ip", "node"])


def _cat_segments(node, req):
    rows = []
    for name, svc in node.indices.items():
        for sid, shard in svc.shards.items():
            for seg in shard.engine.segments:
                st = seg.stats()
                rows.append([name, sid, "p", "127.0.0.1", node.node_id,
                             seg.name, 1, st["num_docs"],
                             st["deleted_docs"], f"{st['memory_in_bytes']}b",
                             f"{st['memory_in_bytes']}b", "true", "true",
                             __version__, "false"])
    return _cat_table(req, rows, ["index", "shard", "prirep", "ip", "id",
                                  "segment", "generation", "docs.count",
                                  "docs.deleted", "size", "size.memory",
                                  "committed", "searchable", "version",
                                  "compound"])


def _cat_tasks(node, req):
    listing = node.tasks.list_tasks()
    rows = []
    for nid, data in listing["nodes"].items():
        for tid, t in data["tasks"].items():
            rows.append([t["action"], tid, "-", t["type"],
                         t["start_time_in_millis"], t["running_time_in_nanos"]])
    return _cat_table(req, rows, ["action", "task_id", "parent_task_id", "type",
                                  "start_time", "running_time"])


def _cat_allocation(node, req):
    n_shards = sum(s.num_shards for s in node.indices.values())
    from elasticsearch_tpu.common.monitor import fs_stats

    fs = fs_stats(node.data_path if node.persistent_path else ".")
    tot = fs.get("total", {})
    total_b = tot.get("total_in_bytes", 0)
    free_b = tot.get("free_in_bytes", 0)
    used_b = max(total_b - free_b, 0)
    rows = [[n_shards, "0b", f"{used_b // (1 << 30)}gb",
             f"{free_b // (1 << 30)}gb", f"{total_b // (1 << 30)}gb",
             int(used_b * 100 / total_b) if total_b else 0,
             "127.0.0.1", "127.0.0.1", node.node_name]]
    return _cat_table(req, rows, ["shards", "disk.indices", "disk.used",
                                  "disk.avail", "disk.total", "disk.percent",
                                  "host", "ip", "node"])


def _cat_recovery(node, req):
    """_cat/recovery (ISSUE 10 satellite): per shard copy, the local
    store recoveries plus live/finished PEER recoveries from the
    multinode recovery sessions (stage init → index → translog →
    finalize → done, file/byte/op progress, source → target) — the
    RecoveryState surface of RestCatRecoveryAction."""
    from elasticsearch_tpu.cluster.multinode import recovery_progress_rows

    def pct(done, total):
        if not total:
            return "100.0%" if done == total else "0.0%"
        return f"{min(done / total, 1.0) * 100:.1f}%"

    rows = []
    for name, svc in node.indices.items():
        for sid, shard in svc.shards.items():
            rows.append([name, sid, "0ms", "store", "done", "-",
                         node.node_name, 0, "100.0%", "0b", "100.0%",
                         0, 0, "100.0%"])
    now_ms = int(time.time() * 1000)
    for r in recovery_progress_rows():
        took_ms = (r["stop_ms"] or now_ms) - (r["start_ms"] or now_ms)
        rows.append([
            r["index"], r["shard"], f"{max(took_ms, 0)}ms", r["type"],
            r["stage"], r["source"] or "-", r["target"],
            r["files_total"],
            pct(r["files_recovered"], r["files_total"]),
            f"{r['bytes_total']}b",
            pct(r["bytes_recovered"], r["bytes_total"]),
            r["ops_total"], r["ops_recovered"],
            pct(r["ops_recovered"], r["ops_total"]),
        ])
    return _cat_table(req, rows, [
        "index", "shard", "time", "type", "stage", "source_node",
        "target_node", "files", "files_percent", "bytes",
        "bytes_percent", "translog_ops", "translog_ops_recovered",
        "translog_ops_percent"])


def _cat_thread_pool(node, req):
    stats = node.thread_pool.stats()
    rows = [[node.node_name, pool, st["active"], st["queue"], st["rejected"]]
            for pool, st in stats.items()]
    return _cat_table(req, rows, ["node_name", "name", "active", "queue", "rejected"])


def _cat_repositories(node, req):
    rows = [[name, body.get("type", "fs")]
            for name, body in node.cluster_service.state.repositories.items()]
    return _cat_table(req, rows, ["id", "type"])


def _cat_snapshots(node, req):
    snaps = node.snapshots.get_snapshot(req.param("repo"))["snapshots"]
    rows = []
    for s in snaps:
        t0 = int(s.get("start_time_in_millis", 0) // 1000)
        t1 = int(s.get("end_time_in_millis", 0) // 1000)
        ns = s.get("shards_total", len(s["indices"]))
        rows.append([s["snapshot"], s["state"], t0,
                     time.strftime("%H:%M:%S", time.gmtime(t0)), t1,
                     time.strftime("%H:%M:%S", time.gmtime(t1)),
                     f"{max(t1 - t0, 0)}s", len(s["indices"]),
                     ns, 0, ns, "-"])
    return _cat_table(req, rows, ["id", "status", "start_epoch", "start_time",
                                  "end_epoch", "end_time", "duration",
                                  "indices", "successful_shards",
                                  "failed_shards", "total_shards", "reason"])
