"""REST controller: route registry + dispatch.

Role model: ``RestController`` (core/.../rest/RestController.java:65,
dispatchRequest:168) + ``BaseRestHandler``. Routes use the same
path-template syntax as the reference's handlers; handlers receive
(node, params, body) and return (status, payload). Errors map to status
codes through the exception taxonomy (common/errors.py), serialized in the
reference's {"error": {...}, "status": N} shape.
"""

from __future__ import annotations

import contextvars
import json
import re
from typing import Any, Callable, Dict, List, Optional, Tuple

from elasticsearch_tpu.common.errors import (
    ElasticsearchTpuException,
    ParsingException,
)

Handler = Callable[..., Tuple[int, Any]]

# response-header side channel (the deprecation Warning-collector
# pattern): dispatch seeds a mutable dict per request; anything on the
# request path may set a header (Retry-After on 429 rejections —
# docs/OVERLOAD.md); the HTTP front door drains it into the response
_resp_headers_var: "contextvars.ContextVar[Optional[dict]]" = \
    contextvars.ContextVar("estpu_response_headers", default=None)


def begin_response_headers() -> None:
    _resp_headers_var.set({})


def set_response_header(name: str, value: str) -> None:
    headers = _resp_headers_var.get()
    if headers is not None:
        headers[name] = value


def collect_response_headers() -> Dict[str, str]:
    out = dict(_resp_headers_var.get() or {})
    _resp_headers_var.set({})
    return out


def header_value(headers: Optional[Dict[str, str]], name: str,
                 default=None):
    """Case-insensitive lookup in a raw request-header dict (HTTP header
    names are case-insensitive; clients send X-Opaque-Id in any case)."""
    lowered = name.lower()
    for k, v in (headers or {}).items():
        if k.lower() == lowered:
            return v
    return default


class RestRequest:
    def __init__(self, method: str, path: str, params: Dict[str, str],
                 body: Optional[bytes], content_type: Optional[str] = None,
                 headers: Optional[Dict[str, str]] = None):
        self.method = method
        self.path = path
        self.params = params  # query params + path params merged
        self.raw_body = body or b""
        self.content_type = content_type
        self.headers = dict(headers or {})

    def header(self, name: str, default=None):
        """Case-insensitive request-header lookup."""
        return header_value(self.headers, name, default)

    def json_body(self, default=None):
        """Parse the structured request body — despite the historical
        name, JSON/YAML/CBOR all parse here via content negotiation
        (XContentFactory semantics; Content-Type first, sniffing second)."""
        if not self.raw_body.strip():
            return default
        from elasticsearch_tpu.common.xcontent import (
            XContentParseError,
            parse,
        )

        try:
            return parse(self.raw_body, self.content_type)
        except XContentParseError as e:
            raise ParsingException(f"request body is not valid: {e}") from e

    def ndjson_lines(self) -> List[dict]:
        out = []
        for line in self.raw_body.split(b"\n"):
            line = line.strip()
            if line:
                try:
                    out.append(json.loads(line))
                except json.JSONDecodeError as e:
                    raise ParsingException(
                        f"Malformed content, found invalid json line: {e}"
                    ) from e
        return out

    def param(self, name: str, default=None):
        return self.params.get(name, default)

    def bool_param(self, name: str, default=False) -> bool:
        v = self.params.get(name)
        if v is None:
            return default
        return v in ("", "true", True)


class Route:
    _PARAM_RE = re.compile(r"\{(\w+)\}")

    def __init__(self, method: str, pattern: str, handler: Handler):
        self.method = method
        self.pattern = pattern
        self.handler = handler
        regex = "^"
        for part in pattern.strip("/").split("/"):
            m = self._PARAM_RE.fullmatch(part)
            if m:
                if m.group(1) == "index":
                    # index names/aliases cannot start with '_' — keeps API
                    # endpoints from being swallowed by /{index} routes.
                    # `_all` is the one legal underscore expression in
                    # index position (/_all/_refresh etc.)
                    regex += f"/(?P<{m.group(1)}>_all|[^_/][^/]*)"
                else:
                    regex += f"/(?P<{m.group(1)}>[^/]+)"
            else:
                regex += "/" + re.escape(part)
        regex += "$"
        self.regex = re.compile(regex)
        # literal segments score higher for route priority
        self.specificity = sum(
            1 for p in pattern.strip("/").split("/") if not self._PARAM_RE.fullmatch(p)
        )

    def match(self, path: str) -> Optional[Dict[str, str]]:
        m = self.regex.match("/" + path.strip("/"))
        if m is None:
            return None
        return m.groupdict()


_SEARCH_MARKERS = ("_search", "_count", "_msearch", "_explain",
                   "_validate", "_field_caps", "_suggest", "_percolate")
_GET_MARKERS = ("_doc", "_mget", "_source", "_termvectors")


def _executor_for(method: str, pattern: str) -> str:
    """Route -> named pool, mirroring the per-action executor choices of
    the reference's transport actions (ThreadPool.Names)."""
    if any(m in pattern for m in _SEARCH_MARKERS):
        return "search"
    if "_bulk" in pattern or "_update" in pattern:
        return "write"
    if any(m in pattern for m in _GET_MARKERS):
        return "get" if method in ("GET", "HEAD") else "write"
    if "{type}/{id}" in pattern or pattern.endswith("/{id}"):
        return "get" if method in ("GET", "HEAD") else "write"
    return "management"


class RestController:
    def __init__(self, node):
        self.node = node
        self.routes: List[Route] = []
        from elasticsearch_tpu.rest import handlers

        handlers.register_all(self)
        # ActionPlugin.getRestHandlers: plugin-provided endpoints
        svc = getattr(node, "plugins_service", None)
        if svc is not None:
            for method, pattern, handler in svc.rest_handlers:
                self.register(method, pattern, handler)

    def register(self, method: str, pattern: str, handler: Handler) -> None:
        self.routes.append(Route(method, pattern, handler))
        self.routes.sort(key=lambda r: -r.specificity)

    def dispatch(self, method: str, path: str, query: Dict[str, str],
                 body: Optional[bytes],
                 content_type: Optional[str] = None,
                 headers: Optional[Dict[str, str]] = None) -> Tuple[int, Any]:
        from urllib.parse import unquote

        from elasticsearch_tpu.common.deprecation import begin_request
        from elasticsearch_tpu.search.telemetry import set_opaque_id

        begin_request()  # per-request Warning-header collector
        begin_response_headers()  # Retry-After etc. (docs/OVERLOAD.md)
        # X-Opaque-Id rides the request context (contextvars copied into
        # the executor thread below): tasks, slowlog lines, and profile
        # output read it back to join work to the client that sent it
        hdrs = headers or {}
        set_opaque_id(header_value(hdrs, "x-opaque-id"))

        path = unquote(path.split("?")[0])
        method_routes = [r for r in self.routes if r.method == method]
        for route in method_routes:
            path_params = route.match(path)
            if path_params is not None:
                params = dict(query)
                params.update(path_params)
                req = RestRequest(method, path, params, body, content_type,
                                  headers=hdrs)
                inflight = None
                reserved = False
                if body and hasattr(self.node, "breaker_service"):
                    # in-flight requests breaker: the buffered request body
                    # counts against memory until the response is built
                    from elasticsearch_tpu.common.breaker import (
                        CircuitBreaker,
                    )
                    inflight = self.node.breaker_service.get_breaker(
                        CircuitBreaker.IN_FLIGHT_REQUESTS)
                try:
                    if inflight is not None:
                        inflight.add_estimate_bytes_and_maybe_break(
                            len(body), "<http_request>")
                        # only a SUCCESSFUL reservation may be released —
                        # a tripped add already rolled itself back, and
                        # releasing it again would drive used negative
                        reserved = True
                    pool = getattr(self.node, "thread_pool", None)
                    if pool is None:
                        return route.handler(self.node, req)
                    # run handler work on the action's named executor; a
                    # full bounded queue rejects with 429 (ThreadPool +
                    # EsRejectedExecutionException semantics). The copied
                    # contextvars context carries the request's
                    # deprecation-warning collector across the thread hop.
                    import contextvars

                    ctx = contextvars.copy_context()
                    return pool.run(
                        _executor_for(method, route.pattern),
                        lambda: ctx.run(route.handler, self.node, req))
                except ElasticsearchTpuException as e:
                    # 429 backpressure contract (docs/OVERLOAD.md): a
                    # rejection carrying a drain-rate-derived
                    # retry_after_s renders it as the Retry-After header
                    # (never in the reference-shaped error body)
                    retry_after = getattr(e, "retry_after_s", None)
                    if retry_after is not None:
                        from elasticsearch_tpu.search.admission import (
                            retry_after_header_value,
                        )

                        set_response_header(
                            "Retry-After",
                            retry_after_header_value(retry_after))
                    return e.status_code, e.to_dict()
                except Exception as e:  # uncaught -> 500, reference behavior
                    return 500, {
                        "error": {"type": type(e).__name__, "reason": str(e)},
                        "status": 500,
                    }
                finally:
                    if reserved:
                        inflight.add_without_breaking(-len(body))
        # path matched under another method -> 405
        for route in self.routes:
            if route.method != method and route.match(path) is not None:
                allowed = sorted({
                    r.method for r in self.routes if r.match(path) is not None
                })
                return 405, {
                    "error": f"Incorrect HTTP method for uri [{path}] and method "
                             f"[{method}], allowed: {allowed}",
                    "status": 405,
                }
        return 400, {
            "error": {
                "type": "illegal_argument_exception",
                "reason": f"no handler found for uri [{path}] and method [{method}]",
            },
            "status": 400,
        }
