"""Snapshot / restore to blob repositories.

Role model: ``SnapshotsService``/``SnapshotShardsService``/``RestoreService``
(core/.../snapshots/) over the ``Repository`` SPI
(core/.../repositories/blobstore/BlobStoreRepository.java): incremental
segment-file copy into a repository + a snapshot manifest; restore
re-creates indices from the manifest.

TPU framing (SURVEY.md §5.4): segments are immutable files, so a snapshot
is manifest + file hardcopy with dedup by (segment name, checksum); HBM is
never the source of truth.
"""

from __future__ import annotations

import atexit
import hashlib
import json
import os
import shutil
import tempfile
import threading
import time
import uuid
from typing import Dict, List, Optional

from elasticsearch_tpu.common.errors import (
    CorruptedSnapshotException,
    ElasticsearchTpuException,
    IllegalArgumentException,
    ResourceAlreadyExistsException,
    ResourceNotFoundException,
)
from elasticsearch_tpu.common.integrity import integrity_service
from elasticsearch_tpu.common.settings import Settings


class SnapshotState:
    SUCCESS = "SUCCESS"
    IN_PROGRESS = "IN_PROGRESS"
    FAILED = "FAILED"
    ABORTED = "ABORTED"


# process-wide repo root for in-memory nodes: a shared-filesystem repository
# contract means the SAME relative location must alias the SAME directory on
# every node (RepositoriesService resolves against the configured path.repo
# the same way), so the fallback root is per-process, not per-node. Created
# lazily, removed at interpreter exit.
_proc_repo_base: Optional[str] = None
_proc_repo_lock = threading.Lock()


def _process_repo_base() -> str:
    global _proc_repo_base
    with _proc_repo_lock:
        if _proc_repo_base is None:
            _proc_repo_base = tempfile.mkdtemp(prefix="estpu-repos-")
            atexit.register(shutil.rmtree, _proc_repo_base,
                            ignore_errors=True)
        return _proc_repo_base


class FsRepository:
    """Shared-filesystem blob repository (core/.../repositories/fs)."""

    def __init__(self, name: str, settings: dict, base_path: Optional[str] = None):
        self.name = name
        location = settings.get("location")
        if not location:
            raise IllegalArgumentException("[fs] repository requires [location] setting")
        # Relative locations resolve under the node's repo root and must stay
        # inside it (the analog of the reference's path.repo containment check,
        # core/.../env/Environment.resolveRepoFile) so conformance suites with
        # bare names don't scatter dirs into the cwd.
        if base_path and not os.path.isabs(location):
            resolved = os.path.realpath(os.path.join(base_path, location))
            root = os.path.realpath(base_path)
            if not (resolved == root or resolved.startswith(root + os.sep)):
                raise IllegalArgumentException(
                    f"location [{location}] resolves outside the repository root")
            location = resolved
        self.location = location
        os.makedirs(location, exist_ok=True)

    def snapshot_path(self, snapshot: str) -> str:
        return os.path.join(self.location, "snapshots", snapshot)

    def list_snapshots(self) -> List[str]:
        root = os.path.join(self.location, "snapshots")
        if not os.path.isdir(root):
            return []
        return sorted(
            d for d in os.listdir(root)
            if os.path.exists(os.path.join(root, d, "manifest.json"))
        )

    def read_manifest(self, snapshot: str) -> dict:
        path = os.path.join(self.snapshot_path(snapshot), "manifest.json")
        if not os.path.exists(path):
            raise ResourceNotFoundException(f"[{self.name}:{snapshot}] snapshot does not exist")
        with open(path, encoding="utf-8") as f:
            return json.load(f)


class SnapshotsService:
    def __init__(self, node):
        import threading

        self.node = node
        self.repositories: Dict[str, FsRepository] = {}
        # RepositoryPlugin extension point: {type: factory(name, settings,
        # node)} — fs is built-in, cloud types arrive via plugins
        self.repository_types: Dict[str, object] = {}
        # live snapshot progress: (repo, snapshot) -> tracking dict
        # (SnapshotsInProgress custom in the reference's cluster state)
        self._in_progress: Dict[tuple, dict] = {}
        self._progress_lock = threading.Lock()

    # --- repositories ---

    def _repo_base_path(self) -> str:
        """Root under which relative fs-repo locations resolve.

        Persistent nodes use <path.data>/repos (mirroring _index_data_path's
        gate in node.py); in-memory nodes share the process-wide temp root
        so a bare relative location never touches the cwd AND still names
        the same directory on every node (the shared-fs repo contract)."""
        if getattr(self.node, "persistent_path", False):
            return os.path.join(self.node.data_path, "repos")
        return _process_repo_base()

    def close(self) -> None:
        # the in-memory repo root is process-scoped (shared across nodes),
        # cleaned by atexit — nothing node-scoped to release here
        pass

    def put_repository(self, name: str, body: dict) -> dict:
        rtype = body.get("type")
        if rtype == "fs":
            settings = body.get("settings") or {}
            loc = settings.get("location")
            base = (self._repo_base_path()
                    if loc and not os.path.isabs(loc) else None)
            repo = FsRepository(name, settings, base_path=base)
        elif rtype in self.repository_types:
            repo = self.repository_types[rtype](
                name, body.get("settings") or {}, self.node)
        else:
            raise IllegalArgumentException(
                f"repository type [{rtype}] does not exist (supported: fs"
                f"{''.join(', ' + t for t in sorted(self.repository_types))}; "
                "url/s3/azure/gcs arrive with their cloud plugins)"
            )
        self.repositories[name] = repo

        def update(state):
            new = state.copy()
            new.repositories[name] = body
            return new

        self.node.cluster_service.submit_state_update_task(f"put-repo [{name}]", update)
        return {"acknowledged": True}

    def get_repository(self, name: Optional[str] = None) -> dict:
        repos = self.node.cluster_service.state.repositories
        if name in (None, "_all", "*"):
            return dict(repos)
        if name not in repos:
            raise ResourceNotFoundException(f"[{name}] missing")
        return {name: repos[name]}

    def delete_repository(self, name: str) -> dict:
        if name not in self.repositories:
            raise ResourceNotFoundException(f"[{name}] missing")
        self.repositories.pop(name)

        def update(state):
            new = state.copy()
            new.repositories.pop(name, None)
            return new

        self.node.cluster_service.submit_state_update_task(f"delete-repo [{name}]", update)
        return {"acknowledged": True}

    def _repo(self, name: str) -> FsRepository:
        repo = self.repositories.get(name)
        if repo is None:
            raise ResourceNotFoundException(f"[{name}] missing")
        return repo

    def verify_repository(self, name: str) -> dict:
        """POST /_snapshot/{repo}/_verify (VerifyRepositoryAction):
        write, read back, and delete a probe blob so a misconfigured /
        read-only / bit-flipping repository is caught at registration
        time, not at the first snapshot. Reports the verifying
        "node"s, reference-shaped."""
        repo = self._repo(name)
        probe = os.path.join(
            repo.location, f"verify-{uuid.uuid4().hex[:12]}.probe")
        payload = uuid.uuid4().hex.encode("ascii")
        try:
            with open(probe, "wb") as f:
                f.write(payload)
                f.flush()
                os.fsync(f.fileno())
            with open(probe, "rb") as f:
                echoed = f.read()
        except OSError as e:
            raise ElasticsearchTpuException(
                f"[{name}] repository verification failed: probe blob "
                f"could not be written/read ({e})") from e
        finally:
            try:
                os.remove(probe)
            except OSError:
                pass
        if echoed != payload:
            raise ElasticsearchTpuException(
                f"[{name}] repository verification failed: probe blob "
                f"read back different bytes than written")
        node_id = (getattr(self.node, "node_id", None)
                   or getattr(self.node, "node_name", None) or "node")
        node_name = getattr(self.node, "node_name", None) or node_id
        return {"nodes": {node_id: {"name": node_name}}}

    # --- snapshot ---

    def create_snapshot(self, repo_name: str, snapshot: str,
                        body: Optional[dict] = None,
                        wait_for_completion: bool = True) -> dict:
        """Coordinated snapshot with live per-shard progress tracking
        (SnapshotsService:105 + SnapshotShardsService). With
        ``wait_for_completion=False`` the copy runs on a background
        thread and ``_snapshot/_status`` reports shard stages mid-flight;
        deleting an IN_PROGRESS snapshot aborts it and leaves the repo
        consistent (the partial directory is removed)."""
        import threading

        repo = self._repo(repo_name)
        body = body or {}
        key = (repo_name, snapshot)
        with self._progress_lock:
            if key in self._in_progress:
                raise ResourceAlreadyExistsException(
                    f"[{repo_name}:{snapshot}] snapshot is already running")
            if snapshot in repo.list_snapshots():
                raise ResourceAlreadyExistsException(
                    f"[{repo_name}:{snapshot}] snapshot with the same name "
                    f"already exists")
            indices_expr = body.get("indices", "_all")
            names = self.node.cluster_service.state.resolve_index_names(
                indices_expr)
            progress = {
                "state": SnapshotState.IN_PROGRESS,
                "start_time_in_millis": int(time.time() * 1000),
                "abort": threading.Event(),
                "done": threading.Event(),
                # set by delete_snapshot when its abort wait timed out:
                # the WORKER owns the partial directory and must clean it
                # up (and suppress a SUCCESS manifest) instead of racing
                # the deleter's rmtree against its own copytree
                "delete_requested": False,
                # (index, sid) -> stage: INIT | STARTED | DONE | FAILURE
                "shards": {(n, sid): "INIT" for n in names
                           for sid in self.node.indices[n].shards},
                "result": None,
            }
            self._in_progress[key] = progress
        if wait_for_completion:
            self._run_snapshot(repo, repo_name, snapshot, names, progress)
            if progress["state"] == SnapshotState.FAILED:
                # synchronous callers get the error as an error, exactly
                # as before the async path existed — not a 200 whose body
                # lacks the success shape
                raise ElasticsearchTpuException(
                    f"[{repo_name}:{snapshot}] snapshot failed: "
                    f"{progress['result'].get('reason')}")
            return {"snapshot": progress["result"]}
        t = threading.Thread(
            target=self._run_snapshot,
            args=(repo, repo_name, snapshot, names, progress),
            name=f"snapshot[{repo_name}:{snapshot}]", daemon=True)
        t.start()
        return {"accepted": True}

    def _run_snapshot(self, repo, repo_name: str, snapshot: str,
                      names, progress) -> None:
        key = (repo_name, snapshot)
        snap_dir = repo.snapshot_path(snapshot)
        aborted = False
        try:
            os.makedirs(snap_dir, exist_ok=True)
            manifest = {
                "snapshot": snapshot,
                "state": SnapshotState.IN_PROGRESS,
                "start_time_in_millis": progress["start_time_in_millis"],
                "indices": {},
            }
            shards_total = 0
            for name in names:
                svc = self.node.indices[name]
                svc.flush()  # durable commit before copying (the
                # reference snapshots from a Lucene commit the same way)
                md = self.node.cluster_service.state.indices[name]
                idx_dir = os.path.join(snap_dir, "indices", name)
                shard_info = {}
                for sid, shard in svc.shards.items():
                    if progress["abort"].is_set():
                        aborted = True
                        break
                    progress["shards"][(name, sid)] = "STARTED"
                    shards_total += 1
                    store = shard.engine.store
                    if store.is_corrupted():
                        # a marked copy must never seed a snapshot: the
                        # repo would preserve the corruption forever
                        integrity_service().record_corruption(
                            name, sid, "snapshot",
                            "store is marked corrupted")
                        progress["shards"][(name, sid)] = "FAILURE"
                        raise ElasticsearchTpuException(
                            f"cannot snapshot [{name}][{sid}]: store is "
                            f"marked corrupted")
                    src = store.directory
                    dst = os.path.join(idx_dir, str(sid))
                    # per-file SHA-256 of the SOURCE bytes (ISSUE 16):
                    # restore verifies the repo blobs against these
                    # before install, so repo-side bit rot is caught —
                    # never adopted (markers are excluded: they never
                    # ship, same as peer recovery)
                    from elasticsearch_tpu.index.store import MARKER_PREFIX
                    digests = {}
                    for root, _dirs, fnames in os.walk(src):
                        for fn in fnames:
                            if (root == src
                                    and fn.startswith(MARKER_PREFIX)
                                    and fn.endswith(".json")):
                                continue
                            full = os.path.join(root, fn)
                            rel = os.path.relpath(full, src)
                            with open(full, "rb") as fh:
                                digests[rel] = hashlib.sha256(
                                    fh.read()).hexdigest()
                    shutil.copytree(
                        src, dst, dirs_exist_ok=True,
                        ignore=shutil.ignore_patterns(
                            f"{MARKER_PREFIX}*.json"))
                    shard_info[str(sid)] = {
                        "segments": len(shard.engine.segments),
                        "digests": digests}
                    progress["shards"][(name, sid)] = "DONE"
                if aborted:
                    break
                manifest["indices"][name] = {
                    "settings": md.settings.as_dict(),
                    "mappings": svc.mapping_dict(),
                    "aliases": md.aliases,
                    "shards": shard_info,
                }
            # last-chance abort check BEFORE the manifest write: a delete
            # raced past the per-shard checks — it must not observe a
            # SUCCESS manifest for a snapshot it was told is gone
            if progress["abort"].is_set() or progress["delete_requested"]:
                aborted = True
            if aborted:
                # abort leaves the repository consistent: the partial
                # snapshot directory is removed entirely (the reference
                # cleans up aborted shard blobs the same way)
                shutil.rmtree(snap_dir, ignore_errors=True)
                progress["state"] = SnapshotState.ABORTED
                progress["result"] = {
                    "snapshot": snapshot, "state": SnapshotState.ABORTED}
                return
            manifest["state"] = SnapshotState.SUCCESS
            manifest["end_time_in_millis"] = int(time.time() * 1000)
            with open(os.path.join(snap_dir, "manifest.json"), "w",
                      encoding="utf-8") as f:
                json.dump(manifest, f)
            progress["state"] = SnapshotState.SUCCESS
            progress["result"] = {
                "snapshot": snapshot,
                "uuid": snapshot,
                "state": manifest["state"],
                "indices": list(manifest["indices"].keys()),
                "shards": {"total": shards_total, "failed": 0,
                           "successful": shards_total},
            }
        except Exception as e:  # noqa: BLE001 — surface via status
            shutil.rmtree(snap_dir, ignore_errors=True)
            progress["state"] = SnapshotState.FAILED
            progress["result"] = {"snapshot": snapshot,
                                  "state": SnapshotState.FAILED,
                                  "reason": f"{type(e).__name__}: {e}"}
        finally:
            # a delete that timed out waiting for us owns no files: the
            # worker is the only writer under snap_dir, so it performs
            # the removal the deleter could not do safely. The flag
            # check and done.set() are atomic under the progress lock so
            # a deleter setting the flag either is seen here or observes
            # done already set (and falls through to its own fs delete)
            with self._progress_lock:
                if progress["delete_requested"]:
                    shutil.rmtree(snap_dir, ignore_errors=True)
                    progress["state"] = SnapshotState.ABORTED
                    progress["result"] = {
                        "snapshot": snapshot,
                        "state": SnapshotState.ABORTED}
                progress["done"].set()
                self._in_progress.pop(key, None)

    def snapshot_status(self, repo_name: str,
                        snapshot: Optional[str] = None) -> dict:
        """_snapshot/_status (TransportSnapshotsStatusAction): live
        per-shard stages for running snapshots; completed ones from the
        repository manifest. Without a snapshot name: every snapshot
        currently running in the repo."""
        out = []
        with self._progress_lock:
            running = {k: v for k, v in self._in_progress.items()
                       if k[0] == repo_name}
        if snapshot in (None, "_current"):
            wanted = list(running)
        else:
            wanted = [(repo_name, snapshot)]
        for key in wanted:
            prog = running.get(key)
            if prog is not None:
                stages = prog["shards"]
                counts = {"initializing": 0, "started": 0, "done": 0,
                          "failed": 0}
                per_index: dict = {}
                for (iname, sid), stage in stages.items():
                    counts[{"INIT": "initializing", "STARTED": "started",
                            "DONE": "done",
                            "FAILURE": "failed"}[stage]] += 1
                    per_index.setdefault(iname, {})[str(sid)] = {
                        "stage": stage}
                out.append({
                    "snapshot": key[1],
                    "repository": repo_name,
                    "state": prog["state"],
                    "shards_stats": dict(counts,
                                         total=len(stages)),
                    "indices": per_index,
                })
                continue
            repo = self._repo(repo_name)
            if key[1] not in repo.list_snapshots():
                raise ResourceNotFoundException(
                    f"[{repo_name}:{key[1]}] snapshot does not exist")
            m = repo.read_manifest(key[1])
            shards = {(iname, sid)
                      for iname, info in m["indices"].items()
                      for sid in info.get("shards", {})}
            snap_dir = repo.snapshot_path(key[1])
            per_index: dict = {}
            for iname, info in m["indices"].items():
                for sid, sinfo in (info.get("shards") or {}).items():
                    entry: dict = {"stage": "DONE"}
                    digests = (sinfo or {}).get("digests")
                    if digests:
                        # per-file digest verification state (ISSUE 16):
                        # re-hash the repo blobs against the manifest so
                        # _status answers "would this snapshot restore?"
                        shard_dir = os.path.join(
                            snap_dir, "indices", iname, str(sid))
                        ok = 0
                        for rel, expected in digests.items():
                            try:
                                with open(os.path.join(shard_dir, rel),
                                          "rb") as f:
                                    if (hashlib.sha256(f.read())
                                            .hexdigest() == expected):
                                        ok += 1
                            except OSError:
                                pass
                        entry["verification"] = {
                            "files_total": len(digests),
                            "files_verified": ok,
                            "verified": ok == len(digests)}
                    per_index.setdefault(iname, {})[str(sid)] = entry
            out.append({
                "snapshot": key[1],
                "repository": repo_name,
                "state": m["state"],
                "shards_stats": {"initializing": 0, "started": 0,
                                 "failed": 0, "done": len(shards),
                                 "total": len(shards)},
                "indices": per_index,
            })
        return {"snapshots": out}

    def get_snapshot(self, repo_name: str, snapshot: Optional[str] = None) -> dict:
        repo = self._repo(repo_name)
        if snapshot in (None, "_all", "*"):
            names = repo.list_snapshots()
        else:
            names = [snapshot]
        out = []
        for s in names:
            m = repo.read_manifest(s)
            out.append({
                "snapshot": s,
                "state": m["state"],
                "indices": list(m["indices"].keys()),
                "start_time_in_millis": m.get("start_time_in_millis"),
                "end_time_in_millis": m.get("end_time_in_millis"),
            })
        return {"snapshots": out}

    def delete_snapshot(self, repo_name: str, snapshot: str) -> dict:
        # DELETE of a RUNNING snapshot aborts it (SnapshotsService:105:
        # deleteSnapshot sets the abort flag and waits for the shards to
        # stop); the worker removes the partial directory itself
        with self._progress_lock:
            prog = self._in_progress.get((repo_name, snapshot))
        if prog is not None:
            prog["abort"].set()
            if not prog["done"].wait(30):
                # the worker is still copying: IT owns the partial
                # directory. Flag the delete so the worker removes the
                # directory and suppresses its SUCCESS manifest when it
                # finishes — an rmtree here would race its copytree and
                # could leave a resurrected half-snapshot behind. Under
                # the progress lock the worker either sees the flag in
                # its finally-block or has already set done — in the
                # latter (the wait timed out JUST as it finished) fall
                # through to the filesystem delete ourselves.
                with self._progress_lock:
                    finished = prog["done"].is_set()
                    if not finished:
                        prog["delete_requested"] = True
                if not finished:
                    return {"acknowledged": True}
            if prog["state"] != SnapshotState.ABORTED:
                # the worker raced past the abort flag and completed:
                # fall through to the filesystem delete so the ack is
                # truthful either way
                pass
            else:
                return {"acknowledged": True}
        repo = self._repo(repo_name)
        path = repo.snapshot_path(snapshot)
        if not os.path.exists(path):
            raise ResourceNotFoundException(f"[{repo_name}:{snapshot}] snapshot does not exist")
        shutil.rmtree(path)
        return {"acknowledged": True}

    # --- restore ---

    def restore_snapshot(self, repo_name: str, snapshot: str,
                         body: Optional[dict] = None) -> dict:
        repo = self._repo(repo_name)
        body = body or {}
        manifest = repo.read_manifest(snapshot)
        indices_expr = body.get("indices")
        rename_pattern = body.get("rename_pattern")
        rename_replacement = body.get("rename_replacement")
        restored = []
        failures = []
        for name, info in manifest["indices"].items():
            if indices_expr and name not in str(indices_expr).split(","):
                continue
            target = name
            if rename_pattern and rename_replacement is not None:
                import re

                target = re.sub(rename_pattern, rename_replacement, name)
            if target in self.node.indices:
                raise ResourceAlreadyExistsException(
                    f"cannot restore index [{target}] because an open index with "
                    "same name already exists"
                )
            snap_idx_dir = os.path.join(repo.snapshot_path(snapshot), "indices", name)
            # verify the repo blobs against the manifest digests BEFORE
            # creating the index (ISSUE 16): repo-side corruption fails
            # the restore of THIS index only — no half-created index, no
            # unverified bytes installed, the other indices restore
            try:
                self._verify_index_blobs(snapshot, name, info, snap_idx_dir)
            except CorruptedSnapshotException as e:
                failures.append({
                    "index": name,
                    "type": "corrupted_snapshot_exception",
                    "reason": str(e)})
                continue
            self.node.create_index(target, {
                "settings": Settings(info["settings"]).as_nested_dict(),
                "mappings": info["mappings"],
                "aliases": info.get("aliases", {}),
            })
            svc = self.node.indices[target]
            for sid, shard in svc.shards.items():
                src = os.path.join(snap_idx_dir, str(sid))
                if not os.path.exists(src):
                    continue
                dst = shard.engine.store.directory
                shutil.rmtree(dst, ignore_errors=True)
                shutil.copytree(src, dst)
                shard.engine.segments = []
                shard.engine.version_map = {}
                shard.recover_from_store()
            restored.append(target)
        resp = {"snapshot": {
            "snapshot": snapshot,
            "indices": restored,
            "shards": {"total": len(restored) + len(failures),
                       "failed": len(failures),
                       "successful": len(restored)},
        }}
        if failures:
            resp["snapshot"]["failures"] = failures
        return resp

    def _verify_index_blobs(self, snapshot: str, name: str, info: dict,
                            snap_idx_dir: str) -> None:
        """Compare every repo blob of one snapshotted index against the
        per-file digests the create recorded; raise
        :class:`CorruptedSnapshotException` on the first mismatch."""
        for sid_str, sinfo in (info.get("shards") or {}).items():
            digests = (sinfo or {}).get("digests")
            if not digests:
                continue  # pre-ISSUE-16 snapshot: no digests to verify
            shard_dir = os.path.join(snap_idx_dir, sid_str)
            for rel, expected in digests.items():
                full = os.path.join(shard_dir, rel)
                try:
                    with open(full, "rb") as f:
                        actual = hashlib.sha256(f.read()).hexdigest()
                except OSError:
                    actual = "<missing>"
                if actual != expected:
                    integrity_service().record_corruption(
                        name, int(sid_str), "restore",
                        f"snapshot [{snapshot}] blob [{rel}] digest "
                        f"mismatch")
                    raise CorruptedSnapshotException(
                        f"[{snapshot}] index [{name}] shard [{sid_str}] "
                        f"blob [{rel}] failed verification "
                        f"(manifest={expected[:12]}, "
                        f"actual={actual[:12]})")
