"""Hierarchical memory circuit breakers.

Role model: ``HierarchyCircuitBreakerService`` + ``ChildMemoryCircuitBreaker``
(core/.../indices/breaker/HierarchyCircuitBreakerService.java:43,
common/breaker/ChildMemoryCircuitBreaker.java): child breakers (request,
fielddata, in-flight, accounting) account bytes; the parent trips when the
sum crosses its limit; trips surface as HTTP 429.

TPU adaptation: the accounted resource is *host + HBM staging* memory for
query-time data structures (agg buckets, fielddata ordinal maps, in-flight
request payloads). HBM-resident segment data is accounted by the
"accounting" breaker the way Lucene segment memory is in the reference.
"""

from __future__ import annotations

import threading
from typing import Dict, Optional

from elasticsearch_tpu.common.errors import CircuitBreakingException


class CircuitBreaker:
    PARENT = "parent"
    REQUEST = "request"
    FIELDDATA = "fielddata"
    IN_FLIGHT_REQUESTS = "in_flight_requests"
    ACCOUNTING = "accounting"

    def __init__(self, name: str, limit_bytes: int, overhead: float = 1.0,
                 parent: Optional["CircuitBreaker"] = None):
        self.name = name
        self.limit_bytes = limit_bytes
        self.overhead = overhead
        self.parent = parent
        self._used = 0
        self._trip_count = 0
        self._lock = threading.Lock()

    @property
    def used_bytes(self) -> int:
        return self._used

    @property
    def trip_count(self) -> int:
        return self._trip_count

    def add_estimate_bytes_and_maybe_break(self, bytes_: int, label: str = "") -> int:
        with self._lock:
            new_used = self._used + bytes_
            estimate = int(new_used * self.overhead)
            if bytes_ > 0 and self.limit_bytes > 0 and estimate > self.limit_bytes:
                self._trip_count += 1
                raise CircuitBreakingException(
                    f"[{self.name}] Data too large, data for [{label}] would be "
                    f"[{estimate}/{estimate}b], which is larger than the limit of "
                    f"[{self.limit_bytes}b]",
                    bytes_wanted=estimate,
                    byte_limit=self.limit_bytes,
                )
            self._used = new_used
        if self.parent is not None:
            try:
                self.parent.check_parent(label)
            except CircuitBreakingException:
                with self._lock:
                    self._used -= bytes_
                raise
        return self._used

    def add_without_breaking(self, bytes_: int) -> int:
        with self._lock:
            self._used += bytes_
            return self._used

    def check_parent(self, label: str) -> None:
        # parent looks at the sum of its children (tracked by the service)
        pass

    def stats(self) -> dict:
        return {
            "limit_size_in_bytes": self.limit_bytes,
            "estimated_size_in_bytes": self._used,
            "overhead": self.overhead,
            "tripped": self._trip_count,
        }


class ParentBreaker(CircuitBreaker):
    def __init__(self, limit_bytes: int, children: Dict[str, CircuitBreaker]):
        super().__init__(CircuitBreaker.PARENT, limit_bytes)
        self.children = children

    def check_parent(self, label: str) -> None:
        # the accounting child mirrors the DEVICE-memory ledger (HBM
        # staging — common/memory.py), a different physical resource
        # than the host working-set this parent bounds; its own budget
        # breaker enforces it by LRU-evict + plane demotion, never 429,
        # so it must not eat the host children's headroom here
        total = sum(c.used_bytes for name, c in self.children.items()
                    if name != CircuitBreaker.ACCOUNTING)
        if self.limit_bytes > 0 and total > self.limit_bytes:
            with self._lock:
                self._trip_count += 1
            raise CircuitBreakingException(
                f"[parent] Data too large, data for [{label}] would be [{total}b], "
                f"which is larger than the limit of [{self.limit_bytes}b]",
                bytes_wanted=total,
                byte_limit=self.limit_bytes,
            )


class CircuitBreakerService:
    """Builds the breaker hierarchy from settings and hands out children."""

    def __init__(self, total_limit: int = 0, request_limit: int = 0,
                 fielddata_limit: int = 0):
        children: Dict[str, CircuitBreaker] = {}
        self.parent = ParentBreaker(total_limit, children)
        for name, limit in (
            (CircuitBreaker.REQUEST, request_limit),
            (CircuitBreaker.FIELDDATA, fielddata_limit),
            (CircuitBreaker.IN_FLIGHT_REQUESTS, total_limit),
            (CircuitBreaker.ACCOUNTING, 0),
        ):
            children[name] = CircuitBreaker(name, limit, parent=self.parent)
        self._children = children

    def get_breaker(self, name: str) -> CircuitBreaker:
        if name == CircuitBreaker.PARENT:
            return self.parent
        return self._children[name]

    def stats(self) -> dict:
        out = {name: b.stats() for name, b in self._children.items()}
        out[CircuitBreaker.PARENT] = self.parent.stats()
        return out


def noop_breaker_service() -> CircuitBreakerService:
    """Breakers with no limits — used by tests and single-user tools."""
    return CircuitBreakerService(0, 0, 0)


# ---------------------------------------------------------------------------
# Process-level service (the node configures it from settings at startup;
# library code reaches it through breaker_service())
# ---------------------------------------------------------------------------

_service: Optional[CircuitBreakerService] = None
_service_lock = threading.Lock()

# default budget when no settings configure one: the reference defaults to
# percentages of the JVM heap; here an absolute working-set budget
_DEFAULT_TOTAL = 1_500_000_000


def breaker_service() -> CircuitBreakerService:
    global _service
    with _service_lock:
        if _service is None:
            _service = CircuitBreakerService(
                total_limit=_DEFAULT_TOTAL,
                request_limit=int(_DEFAULT_TOTAL * 0.6),
                fielddata_limit=int(_DEFAULT_TOTAL * 0.6),
            )
        return _service


def configure_breaker_service(settings) -> CircuitBreakerService:
    """Node startup: (re)configure the hierarchy's LIMITS from
    indices.breaker.* settings (HierarchyCircuitBreakerService). The
    service object and its accounted bytes survive — multiple in-process
    nodes share one process-wide accounting (last configuration wins on
    limits); replacing the object would silently forget every byte the
    running searches already accounted."""
    total = settings.get_bytes("indices.breaker.total.limit",
                               _DEFAULT_TOTAL)
    request = settings.get_bytes("indices.breaker.request.limit",
                                 int(total * 0.6))
    fielddata = settings.get_bytes("indices.breaker.fielddata.limit",
                                   int(total * 0.6))
    svc = breaker_service()
    svc.parent.limit_bytes = total
    svc.get_breaker(CircuitBreaker.REQUEST).limit_bytes = request
    svc.get_breaker(CircuitBreaker.FIELDDATA).limit_bytes = fielddata
    svc.get_breaker(CircuitBreaker.IN_FLIGHT_REQUESTS).limit_bytes = total
    return svc
