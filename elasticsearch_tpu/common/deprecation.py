"""Deprecation logging with response-header propagation.

Role model: ``DeprecationLogger`` (reference:
core/src/main/java/org/elasticsearch/common/logging/DeprecationLogger.java)
— deprecated-usage warnings are (a) logged once per process per unique
message (dedup) and (b) attached to the current HTTP response as RFC-7234
``Warning`` headers (code 299) via a request-scoped collector (the
reference threads this through ``ThreadContext`` response headers).
"""

from __future__ import annotations

import logging
import threading
from typing import List

_logger = logging.getLogger("elasticsearch_tpu.deprecation")
_seen: set = set()
_seen_lock = threading.Lock()
_tls = threading.local()


def begin_request() -> None:
    """Reset the current thread's warning collector (called by the REST
    dispatcher at the start of each request)."""
    _tls.warnings = []


def collect_warnings() -> List[str]:
    """Drain the warnings recorded during the current request."""
    out = list(getattr(_tls, "warnings", []))
    _tls.warnings = []
    return out


def warning_header_value(message: str) -> str:
    """RFC 7234 warn-code 299 header value (DeprecationLogger.formatWarning)."""
    return f'299 elasticsearch_tpu "{message}"'


class DeprecationLogger:
    def __init__(self, name: str = "deprecation"):
        self._name = name

    def deprecated(self, message: str) -> None:
        with _seen_lock:
            if message not in _seen:
                _seen.add(message)
                _logger.warning("[%s] %s", self._name, message)
        warnings = getattr(_tls, "warnings", None)
        if warnings is not None and message not in warnings:
            warnings.append(message)
