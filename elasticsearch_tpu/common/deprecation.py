"""Deprecation logging with response-header propagation.

Role model: ``DeprecationLogger`` (reference:
core/src/main/java/org/elasticsearch/common/logging/DeprecationLogger.java)
— deprecated-usage warnings are (a) logged once per process per unique
message (dedup) and (b) attached to the current HTTP response as RFC-7234
``Warning`` headers (code 299) via a request-scoped collector (the
reference threads this through ``ThreadContext`` response headers).
"""

from __future__ import annotations

import contextvars
import logging
import threading
from typing import List, Optional

_logger = logging.getLogger("elasticsearch_tpu.deprecation")
_seen: set = set()
_seen_lock = threading.Lock()
# a ContextVar (not threading.local): the REST dispatcher captures its
# context and runs the handler on a thread-pool worker (ThreadPool), and
# the copied context carries the SAME collector list across that hop
_warnings_var: "contextvars.ContextVar[Optional[list]]" =     contextvars.ContextVar("estpu_request_warnings", default=None)


def begin_request() -> None:
    """Reset the current request's warning collector (called by the REST
    dispatcher at the start of each request)."""
    _warnings_var.set([])


def collect_warnings() -> List[str]:
    """Drain the warnings recorded during the current request."""
    out = list(_warnings_var.get() or [])
    _warnings_var.set([])
    return out


def warning_header_value(message: str) -> str:
    """RFC 7234 warn-code 299 header value (DeprecationLogger.formatWarning)."""
    return f'299 elasticsearch_tpu "{message}"'


class DeprecationLogger:
    def __init__(self, name: str = "deprecation"):
        self._name = name

    def deprecated(self, message: str) -> None:
        with _seen_lock:
            if message not in _seen:
                _seen.add(message)
                _logger.warning("[%s] %s", self._name, message)
        warnings = _warnings_var.get()
        if warnings is not None and message not in warnings:
            warnings.append(message)
