"""Device-staging fault model: classification + bounded retry (ISSUE 10).

Role model: the reference's per-layer failure contracts (SURVEY §3.2
scatter-gather failure handling, §5.8 disruption tests) — every Lucene /
disk / network touchpoint there classifies its faults and either retries
or degrades explicitly. The TPU inversion: the fragile boundary is
**HBM staging** (`device_put` of posting tables, live masks, embedding
matrices) and kernel launches, which until this module were guarded by
blanket ``except Exception`` that silently demoted forever.

Two pieces (docs/RESILIENCE.md "Device-plane faults"):

- ``classify_staging_fault``: split device faults into

  * **transient** — RESOURCE_EXHAUSTED / transfer / device-unavailable
    shapes (and the injected :class:`TransientDeviceError`): the staging
    is expected to succeed on a retry once pressure clears. Retried with
    bounded exponential backoff (``search.staging.retry.*``).
  * **deterministic** — shape/compile/value errors that would recur on
    every attempt: never retried; the caller demotes the plane ladder
    immediately and quarantines the plane with reason ``staging_fault``.

- ``run_staged``: the one retry loop every multi-array staging site runs
  its attempt through. Transient faults sleep
  ``backoff_ms * 2**attempt`` between attempts (bounded by
  ``max_attempts``); every retry and terminal fault is recorded on the
  DeviceMemoryAccountant (``_stats search.memory`` —
  ``staging_retries_total`` / ``staging_faults_*`` / the
  ``staging_fault_events`` ring) so operators can tell a device under
  pressure from a genuinely broken staging site.

The retry knobs are node settings (dynamic, with the explicitness
contract of ``search.pallas.*``: an explicit cluster-level value wins,
clearing it reverts to the node file): the node seeds the module-level
config at startup and ``PUT _cluster/settings`` keeps it live. Staging
sites read the PROCESS-level config (``staging_retry_config(None)``) —
an index's create-time Settings snapshot must not freeze the budget
against later dynamic updates.
"""

from __future__ import annotations

import threading
import time
from typing import Optional, Tuple

DEFAULT_MAX_ATTEMPTS = 3
DEFAULT_BACKOFF_MS = 10.0

TRANSIENT = "transient"
DETERMINISTIC = "deterministic"


class StagingBail(Exception):
    """A structural (request/mapping-shaped) inability discovered inside
    a staging attempt — NOT a device fault. ``run_staged`` re-raises it
    immediately: no retry, no fault accounting (the caller owns its
    meaning, e.g. 'this segment set can never stage this field')."""


class TransientDeviceError(RuntimeError):
    """A transient device-plane fault (the RESOURCE_EXHAUSTED / transfer
    error analog): staging is expected to succeed on retry. Raised by
    the fault-injection schemes (testing/disruption.py
    StagingFailScheme) and matched by name/type in classification."""


# message markers the XLA runtime uses for pressure/transport faults —
# these recur only while the device is under pressure, so they retry
_TRANSIENT_MARKERS = (
    "resource_exhausted",
    "resource exhausted",
    "out of memory",
    "unavailable",
    "deadline_exceeded",
    "data_loss",
    "transfer",
    "connection reset",
)


def classify_staging_fault(exc: BaseException) -> str:
    """``transient`` or ``deterministic`` (see module docstring).

    Shape/compile errors (ValueError/TypeError and friends) are
    deterministic — the same arrays re-raise them on every attempt —
    while allocator/transport shapes (by type or by the XLA runtime's
    message vocabulary) are transient."""
    if isinstance(exc, (TransientDeviceError, MemoryError, OSError,
                        ConnectionError, TimeoutError)):
        return TRANSIENT
    if isinstance(exc, (ValueError, TypeError, KeyError, IndexError,
                        AssertionError, AttributeError)):
        return DETERMINISTIC
    msg = str(exc).lower()
    if any(marker in msg for marker in _TRANSIENT_MARKERS):
        # XlaRuntimeError and friends carry the grpc-style status name
        return TRANSIENT
    return DETERMINISTIC


# ---------------------------------------------------------------------------
# Retry configuration (search.staging.retry.*)
# ---------------------------------------------------------------------------

_cfg_lock = threading.Lock()
_max_attempts = DEFAULT_MAX_ATTEMPTS
_backoff_ms = DEFAULT_BACKOFF_MS


def configure_staging_retry(max_attempts: Optional[int] = None,
                            backoff_ms: Optional[float] = None) -> None:
    """Set the process-level retry config (node startup + dynamic
    cluster-settings updates). None leaves a knob unchanged."""
    global _max_attempts, _backoff_ms
    with _cfg_lock:
        if max_attempts is not None:
            _max_attempts = max(1, int(max_attempts))
        if backoff_ms is not None:
            _backoff_ms = max(0.0, float(backoff_ms))


def staging_retry_config(settings=None) -> Tuple[int, float]:
    """(max_attempts, backoff_ms) — an index/node ``Settings`` carrying
    the keys wins over the process-level config (create_index seeds the
    prefix so per-index overrides compose like search.pallas.*)."""
    attempts, backoff = _max_attempts, _backoff_ms
    if settings is not None:
        try:
            attempts = int(settings.get_int(
                "search.staging.retry.max_attempts", attempts))
            backoff = float(settings.get_float(
                "search.staging.retry.backoff_ms", backoff))
        except (TypeError, ValueError):
            pass
    return max(1, attempts), max(0.0, backoff)


def run_staged(fn, *, index: str, kind: str, plane: str = "host",
               settings=None, retry: Optional[Tuple[int, float]] = None):
    """Run one staging attempt with the classified-recovery contract.

    ``fn`` performs the whole attempt (fault-injection hook included, so
    a retried attempt re-consults the schemes). Transient faults retry
    up to ``max_attempts`` total attempts with exponential backoff;
    deterministic faults raise immediately. The terminal fault (either
    class) is recorded on the accountant — the CALLER owns rollback of
    any partially-published arrays and the ladder/quarantine decision —
    and re-raised."""
    from elasticsearch_tpu.common.errors import TaskCancelledException
    from elasticsearch_tpu.common.memory import memory_accountant
    from elasticsearch_tpu.search.cancellation import TimeExceededException

    max_attempts, backoff_ms = retry or staging_retry_config(settings)
    acct = memory_accountant()
    attempt = 0
    while True:
        try:
            return fn()
        except StagingBail:
            raise  # structural inability: the caller's contract, not ours
        except (TaskCancelledException, TimeExceededException):
            # cancellation-passthrough contract (tested by the contract
            # lint): a cancelled/timed-out attempt is the CALLER's clean
            # partial/cancel path — recording it as a device fault would
            # retry a dead query and bench a healthy plane
            raise
        except Exception as e:  # noqa: BLE001 — classified below;
            # non-Exception BaseExceptions (KeyboardInterrupt) pass
            cls = classify_staging_fault(e)
            if cls == TRANSIENT and attempt + 1 < max_attempts:
                attempt += 1
                acct.note_staging_retry(index, kind)
                if backoff_ms > 0:
                    time.sleep(backoff_ms * (2 ** (attempt - 1)) / 1000.0)
                continue
            acct.note_staging_fault(index, kind, transient=(cls == TRANSIENT),
                                    retries=attempt, plane=plane,
                                    error=f"{type(e).__name__}: {e}")
            raise
