"""XContent: pluggable request/response body formats (JSON, YAML, CBOR).

Role model: ``XContentFactory`` / ``XContentType``
(core/src/main/java/org/elasticsearch/common/xcontent/) — the reference
negotiates JSON/YAML/CBOR/SMILE from the Content-Type header with
first-bytes sniffing as the fallback, and renders responses per the
Accept header or ``?format=``. SMILE is omitted (no decoder in this
image and negligible use); CBOR is a self-contained RFC 7049 subset
codec covering the JSON data model (maps, arrays, text, ints, floats,
bool, null, byte strings).
"""

from __future__ import annotations

import json
import struct
from typing import Any, Optional, Tuple

import yaml

JSON = "json"
YAML = "yaml"
CBOR = "cbor"

MIME = {
    JSON: "application/json; charset=UTF-8",
    YAML: "application/yaml",
    CBOR: "application/cbor",
}


class XContentParseError(ValueError):
    pass


def type_from_media(media: Optional[str]) -> Optional[str]:
    """Content-Type / Accept header -> format name (None = unknown).
    Accept lists ("a/b, c/d;q=0.5") resolve to the first recognized
    media type."""
    if not media:
        return None
    for part in media.split(","):
        m = part.split(";")[0].strip().lower()
        if m in ("application/json", "application/x-ndjson", "text/json"):
            return JSON
        if m in ("application/yaml", "text/yaml", "application/x-yaml"):
            return YAML
        if m == "application/cbor":
            return CBOR
    return None


def sniff_type(body: bytes) -> str:
    """First-bytes detection (XContentFactory.xContentType)."""
    i = 0
    while i < min(len(body), 32) and body[i] in b" \t\r\n":
        i += 1
    head = body[i:]
    if head[:1] in (b"{", b"[", b'"'):
        return JSON
    if head[:3] == b"---":
        return YAML
    if body[:1] and (body[0] >> 5) in (4, 5):  # CBOR array/map major types
        return CBOR
    return JSON


def parse(body: bytes, content_type: Optional[str] = None) -> Any:
    fmt = type_from_media(content_type) or sniff_type(body)
    try:
        if fmt == JSON:
            return json.loads(body)
        if fmt == YAML:
            return yaml.safe_load(body)
        return cbor_decode(body)
    except XContentParseError:
        raise
    except Exception as e:  # noqa: BLE001 — normalized parse error
        raise XContentParseError(f"not valid {fmt}: {e}") from e


class _LenientDumper(yaml.SafeDumper):
    """Objects outside the YAML-native model degrade to strings, matching
    json.dumps(default=str) and the CBOR encoder's fallback — a response
    value must never crash the serialization path."""


_LenientDumper.add_representer(
    bytes, lambda d, v: d.represent_str(v.decode("utf-8", "replace")))
_LenientDumper.add_multi_representer(
    object, lambda d, v: d.represent_str(str(v)))


def serialize(obj: Any, fmt: str, pretty: bool = False) -> Tuple[bytes, str]:
    if fmt == YAML:
        return (yaml.dump(obj, Dumper=_LenientDumper,
                          default_flow_style=False,
                          sort_keys=False).encode("utf-8"), MIME[YAML])
    if fmt == CBOR:
        return cbor_encode(obj), MIME[CBOR]
    return (json.dumps(obj, indent=2 if pretty else None,
                       default=str).encode("utf-8"), MIME[JSON])


def response_format(params: dict, accept: Optional[str]) -> str:
    fmt = (params.get("format") or "").lower()
    if fmt in (JSON, YAML, CBOR):
        return fmt
    return type_from_media(accept) or JSON


# ----------------------------------------------------------------------
# Minimal CBOR (RFC 7049 subset: the JSON data model + byte strings)
# ----------------------------------------------------------------------


def _enc_head(major: int, value: int) -> bytes:
    if value < 24:
        return bytes([(major << 5) | value])
    if value < 1 << 8:
        return bytes([(major << 5) | 24, value])
    if value < 1 << 16:
        return bytes([(major << 5) | 25]) + value.to_bytes(2, "big")
    if value < 1 << 32:
        return bytes([(major << 5) | 26]) + value.to_bytes(4, "big")
    return bytes([(major << 5) | 27]) + value.to_bytes(8, "big")


def cbor_encode(obj: Any) -> bytes:
    out = bytearray()
    _encode_into(obj, out)
    return bytes(out)


def _encode_into(obj: Any, out: bytearray) -> None:
    if obj is None:
        out.append(0xF6)
    elif obj is True:
        out.append(0xF5)
    elif obj is False:
        out.append(0xF4)
    elif isinstance(obj, int):
        if obj >= 1 << 64 or obj < -(1 << 64):
            # beyond CBOR's 64-bit heads: degrade to a string like every
            # other unencodable (bignum tags add little for a search API)
            _encode_into(str(obj), out)
        elif obj >= 0:
            out += _enc_head(0, obj)
        else:
            out += _enc_head(1, -1 - obj)
    elif isinstance(obj, float):
        out.append(0xFB)
        out += struct.pack(">d", obj)
    elif isinstance(obj, bytes):
        out += _enc_head(2, len(obj))
        out += obj
    elif isinstance(obj, str):
        raw = obj.encode("utf-8")
        out += _enc_head(3, len(raw))
        out += raw
    elif isinstance(obj, (list, tuple)):
        out += _enc_head(4, len(obj))
        for item in obj:
            _encode_into(item, out)
    elif isinstance(obj, dict):
        out += _enc_head(5, len(obj))
        for k, v in obj.items():
            _encode_into(str(k), out)
            _encode_into(v, out)
    else:
        _encode_into(str(obj), out)  # objects degrade to strings like json


def cbor_decode(data: bytes) -> Any:
    obj, pos = _decode_at(data, 0)
    if pos != len(data):
        raise XContentParseError(
            f"trailing bytes after CBOR value ({len(data) - pos})")
    return obj


def _decode_at(data: bytes, pos: int) -> Tuple[Any, int]:
    if pos >= len(data):
        raise XContentParseError("truncated CBOR")
    initial = data[pos]
    major, info = initial >> 5, initial & 0x1F
    pos += 1
    if major == 7:
        if initial == 0xF6 or initial == 0xF7:  # null / undefined
            return None, pos
        if initial == 0xF5:
            return True, pos
        if initial == 0xF4:
            return False, pos
        if initial == 0xFB:
            return struct.unpack(">d", data[pos:pos + 8])[0], pos + 8
        if initial == 0xFA:
            return struct.unpack(">f", data[pos:pos + 4])[0], pos + 4
        raise XContentParseError(f"unsupported simple value {initial:#x}")
    if info < 24:
        length = info
    elif info == 24:
        length = data[pos]
        pos += 1
    elif info == 25:
        length = int.from_bytes(data[pos:pos + 2], "big")
        pos += 2
    elif info == 26:
        length = int.from_bytes(data[pos:pos + 4], "big")
        pos += 4
    elif info == 27:
        length = int.from_bytes(data[pos:pos + 8], "big")
        pos += 8
    else:
        raise XContentParseError(
            f"indefinite-length CBOR not supported (major {major})")
    if major == 0:
        return length, pos
    if major == 1:
        return -1 - length, pos
    if major in (2, 3):
        if pos + length > len(data):
            raise XContentParseError("truncated CBOR string")
        raw = data[pos:pos + length]
        return (raw if major == 2 else raw.decode("utf-8")), pos + length
    if major == 4:
        items = []
        for _ in range(length):
            item, pos = _decode_at(data, pos)
            items.append(item)
        return items, pos
    if major == 5:
        out = {}
        for _ in range(length):
            k, pos = _decode_at(data, pos)
            v, pos = _decode_at(data, pos)
            out[k] = v
        return out, pos
    # major 6: semantic tag — skip the tag, decode the payload
    return _decode_at(data, pos)
