"""Typed, scoped, dynamically-updatable settings.

Role model: ``Setting``/``Settings``/``ClusterSettings``
(core/src/main/java/org/elasticsearch/common/settings/Setting.java,
ClusterSettings.java) — every tunable is a typed ``Setting`` object with a
scope (node or index), a default, optional dynamic updatability, and
registered update listeners. ``Settings`` itself is an immutable string map;
typed access always goes through a ``Setting``.
"""

from __future__ import annotations

import fnmatch
from typing import Any, Callable, Dict, Iterable, Optional

from elasticsearch_tpu.common.errors import IllegalArgumentException
from elasticsearch_tpu.common.units import parse_byte_size, parse_time_value


class Settings:
    """Immutable flat key->value map with typed getters.

    Keys are dotted paths ("index.number_of_shards"). Values are stored as
    given (str/int/float/bool/list); typed getters coerce.
    """

    EMPTY: "Settings"

    def __init__(self, data: Optional[Dict[str, Any]] = None):
        self._data: Dict[str, Any] = dict(data or {})

    @staticmethod
    def of(**kwargs) -> "Settings":
        return Settings({k.replace("__", "."): v for k, v in kwargs.items()})

    @staticmethod
    def from_dict(d: Optional[Dict[str, Any]]) -> "Settings":
        """Flatten a possibly-nested dict into dotted keys."""
        flat: Dict[str, Any] = {}

        def walk(prefix: str, obj):
            for k, v in obj.items():
                if isinstance(v, dict):
                    walk(prefix + k + ".", v)
                else:
                    flat[prefix + k] = v

        walk("", d or {})
        return Settings(flat)

    def as_dict(self) -> Dict[str, Any]:
        return dict(self._data)

    def with_index_prefix(self) -> "Settings":
        """Normalize index-level settings: bare keys get the ``index.``
        prefix (the reference accepts both ``number_of_shards`` and
        ``index.number_of_shards`` in create-index/update-settings bodies
        and canonicalizes via IndexScopedSettings prefix normalization —
        silently dropping the bare form loses e.g. the shard count)."""
        out = {}
        for k, v in self._data.items():
            if not k.startswith("index.") and k != "index":
                k = "index." + k
            out[k] = v
        return Settings(out)

    def as_nested_dict(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {}
        for key, value in sorted(self._data.items()):
            node = out
            parts = key.split(".")
            for p in parts[:-1]:
                nxt = node.get(p)
                if not isinstance(nxt, dict):
                    nxt = {}
                    node[p] = nxt
                node = nxt
            node[parts[-1]] = value
        return out

    def keys(self) -> Iterable[str]:
        return self._data.keys()

    def __contains__(self, key: str) -> bool:
        return key in self._data

    def __len__(self) -> int:
        return len(self._data)

    def __eq__(self, other) -> bool:
        return isinstance(other, Settings) and self._data == other._data

    def __hash__(self):
        return hash(tuple(sorted((k, str(v)) for k, v in self._data.items())))

    def get(self, key: str, default: Any = None) -> Any:
        return self._data.get(key, default)

    def get_str(self, key: str, default: Optional[str] = None) -> Optional[str]:
        v = self._data.get(key)
        return default if v is None else str(v)

    def get_int(self, key: str, default: Optional[int] = None) -> Optional[int]:
        v = self._data.get(key)
        if v is None:
            return default
        try:
            return int(v)
        except (TypeError, ValueError):
            raise IllegalArgumentException(
                f"Failed to parse value [{v}] for setting [{key}]"
            ) from None

    def get_float(self, key: str, default: Optional[float] = None) -> Optional[float]:
        v = self._data.get(key)
        if v is None:
            return default
        try:
            return float(v)
        except (TypeError, ValueError):
            raise IllegalArgumentException(
                f"Failed to parse value [{v}] for setting [{key}]"
            ) from None

    def get_bool(self, key: str, default: Optional[bool] = None) -> Optional[bool]:
        v = self._data.get(key)
        if v is None:
            return default
        if isinstance(v, bool):
            return v
        s = str(v).lower()
        if s == "true":
            return True
        if s == "false":
            return False
        raise IllegalArgumentException(
            f"Failed to parse value [{v}] as only [true] or [false] are allowed for "
            f"setting [{key}]"
        )

    def get_list(self, key: str, default: Optional[list] = None) -> Optional[list]:
        v = self._data.get(key)
        if v is None:
            return default
        if isinstance(v, (list, tuple)):
            return list(v)
        return [p.strip() for p in str(v).split(",") if p.strip()]

    def get_time(self, key: str, default: Optional[float] = None) -> Optional[float]:
        v = self._data.get(key)
        return default if v is None else parse_time_value(v, key)

    def get_bytes(self, key: str, default: Optional[int] = None) -> Optional[int]:
        v = self._data.get(key)
        return default if v is None else parse_byte_size(v, key)

    def filtered_by_prefix(self, prefix: str) -> "Settings":
        return Settings({k: v for k, v in self._data.items() if k.startswith(prefix)})

    def merged_with(self, other: "Settings") -> "Settings":
        d = dict(self._data)
        for k, v in other._data.items():
            if v is None:
                d.pop(k, None)
            else:
                d[k] = v
        return Settings(d)


Settings.EMPTY = Settings()


class Scope:
    NODE = "node"
    INDEX = "index"


class Setting:
    """A typed setting definition.

    parser: raw value -> typed value (raises IllegalArgumentException on bad
    input). validator: typed value -> None or raises.
    """

    def __init__(
        self,
        key: str,
        default: Any,
        parser: Callable[[Any], Any],
        scope: str = Scope.NODE,
        dynamic: bool = False,
        validator: Optional[Callable[[Any], None]] = None,
        deprecated: bool = False,
    ):
        self.key = key
        self.default = default
        self.parser = parser
        self.scope = scope
        self.dynamic = dynamic
        self.validator = validator
        self.deprecated = deprecated

    def get(self, settings: Settings) -> Any:
        raw = settings.get(self.key)
        if raw is None:
            value = self.default(settings) if callable(self.default) else self.default
        else:
            try:
                value = self.parser(raw)
            except IllegalArgumentException:
                raise
            except (TypeError, ValueError) as e:
                raise IllegalArgumentException(
                    f"Failed to parse value [{raw}] for setting [{self.key}]"
                ) from e
        if self.validator is not None and value is not None:
            self.validator(value)
        return value

    def exists(self, settings: Settings) -> bool:
        return self.key in settings

    # --- typed constructors, mirroring Setting.intSetting/boolSetting/... ---

    @staticmethod
    def int_setting(key, default, min_value=None, max_value=None, **kw) -> "Setting":
        def validate(v):
            if min_value is not None and v < min_value:
                raise IllegalArgumentException(
                    f"Failed to parse value [{v}] for setting [{key}] must be >= {min_value}"
                )
            if max_value is not None and v > max_value:
                raise IllegalArgumentException(
                    f"Failed to parse value [{v}] for setting [{key}] must be <= {max_value}"
                )

        return Setting(key, default, int, validator=validate, **kw)

    @staticmethod
    def bool_setting(key, default, **kw) -> "Setting":
        def parse(v):
            if isinstance(v, bool):
                return v
            s = str(v).lower()
            if s in ("true", "false"):
                return s == "true"
            raise IllegalArgumentException(
                f"Failed to parse value [{v}] as only [true] or [false] are allowed for "
                f"setting [{key}]"
            )

        return Setting(key, default, parse, **kw)

    @staticmethod
    def float_setting(key, default, min_value=None, **kw) -> "Setting":
        def validate(v):
            if min_value is not None and v < min_value:
                raise IllegalArgumentException(
                    f"Failed to parse value [{v}] for setting [{key}] must be >= {min_value}"
                )

        return Setting(key, default, float, validator=validate, **kw)

    @staticmethod
    def str_setting(key, default, choices=None, **kw) -> "Setting":
        def validate(v):
            if choices is not None and v not in choices:
                raise IllegalArgumentException(
                    f"unknown value [{v}] for setting [{key}], allowed: {sorted(choices)}"
                )

        return Setting(key, default, str, validator=validate, **kw)

    @staticmethod
    def time_setting(key, default, **kw) -> "Setting":
        return Setting(key, default, lambda v: parse_time_value(v, key), **kw)

    @staticmethod
    def bytes_setting(key, default, **kw) -> "Setting":
        return Setting(key, default, lambda v: parse_byte_size(v, key), **kw)

    @staticmethod
    def list_setting(key, default, **kw) -> "Setting":
        def parse(v):
            if isinstance(v, (list, tuple)):
                return list(v)
            return [p.strip() for p in str(v).split(",") if p.strip()]

        return Setting(key, default, parse, **kw)


class AbstractScopedSettings:
    """Registry of known settings for one scope + dynamic update dispatch.

    Role model: ``AbstractScopedSettings`` / ``ClusterSettings``
    (common/settings/ClusterSettings.java:416 is the master list).
    """

    def __init__(self, scope: str, registered: Iterable[Setting]):
        self.scope = scope
        self._settings: Dict[str, Setting] = {}
        self._listeners: list = []  # (setting, callback)
        for s in registered:
            self.register(s)

    def register(self, setting: Setting) -> None:
        if setting.scope != self.scope:
            raise IllegalArgumentException(
                f"setting [{setting.key}] has scope [{setting.scope}], expected "
                f"[{self.scope}]"
            )
        if setting.key in self._settings:
            raise IllegalArgumentException(f"setting [{setting.key}] already registered")
        self._settings[setting.key] = setting

    def get_setting(self, key: str) -> Optional[Setting]:
        return self._settings.get(key)

    def is_registered(self, key: str) -> bool:
        return key in self._settings or any(
            fnmatch.fnmatch(key, pat) for pat in self._settings if "*" in pat
        )

    def is_dynamic(self, key: str) -> bool:
        s = self._settings.get(key)
        return s is not None and s.dynamic

    def validate(self, settings: Settings, allow_unknown: bool = False) -> None:
        for key in settings.keys():
            if not self.is_registered(key):
                if not allow_unknown:
                    raise IllegalArgumentException(f"unknown setting [{key}]")
                continue
            s = self._settings.get(key)
            if s is not None:
                s.get(settings)  # parse+validate

    def validate_dynamic_update(self, settings: Settings) -> None:
        for key in settings.keys():
            s = self._settings.get(key)
            if s is None:
                raise IllegalArgumentException(f"unknown setting [{key}]")
            if not s.dynamic:
                raise IllegalArgumentException(
                    f"final or non-dynamic setting [{key}] cannot be updated"
                )
            s.get(settings)

    def add_settings_update_consumer(self, setting: Setting, consumer) -> None:
        if setting.key not in self._settings:
            raise IllegalArgumentException(f"setting [{setting.key}] not registered")
        self._listeners.append((setting, consumer))

    def apply_settings(self, old: Settings, new: Settings) -> None:
        """Fire update consumers for settings whose value changed.

        The raw string participates alongside the typed value: an
        EXPLICIT update to a value that happens to equal the setting's
        default (e.g. flipping a node-file-enabled boolean back off via
        PUT _cluster/settings) must still reach consumers — the typed
        comparison alone reads absent-and-default == explicit-default
        and would swallow it. Consumers are idempotent setters, so the
        extra fires are harmless."""
        for setting, consumer in self._listeners:
            before, after = setting.get(old), setting.get(new)
            if before != after or old.get(setting.key) != new.get(
                    setting.key):
                consumer(after)


# ---------------------------------------------------------------------------
# The registered node + index settings (growing list; ES has ~400).
# ---------------------------------------------------------------------------

CLUSTER_NAME = Setting.str_setting("cluster.name", "elasticsearch-tpu")
NODE_NAME = Setting.str_setting("node.name", "node-0")
NODE_DATA = Setting.bool_setting("node.data", True)
NODE_MASTER = Setting.bool_setting("node.master", True)
NODE_INGEST = Setting.bool_setting("node.ingest", True)
PATH_DATA = Setting.str_setting("path.data", "data")
PATH_REPO = Setting.list_setting("path.repo", [])
HTTP_PORT = Setting.int_setting("http.port", 9200, min_value=0, max_value=65535)
HTTP_HOST = Setting.str_setting("http.host", "127.0.0.1")
ACTION_AUTO_CREATE_INDEX = Setting.bool_setting(
    "action.auto_create_index", True, dynamic=True
)
ACTION_DESTRUCTIVE_REQUIRES_NAME = Setting.bool_setting(
    "action.destructive_requires_name", False, dynamic=True
)
SEARCH_DEFAULT_SIZE = Setting.int_setting("search.default_size", 10, min_value=0)
SEARCH_MAX_BUCKETS = Setting.int_setting(
    "search.max_buckets", 65536, min_value=1, dynamic=True
)
SEARCH_KEEPALIVE = Setting.time_setting(
    "search.default_keep_alive", "5m", dynamic=True
)
SEARCH_DEFAULT_TIMEOUT = Setting.time_setting(
    # query-phase deadline applied when a request carries no `timeout`
    # param (SearchService.DEFAULT_SEARCH_TIMEOUT_SETTING); None = no
    # timeout. Expired deadlines return accumulated hits with
    # timed_out: true — they do not error (The Tail at Scale degradation)
    "search.default_search_timeout", None, dynamic=True
)
SEARCH_ALLOW_PARTIAL_RESULTS = Setting.bool_setting(
    # TransportSearchAction.SHARD_COUNT... analog of
    # search.default_allow_partial_results: whether shard failures /
    # expired timeouts degrade to partial results (true) or fail the
    # request with search_phase_execution_exception (false); a request's
    # allow_partial_search_results param overrides
    "search.default_allow_partial_results", True, dynamic=True
)
BREAKER_TOTAL_LIMIT = Setting.str_setting(
    "indices.breaker.total.limit", "70%", dynamic=True
)
BREAKER_REQUEST_LIMIT = Setting.str_setting(
    "indices.breaker.request.limit", "60%", dynamic=True
)
BREAKER_FIELDDATA_LIMIT = Setting.str_setting(
    "indices.breaker.fielddata.limit", "60%", dynamic=True
)

# --- transport resilience (transport/local.py RetryPolicy/ConnectionHealth;
# wired through cluster/multinode.py — see docs/RESILIENCE.md) ---

TRANSPORT_REQUEST_TIMEOUT = Setting.time_setting(
    "transport.request.timeout", "30s", dynamic=True
)
TRANSPORT_RETRY_MAX_ATTEMPTS = Setting.int_setting(
    "transport.retry.max_attempts", 3, min_value=1, dynamic=True
)
TRANSPORT_RETRY_INITIAL_BACKOFF = Setting.time_setting(
    "transport.retry.initial_backoff", "50ms", dynamic=True
)
TRANSPORT_RETRY_BACKOFF_MULTIPLIER = Setting.float_setting(
    "transport.retry.backoff_multiplier", 2.0, min_value=1.0, dynamic=True
)
TRANSPORT_RETRY_MAX_BACKOFF = Setting.time_setting(
    "transport.retry.max_backoff", "2s", dynamic=True
)
TRANSPORT_HEALTH_FAILURE_THRESHOLD = Setting.int_setting(
    "transport.health.failure_threshold", 3, min_value=1, dynamic=True
)
TRANSPORT_HEALTH_QUARANTINE = Setting.time_setting(
    "transport.health.quarantine", "1s", dynamic=True
)
FD_PING_TIMEOUT = Setting.time_setting(
    # discovery.zen.fd.ping_timeout: the reference defaults to 30s over
    # real sockets; the in-process cluster detects an unresponsive node in
    # seconds so FD ticks stay cheap
    "discovery.zen.fd.ping_timeout", "5s", dynamic=True
)
FD_PING_RETRIES = Setting.int_setting(
    "discovery.zen.fd.ping_retries", 3, min_value=1, dynamic=True
)
PUBLISH_TIMEOUT = Setting.time_setting(
    "discovery.zen.publish_timeout", "30s", dynamic=True
)
REPLICATION_TIMEOUT = Setting.time_setting(
    # per-replica write fan-out deadline: a blackholed replica is failed
    # (and rerouted by the master) instead of blocking the primary
    "cluster.replication.timeout", "30s", dynamic=True
)
RECOVERY_RETRY_DELAY_NETWORK = Setting.time_setting(
    "indices.recovery.retry_delay_network", "500ms", dynamic=True
)
RECOVERY_MAX_RETRIES = Setting.int_setting(
    "indices.recovery.max_retries", 5, min_value=1, dynamic=True
)
RECOVERY_ACTION_TIMEOUT = Setting.time_setting(
    "indices.recovery.internal_action_timeout", "30s", dynamic=True
)
def _validate_tiles_per_step(v):
    # must divide the power-of-two tile counts the kernel produces; the
    # kernel helper only honors these values, so reject everything else
    # here instead of silently running with 1
    if v not in (1, 2, 4, 8):
        raise IllegalArgumentException(
            f"Failed to parse value [{v}] for setting "
            f"[search.pallas.tiles_per_step]: must be one of 1, 2, 4, 8")


# --- cross-query micro-batching (search/batching.py; docs/BATCHING.md) ---

SEARCH_BATCH_ENABLED = Setting.bool_setting(
    # amortize one corpus-stream pass of the Pallas scoring plane across
    # concurrent compatible queries (mesh_pallas + host-pallas rungs);
    # false = every query executes unbatched
    "search.batch.enabled", True, dynamic=True
)
SEARCH_BATCH_WINDOW_MS = Setting.float_setting(
    # how long the first query of a concurrent burst waits for peers
    # before dispatching (milliseconds). Only paid under concurrency — a
    # lone query never waits.
    "search.batch.window_ms", 0.2, min_value=0.0, dynamic=True
)
SEARCH_BATCH_MAX_QUERIES = Setting.int_setting(
    # batch size bound (the kernel's q_batch): per-query VMEM
    # accumulators and the per-tile top-k loop grow linearly with this
    "search.batch.max_queries", 16, min_value=1, max_value=64, dynamic=True
)
SEARCH_BATCH_MAX_WINDOW_MS = Setting.float_setting(
    # upper bound of the ADAPTIVE batch window (docs/OVERLOAD.md): under
    # admission-queue pressure the effective window widens linearly from
    # search.batch.window_ms toward this bound, trading p50 for
    # throughput; observable via the batch_window_effective_ms gauge
    "search.batch.max_window_ms", 5.0, min_value=0.0, dynamic=True
)

# --- multi-tenant overload control (search/admission.py;
# docs/OVERLOAD.md) ---

SEARCH_QUEUE_SIZE = Setting.int_setting(
    # bounded search admission queue depth, consulted at IndexService
    # dispatch BEFORE any staging/launch work (the reference's search
    # threadpool queue_size); overflow rejects with HTTP 429
    # es_rejected_execution_exception + a drain-rate-derived Retry-After
    "search.queue.size", 1000, min_value=1, dynamic=True
)
SEARCH_ADMISSION_ENABLED = Setting.bool_setting(
    # the overload-control plane's kill switch: false admits everything
    # unconditionally (no queueing, no brownout, no rejection)
    "search.admission.enabled", True, dynamic=True
)
SEARCH_ADMISSION_MAX_CONCURRENT = Setting.int_setting(
    # in-flight search bound per index; 0 = auto (max(16, 3*cores/2+1),
    # mirroring the search threadpool sizing). Arrivals over the bound
    # queue and drain by weighted deficit-round-robin over tenants.
    "search.admission.max_concurrent", 0, min_value=0, dynamic=True
)
SEARCH_ADMISSION_WEIGHTS = Setting.str_setting(
    # per-tenant DRR weights, "tenantA:4,tenantB:1" (tenant = the
    # request's X-Opaque-Id; unlisted tenants weigh 1)
    "search.admission.weights", "", dynamic=True
)
SEARCH_ADMISSION_BROWNOUT_PRUNED = Setting.float_setting(
    # brownout step 1 threshold (queue pressure = queued/capacity):
    # force pruned/gte-totals eligibility before queueing deeper
    "search.admission.brownout.pruned_threshold", 0.25, min_value=0.0,
    dynamic=True
)
SEARCH_ADMISSION_BROWNOUT_RESCORE = Setting.float_setting(
    # brownout step 2 threshold: shed the rescore phase
    "search.admission.brownout.rescore_threshold", 0.5, min_value=0.0,
    dynamic=True
)
SEARCH_ADMISSION_BROWNOUT_FEATURES = Setting.float_setting(
    # brownout step 3 threshold: shed aggs/suggest (responses marked
    # _degraded); step 4 — rejection — is the queue-overflow 429
    "search.admission.brownout.features_threshold", 0.75, min_value=0.0,
    dynamic=True
)

SEARCH_PALLAS_TILES_PER_STEP = Setting(
    # TPU-specific DMA buffering toggle: tiles folded into one grid step
    # of the tile-scoring kernel (ops/pallas_scoring.py) so their posting-
    # window DMAs double-buffer against compute; exported to the kernel
    # via ES_TPU_PALLAS_TPS at node startup. 1 = historical behavior.
    "search.pallas.tiles_per_step", 1, int,
    validator=_validate_tiles_per_step,
)

# --- postings codec + block-max pruned scoring (docs/PRUNING.md) ---

SEARCH_PALLAS_POSTINGS_CODEC = Setting.str_setting(
    # node-wide default postings representation for the tile-scoring
    # kernel's HBM staging: "raw" = (docs i32, frac f32) pairs
    # (historical, bit-exact); "packed" = one bit-packed i32 word per
    # posting (half the staged bytes AND half the per-query posting DMA
    # traffic; frac quantized to 12 bits — see docs/PRUNING.md for the
    # parity trade-off). Exported via ES_TPU_PALLAS_CODEC at startup;
    # index.search.pallas.postings_codec overrides per index.
    "search.pallas.postings_codec", "raw", choices={"raw", "packed"},
)


def _validate_probe_tiles(v):
    # probe counts are shape-bucketed into the compiled pruned program;
    # powers of two keep the variant count bounded. NB the probe/rest
    # subset sizes need not divide search.pallas.tiles_per_step — the
    # kernel clamps tps down to a divisor per launch, so small probe
    # values (2, 4) quietly reduce the DMA double-buffering depth of the
    # pruned passes (see score_tiles).
    if v not in (2, 4, 8, 16, 32):
        raise IllegalArgumentException(
            f"Failed to parse value [{v}] for setting "
            f"[search.pallas.pruning.probe_tiles]: must be one of "
            f"2, 4, 8, 16, 32")


SEARCH_PALLAS_PRUNING_ENABLED = Setting.bool_setting(
    # block-max pruned top-k scoring on the mesh_pallas rung: skip tiles
    # whose summed per-(tile, term) upper-bound impact cannot beat the
    # running k-th score. Under pruning hit TOTALS become a documented
    # lower bound (WAND semantics) — default off; exact-total consumers
    # and dense-output queries (aggs, counts, sort) always run
    # exhaustively regardless.
    "search.pallas.pruning.enabled", False, dynamic=True
)
SEARCH_PALLAS_PRUNING_PROBE_TILES = Setting(
    # how many highest-bound tiles the probe pass scores unconditionally
    # to seed the pruning threshold (the block-size knob of the pruned
    # program; bigger = better threshold, less pruning headroom)
    "search.pallas.pruning.probe_tiles", 8, int,
    validator=_validate_probe_tiles, dynamic=True,
)

# --- dense-vector kNN retrieval on the MXU (docs/VECTOR.md) ---


def _validate_knn_tile_sub(v):
    # tile sublane counts the kNN kernel's geometry helper honors; the
    # doc space and the VMEM budget may still shrink the effective tile
    if v not in (8, 16, 32, 64, 128):
        raise IllegalArgumentException(
            f"Failed to parse value [{v}] for setting "
            f"[search.knn.tile_sub]: must be one of 8, 16, 32, 64, 128")


SEARCH_KNN_ENABLED = Setting.bool_setting(
    # serve eligible kNN queries from the mesh MXU program
    # (ops/pallas_knn.py); false = every vector query runs the host
    # plan-node rung (exact same scores, no MXU batching)
    "search.knn.enabled", True, dynamic=True
)
SEARCH_KNN_TILE_SUB = Setting(
    # doc-tile sublane count of the kNN kernel: W = tile_sub * 128 docs
    # per grid step. Bigger tiles amortize the fixed per-step dispatch
    # cost; the geometry helper shrinks the tile when the f32-converted
    # embedding block would overflow VMEM (high-dimensional fields)
    "search.knn.tile_sub", 64, int,
    validator=_validate_knn_tile_sub, dynamic=True,
)

# --- fused on-device aggregations (ISSUE 13, docs/AGGS.md) ---

SEARCH_AGGS_FUSED = Setting.bool_setting(
    # reduce eligible aggregation bodies INSIDE the mesh program (the
    # columnar doc-values plane) instead of shipping per-slot matched
    # masks to the host; false = every agg runs the host reduce.
    # Results are byte-identical either way (the engineered-exact
    # envelope in docs/AGGS.md gates eligibility structurally).
    "search.aggs.fused", True, dynamic=True
)

# --- device-memory accountant (ISSUE 9, docs/OBSERVABILITY.md) ---

SEARCH_MEMORY_HBM_BUDGET = Setting.bytes_setting(
    # HBM staging budget for the DeviceMemoryAccountant (0 = unlimited).
    # Over budget, a new staging first LRU-evicts the coldest staged
    # scopes (segment tables, mesh executors — both restage lazily),
    # then DEMOTES to the host rung with plane-ladder decision reason
    # hbm_budget: queries degrade, never 429/5xx. The accounting breaker
    # child mirrors the ledger, so the budget also shows as its limit.
    "search.memory.hbm_budget_bytes", "0b", dynamic=True
)

# --- device-staging retry (ISSUE 10, docs/RESILIENCE.md) ---

SEARCH_STAGING_RETRY_MAX_ATTEMPTS = Setting.int_setting(
    # total attempts for one device staging (HBM transfer group) whose
    # fault classified TRANSIENT (RESOURCE_EXHAUSTED / transfer error);
    # deterministic faults (shape/compile) never retry — they demote
    # the plane ladder immediately and quarantine with reason
    # staging_fault. 1 = no retries.
    "search.staging.retry.max_attempts", 3, min_value=1, max_value=10,
    dynamic=True
)
SEARCH_STAGING_RETRY_BACKOFF_MS = Setting.float_setting(
    # first-retry backoff in milliseconds; doubles per retry
    # (exponential). Keep small: staging sits on the query path — the
    # retry only exists to ride out momentary device pressure.
    "search.staging.retry.backoff_ms", 10.0, min_value=0.0, dynamic=True
)

# --- zero-downtime rollout: compile cache + graceful drain (ISSUE 14,
# docs/RESILIENCE.md "Rollout & drain") ---

SEARCH_COMPILE_CACHE_PATH = Setting.str_setting(
    # JAX persistent compilation cache directory: a restarted node
    # deserializes compiled mesh-program executables from disk instead
    # of paying the 2–27 s first-compile stall per variant. Empty =
    # disabled. Startup-only (the XLA cache must configure before the
    # first compile).
    "search.compile.cache_path", ""
)
SEARCH_COMPILE_WARM_ON_START = Setting.bool_setting(
    # replay the persisted program-variant lattice in the background
    # after node start / index recovery (compile_cache.VariantRegistry):
    # first compiles — persistent-cache deserializations included — are
    # absorbed OFF the query path (programs_warmed_total), so a warmed
    # rolling restart serves zero query-path first compiles
    "search.compile.warm_on_start", True
)
SEARCH_DRAIN_DEADLINE = Setting.time_setting(
    # graceful-drain deadline: a draining node stops admitting (clean
    # 503 + Retry-After, queued entries shed with the same contract)
    # and waits at most this long for in-flight searches before it
    # flushes (synced-flush marker) and shuts down; also the
    # Retry-After a drain rejection carries
    "search.drain.deadline", "30s", dynamic=True
)

# --- phase-attributed query telemetry (docs/OBSERVABILITY.md) ---

SEARCH_TELEMETRY_ENABLED = Setting.bool_setting(
    # the always-on phase tracer's kill switch: false stops per-query
    # span recording (profile/_stats phases/slowlog enrichment go
    # quiet); the tracer is bounded-overhead either way — this exists
    # for incident triage, not steady-state tuning
    "search.telemetry.enabled", True, dynamic=True
)

NODE_SETTINGS = [
    CLUSTER_NAME,
    NODE_NAME,
    NODE_DATA,
    NODE_MASTER,
    NODE_INGEST,
    PATH_DATA,
    PATH_REPO,
    HTTP_PORT,
    HTTP_HOST,
    ACTION_AUTO_CREATE_INDEX,
    ACTION_DESTRUCTIVE_REQUIRES_NAME,
    SEARCH_DEFAULT_SIZE,
    SEARCH_MAX_BUCKETS,
    SEARCH_KEEPALIVE,
    SEARCH_DEFAULT_TIMEOUT,
    SEARCH_ALLOW_PARTIAL_RESULTS,
    BREAKER_TOTAL_LIMIT,
    BREAKER_REQUEST_LIMIT,
    BREAKER_FIELDDATA_LIMIT,
    TRANSPORT_REQUEST_TIMEOUT,
    TRANSPORT_RETRY_MAX_ATTEMPTS,
    TRANSPORT_RETRY_INITIAL_BACKOFF,
    TRANSPORT_RETRY_BACKOFF_MULTIPLIER,
    TRANSPORT_RETRY_MAX_BACKOFF,
    TRANSPORT_HEALTH_FAILURE_THRESHOLD,
    TRANSPORT_HEALTH_QUARANTINE,
    FD_PING_TIMEOUT,
    FD_PING_RETRIES,
    PUBLISH_TIMEOUT,
    REPLICATION_TIMEOUT,
    RECOVERY_RETRY_DELAY_NETWORK,
    RECOVERY_MAX_RETRIES,
    RECOVERY_ACTION_TIMEOUT,
    SEARCH_BATCH_ENABLED,
    SEARCH_BATCH_WINDOW_MS,
    SEARCH_BATCH_MAX_QUERIES,
    SEARCH_BATCH_MAX_WINDOW_MS,
    SEARCH_QUEUE_SIZE,
    SEARCH_ADMISSION_ENABLED,
    SEARCH_ADMISSION_MAX_CONCURRENT,
    SEARCH_ADMISSION_WEIGHTS,
    SEARCH_ADMISSION_BROWNOUT_PRUNED,
    SEARCH_ADMISSION_BROWNOUT_RESCORE,
    SEARCH_ADMISSION_BROWNOUT_FEATURES,
    SEARCH_PALLAS_TILES_PER_STEP,
    SEARCH_PALLAS_POSTINGS_CODEC,
    SEARCH_PALLAS_PRUNING_ENABLED,
    SEARCH_PALLAS_PRUNING_PROBE_TILES,
    SEARCH_KNN_ENABLED,
    SEARCH_KNN_TILE_SUB,
    SEARCH_AGGS_FUSED,
    SEARCH_MEMORY_HBM_BUDGET,
    SEARCH_STAGING_RETRY_MAX_ATTEMPTS,
    SEARCH_STAGING_RETRY_BACKOFF_MS,
    SEARCH_COMPILE_CACHE_PATH,
    SEARCH_COMPILE_WARM_ON_START,
    SEARCH_DRAIN_DEADLINE,
    SEARCH_TELEMETRY_ENABLED,
]

# --- index-scoped ---

# 6.x default: FIVE primary shards (IndexMetaData.SETTING_NUMBER_OF_SHARDS
# default; 7.0 changed it to 1) — conformance tests encode the 5-shard
# doc distribution
INDEX_NUMBER_OF_SHARDS = Setting.int_setting(
    "index.number_of_shards", 5, min_value=1, max_value=1024, scope=Scope.INDEX
)
INDEX_NUMBER_OF_REPLICAS = Setting.int_setting(
    "index.number_of_replicas", 1, min_value=0, scope=Scope.INDEX, dynamic=True
)
INDEX_REFRESH_INTERVAL = Setting.time_setting(
    "index.refresh_interval", "1s", scope=Scope.INDEX, dynamic=True
)
INDEX_MAX_RESULT_WINDOW = Setting.int_setting(
    "index.max_result_window", 10000, min_value=1, scope=Scope.INDEX, dynamic=True
)
INDEX_MAX_SLICES_PER_SCROLL = Setting.int_setting(
    "index.max_slices_per_scroll", 1024, min_value=1, scope=Scope.INDEX,
    dynamic=True
)
INDEX_BLOCK_SIZE = Setting.int_setting(
    # TPU-specific: posting block width (lane dimension); must stay a
    # multiple of 128 so blocks map onto VPU lanes.
    "index.tpu.posting_block_size",
    128,
    min_value=128,
    scope=Scope.INDEX,
)
INDEX_TRANSLOG_DURABILITY = Setting.str_setting(
    "index.translog.durability",
    "request",
    choices={"request", "async"},
    scope=Scope.INDEX,
    dynamic=True,
)
INDEX_TRANSLOG_FLUSH_THRESHOLD = Setting.bytes_setting(
    "index.translog.flush_threshold_size", "512mb", scope=Scope.INDEX, dynamic=True
)
INDEX_QUERY_DEFAULT_FIELD = Setting.str_setting(
    "index.query.default_field", "_all", scope=Scope.INDEX, dynamic=True
)
INDEX_MAPPING_TOTAL_FIELDS_LIMIT = Setting.int_setting(
    "index.mapping.total_fields.limit", 1000, min_value=1, scope=Scope.INDEX, dynamic=True
)
INDEX_MAPPING_DENSE_VECTOR_MAX_DIMS = Setting.int_setting(
    # upper bound on a dense_vector field's [dims] (validated at mapping
    # compile): staged embedding bytes grow linearly with dims, and the
    # kNN kernel's VMEM tile shrinks with them (docs/VECTOR.md)
    "index.mapping.dense_vector.max_dims", 1024, min_value=1,
    scope=Scope.INDEX,
)

# --- mesh data plane (parallel/plan_exec.py; docs/MESH.md) ---

INDEX_SEARCH_MESH = Setting.bool_setting(
    # serve eligible searches as one multi-device mesh program (true) or
    # always host-merge per shard (false)
    "index.search.mesh", True, scope=Scope.INDEX
)
INDEX_SEARCH_MESH_MAX_SLOTS = Setting.int_setting(
    # packing limit: how many segments may pack onto one device before
    # the index falls back to the host path (slots unroll in the device
    # program, so compile time and per-device work grow with this)
    "index.search.mesh.max_slots_per_device", 4, min_value=1, max_value=64,
    scope=Scope.INDEX
)
INDEX_SEARCH_MESH_PLANE = Setting.str_setting(
    # scoring-plane override inside the mesh program: auto = tile kernel
    # when stageable with scatter fallback; pallas = kernel or host
    # (never the scatter mesh); scatter = never build kernel plans
    "index.search.mesh.plane", "auto",
    choices={"auto", "pallas", "scatter"}, scope=Scope.INDEX
)
INDEX_SEARCH_PALLAS_POSTINGS_CODEC = Setting.str_setting(
    # per-index override of the kernel-plane postings representation
    # ("default" follows the node-wide search.pallas.postings_codec);
    # consulted when segments/mesh tables stage, so a change applies to
    # stagings performed AFTER it (docs/PRUNING.md)
    "index.search.pallas.postings_codec", "default",
    choices={"default", "raw", "packed"}, scope=Scope.INDEX
)
INDEX_SEARCH_AGGS_FUSED = Setting.str_setting(
    # per-index override of the fused on-device aggregation plane
    # ("default" follows the node-wide search.aggs.fused; an EXPLICIT
    # cluster-level search.aggs.fused still wins while set — the
    # put_cluster_settings explicitness contract, docs/AGGS.md)
    "index.search.aggs.fused", "default",
    choices={"default", "true", "false"}, scope=Scope.INDEX, dynamic=True
)
INDEX_SEARCH_PLANE_QUARANTINE_COOLDOWN = Setting.time_setting(
    # plane-health quarantine: after a mesh_pallas / mesh plane failure
    # (compile error, OOM, runtime fault) the plane is benched for this
    # index and queries serve from the next rung of the ladder; after
    # the cooldown one query probes the plane again
    "index.search.plane_quarantine.cooldown", "60s", scope=Scope.INDEX,
    dynamic=True
)
INDEX_STAGING_DELTA_ENABLED = Setting.bool_setting(
    # delta device staging (ISSUE 20, docs/MESH.md "Slot allocator &
    # generations"): refreshes that add segments within free slot
    # capacity append ONLY the new tables, deletes flip only live-mask
    # columns in place; false forces the pre-delta full-rebuild path
    # (the geometry-change fallback becomes the only path)
    "index.staging.delta.enabled", True, scope=Scope.INDEX, dynamic=True
)
INDEX_STAGING_COMPACT_THRESHOLD = Setting.float_setting(
    # background slot compaction trigger: when any staged slot's
    # tombstone density reaches this fraction (or free slots are
    # exhausted), a single-flight background pass merges sparse slots
    # into fresh ones and restages a compact generation; <= 0 disables
    "index.staging.compact.threshold", 0.25, scope=Scope.INDEX,
    dynamic=True
)
INDEX_SCRUB_INTERVAL = Setting.time_setting(
    # background store/device scrubber (ISSUE 16, docs/RESILIENCE.md
    # "Data integrity"): re-verify sealed-segment checksums and compare
    # a sampled digest of device-staged tables against host truth every
    # interval. Off by default (None/negative disables) — scrubbing
    # reads every committed byte, so operators opt in per index or via
    # the cluster-level override like every other dynamic knob
    "index.scrub.interval", None, scope=Scope.INDEX, dynamic=True
)
INDEX_SEARCH_SLOWLOG_WARN = Setting.time_setting(
    "index.search.slowlog.threshold.query.warn", None, scope=Scope.INDEX,
    dynamic=True
)
INDEX_SEARCH_SLOWLOG_INFO = Setting.time_setting(
    "index.search.slowlog.threshold.query.info", None, scope=Scope.INDEX,
    dynamic=True
)

INDEX_SETTINGS = [
    INDEX_SEARCH_MESH,
    INDEX_SEARCH_MESH_MAX_SLOTS,
    INDEX_SEARCH_MESH_PLANE,
    INDEX_SEARCH_PALLAS_POSTINGS_CODEC,
    INDEX_SEARCH_AGGS_FUSED,
    INDEX_SEARCH_PLANE_QUARANTINE_COOLDOWN,
    INDEX_STAGING_DELTA_ENABLED,
    INDEX_STAGING_COMPACT_THRESHOLD,
    INDEX_SCRUB_INTERVAL,
    INDEX_SEARCH_SLOWLOG_WARN,
    INDEX_SEARCH_SLOWLOG_INFO,
    INDEX_NUMBER_OF_SHARDS,
    INDEX_NUMBER_OF_REPLICAS,
    INDEX_REFRESH_INTERVAL,
    INDEX_MAX_RESULT_WINDOW,
    INDEX_MAX_SLICES_PER_SCROLL,
    INDEX_BLOCK_SIZE,
    INDEX_TRANSLOG_DURABILITY,
    INDEX_TRANSLOG_FLUSH_THRESHOLD,
    INDEX_QUERY_DEFAULT_FIELD,
    INDEX_MAPPING_TOTAL_FIELDS_LIMIT,
    INDEX_MAPPING_DENSE_VECTOR_MAX_DIMS,
]


def cluster_settings() -> AbstractScopedSettings:
    return AbstractScopedSettings(Scope.NODE, NODE_SETTINGS)


def index_scoped_settings() -> AbstractScopedSettings:
    return AbstractScopedSettings(Scope.INDEX, INDEX_SETTINGS)
