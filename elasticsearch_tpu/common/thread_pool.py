"""Named bounded executors with rejection — the node's thread pools.

Role model: ``ThreadPool`` (core/src/main/java/org/elasticsearch/
threadpool/ThreadPool.java:67-77) — fixed pools per workload class
(search, write/index, get, management, generic ...) with bounded queues,
and ``EsRejectedExecutionException`` when a queue is full, which the REST
layer surfaces as HTTP 429 (RestStatus.TOO_MANY_REQUESTS). The bounded
queue is the backpressure mechanism: a node drowning in search traffic
rejects new work instead of queueing unboundedly and falling over.

Pool sizing follows the reference's formulas scaled to this process:
search = 3*cores/2+1 with queue 1000, write = cores with queue 200,
get = cores with queue 1000, management/generic = small scaling pools.
"""

from __future__ import annotations

import os
import queue
import threading
import time
from collections import deque
from concurrent.futures import Future
from dataclasses import dataclass
from typing import Any, Callable, Dict, Optional

from elasticsearch_tpu.common.errors import ElasticsearchTpuException


class EsRejectedExecutionException(ElasticsearchTpuException):
    """Queue full — maps to HTTP 429 like the reference's
    EsRejectedExecutionException -> RestStatus.TOO_MANY_REQUESTS."""

    status_code = 429


@dataclass
class PoolStats:
    threads: int
    queue_size: int
    active: int = 0
    queue: int = 0
    rejected: int = 0
    completed: int = 0

    def as_dict(self) -> dict:
        return {
            "threads": self.threads,
            "queue_size": self.queue_size,
            "active": self.active,
            "queue": self.queue,
            "rejected": self.rejected,
            "completed": self.completed,
        }


_STOP = object()  # worker shutdown sentinel


def estimate_retry_after(completions, waiting: int) -> float:
    """Seconds until ``waiting`` work items have plausibly drained one
    slot, from a ring of recent completion timestamps (monotonic
    seconds): the Retry-After a 429 carries, clamped [1, 30] and
    defaulting to 1s without enough signal. Shared by the thread-pool
    executors and the search admission plane so both 429 sources a
    client sees stay consistent (docs/OVERLOAD.md)."""
    now = time.monotonic()
    recent = [t for t in completions if now - t <= 5.0]
    if len(recent) < 2:
        return 1.0
    rate = len(recent) / max(now - recent[0], 1e-6)
    return min(max(waiting / rate, 1.0), 30.0)


class _Executor:
    """Fixed worker pool over a bounded queue (EsThreadPoolExecutor).
    Workers start lazily on the first submit and block on the queue (no
    idle polling); shutdown completes queued futures with a rejection so
    no caller hangs forever."""

    def __init__(self, name: str, threads: int, queue_size: int):
        self.name = name
        self.threads = threads
        self.queue_size = queue_size
        self._queue: "queue.Queue" = queue.Queue(maxsize=queue_size)
        self._lock = threading.Lock()
        self._active = 0
        self._rejected = 0
        self._completed = 0
        self._shut = False
        self._workers: list = []
        # recent completion timestamps: the observed drain rate behind
        # the Retry-After a rejection carries (docs/OVERLOAD.md) — a
        # client backing off proportionally to the real overload instead
        # of a fixed guess
        self._completions: deque = deque(maxlen=64)

    def _ensure_workers(self) -> None:
        with self._lock:
            if self._workers or self._shut:
                return
            self._workers = [
                threading.Thread(target=self._worker, daemon=True,
                                 name=f"estpu[{self.name}][{i}]")
                for i in range(self.threads)
            ]
            for w in self._workers:
                w.start()

    def _worker(self) -> None:
        while True:
            item = self._queue.get()
            if item is _STOP:
                return
            fn, future = item
            with self._lock:
                self._active += 1
            try:
                future.set_result(fn())
            except BaseException as e:  # noqa: BLE001 — delivered to caller
                future.set_exception(e)
            finally:
                with self._lock:
                    self._active -= 1
                    self._completed += 1
                    self._completions.append(time.monotonic())

    def submit(self, fn: Callable[[], Any]) -> Future:
        """Enqueue; raises EsRejectedExecutionException when the bounded
        queue is full (the backpressure signal). The shut-check and the
        enqueue happen under the pool lock so a concurrent shutdown can
        never strand a task behind the stop sentinels (which would hang
        its caller forever)."""
        self._ensure_workers()
        future: Future = Future()
        with self._lock:
            if self._shut:
                raise EsRejectedExecutionException(
                    f"rejected execution on [{self.name}]: pool is shut "
                    f"down")
            try:
                self._queue.put_nowait((fn, future))
            except queue.Full:
                self._rejected += 1
                exc = EsRejectedExecutionException(
                    f"rejected execution on [{self.name}]: queue capacity "
                    f"[{self.queue_size}] is full")
                exc.retry_after_s = estimate_retry_after(
                    self._completions, self._queue.qsize())
                raise exc from None
        return future

    def resize_queue(self, queue_size: int) -> None:
        """Dynamic queue-depth update (search.queue.size): stdlib Queue
        checks maxsize at put time, so mutating it under the queue's
        own mutex retargets the bound for every later submit; already-
        queued work is never dropped by a shrink."""
        queue_size = max(1, int(queue_size))
        with self._queue.mutex:
            self._queue.maxsize = queue_size
            self._queue.not_full.notify_all()
        self.queue_size = queue_size

    def stats(self) -> PoolStats:
        with self._lock:
            return PoolStats(
                threads=self.threads, queue_size=self.queue_size,
                active=self._active, queue=self._queue.qsize(),
                rejected=self._rejected, completed=self._completed)

    def shutdown(self) -> None:
        with self._lock:
            self._shut = True  # submits are locked out from here on
            started = len(self._workers)
            # fail queued-but-unstarted work so blocked callers wake up
            while True:
                try:
                    item = self._queue.get_nowait()
                except queue.Empty:
                    break
                if item is not _STOP:
                    item[1].set_exception(EsRejectedExecutionException(
                        f"[{self.name}] shut down before execution"))
        # sentinels outside the lock: workers may need to drain a few
        # before capacity frees when threads > queue_size
        for _ in range(started):
            self._queue.put(_STOP)


class ThreadPool:
    """The node's named executors (ThreadPool.Names)."""

    def __init__(self, cores: Optional[int] = None,
                 overrides: Optional[Dict[str, dict]] = None):
        cores = cores or os.cpu_count() or 4
        spec = {
            # the reference's sizing formulas (ThreadPool.java halfProc etc.)
            "search": {"threads": 3 * cores // 2 + 1, "queue_size": 1000},
            "write": {"threads": cores, "queue_size": 200},
            "get": {"threads": cores, "queue_size": 1000},
            "management": {"threads": max(2, cores // 2),
                           "queue_size": 100},
            "generic": {"threads": max(4, cores), "queue_size": 500},
        }
        for name, over in (overrides or {}).items():
            spec.setdefault(name, {"threads": 2, "queue_size": 100})
            spec[name].update(over)
        self.executors: Dict[str, _Executor] = {
            name: _Executor(name, **cfg) for name, cfg in spec.items()
        }

    def executor(self, name: str) -> _Executor:
        return self.executors.get(name) or self.executors["generic"]

    def submit(self, name: str, fn: Callable[[], Any]) -> Future:
        return self.executor(name).submit(fn)

    def run(self, name: str, fn: Callable[[], Any],
            timeout: Optional[float] = None):
        """Submit + wait: the REST dispatch pattern (handler work runs on
        the action's executor; the IO thread blocks for the response)."""
        return self.submit(name, fn).result(timeout)

    def stats(self) -> dict:
        return {name: ex.stats().as_dict()
                for name, ex in sorted(self.executors.items())}

    def shutdown(self) -> None:
        for ex in self.executors.values():
            ex.shutdown()
