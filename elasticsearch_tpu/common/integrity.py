"""IntegrityService: the corruption-detection ledger (ISSUE 16).

Role model: the reference's corruption bookkeeping spread across
``Store.markStoreCorrupted`` + ``ShardStateMetaData`` + the
``indices.stats`` store block — pulled into one process singleton so
every detection site (store load, peer-recovery file install, snapshot
restore, query-path staging, the background scrubber) reports through
the same counters and the ``_stats`` integrity block can answer "has
this node ever served — or refused to serve — corrupt bytes, and
where was it caught?".

Three pieces (docs/OBSERVABILITY.md "Data integrity"):

- ``corruption_detected_total`` + the per-site split
  (``corruption_detected_by_site``): one increment per DETECTION, keyed
  by where the bad bytes were caught (``load``, ``recovery``,
  ``restore``, ``query``, ``scrub``, ``snapshot``). Detection is the
  contract: a corruption nobody counted is a corruption that may have
  served.

- the **marker events ring**: every ``corrupted_*`` marker write and
  clear appends ``{index, shard, site, reason, marker, action}`` to a
  bounded ring — the operator's join key between a RED shard in
  ``_cat/shards`` and the detection that quarantined it.

- the **scrub counters**: ``scrub_runs_total`` /
  ``scrub_bytes_verified_total`` / ``scrub_drift_total`` — how much the
  background scrubber (``index.scrub.interval``) has re-verified and
  how often device-staged tables drifted from host truth (each drift
  invalidates the staging and restages with lifecycle reason
  ``scrub`` — drifted tables count, never serve).
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional

# Detection sites (the per-site axis of corruption_detected_by_site).
# Every detection site classifies itself onto these:
#   load      segment load over an existing data path (boot/reconcile)
#   recovery  peer-recovery file install digest verification
#   restore   snapshot restore manifest-digest verification
#   query     a CorruptIndexException surfacing on the search path
#   scrub     the background scrubber (checksums or device drift)
#   snapshot  snapshot create reading a copy that fails verification
SITES = ("load", "recovery", "restore", "query", "scrub", "snapshot")


class IntegrityService:
    """Process-wide corruption/scrub ledger (thread-safe)."""

    MAX_EVENTS = 128

    def __init__(self):
        self._lock = threading.Lock()
        self.corruption_detected_total = 0
        self._by_site: Dict[str, int] = {site: 0 for site in SITES}
        self.scrub_runs_total = 0
        self.scrub_bytes_verified_total = 0
        self.scrub_drift_total = 0
        self.markers_written_total = 0
        self.markers_cleared_total = 0
        self.marker_events: List[dict] = []
        self.events_dropped = 0

    def _push(self, event: dict) -> None:
        self.marker_events.append(event)
        if len(self.marker_events) > self.MAX_EVENTS:
            del self.marker_events[0]
            self.events_dropped += 1

    # -- detection -------------------------------------------------------

    def record_corruption(self, index: str, shard: int, site: str,
                          reason: str) -> None:
        """One detected corruption (counted at DETECTION, before any
        quarantine/heal side effects run — even a failed heal leaves
        the detection visible)."""
        assert site in SITES, site
        with self._lock:
            self.corruption_detected_total += 1
            self._by_site[site] += 1
            self._push({
                "action": "detected", "index": index or "_unknown",
                "shard": int(shard), "site": site,
                "reason": str(reason)[:200],
                "timestamp_ms": int(time.time() * 1000),
            })

    def record_marker(self, index: str, shard: int, marker: dict, *,
                      action: str = "marked") -> None:
        """A ``corrupted_*`` marker lifecycle event (``marked`` when the
        quarantine wrote it, ``cleared`` when a successful re-recovery
        replaced the bytes)."""
        assert action in ("marked", "cleared"), action
        with self._lock:
            if action == "marked":
                self.markers_written_total += 1
            else:
                self.markers_cleared_total += 1
            self._push({
                "action": action, "index": index or "_unknown",
                "shard": int(shard),
                "site": str(marker.get("site", "load")),
                "reason": str(marker.get("reason", ""))[:200],
                "marker": str(marker.get("marker", "")),
                "timestamp_ms": int(time.time() * 1000),
            })

    # -- scrubber --------------------------------------------------------

    def record_scrub_run(self, nbytes_verified: int) -> None:
        with self._lock:
            self.scrub_runs_total += 1
            self.scrub_bytes_verified_total += max(0, int(nbytes_verified))

    def record_scrub_drift(self, index: str, shard: int, scope: str,
                           kind: str) -> None:
        """Device-staged table digest drifted from host truth: the
        staging was invalidated (restage reason ``scrub``) — the drifted
        bytes never served."""
        with self._lock:
            self.scrub_drift_total += 1
            self._push({
                "action": "drift", "index": index or "_unknown",
                "shard": int(shard), "site": "scrub",
                "reason": f"device staging drift [{scope}/{kind}]",
                "timestamp_ms": int(time.time() * 1000),
            })

    # -- export ----------------------------------------------------------

    def stats(self, index: Optional[str] = None) -> dict:
        """The ``search.integrity`` stats block (per index, or node-wide
        with ``index=None``). Counters are node-global (detections on a
        since-deleted index must stay visible); the event ring filters
        per index."""
        with self._lock:
            events = (list(self.marker_events) if index is None
                      else [e for e in self.marker_events
                            if e["index"] == index])
            return {
                "corruption_detected_total": self.corruption_detected_total,
                "corruption_detected_by_site": dict(self._by_site),
                "scrub_runs_total": self.scrub_runs_total,
                "scrub_bytes_verified_total": self.scrub_bytes_verified_total,
                "scrub_drift_total": self.scrub_drift_total,
                "markers_written_total": self.markers_written_total,
                "markers_cleared_total": self.markers_cleared_total,
                "marker_events": events,
                "events_dropped": self.events_dropped,
            }


# ---------------------------------------------------------------------------
# Process-level singleton (detection sites reach it through
# integrity_service(); mirrors the memory_accountant() idiom)
# ---------------------------------------------------------------------------

_service: Optional[IntegrityService] = None
_service_lock = threading.Lock()


def integrity_service() -> IntegrityService:
    global _service
    svc = _service
    if svc is not None:
        return svc
    with _service_lock:
        if _service is None:
            _service = IntegrityService()
        return _service
