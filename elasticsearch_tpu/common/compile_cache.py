"""Persistent compilation cache + AOT program-variant warming (ISSUE 14).

Production rollout means nodes restart constantly — and today every
restart pays a 2–27 s first-compile stall per program variant (geometry
× q_batch × codec/pruning/sel × knn × agg) before the fast plane serves
again (ROADMAP item 4). This module makes restart a non-event for the
compile plane:

- **persistent compilation cache** — ``configure_compile_cache(path)``
  enables JAX's on-disk executable cache (``search.compile.cache_path``)
  so a restarted process deserializes XLA executables instead of
  recompiling them;
- **variant registry** — every compiled mesh-program variant records a
  stable key (and, per index, a replayable warm spec) into a JSON file
  persisted beside the store, so the NEXT process knows the whole
  variant lattice before the first query arrives;
- **AOT warming** — on node start / index open / post-failover
  promotion, the recorded lattice is replayed in the background under
  :func:`warming` so first-call stalls (cache deserialization included)
  are absorbed OFF the query path;
- **telemetry** — ``compile_cache_{hit,miss}_total``,
  ``programs_warmed_total``, ``query_path_first_compile_total`` and a
  log2-ms first-compile-stall histogram, exported as the ``compile``
  block of ``_stats`` / ``_nodes/stats`` (docs/OBSERVABILITY.md).

Accounting semantics: a variant's FIRST invocation in a process is its
compile (or persistent-cache deserialization). It counts as a *hit*
when the variant key was already in the registry persisted by a prior
process AND the persistent cache is enabled (the executable should be
on disk); otherwise a *miss* (a full XLA compile). Independently it
counts as *warmed* when it ran under the warming context, else as a
query-path first compile — the number a warmed rolling restart must
hold at zero (the ChaosSoak rolling-restart phase asserts exactly
that).
"""

from __future__ import annotations

import contextvars
import hashlib
import json
import os
import threading
import time
from collections import deque
from contextlib import contextmanager
from typing import Dict, List, Optional

# log2-ish ms buckets for the first-compile stall histogram; the le_*
# naming matches the telemetry histograms (bucket labels are skipped by
# the observability lint, the block keys themselves are documented)
_STALL_BUCKETS_MS = (1.0, 8.0, 64.0, 512.0, 4096.0, 32768.0)
_EVENT_RING = 64

# warming context: first compiles under it are the warmer's, not the
# query path's (the contextvar survives same-thread nested calls)
_WARMING: contextvars.ContextVar[bool] = contextvars.ContextVar(
    "es_tpu_compile_warming", default=False)

_CACHE_PATH: Optional[str] = None


def in_warming() -> bool:
    return _WARMING.get()


@contextmanager
def warming():
    """Mark first compiles in this context as background warming (they
    count into ``programs_warmed_total``, never into
    ``query_path_first_compile_total``)."""
    token = _WARMING.set(True)
    try:
        yield
    finally:
        _WARMING.reset(token)


def configure_compile_cache(path: Optional[str]) -> bool:
    """Enable JAX's persistent compilation cache at ``path``
    (``search.compile.cache_path``). Thresholds are dropped to zero so
    every mesh program caches — the 2–27 s stalls this kills are
    exactly the big-program compiles. Returns False (and stays
    disabled) when this jax build has no persistent cache."""
    global _CACHE_PATH
    if not path:
        _CACHE_PATH = None
        try:  # also disable the XLA-side cache (bench cold leg)
            import jax

            jax.config.update("jax_compilation_cache_dir", None)
        except Exception:  # noqa: BLE001 — best-effort
            pass
        return False
    try:
        import jax

        os.makedirs(path, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", path)
        for opt, val in (("jax_persistent_cache_min_compile_time_secs", 0),
                         ("jax_persistent_cache_min_entry_size_bytes", -1)):
            try:
                jax.config.update(opt, val)
            except Exception:  # noqa: BLE001 — older jax: keep defaults
                pass
    except Exception:  # noqa: BLE001 — no jax / no cache support
        _CACHE_PATH = None
        return False
    _CACHE_PATH = path
    return True


def compile_cache_enabled() -> bool:
    return _CACHE_PATH is not None


def compile_cache_path() -> Optional[str]:
    return _CACHE_PATH


def variant_key(family: str, *parts) -> str:
    """Stable cross-process key for one compiled program variant: the
    family plus a digest of its shape-defining parts (the same strings
    the lru_cache keys are built from are deterministic across
    processes)."""
    digest = hashlib.sha1(
        "|".join(str(p) for p in parts).encode("utf-8")).hexdigest()[:16]
    return f"{family}:{digest}"


class VariantRegistry:
    """The persisted program-variant lattice: every compiled variant's
    key, plus per-index replayable warm specs (the query shapes that
    compiled them). ``path=None`` keeps it in-memory (tests, nodes
    without a data path)."""

    MAX_WARM_PER_INDEX = 64
    MAX_PROGRAMS = 1024

    def __init__(self, path: Optional[str] = None):
        self.path = path
        self._lock = threading.Lock()
        self.programs: set = set()
        # warm specs: {index: {dedup_key: spec}}
        self.warm: Dict[str, Dict[str, dict]] = {}
        if path and os.path.exists(path):
            try:
                with open(path, encoding="utf-8") as f:
                    data = json.load(f)
                self.programs = set(data.get("programs") or [])
                self.warm = {
                    idx: dict(entries)
                    for idx, entries in (data.get("warm") or {}).items()}
            except (OSError, json.JSONDecodeError, TypeError):
                pass  # a corrupt registry warms nothing; it rebuilds
        # hit/miss baseline: what a PRIOR process had compiled (and the
        # persistent cache should therefore serve from disk)
        self._preexisting = frozenset(self.programs)

    def program_known(self, key: str) -> bool:
        return key in self._preexisting

    def record_program(self, key: str) -> None:
        with self._lock:
            if key in self.programs:
                return
            if len(self.programs) >= self.MAX_PROGRAMS:
                return  # runaway-variant backstop; warming stays bounded
            self.programs.add(key)
            self._persist_locked()

    def has_warm(self, index: str, dedup_key: str) -> bool:
        """Lock-free membership probe for the query hot path: dict
        reads are atomic, and a rare stale False only costs one
        record_warm call that dedups under the lock anyway."""
        entries = self.warm.get(index)
        return entries is not None and dedup_key in entries

    def record_warm(self, index: str, dedup_key: str, spec: dict) -> None:
        with self._lock:
            entries = self.warm.setdefault(index, {})
            if dedup_key in entries:
                return
            if len(entries) >= self.MAX_WARM_PER_INDEX:
                return
            entries[dedup_key] = spec
            self._persist_locked()

    def warm_entries(self, index: str) -> List[dict]:
        with self._lock:
            return [dict(s) for s in self.warm.get(index, {}).values()]

    def indices(self) -> List[str]:
        with self._lock:
            return sorted(self.warm)

    def forget_index(self, index: str) -> None:
        with self._lock:
            if self.warm.pop(index, None) is not None:
                self._persist_locked()

    def _persist_locked(self) -> None:
        if not self.path:
            return
        try:
            os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
            tmp = self.path + ".tmp"
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump({"programs": sorted(self.programs),
                           "warm": self.warm}, f)
            os.replace(tmp, self.path)
        except OSError:
            pass  # registry persistence is best-effort; warming degrades


_REGISTRY = VariantRegistry(None)
_REGISTRY_LOCK = threading.Lock()


def variant_registry() -> VariantRegistry:
    return _REGISTRY


def set_variant_registry(registry: VariantRegistry) -> VariantRegistry:
    """Install the node's persisted registry (last constructed node
    wins, like the ES_TPU_* env exports — one registry per process)."""
    global _REGISTRY
    with _REGISTRY_LOCK:
        _REGISTRY = registry
    return registry


class CompileCacheStats:
    """Process-global compile-plane telemetry — the ``compile`` block of
    ``_stats``/``_nodes/stats`` (docs/OBSERVABILITY.md)."""

    def __init__(self):
        self._lock = threading.Lock()
        self.compile_cache_hit_total = 0
        self.compile_cache_miss_total = 0
        self.programs_warmed_total = 0
        self.query_path_first_compile_total = 0
        self._stall_hist = {f"le_{int(b)}": 0 for b in _STALL_BUCKETS_MS}
        self._stall_hist["le_inf"] = 0
        self._events: deque = deque(maxlen=_EVENT_RING)

    def record_first_call(self, family: str, variant: str, seconds: float,
                          warmed: bool, cache_hit: bool) -> None:
        ms = seconds * 1000.0
        with self._lock:
            if cache_hit:
                self.compile_cache_hit_total += 1
            else:
                self.compile_cache_miss_total += 1
            if warmed:
                self.programs_warmed_total += 1
            else:
                self.query_path_first_compile_total += 1
            for bound in _STALL_BUCKETS_MS:
                if ms <= bound:
                    self._stall_hist[f"le_{int(bound)}"] += 1
                    break
            else:
                self._stall_hist["le_inf"] += 1
            self._events.append({
                "family": family, "variant": variant,
                "stall_ms": round(ms, 3), "warmed": bool(warmed),
                "cache_hit": bool(cache_hit),
                "ts_ms": int(time.time() * 1000),
            })

    def stats(self) -> dict:
        with self._lock:
            return {
                "cache_enabled": compile_cache_enabled(),
                "cache_path": _CACHE_PATH,
                "variants_recorded": len(variant_registry().programs),
                "compile_cache_hit_total": self.compile_cache_hit_total,
                "compile_cache_miss_total": self.compile_cache_miss_total,
                "programs_warmed_total": self.programs_warmed_total,
                "query_path_first_compile_total":
                    self.query_path_first_compile_total,
                "first_compile_stall_ms": dict(self._stall_hist),
                "first_compile_events": list(self._events),
            }

    def reset_for_tests(self) -> None:
        with self._lock:
            self.compile_cache_hit_total = 0
            self.compile_cache_miss_total = 0
            self.programs_warmed_total = 0
            self.query_path_first_compile_total = 0
            for k in self._stall_hist:
                self._stall_hist[k] = 0
            self._events.clear()


_STATS = CompileCacheStats()


def compile_stats() -> CompileCacheStats:
    return _STATS


def instrument_program(run, family: str, key: str):
    """Wrap one compiled-program entry (an lru_cache'd jitted function):
    its FIRST invocation is the XLA compile / persistent-cache
    deserialization — time it, classify it hit/miss + warmed/query-path,
    and record the variant key in the registry. Later calls go straight
    through (one flag check)."""
    state = {"done": False}
    lock = threading.Lock()

    def wrapped(*args, **kwargs):
        if state["done"]:
            return run(*args, **kwargs)
        with lock:  # serialize racers onto ONE timed compile
            if state["done"]:
                return run(*args, **kwargs)
            t0 = time.perf_counter()
            out = run(*args, **kwargs)
            dt = time.perf_counter() - t0
            registry = variant_registry()
            known = registry.program_known(key)
            registry.record_program(key)
            _STATS.record_first_call(
                family, key, dt, warmed=in_warming(),
                cache_hit=known and compile_cache_enabled())
            state["done"] = True
            return out

    wrapped.__wrapped__ = run
    wrapped.variant_key = key
    return wrapped


def body_skeleton(body: dict) -> str:
    """Shape signature of a query body: the warm-spec dedup key — two
    bodies produce the same skeleton exactly when they compile the same
    program variant. Keys and SHAPE-relevant values survive (numbers:
    size/from/k/window are compile-time shapes; strings reduce to their
    token count: a 2-term match compiles a different plan than a 1-term
    one); free-text VALUES are dropped, so a hot query template records
    once, not once per term."""

    def walk(obj):
        if isinstance(obj, dict):
            return {k: walk(v) for k, v in sorted(obj.items())}
        if isinstance(obj, list):
            return [len(obj)] + [walk(v) for v in obj[:4]]
        if isinstance(obj, bool):
            return "b"
        if isinstance(obj, (int, float)):
            return obj
        if isinstance(obj, str):
            return f"s{len(obj.split())}"
        return "x"

    return json.dumps(walk(body), separators=(",", ":"))
