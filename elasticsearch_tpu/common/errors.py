"""Exception taxonomy with REST status codes.

Role model: the reference's ``ElasticsearchException`` hierarchy
(core/src/main/java/org/elasticsearch/ElasticsearchException.java) where
every failure maps to an HTTP status and serializes to a structured JSON
body (``type``, ``reason``, nested ``caused_by``).
"""

from __future__ import annotations


def es_type_name(class_name: str) -> str:
    """CamelCase -> snake_case, mirroring ES "type" strings like
    "index_not_found_exception"."""
    out = []
    for i, ch in enumerate(class_name):
        if ch.isupper() and i > 0:
            out.append("_")
        out.append(ch.lower())
    return "".join(out)


class ElasticsearchTpuException(Exception):
    """Base for all engine errors; carries an HTTP status."""

    status_code = 500

    def __init__(self, reason: str, **metadata):
        super().__init__(reason)
        self.reason = reason
        self.metadata = metadata

    @property
    def error_type(self) -> str:
        return es_type_name(type(self).__name__)

    def to_dict(self) -> dict:
        err = {"type": self.error_type, "reason": self.reason}
        err.update(self.metadata)
        cause = self.__cause__
        if isinstance(cause, ElasticsearchTpuException):
            err["caused_by"] = cause.to_dict()
        elif cause is not None:
            err["caused_by"] = {"type": type(cause).__name__, "reason": str(cause)}
        return {"error": err, "status": self.status_code}


class IndexNotFoundException(ElasticsearchTpuException):
    status_code = 404

    def __init__(self, index: str):
        super().__init__(f"no such index [{index}]", index=index)


class IndexAlreadyExistsException(ElasticsearchTpuException):
    status_code = 400

    def __init__(self, index: str):
        super().__init__(f"index [{index}] already exists", index=index)


class DocumentMissingException(ElasticsearchTpuException):
    status_code = 404

    def __init__(self, index: str, doc_id: str):
        super().__init__(f"[{index}]: document missing [{doc_id}]", index=index)


class ShardNotFoundException(ElasticsearchTpuException):
    status_code = 404


class ParsingException(ElasticsearchTpuException):
    """Malformed query DSL / request body (ES: ParsingException, 400)."""

    status_code = 400


class QueryShardException(ElasticsearchTpuException):
    """Query cannot execute against this shard's mapping (ES: 400)."""

    status_code = 400


class QueryPhaseExecutionException(ElasticsearchTpuException):
    """Query phase failed executing (ES: 500) — e.g. slice count over
    index.max_slices_per_scroll."""

    status_code = 500


class MapperParsingException(ElasticsearchTpuException):
    status_code = 400


class RoutingMissingException(ElasticsearchTpuException):
    """A parent-mapped (or routing-required) type got a single-doc op
    without routing/parent (ES: RoutingMissingException, 400)."""

    status_code = 400

    def __init__(self, doc_type: str, doc_id: str):
        super().__init__(
            f"routing is required for [{doc_type}]/[{doc_id}]")


class IllegalArgumentException(ElasticsearchTpuException):
    status_code = 400


class ActionRequestValidationException(ElasticsearchTpuException):
    status_code = 400


class UnavailableShardsException(ElasticsearchTpuException):
    """wait_for_active_shards not met (action/UnavailableShardsException)."""

    status_code = 503


class ResourceNotFoundException(ElasticsearchTpuException):
    status_code = 404


class ResourceAlreadyExistsException(ElasticsearchTpuException):
    status_code = 400


class VersionConflictEngineException(ElasticsearchTpuException):
    """Optimistic concurrency failure (ES: 409)."""

    status_code = 409

    def __init__(self, doc_id: str, current_version: int, expected: int):
        super().__init__(
            f"[{doc_id}]: version conflict, current version [{current_version}] "
            f"is different than the one provided [{expected}]"
        )


class CircuitBreakingException(ElasticsearchTpuException):
    """Memory circuit breaker tripped (ES: 429)."""

    status_code = 429

    def __init__(self, reason: str, bytes_wanted: int = 0, byte_limit: int = 0):
        super().__init__(reason, bytes_wanted=bytes_wanted, bytes_limit=byte_limit)


class EsRejectedExecutionException(ElasticsearchTpuException):
    """Thread-pool queue full — backpressure signal (ES: 429)."""

    status_code = 429


class NodeDrainingException(ElasticsearchTpuException):
    """The node is draining for a rollout/restart (ISSUE 14,
    docs/RESILIENCE.md "Rollout & drain"): new searches are refused with
    a clean 503 + Retry-After so the balancer/client routes around the
    node; in-flight work finishes within the drain deadline. Never a
    timeout, never a 5xx-with-stack — the REST layer renders the
    ``retry_after_s`` attribute as the ``Retry-After`` header exactly
    like the 429 rejections."""

    status_code = 503


class TaskCancelledException(ElasticsearchTpuException):
    status_code = 400


class TranslogCorruptedException(ElasticsearchTpuException):
    """Unreadable translog data at or below the checkpointed seqno —
    acked (possibly committed) operations cannot be replayed (ES:
    TranslogCorruptedException). A torn FINAL line of the newest
    generation is NOT this: that is an unacked in-flight append cut by a
    crash, tolerated by recovery."""

    status_code = 500


class CorruptedSnapshotException(ElasticsearchTpuException):
    """Snapshot blob bytes no longer match the per-file digests the
    create recorded in the manifest (ES: CorruptedSnapshotException,
    snake type ``corrupted_snapshot_exception``) — the restore of THAT
    index fails rather than installing unverified bytes (ISSUE 16)."""

    status_code = 500


class SearchPhaseExecutionException(ElasticsearchTpuException):
    status_code = 500

    def __init__(self, phase: str, reason: str, shard_failures=()):
        super().__init__(reason, phase=phase)
        self.shard_failures = list(shard_failures)

    def to_dict(self) -> dict:
        d = super().to_dict()
        d["error"]["failed_shards"] = [
            {"shard": f.get("shard"), "index": f.get("index"), "reason": f.get("reason")}
            for f in self.shard_failures
        ]
        return d


class NodeNotConnectedException(ElasticsearchTpuException):
    status_code = 500


class ConnectTransportException(NodeNotConnectedException):
    """Connection-level failure before the request reached the peer
    (ES: ConnectTransportException). Raised by the per-node connection
    health tracker when it fast-fails to a known-dead node; subclasses
    NodeNotConnectedException so every existing failover path treats it
    as a connection loss."""


class ReceiveTimeoutTransportException(NodeNotConnectedException):
    """The request was sent but no response arrived within the deadline
    (ES: ReceiveTimeoutTransportException). Subclasses
    NodeNotConnectedException: an unresponsive peer must trip the same
    failover/fault-detection paths as a disconnected one."""


class MasterNotDiscoveredException(ElasticsearchTpuException):
    status_code = 503


class ClusterBlockException(ElasticsearchTpuException):
    status_code = 403


class InvalidIndexNameException(ElasticsearchTpuException):
    status_code = 400

    def __init__(self, index: str, reason: str):
        super().__init__(f"Invalid index name [{index}], {reason}", index=index)
