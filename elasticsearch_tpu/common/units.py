"""Value-with-unit parsing: durations and byte sizes.

Role model: ``TimeValue`` / ``ByteSizeValue``
(core/src/main/java/org/elasticsearch/common/unit/). Settings like
``index.refresh_interval: "1s"`` and ``indices.breaker.total.limit: "70%"``
flow through these parsers.
"""

from __future__ import annotations

from elasticsearch_tpu.common.errors import IllegalArgumentException

_TIME_UNITS = {
    "nanos": 1e-9,
    "micros": 1e-6,
    "ms": 1e-3,
    "s": 1.0,
    "m": 60.0,
    "h": 3600.0,
    "d": 86400.0,
}

_BYTE_UNITS = {
    "b": 1,
    "kb": 1024,
    "mb": 1024**2,
    "gb": 1024**3,
    "tb": 1024**4,
    "pb": 1024**5,
}


def parse_time_value(value, setting_name: str = "") -> float:
    """Parse '30s' / '1m' / '500ms' / -1 into seconds (float). -1 => -1.0."""
    if isinstance(value, (int, float)):
        if value == -1:
            return -1.0
        raise IllegalArgumentException(
            f"failed to parse setting [{setting_name}] with value [{value}] as a time "
            "value: unit is missing or unrecognized"
        )
    s = str(value).strip().lower()
    if s in ("-1", "-1ms"):
        return -1.0
    for unit in sorted(_TIME_UNITS, key=len, reverse=True):
        if s.endswith(unit):
            num = s[: -len(unit)].strip()
            try:
                return float(num) * _TIME_UNITS[unit]
            except ValueError:
                break
    raise IllegalArgumentException(
        f"failed to parse setting [{setting_name}] with value [{value}] as a time value"
    )


def format_time_value(seconds: float) -> str:
    if seconds == -1.0:
        return "-1"
    if seconds >= 1 and seconds == int(seconds):
        return f"{int(seconds)}s"
    ms = seconds * 1000.0
    if ms == int(ms):
        return f"{int(ms)}ms"
    return f"{ms}ms"


def parse_byte_size(value, setting_name: str = "") -> int:
    """Parse '10gb' / '512mb' / bare int (bytes) into bytes."""
    if isinstance(value, int):
        return value
    s = str(value).strip().lower()
    if s == "-1":
        return -1
    for unit in sorted(_BYTE_UNITS, key=len, reverse=True):
        if s.endswith(unit):
            num = s[: -len(unit)].strip()
            try:
                return int(float(num) * _BYTE_UNITS[unit])
            except ValueError:
                break
    try:
        return int(s)
    except ValueError:
        raise IllegalArgumentException(
            f"failed to parse setting [{setting_name}] with value [{value}] as a size "
            "in bytes"
        ) from None


def parse_ratio_or_bytes(value, total: int, setting_name: str = "") -> int:
    """Parse '70%' against a total, or an absolute byte size."""
    s = str(value).strip()
    if s.endswith("%"):
        try:
            pct = float(s[:-1])
        except ValueError:
            raise IllegalArgumentException(
                f"failed to parse [{value}] as a percentage for [{setting_name}]"
            ) from None
        return int(total * pct / 100.0)
    return parse_byte_size(value, setting_name)
