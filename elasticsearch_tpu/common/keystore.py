"""Encrypted secure-settings keystore (KeyStoreWrapper analog).

Role model: the reference's ``common/settings/KeyStoreWrapper.java`` +
the ``elasticsearch-keystore`` CLI (``AddStringKeyStoreCommand``):
secrets (repository credentials, passwords) live in an encrypted file
beside the config, not in elasticsearch.yml, and are exposed to the node
as filtered "secure settings".

Construction (stdlib-only — no AES available in this image):
- key = PBKDF2-HMAC-SHA256(password, salt, 100k iterations)
- keystream block i = SHA256(key || nonce || i); ciphertext = XOR
  (a CTR-mode stream built from a PRF — the standard construction, with
  SHA256 as the block PRF)
- integrity/authenticity: HMAC-SHA256(mac_key, nonce || ciphertext)
  with mac_key = PBKDF2(password, salt || "mac"), verified before
  decryption (encrypt-then-MAC)

A fresh random nonce per save means re-saving the same secrets never
reuses a keystream.
"""

from __future__ import annotations

import hashlib
import hmac as _hmac
import json
import os
from typing import Dict, List, Optional

from elasticsearch_tpu.common.errors import (
    ElasticsearchTpuException,
    IllegalArgumentException,
)

_ITERATIONS = 100_000
_MAGIC = "estpu-keystore"
_VERSION = 1


class KeystoreException(ElasticsearchTpuException):
    """Wrong password, corrupted file, or tampered content."""


def _keys(password: str, salt: bytes):
    enc = hashlib.pbkdf2_hmac("sha256", password.encode(), salt,
                              _ITERATIONS)
    mac = hashlib.pbkdf2_hmac("sha256", password.encode(), salt + b"mac",
                              _ITERATIONS)
    return enc, mac


def _keystream_xor(key: bytes, nonce: bytes, data: bytes) -> bytes:
    out = bytearray(len(data))
    block = b""
    for i in range(len(data)):
        j = i % 32
        if j == 0:
            block = hashlib.sha256(
                key + nonce + (i // 32).to_bytes(8, "big")).digest()
        out[i] = data[i] ^ block[j]
    return bytes(out)


class KeyStore:
    """In-memory view of the secure settings; ``save``/``load`` move it
    through the encrypted on-disk format."""

    FILENAME = "elasticsearch_tpu.keystore"

    def __init__(self, secrets: Optional[Dict[str, str]] = None):
        self._secrets: Dict[str, str] = dict(secrets or {})

    # --- CLI-surface operations (add/list/remove/create) ---

    def set_string(self, name: str, value: str) -> None:
        if not name or name != name.lower():
            raise IllegalArgumentException(
                f"keystore setting name [{name}] must be lowercase")
        self._secrets[name] = str(value)

    def get_string(self, name: str) -> Optional[str]:
        return self._secrets.get(name)

    def remove(self, name: str) -> None:
        if name not in self._secrets:
            raise IllegalArgumentException(
                f"keystore does not contain setting [{name}]")
        del self._secrets[name]

    def list_settings(self) -> List[str]:
        return sorted(self._secrets)

    def as_settings_dict(self) -> Dict[str, str]:
        """The secure settings merged (filtered) into node settings."""
        return dict(self._secrets)

    # --- persistence ---

    def save(self, path: str, password: str = "") -> None:
        salt = os.urandom(16)
        nonce = os.urandom(16)
        enc_key, mac_key = _keys(password, salt)
        plaintext = json.dumps(self._secrets).encode()
        ciphertext = _keystream_xor(enc_key, nonce, plaintext)
        tag = _hmac.new(mac_key, nonce + ciphertext,
                        hashlib.sha256).hexdigest()
        payload = {
            "magic": _MAGIC,
            "version": _VERSION,
            "salt": salt.hex(),
            "nonce": nonce.hex(),
            "tag": tag,
            "data": ciphertext.hex(),
        }
        tmp = path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(payload, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)  # MetaDataStateFormat atomic-rename rule

    @classmethod
    def load(cls, path: str, password: str = "") -> "KeyStore":
        with open(path, encoding="utf-8") as f:
            payload = json.load(f)
        if payload.get("magic") != _MAGIC:
            raise KeystoreException(f"[{path}] is not a keystore file")
        salt = bytes.fromhex(payload["salt"])
        nonce = bytes.fromhex(payload["nonce"])
        ciphertext = bytes.fromhex(payload["data"])
        enc_key, mac_key = _keys(password, salt)
        tag = _hmac.new(mac_key, nonce + ciphertext,
                        hashlib.sha256).hexdigest()
        if not _hmac.compare_digest(tag, payload.get("tag", "")):
            raise KeystoreException(
                "keystore password is wrong, or the file was tampered "
                "with (MAC verification failed)")
        plaintext = _keystream_xor(enc_key, nonce, ciphertext)
        return cls(json.loads(plaintext))

    @classmethod
    def load_if_exists(cls, config_dir: str,
                       password: str = "") -> Optional["KeyStore"]:
        path = os.path.join(config_dir, cls.FILENAME)
        if not os.path.exists(path):
            return None
        return cls.load(path, password)
