"""OS / process / filesystem probes for the monitor stats surface.

Role model: ``monitor/os/OsProbe.java``, ``monitor/process/ProcessProbe``
and ``monitor/fs/FsProbe`` — the reference samples /proc and the JVM;
here the probes read /proc directly (Linux) with graceful degradation
(-1 / absent fields) elsewhere, stdlib-only.
"""

from __future__ import annotations

import os
import time
from typing import Optional


def _read(path: str) -> Optional[str]:
    try:
        with open(path, encoding="ascii") as f:
            return f.read()
    except OSError:
        return None


def os_stats() -> dict:
    """OsProbe.osStats: load averages, cpu percent (best effort), memory
    and swap from /proc/meminfo."""
    out: dict = {"timestamp": int(time.time() * 1000)}
    try:
        la1, la5, la15 = os.getloadavg()
        out["cpu"] = {"load_average": {"1m": round(la1, 2),
                                       "5m": round(la5, 2),
                                       "15m": round(la15, 2)}}
    except OSError:
        out["cpu"] = {}
    mem = _read("/proc/meminfo")
    if mem:
        kv = {}
        for line in mem.splitlines():
            parts = line.split()
            if len(parts) >= 2 and parts[0].endswith(":"):
                kv[parts[0][:-1]] = int(parts[1]) * 1024
        total = kv.get("MemTotal", 0)
        free = kv.get("MemAvailable", kv.get("MemFree", 0))
        used = max(total - free, 0)
        out["mem"] = {
            "total_in_bytes": total,
            "free_in_bytes": free,
            "used_in_bytes": used,
            "free_percent": int(free * 100 / total) if total else 0,
            "used_percent": int(used * 100 / total) if total else 0,
        }
        out["swap"] = {
            "total_in_bytes": kv.get("SwapTotal", 0),
            "free_in_bytes": kv.get("SwapFree", 0),
            "used_in_bytes": max(kv.get("SwapTotal", 0)
                                 - kv.get("SwapFree", 0), 0),
        }
    return out


def process_stats() -> dict:
    """ProcessProbe: open fds, cpu time, virtual/resident memory of THIS
    process from /proc/self."""
    out: dict = {"timestamp": int(time.time() * 1000)}
    try:
        out["open_file_descriptors"] = len(os.listdir("/proc/self/fd"))
    except OSError:
        out["open_file_descriptors"] = -1
    out["max_file_descriptors"] = -1
    try:
        import resource

        out["max_file_descriptors"] = resource.getrlimit(
            resource.RLIMIT_NOFILE)[0]
        ru = resource.getrusage(resource.RUSAGE_SELF)
        out["cpu"] = {
            "percent": -1,
            "total_in_millis": int((ru.ru_utime + ru.ru_stime) * 1000),
        }
        out["mem"] = {"total_virtual_in_bytes": -1,
                      "resident_in_bytes": ru.ru_maxrss * 1024}
    except ImportError:
        pass
    statm = _read("/proc/self/statm")
    if statm:
        pages = statm.split()
        page = os.sysconf("SC_PAGE_SIZE")
        out.setdefault("mem", {})
        out["mem"]["total_virtual_in_bytes"] = int(pages[0]) * page
        out["mem"]["resident_in_bytes"] = int(pages[1]) * page
    return out


def fs_stats(data_path: str = ".") -> dict:
    """FsProbe: totals of the data path's filesystem."""
    import shutil

    try:
        du = shutil.disk_usage(data_path or ".")
    except OSError:
        return {"timestamp": int(time.time() * 1000), "total": {}}
    return {
        "timestamp": int(time.time() * 1000),
        "total": {
            "total_in_bytes": du.total,
            "free_in_bytes": du.free,
            "available_in_bytes": du.free,
        },
        "data": [{"path": data_path,
                  "total_in_bytes": du.total,
                  "free_in_bytes": du.free,
                  "available_in_bytes": du.free}],
    }
