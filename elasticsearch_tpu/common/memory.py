"""DeviceMemoryAccountant: the exact device-memory ledger (ISSUE 9).

Role model: ``HierarchyCircuitBreakerService`` +
``IndexingMemoryController`` (core/.../indices/breaker, indices/
IndexingMemoryController.java) — the reference accounts every byte of
segment memory through the "accounting" breaker child and throttles
indexing against a budget. The TPU inversion: the scarce resource is
**HBM staging** — packed/raw posting tables, live masks, bf16 embedding
columns, block-max bound tables, per-slot mesh tables — allocated by
lazy staging sites all over the query path with (until this ledger) no
accounting, no lifecycle events and no budget.

Three pieces (docs/OBSERVABILITY.md "Device memory"):

- the **ledger**: a hierarchical exact byte map
  ``(index, scope, kind, table) -> bytes`` where *scope* is the staging
  owner (a segment name, or a mesh executor) and *kind* is one of
  ``KINDS``. Every register/release mirrors its delta into the breaker
  hierarchy's ``accounting`` child, so the parent breaker finally sees
  real device bytes. Per-kind sums always equal the ledger total.

- **staging lifecycle events**: each (re)stage appends
  ``{index, segment, kind, bytes, duration_ms, reason}`` to a bounded
  ring (reason ∈ ``REASONS``); the accountant derives the
  **restage-amplification** metric — bytes restaged / bytes logically
  changed — the exact number ROADMAP item 3 (NRT delta staging) must
  drive down.

- the **budget breaker**: ``search.memory.hbm_budget_bytes`` (dynamic,
  0 = unlimited). An over-budget reservation first LRU-evicts the
  coldest *evictable* scopes (segment host-plane stagings, mesh
  executors — both restage lazily on next use), then DENIES the
  reservation: the caller demotes to the next plane rung with ladder
  decision reason ``hbm_budget``. Queries degrade, never 5xx.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

# Table kinds (the per-kind axis of the ledger; _stats search.memory
# staged_bytes keys). Every staging site maps its arrays onto these.
KIND_POSTINGS_RAW = "postings_raw"
KIND_POSTINGS_PACKED = "postings_packed"
KIND_LIVE_MASK = "live_mask"
KIND_BOUND_TABLES = "bound_tables"
KIND_EMBEDDINGS = "embeddings"
KIND_SCALE_NORM = "scale_norm"
KIND_MESH_SLOT_TABLES = "mesh_slot_tables"
KIND_DOC_VALUES = "doc_values"

KINDS = (KIND_POSTINGS_RAW, KIND_POSTINGS_PACKED, KIND_LIVE_MASK,
         KIND_BOUND_TABLES, KIND_EMBEDDINGS, KIND_SCALE_NORM,
         KIND_MESH_SLOT_TABLES, KIND_DOC_VALUES)

# Staging lifecycle reasons (docs/OBSERVABILITY.md):
#   initial             first staging of this table (counts as bytes
#                       logically changed, not as restaged bytes)
#   refresh             the segment set changed (new/retired segments)
#                       and dependent tables restaged
#   delete_invalidation a delete mutated the live mask / invalidated a
#                       staged table
#   geometry_change     the collective geometry (slot packing, tile
#                       sublane ladder) changed shape
#   probe               re-staged on demand after an eviction or a
#                       quarantine probe
#   scrub               the background scrubber (ISSUE 16,
#                       index.scrub.interval) found device/host digest
#                       drift and invalidated the staging — the restage
#                       re-adopts host truth
#   delta_append        an incremental refresh staged ONLY the new
#                       segments' tables into free slots of the live
#                       mesh generation (ISSUE 20) — the delta bytes
#                       count as restaged AND logically changed, so a
#                       pure-append refresh drives amplification to ~1
#   tombstone           a delete updated only the affected slots'
#                       live-mask columns in place (kNN exists∧live and
#                       fused-agg matched masks included)
#   compaction          the background compaction pass merged sparse
#                       slots into fresh ones and released the old
#                       generation (index.staging.compact.threshold)
REASONS = ("initial", "refresh", "delete_invalidation", "geometry_change",
           "probe", "scrub", "delta_append", "tombstone", "compaction")


class _Entry:
    __slots__ = ("bytes", "stage_count")

    def __init__(self):
        self.bytes = 0
        self.stage_count = 0


class DeviceMemoryAccountant:
    """Process-wide device-staging ledger (thread-safe, re-entrant:
    eviction callbacks release through the same lock)."""

    MAX_EVENTS = 128
    MAX_RELEASED_SCOPES = 4096

    def __init__(self):
        self._lock = threading.RLock()
        # (index, scope, kind, table) -> _Entry
        self._entries: Dict[Tuple[str, str, str, str], _Entry] = {}
        # (index, scope) -> last-use monotonic timestamp (LRU axis)
        self._scope_used: Dict[Tuple[str, str], float] = {}
        # (index, scope) -> eviction callback (drops the scope's staged
        # arrays so they lazily restage on next use); scopes without one
        # are not evictable (released only by their owner's lifecycle)
        self._scope_evict: Dict[Tuple[str, str], Callable[[], None]] = {}
        # scopes ever released: a re-register into one is a restage
        # ("probe"), not an "initial". Scope-level (not per-table) and
        # BOUNDED — segment/executor scope names are generation-unique,
        # so an unbounded set would grow forever under refresh/merge
        # churn; overflow drops the oldest (a long-evicted scope that
        # restages after 4096 later releases misclassifies as initial —
        # benign stat drift, not a leak). Cleared with release_index.
        self._released: Dict[Tuple[str, str], None] = {}
        self._total = 0
        self.staging_events: List[dict] = []
        self.eviction_events: List[dict] = []
        self.events_dropped = 0
        self.evictions_total = 0
        self.evicted_bytes_total = 0
        self.budget_denials_total = 0
        # device-staging fault model (ISSUE 10, docs/RESILIENCE.md
        # "Device-plane faults"): classified terminal faults + the
        # bounded-retry counter, with a bounded event ring so operators
        # can join a plane demotion to the staging fault that caused it
        self.staging_retries_total = 0
        self.staging_faults_transient_total = 0
        self.staging_faults_deterministic_total = 0
        self.staging_fault_events: List[dict] = []
        # per-index restage-amplification inputs
        self._restaged: Dict[str, int] = {}
        self._logical: Dict[str, int] = {}
        # 0 = unlimited (the default: single-user tools and tests must
        # never trip a budget they didn't configure)
        self.budget_bytes = 0

    # -- breaker mirror -------------------------------------------------

    @staticmethod
    def _accounting_breaker():
        from elasticsearch_tpu.common.breaker import (
            CircuitBreaker,
            breaker_service,
        )

        return breaker_service().get_breaker(CircuitBreaker.ACCOUNTING)

    def _mirror(self, delta: int) -> None:
        if delta:
            # never raises: budget enforcement is LRU-evict + plane
            # demotion (hbm_budget), not a 429
            self._accounting_breaker().add_without_breaking(delta)

    # -- ledger ---------------------------------------------------------

    def register(self, index: str, scope: str, kind: str, table: str,
                 nbytes: int, *, reason: str = "initial",
                 duration_ms: float = 0.0, plane: str = "host",
                 evict: Optional[Callable[[], None]] = None,
                 quiet: bool = False,
                 amplify_bytes: Optional[int] = None) -> None:
        """Record ``table`` (one staged array group) as holding
        ``nbytes`` of device memory. Re-registering the same key
        REPLACES its bytes (a restage, not a leak). ``quiet`` skips the
        event ring and amplification counters — for accumulator-style
        caches that re-register per increment (the ub-column cache).

        ``amplify_bytes`` decouples ledger truth from amplification
        truth for DELTA restages (ISSUE 20): a tombstone or slot append
        replaces a whole device array (the ledger must hold its full
        ``nbytes``) while only the changed slot ROWS were actually
        restaged — those row bytes feed the amplification counters and
        the event ring. ``delta_append`` rows count as restaged AND
        logically changed (new data arriving IS the logical change), so
        a pure-append refresh reports amplification ~1."""
        assert kind in KINDS, kind
        assert reason in REASONS, reason
        index = index or "_unassigned"
        key = (index, scope, kind, table)
        with self._lock:
            entry = self._entries.get(key)
            if entry is None:
                entry = self._entries[key] = _Entry()
                if (reason == "initial"
                        and (index, scope) in self._released):
                    reason = "probe"  # restaged after an eviction/release
            elif reason == "initial":
                # an in-place replacement of live bytes is a restage even
                # when the call site didn't classify it
                reason = "probe"
            delta = int(nbytes) - entry.bytes
            entry.bytes = int(nbytes)
            entry.stage_count += 1
            self._total += delta
            self._scope_used[(index, scope)] = time.monotonic()
            if evict is not None:
                self._scope_evict[(index, scope)] = evict
            if not quiet:
                amp = int(nbytes if amplify_bytes is None
                          else amplify_bytes)
                if reason == "initial":
                    self._logical[index] = (self._logical.get(index, 0)
                                            + amp)
                else:
                    self._restaged[index] = (self._restaged.get(index, 0)
                                             + amp)
                    if reason == "delta_append":
                        # the appended rows are new data: they grow the
                        # logical denominator too, keeping the ratio ~1
                        # for a clean append
                        self._logical[index] = (
                            self._logical.get(index, 0) + amp)
                self._push(self.staging_events, {
                    "index": index, "segment": scope, "kind": kind,
                    "table": table, "bytes": amp,
                    "duration_ms": round(float(duration_ms), 3),
                    "reason": reason, "plane": plane,
                    "timestamp_ms": int(time.time() * 1000),
                })
            self._mirror(delta)

    def _push(self, ring: List[dict], event: dict) -> None:
        ring.append(event)
        if len(ring) > self.MAX_EVENTS:
            del ring[0]
            self.events_dropped += 1

    def set_evict(self, index: str, scope: str,
                  evict: Callable[[], None]) -> None:
        """Arm (or re-arm) a scope's eviction callback AFTER its owner
        fully installed the staged generation. Registering the callback
        during construction would let the budget evict a half-built
        generation while the owner still points at the previous one —
        releasing the wrong scope (see MeshPlanExecutor.make_evictable).
        No-op for a scope with no live ledger entries."""
        with self._lock:
            key = (index or "_unassigned", scope)
            if any(k[0] == key[0] and k[1] == key[1]
                   for k in self._entries):
                self._scope_evict[key] = evict

    def touch(self, index: str, scope: str) -> None:
        """LRU hint: the scope's staged tables served a query."""
        with self._lock:
            key = (index or "_unassigned", scope)
            if key in self._scope_used:
                self._scope_used[key] = time.monotonic()

    def note_logical_change(self, index: str, nbytes: int) -> None:
        """Record bytes of data that LOGICALLY changed (docs indexed,
        live-mask bits flipped) — the denominator of restage
        amplification."""
        with self._lock:
            self._logical[index] = self._logical.get(index, 0) + int(nbytes)

    def note_staging_retry(self, index: str, kind: str) -> None:
        """One transient staging fault absorbed by the bounded-retry
        loop (common/staging.run_staged) — the attempt will re-run."""
        with self._lock:
            self.staging_retries_total += 1

    def note_staging_fault(self, index: str, kind: str, *,
                           transient: bool, retries: int = 0,
                           plane: str = "host",
                           error: str = "") -> None:
        """A TERMINAL staging fault (transient with retries exhausted,
        or deterministic): the caller rolled back its partial staging
        and is demoting the plane ladder — record it so
        ``_stats search.memory`` can tell device pressure from a broken
        staging site."""
        with self._lock:
            if transient:
                self.staging_faults_transient_total += 1
            else:
                self.staging_faults_deterministic_total += 1
            self._push(self.staging_fault_events, {
                "index": index or "_unassigned", "kind": kind,
                "classification": ("transient" if transient
                                   else "deterministic"),
                "retries": int(retries), "plane": plane,
                "error": str(error)[:200],
                "timestamp_ms": int(time.time() * 1000),
            })

    def force_evict(self, scopes: int = 1) -> int:
        """Evict the N coldest evictable scopes regardless of budget —
        the EvictionStormScheme's lever (testing/disruption.py): drives
        the LRU evictor under query load so restage-under-pressure
        paths are exercised deterministically. Returns bytes evicted."""
        freed = 0
        with self._lock:
            for _ in range(max(0, int(scopes))):
                before = self.evictions_total
                freed += self._evict_locked(1)  # 1 byte => one scope
                if self.evictions_total == before:
                    break  # nothing evictable left
        return freed

    def release_scope(self, index: str, scope: str) -> int:
        """Release every table of one staging owner (segment retirement,
        executor rebuild, eviction). Returns the bytes released."""
        index = index or "_unassigned"
        with self._lock:
            keys = [k for k in self._entries
                    if k[0] == index and k[1] == scope]
            freed = 0
            for k in keys:
                freed += self._entries.pop(k).bytes
            self._scope_used.pop((index, scope), None)
            self._scope_evict.pop((index, scope), None)
            if keys:
                # remember the scope so a later restage classifies as
                # "probe" (bounded, recency-ordered — see _released)
                self._released.pop((index, scope), None)
                self._released[(index, scope)] = None
                while len(self._released) > self.MAX_RELEASED_SCOPES:
                    self._released.pop(next(iter(self._released)))
            self._total -= freed
            self._mirror(-freed)
            return freed

    def release_index(self, index: str) -> int:
        """Index close/delete: release everything it still holds (the
        structured per-scope releases should have run already — this is
        the ledger-exactness backstop) and forget its restage history."""
        index = index or "_unassigned"
        with self._lock:
            for scope in {k[1] for k in self._entries if k[0] == index}:
                self.release_scope(index, scope)
            self._released = {k: None for k in self._released
                              if k[0] != index}
            self._restaged.pop(index, None)
            self._logical.pop(index, None)
            return 0

    # -- budget ---------------------------------------------------------

    def set_budget(self, nbytes: Optional[int]) -> None:
        """Dynamic budget update (search.memory.hbm_budget_bytes).
        Lowering the budget evicts immediately; the accounting breaker's
        limit mirrors it so _nodes/stats breakers shows the real bound."""
        self.budget_bytes = int(nbytes or 0)
        self._accounting_breaker().limit_bytes = self.budget_bytes
        if self.budget_bytes > 0:
            self.enforce_budget()

    def enforce_budget(self) -> int:
        """Evict coldest evictable scopes until the ledger fits the
        budget. Returns bytes evicted."""
        if self.budget_bytes <= 0:
            return 0
        with self._lock:
            return self._evict_locked(self._total - self.budget_bytes)

    def try_reserve(self, index: str, nbytes: int,
                    exclude_scope: Optional[str] = None,
                    mandatory: bool = False) -> bool:
        """Budget gate for a staging site about to allocate ``nbytes``.
        True = proceed. False = over budget even after LRU eviction —
        the caller must demote to the next plane rung (ladder reason
        ``hbm_budget``), never error. ``exclude_scope`` protects the
        scope being staged from evicting itself. ``mandatory`` marks a
        pressure-valve reservation the caller proceeds with regardless
        (host-rung tables the byte-parity contract needs): it still
        LRU-evicts to make room but an over-budget outcome is not a
        denial — ``budget_denials_total`` counts only real demotions."""
        if self.budget_bytes <= 0 or nbytes <= 0:
            return True
        index = index or "_unassigned"
        with self._lock:
            need = self._total + int(nbytes) - self.budget_bytes
            if need > 0:
                self._evict_locked(need, exclude=(index, exclude_scope))
            if self._total + int(nbytes) <= self.budget_bytes:
                return True
            if not mandatory:
                self.budget_denials_total += 1
            return False

    def _evict_locked(self, need: int,
                      exclude: Optional[Tuple[str, str]] = None) -> int:
        if need <= 0:
            return 0
        candidates = sorted(
            ((used, key) for key, used in self._scope_used.items()
             if key in self._scope_evict and key != exclude),
            key=lambda kv: kv[0])
        freed = 0
        for _used, (index, scope) in candidates:
            if freed >= need:
                break
            cb = self._scope_evict.get((index, scope))
            before = sum(e.bytes for k, e in self._entries.items()
                         if k[0] == index and k[1] == scope)
            try:
                if cb is not None:
                    cb()  # owner drops its arrays + releases its scope
            except Exception:  # noqa: BLE001 — eviction must terminate
                pass
            # idempotent backstop: the callback should have released
            self.release_scope(index, scope)
            freed += before
            self.evictions_total += 1
            self.evicted_bytes_total += before
            self._push(self.eviction_events, {
                "index": index, "segment": scope, "bytes": before,
                "timestamp_ms": int(time.time() * 1000),
            })
        return freed

    # -- export ---------------------------------------------------------

    def staged_bytes(self, index: Optional[str] = None) -> int:
        with self._lock:
            if index is None:
                return self._total
            return sum(e.bytes for k, e in self._entries.items()
                       if k[0] == index)

    def staged_bytes_by_kind(self, index: Optional[str] = None) -> dict:
        """Per-kind staged bytes. Sums EXACTLY to the ledger total for
        the same filter (the _stats search.memory invariant)."""
        with self._lock:
            out = {kind: 0 for kind in KINDS}
            for (idx, _scope, kind, _table), e in self._entries.items():
                if index is None or idx == index:
                    out[kind] += e.bytes
            return out

    def stats(self, index: Optional[str] = None) -> dict:
        """The ``search.memory`` stats block (per index, or node-wide
        with ``index=None``). Event rings and eviction/denial counters
        are node-global (the budget is a node resource); byte sums and
        amplification are filtered."""
        with self._lock:
            by_kind = self.staged_bytes_by_kind(index)
            if index is None:
                restaged = sum(self._restaged.values())
                logical = sum(self._logical.values())
                staging = list(self.staging_events)
                evictions = list(self.eviction_events)
                faults = list(self.staging_fault_events)
            else:
                restaged = self._restaged.get(index, 0)
                logical = self._logical.get(index, 0)
                staging = [e for e in self.staging_events
                           if e["index"] == index]
                evictions = [e for e in self.eviction_events
                             if e["index"] == index]
                faults = [e for e in self.staging_fault_events
                          if e["index"] == index]
            return {
                "hbm_budget_bytes": self.budget_bytes,
                "staged_bytes_total": sum(by_kind.values()),
                "staged_bytes": by_kind,
                "restaged_bytes_total": restaged,
                "bytes_logically_changed_total": logical,
                "restage_amplification": (
                    round(restaged / logical, 4) if logical else None),
                "staging_events": staging,
                "eviction_events": evictions,
                "events_dropped": self.events_dropped,
                "evictions_total": self.evictions_total,
                "evicted_bytes_total": self.evicted_bytes_total,
                "budget_denials_total": self.budget_denials_total,
                # classified staging-fault model (ISSUE 10,
                # docs/RESILIENCE.md): retry/fault counters are
                # node-global like the eviction counters; the event
                # ring filters per index
                "staging_retries_total": self.staging_retries_total,
                "staging_faults_transient_total":
                    self.staging_faults_transient_total,
                "staging_faults_deterministic_total":
                    self.staging_faults_deterministic_total,
                "staging_fault_events": faults,
            }

    def table(self) -> List[dict]:
        """Per-(index, scope, kind) rows for the _cat/staging endpoint,
        hottest first."""
        with self._lock:
            now = time.monotonic()
            rows: Dict[Tuple[str, str, str], dict] = {}
            for (index, scope, kind, _table), e in self._entries.items():
                row = rows.setdefault((index, scope, kind), {
                    "index": index, "segment": scope, "kind": kind,
                    "bytes": 0, "tables": 0, "stage_count": 0,
                })
                row["bytes"] += e.bytes
                row["tables"] += 1
                row["stage_count"] += e.stage_count
            for key, row in rows.items():
                used = self._scope_used.get((key[0], key[1]))
                row["idle_s"] = (round(now - used, 3)
                                 if used is not None else None)
                row["evictable"] = (key[0], key[1]) in self._scope_evict
            return sorted(rows.values(),
                          key=lambda r: (r["idle_s"] is None,
                                         r["idle_s"] or 0.0))


# ---------------------------------------------------------------------------
# Process-level singleton (node startup configures the budget; staging
# sites reach it through memory_accountant())
# ---------------------------------------------------------------------------

_accountant: Optional[DeviceMemoryAccountant] = None
_accountant_lock = threading.Lock()


def memory_accountant() -> DeviceMemoryAccountant:
    global _accountant
    # lock-free fast path: this accessor sits on the per-query hot path
    # (every register/touch/reserve) — only the first call ever needs
    # the lock (assignment is atomic under the GIL)
    acct = _accountant
    if acct is not None:
        return acct
    with _accountant_lock:
        if _accountant is None:
            _accountant = DeviceMemoryAccountant()
        return _accountant
