"""Aggregations: parse, per-segment partials, associative reduce.

Role model: search/aggregations/ in the reference (368 files) — an
``Aggregator`` tree collecting per-doc into buckets, with two-level reduce
(shard partials -> coordinator merge, InternalAggregation.doReduce:129)
and pipeline aggs post-processing the reduced tree.

TPU design: partials are computed by the kernels in ops/aggs.py over the
query's matched-doc mask (no per-doc collector calls); every partial is an
associative structure (count maps, HLL registers, stats tuples) so the
same reduce works across segments, shards, and — via psum-style tree
reduction — across a device mesh (SURVEY.md §5.7). Sub-aggregations use a
two-phase protocol: reduce picks the surviving buckets, then each bucket's
filter mask drives a recursive partial pass (the reference's deferred /
breadth-first collection, bucket/BestBucketsDeferringCollector).
"""

from __future__ import annotations

import math
from typing import Callable, Dict, List, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from elasticsearch_tpu.common.errors import ParsingException
from elasticsearch_tpu.mapper.field_types import format_epoch_millis, parse_date
from elasticsearch_tpu.ops import aggs as agg_ops

# ---------------------------------------------------------------------------
# Specs (parse)
# ---------------------------------------------------------------------------

BUCKET_TYPES = {"terms", "histogram", "date_histogram", "range", "date_range",
                "filter", "filters", "global", "missing", "significant_terms",
                "sampler", "diversified_sampler", "adjacency_matrix",
                "geohash_grid", "children", "nested", "reverse_nested",
                "scripted_metric"}
METRIC_TYPES = {"min", "max", "sum", "avg", "stats", "extended_stats",
                "value_count", "cardinality", "percentiles", "top_hits",
                "geo_bounds", "geo_centroid", "matrix_stats"}
PIPELINE_TYPES = {"derivative", "cumulative_sum", "moving_avg", "avg_bucket",
                  "sum_bucket", "min_bucket", "max_bucket", "stats_bucket",
                  "bucket_script", "bucket_selector", "bucket_sort", "serial_diff"}

# SearchPlugin.getAggregations extension point:
# {agg_type: run(spec, views) -> result dict} — owns compute AND reduce
CUSTOM_AGGS: Dict[str, object] = {}


class AggSpec:
    def __init__(self, name: str, agg_type: str, body: dict, subs: List["AggSpec"]):
        self.name = name
        self.type = agg_type
        self.body = body
        self.subs = subs


def parse_aggs(aggs_body: Optional[dict]) -> List[AggSpec]:
    if not aggs_body:
        return []
    specs = []
    for name, spec in aggs_body.items():
        sub_body = spec.get("aggs") or spec.get("aggregations")
        types = [k for k in spec if k not in ("aggs", "aggregations", "meta")]
        if len(types) != 1:
            raise ParsingException(
                f"Expected exactly one aggregation type for [{name}], found {types}"
            )
        t = types[0]
        if t not in BUCKET_TYPES | METRIC_TYPES | PIPELINE_TYPES \
                and t not in CUSTOM_AGGS:
            raise ParsingException(f"Unknown aggregation type [{t}] for [{name}]")
        specs.append(AggSpec(name, t, spec[t], parse_aggs(sub_body)))
    return specs


# ---------------------------------------------------------------------------
# Per-segment partial computation
# ---------------------------------------------------------------------------
# A "SegmentAccess" duck: needs .segment (Segment), .mask (np bool [nd1]),
# and .query_ctx for filter/filters sub-queries.


class SegmentView:
    """One segment + the matched mask for the current (sub-)aggregation."""

    def __init__(self, segment, mask: np.ndarray, shard_ctx=None,
                 scores: Optional[np.ndarray] = None, nested_ctx=None,
                 root_view: Optional["SegmentView"] = None):
        self.segment = segment
        self.mask = mask  # np bool [nd1], already includes live
        self.shard_ctx = shard_ctx  # ShardQueryContext for filter aggs
        self.scores = scores  # np f32 [nd1] (top_hits)
        # set when this view ranges over a nested sub-segment: the join
        # back to the enclosing docs (for reverse_nested)
        self.nested_ctx = nested_ctx
        self.root_view = root_view

    def with_mask(self, mask: np.ndarray) -> "SegmentView":
        return SegmentView(self.segment, mask, self.shard_ctx, self.scores,
                           self.nested_ctx, self.root_view)


def _resolve_value_field(segment, field: str):
    """Find the numeric column for a field (falls back to .keyword-stripped)."""
    col = segment.numeric_columns.get(field)
    if col is not None:
        return col
    return None


def _resolve_ordinal_field(segment, field: str):
    col = segment.ordinal_columns.get(field)
    if col is not None:
        return col
    # terms on "myfield" where mapping used text + .keyword multi-field
    col = segment.ordinal_columns.get(f"{field}.keyword")
    if col is not None:
        return col
    return _text_fielddata(segment, field)


_fielddata_build_lock = __import__("threading").Lock()


def _text_fielddata(segment, field: str):
    """Build (and cache) an ordinal view of a text field from its postings
    — the reference's heap-loaded text fielddata (index/fielddata/), built
    lazily at first aggregation. (The reference gates this behind
    fielddata=true; we build it implicitly — documented delta.)

    Serialized under a build lock: concurrent search-pool threads racing
    the dev_cache check would double-build AND double-account the
    fielddata breaker bytes."""
    cache_key = f"fielddata.{field}"
    hit = segment.dev_cache.get(cache_key)
    if hit is not None:
        return hit
    with _fielddata_build_lock:
        hit = segment.dev_cache.get(cache_key)
        if hit is not None:
            return hit
        return _build_text_fielddata(segment, field, cache_key)


def _build_text_fielddata(segment, field: str, cache_key: str):
    terms = segment.terms_for_field(field)
    if not terms:
        return None
    from elasticsearch_tpu.common.breaker import (
        CircuitBreaker,
        breaker_service,
    )

    # fielddata breaker: account BEFORE building (the reference's
    # RamAccountingTermsEnum pattern — fail fast, not after allocation);
    # the segment remembers the charge so dropping it releases the bytes
    est_bytes = sum(int(segment.term_doc_freq[tid]) for _, tid in terms) * 8 \
        + segment.nd_pad * 5
    breaker_service().get_breaker(
        CircuitBreaker.FIELDDATA).add_estimate_bytes_and_maybe_break(
        est_bytes, f"fielddata [{field}]")
    segment.breaker_charges[cache_key] = est_bytes
    from elasticsearch_tpu.index.segment import OrdinalColumn, next_pow2

    token_list = [t for t, _ in terms]
    pairs = []  # (doc, ord)
    for ordinal, (_, tid) in enumerate(terms):
        start = int(segment.term_block_start[tid])
        count = int(segment.term_block_count[tid])
        block = segment.block_docs[start: start + count].ravel()
        for doc in block[block < segment.nd_pad]:
            pairs.append((int(doc), ordinal))
    pairs.sort()
    n_vals = len(pairs)
    cap = next_pow2(max(n_vals, 1))
    flat_docs = np.full(cap, segment.nd_pad, dtype=np.int32)
    flat_ords = np.zeros(cap, dtype=np.int32)
    first_ord = np.full(segment.nd_pad, -1, dtype=np.int32)
    exists = np.zeros(segment.nd_pad, dtype=bool)
    for i, (doc, o) in enumerate(pairs):
        flat_docs[i] = doc
        flat_ords[i] = o
        if first_ord[doc] < 0:
            first_ord[doc] = o
        exists[doc] = True
    col = OrdinalColumn(token_list, flat_ords, flat_docs, first_ord, exists, n_vals)
    segment.dev_cache[cache_key] = col
    return col


def compute_partial(spec: AggSpec, view: SegmentView) -> dict:
    fn = _PARTIAL_FNS.get(spec.type)
    if fn is None:
        raise ParsingException(f"Unsupported aggregation type [{spec.type}]")
    return fn(spec, view)


# --- metrics ---


def _metric_values(spec: AggSpec, view: SegmentView) -> np.ndarray:
    """All values of matched docs for the agg's field (host numpy)."""
    field = spec.body.get("field")
    seg = view.segment
    col = _resolve_value_field(seg, field)
    if col is None:
        ocol = _resolve_ordinal_field(seg, field)
        if ocol is not None:
            sel = view.mask[ocol.flat_docs[: ocol.count]]
            return ocol.flat_ords[: ocol.count][sel].astype(np.float64)
        return np.empty(0, dtype=np.float64)
    sel = view.mask[col.flat_docs[: col.count]]
    vals = col.flat_values[: col.count][sel]
    if "missing" in spec.body:
        # docs matched but without the field contribute the missing value
        missing_docs = int(view.mask[: seg.nd_pad][~col.exists].sum())
        if missing_docs:
            vals = np.concatenate([vals, np.full(missing_docs, float(spec.body["missing"]))])
    return vals


def _partial_stats(spec, view):
    vals = _metric_values(spec, view)
    if vals.size == 0:
        return {"count": 0, "sum": 0.0, "min": math.inf, "max": -math.inf, "sq": 0.0}
    return {
        "count": int(vals.size),
        "sum": float(vals.sum()),
        "min": float(vals.min()),
        "max": float(vals.max()),
        "sq": float((vals * vals).sum()),
    }


def _partial_cardinality(spec, view):
    field = spec.body.get("field")
    seg = view.segment
    precision = _hll_precision(spec.body.get("precision_threshold"))
    mask_dev = jnp.asarray(view.mask)
    ocol = _resolve_ordinal_field(seg, field)
    if ocol is not None:
        key = f"hll.ord.{field}"
        if key not in seg.dev_cache:
            hashes = agg_ops.hash_string_values(ocol.terms)
            seg.dev_cache[key] = jnp.asarray(hashes[np.clip(ocol.flat_ords, 0, None)])
        hashes = seg.dev_cache[key]
        valid = jnp.asarray(np.arange(len(ocol.flat_docs)) < ocol.count)
        regs = agg_ops.hll_registers(
            jnp.asarray(ocol.flat_docs), hashes, valid, mask_dev, precision=precision
        )
        return {"registers": np.asarray(regs), "precision": precision}
    col = _resolve_value_field(seg, field)
    if col is None:
        return {"registers": np.zeros(1 << precision, np.int32), "precision": precision}
    key = f"hll.num.{field}"
    if key not in seg.dev_cache:
        seg.dev_cache[key] = jnp.asarray(agg_ops.hash_numeric_values(col.flat_values))
    hashes = seg.dev_cache[key]
    valid = jnp.asarray(np.arange(len(col.flat_docs)) < col.count)
    regs = agg_ops.hll_registers(
        jnp.asarray(col.flat_docs), hashes, valid, mask_dev, precision=precision
    )
    return {"registers": np.asarray(regs), "precision": precision}


def _hll_precision(threshold) -> int:
    if threshold is None:
        return agg_ops.HLL_DEFAULT_PRECISION
    # ES: registers ~ threshold*... pick smallest p with 2^p >= 5*threshold
    t = max(int(threshold), 1)
    p = 4
    while (1 << p) < 5 * t and p < 18:
        p += 1
    return p


def _partial_percentiles(spec, view):
    # exact sample (the reference approximates with TDigest; exact values
    # are a superset in accuracy — partials carry the raw matched values,
    # bounded by sampling at 100k per segment)
    vals = _metric_values(spec, view)
    limit = 100_000
    if vals.size > limit:
        rng = np.random.RandomState(13)
        vals = rng.choice(vals, limit, replace=False)
    return {"values": vals}


def _partial_top_hits(spec, view):
    size = int(spec.body.get("size", 3))
    seg = view.segment
    scores = view.scores if view.scores is not None else np.zeros(seg.nd_pad + 1, np.float32)
    masked = np.where(view.mask[: seg.nd_pad], scores[: seg.nd_pad], -np.inf)
    if masked.size == 0:
        return {"hits": []}
    k = min(size, masked.size)
    idx = np.argpartition(-masked, k - 1)[:k]
    idx = idx[np.argsort(-masked[idx], kind="stable")]
    hits = []
    for d in idx:
        if masked[d] == -np.inf:
            continue
        hits.append({
            "_id": seg.doc_ids[d],
            "_score": float(masked[d]),
            "_source": seg.sources[d],
        })
    return {"hits": hits}


# --- buckets ---


def _partial_terms(spec, view):
    field = spec.body["field"]
    seg = view.segment
    ocol = _resolve_ordinal_field(seg, field)
    mask_dev = jnp.asarray(view.mask)
    if ocol is not None and ocol.count > 0:
        docs = seg.device_column(f"ord.{_f(seg, field)}.docs", lambda: ocol.flat_docs)
        ords = seg.device_column(f"ord.{_f(seg, field)}.ords", lambda: ocol.flat_ords)
        counts = np.asarray(agg_ops.ordinal_counts(docs, ords, mask_dev, len(ocol.terms)))
        return {"counts": {ocol.terms[i]: int(c) for i, c in enumerate(counts) if c > 0},
                "doc_count_error_upper_bound": 0}
    col = _resolve_value_field(seg, field)
    if col is None or col.count == 0:
        return {"counts": {}, "doc_count_error_upper_bound": 0}
    sel = view.mask[col.flat_docs[: col.count]]
    vals = col.flat_values[: col.count][sel]
    docs_sel = col.flat_docs[: col.count][sel]
    # numeric terms: dedupe (doc, value)
    uniq = set(zip(docs_sel.tolist(), vals.tolist()))
    counts: Dict = {}
    for _, v in uniq:
        k = int(v) if float(v).is_integer() else float(v)
        counts[k] = counts.get(k, 0) + 1
    return {"counts": counts, "doc_count_error_upper_bound": 0}


def _f(seg, field):
    """Resolve the actual ordinal column name used for a field."""
    return field if field in seg.ordinal_columns else f"{field}.keyword"


def _terms_global_merge(spec, views) -> Optional[Dict]:
    """Cross-segment terms counts in GLOBAL ordinal space
    (GlobalOrdinalsStringTermsAggregator): per-segment device counts fold
    into one int64 array via the cached local->global maps; strings only
    materialize for the surviving buckets. None when any segment lacks a
    string-ordinal column for the field (numeric terms keep the
    string-keyed path)."""
    from elasticsearch_tpu.index.global_ordinals import global_ordinals

    field = spec.body.get("field")
    if field is None or not views:
        return None
    cols = []
    for v in views:
        ocol = _resolve_ordinal_field(v.segment, field)
        if ocol is None and _resolve_value_field(v.segment, field) is not None:
            return None  # numeric terms
        cols.append(ocol)
    # pass the resolved columns through: text fields materialize ordinal
    # fielddata lazily and live outside segment.ordinal_columns
    gords = global_ordinals([v.segment for v in views], field, columns=cols)
    if not gords.terms:
        return {}
    total = np.zeros(len(gords.terms), np.int64)
    for v, ocol in zip(views, cols):
        if ocol is None or ocol.count == 0:
            continue
        seg = v.segment
        docs = seg.device_column(f"ord.{_f(seg, field)}.docs",
                                 lambda: ocol.flat_docs)
        ords = seg.device_column(f"ord.{_f(seg, field)}.ords",
                                 lambda: ocol.flat_ords)
        counts = np.asarray(agg_ops.ordinal_counts(
            docs, ords, jnp.asarray(v.mask), len(ocol.terms)))
        gords.fold_counts(seg, counts.astype(np.int64), total)
    nz = np.nonzero(total)[0]
    return {gords.terms[i]: int(total[i]) for i in nz}


_CAL_INTERVALS = {"year": "Y", "quarter": None, "month": "M", "week": "W",
                  "day": "D", "hour": "h", "minute": "m", "second": "s"}
_FIXED_MS = {"ms": 1, "s": 1000, "m": 60_000, "h": 3_600_000, "d": 86_400_000}


def _date_interval_ms(interval: str) -> Optional[float]:
    """Fixed intervals -> millis; calendar intervals return None."""
    s = str(interval)
    if s in _CAL_INTERVALS:
        return None
    for unit in sorted(_FIXED_MS, key=len, reverse=True):
        if s.endswith(unit):
            try:
                return float(s[: -len(unit)]) * _FIXED_MS[unit]
            except ValueError:
                break
    raise ParsingException(f"unable to parse interval [{interval}]")


def _calendar_bucket_keys(millis: np.ndarray, interval: str) -> np.ndarray:
    """Calendar rounding via numpy datetime64 (host columnar op)."""
    dt = millis.astype("int64").astype("datetime64[ms]")
    if interval == "quarter":
        months = dt.astype("datetime64[M]").astype(np.int64)
        q_start = (months // 3) * 3
        return q_start.astype("datetime64[M]").astype("datetime64[ms]").astype(np.int64)
    unit = _CAL_INTERVALS[interval]
    return dt.astype(f"datetime64[{unit}]").astype("datetime64[ms]").astype(np.int64)


def _partial_histogram(spec, view, is_date=False):
    field = spec.body["field"]
    seg = view.segment
    col = _resolve_value_field(seg, field)
    if col is None or col.count == 0:
        return {"counts": {}}
    sel = view.mask[col.flat_docs[: col.count]]
    vals = col.flat_values[: col.count][sel]
    if vals.size == 0:
        return {"counts": {}}
    if is_date:
        interval = spec.body.get("interval") or spec.body.get("calendar_interval") \
            or spec.body.get("fixed_interval")
        ms = _date_interval_ms(interval)
        if ms is None:
            keys = _calendar_bucket_keys(vals.astype(np.int64), str(interval))
        else:
            offset = float(spec.body.get("offset", 0) or 0)
            keys = (np.floor((vals - offset) / ms) * ms + offset).astype(np.int64)
    else:
        interval = float(spec.body["interval"])
        offset = float(spec.body.get("offset", 0.0))
        keys = np.floor((vals - offset) / interval) * interval + offset
    counts: Dict = {}
    uniq, cnt = np.unique(keys, return_counts=True)
    for k, c in zip(uniq.tolist(), cnt.tolist()):
        counts[k] = counts.get(k, 0) + int(c)
    return {"counts": counts}


def _partial_range(spec, view, is_date=False):
    field = spec.body["field"]
    ranges = spec.body["ranges"]
    seg = view.segment
    col = _resolve_value_field(seg, field)
    out = []
    conv = (lambda v: float(parse_date(v))) if is_date else float
    for r in ranges:
        lo = conv(r["from"]) if "from" in r else -np.inf
        hi = conv(r["to"]) if "to" in r else np.inf
        if col is None or col.count == 0:
            out.append(0)
            continue
        sel = view.mask[col.flat_docs[: col.count]]
        in_r = (col.flat_values[: col.count] >= lo) & (col.flat_values[: col.count] < hi) & sel
        out.append(int(len(set(col.flat_docs[: col.count][in_r].tolist()))))
    return {"range_counts": out}


def _partial_filter(spec, view):
    from elasticsearch_tpu.search import plan as P
    from elasticsearch_tpu.search.query_dsl import parse_query

    qb = parse_query(spec.body)
    node = qb.to_plan(view.shard_ctx, view.segment)
    _, matched = P.execute(view.segment.device_arrays(), node)
    sub_mask = np.asarray(matched) & view.mask
    return {"doc_count": int(sub_mask[: view.segment.nd_pad].sum()),
            "_mask": sub_mask}


def _partial_filters(spec, view):
    filters = spec.body.get("filters")
    out = {}
    if isinstance(filters, dict):
        items = filters.items()
    else:
        items = ((str(i), f) for i, f in enumerate(filters))
    for key, f in items:
        sub = _partial_filter(AggSpec(key, "filter", f, []), view)
        out[key] = sub
    return {"filters": out}


def _partial_global(spec, view):
    seg = view.segment
    mask = np.concatenate([seg.live, np.zeros(1, bool)])
    return {"doc_count": int(seg.live_doc_count), "_mask": mask}


def _partial_missing(spec, view):
    field = spec.body["field"]
    seg = view.segment
    exists = seg.exists_masks.get(field)
    if exists is None:
        sub_mask = view.mask.copy()
    else:
        sub_mask = view.mask.copy()
        sub_mask[: seg.nd_pad] &= ~exists
    return {"doc_count": int(sub_mask[: seg.nd_pad].sum()), "_mask": sub_mask}


# --- geo metrics ---


def _geo_values(spec, view):
    seg = view.segment
    col = seg.geo_columns.get(spec.body["field"])
    if col is None or col.count == 0:
        import numpy as _np

        return _np.empty(0, _np.float32), _np.empty(0, _np.float32)
    sel = view.mask[col.flat_docs[: col.count]]
    return col.lat[: col.count][sel], col.lon[: col.count][sel]


def _partial_geo_bounds(spec, view):
    lat, lon = _geo_values(spec, view)
    if lat.size == 0:
        return {"top": None}
    return {
        "top": float(lat.max()), "bottom": float(lat.min()),
        "left": float(lon.min()), "right": float(lon.max()),
    }


def _partial_geo_centroid(spec, view):
    lat, lon = _geo_values(spec, view)
    return {"count": int(lat.size), "lat_sum": float(lat.sum()),
            "lon_sum": float(lon.sum())}


def _partial_geohash_grid(spec, view):
    from elasticsearch_tpu.utils.geohash import encode

    precision = int(spec.body.get("precision", 5))
    lat, lon = _geo_values(spec, view)
    counts: Dict[str, int] = {}
    for la, lo in zip(lat.tolist(), lon.tolist()):
        h = encode(la, lo, precision)
        counts[h] = counts.get(h, 0) + 1
    return {"counts": counts}


def _partial_matrix_stats(spec, view):
    """matrix_stats (modules/aggs-matrix-stats): per-field-pair covariance/
    correlation over docs having all fields."""
    fields = spec.body["fields"]
    seg = view.segment
    cols = []
    for f in fields:
        col = _resolve_value_field(seg, f)
        if col is None:
            return {"n": 0, "fields": fields}
        cols.append(col)
    sel = view.mask[: seg.nd_pad].copy()
    for col in cols:
        sel &= col.exists
    data = np.stack([np.where(sel, c.first_value, 0.0) for c in cols])
    n = int(sel.sum())
    if n == 0:
        return {"n": 0, "fields": fields}
    # sufficient statistics (associative across segments)
    sums = data.sum(axis=1)
    prods = data @ data.T
    return {"n": n, "fields": fields, "sums": sums, "prods": prods}


_PARTIAL_FNS: Dict[str, Callable] = {
    "geo_bounds": _partial_geo_bounds,
    "geo_centroid": _partial_geo_centroid,
    "geohash_grid": _partial_geohash_grid,
    "matrix_stats": _partial_matrix_stats,
    "min": _partial_stats, "max": _partial_stats, "sum": _partial_stats,
    "avg": _partial_stats, "stats": _partial_stats, "extended_stats": _partial_stats,
    "value_count": _partial_stats,
    "cardinality": _partial_cardinality,
    "percentiles": _partial_percentiles,
    "top_hits": _partial_top_hits,
    "terms": _partial_terms,
    "histogram": lambda s, v: _partial_histogram(s, v, is_date=False),
    "date_histogram": lambda s, v: _partial_histogram(s, v, is_date=True),
    "range": lambda s, v: _partial_range(s, v, is_date=False),
    "date_range": lambda s, v: _partial_range(s, v, is_date=True),
    "filter": _partial_filter,
    "filters": _partial_filters,
    "global": _partial_global,
    "missing": _partial_missing,
}


# ---------------------------------------------------------------------------
# Reduce (partials -> final response), two-phase sub-agg execution
# ---------------------------------------------------------------------------


def _reduce_stats(partials: List[dict]) -> dict:
    out = {"count": 0, "sum": 0.0, "min": math.inf, "max": -math.inf, "sq": 0.0}
    for p in partials:
        out["count"] += p["count"]
        out["sum"] += p["sum"]
        out["min"] = min(out["min"], p["min"])
        out["max"] = max(out["max"], p["max"])
        out["sq"] += p["sq"]
    return out


def _finalize_metric(spec: AggSpec, partials: List[dict]) -> dict:
    t = spec.type
    if t in ("min", "max", "sum", "avg", "stats", "extended_stats", "value_count"):
        st = _reduce_stats(partials)
        count, total = st["count"], st["sum"]
        if t == "min":
            return {"value": None if count == 0 else st["min"]}
        if t == "max":
            return {"value": None if count == 0 else st["max"]}
        if t == "sum":
            return {"value": total}
        if t == "avg":
            return {"value": None if count == 0 else total / count}
        if t == "value_count":
            return {"value": count}
        base = {
            "count": count,
            "min": None if count == 0 else st["min"],
            "max": None if count == 0 else st["max"],
            "avg": None if count == 0 else total / count,
            "sum": total,
        }
        if t == "stats":
            return base
        variance = 0.0
        if count > 0:
            variance = max(st["sq"] / count - (total / count) ** 2, 0.0)
        base.update({
            "sum_of_squares": st["sq"],
            "variance": variance,
            "std_deviation": math.sqrt(variance),
            "std_deviation_bounds": {
                "upper": (total / count + 2 * math.sqrt(variance)) if count else None,
                "lower": (total / count - 2 * math.sqrt(variance)) if count else None,
            },
        })
        return base
    if t == "cardinality":
        regs = None
        for p in partials:
            regs = p["registers"] if regs is None else np.maximum(regs, p["registers"])
        if regs is None:
            return {"value": 0}
        return {"value": int(round(agg_ops.hll_estimate(regs)))}
    if t == "percentiles":
        vals = np.concatenate([p["values"] for p in partials]) if partials else np.empty(0)
        pcts = spec.body.get("percents", [1, 5, 25, 50, 75, 95, 99])
        if vals.size == 0:
            return {"values": {str(float(p)): None for p in pcts}}
        return {"values": {
            str(float(p)): float(np.percentile(vals, p)) for p in pcts
        }}
    if t == "top_hits":
        size = int(spec.body.get("size", 3))
        all_hits = [h for p in partials for h in p["hits"]]
        all_hits.sort(key=lambda h: -h["_score"])
        return {"hits": {
            "total": len(all_hits),
            "hits": all_hits[:size],
        }}
    if t == "geo_bounds":
        tops = [p for p in partials if p.get("top") is not None]
        if not tops:
            return {"bounds": None}
        return {"bounds": {
            "top_left": {"lat": max(p["top"] for p in tops),
                         "lon": min(p["left"] for p in tops)},
            "bottom_right": {"lat": min(p["bottom"] for p in tops),
                             "lon": max(p["right"] for p in tops)},
        }}
    if t == "geo_centroid":
        count = sum(p["count"] for p in partials)
        if count == 0:
            return {"count": 0, "location": None}
        return {"count": count, "location": {
            "lat": sum(p["lat_sum"] for p in partials) / count,
            "lon": sum(p["lon_sum"] for p in partials) / count,
        }}
    if t == "matrix_stats":
        live = [p for p in partials if p.get("n")]
        if not live:
            return {"doc_count": 0, "fields": []}
        fields = live[0]["fields"]
        n = sum(p["n"] for p in live)
        sums = sum(p["sums"] for p in live)
        prods = sum(p["prods"] for p in live)
        means = sums / n
        cov = prods / n - np.outer(means, means)
        std = np.sqrt(np.clip(np.diag(cov), 1e-30, None))
        corr = cov / np.outer(std, std)
        out_fields = []
        for i, f in enumerate(fields):
            out_fields.append({
                "name": f,
                "count": n,
                "mean": float(means[i]),
                "variance": float(cov[i, i]),
                "covariance": {g: float(cov[i, j]) for j, g in enumerate(fields)},
                "correlation": {g: float(corr[i, j]) for j, g in enumerate(fields)},
            })
        return {"doc_count": n, "fields": out_fields}
    raise ParsingException(f"cannot finalize metric [{t}]")


def _agg_request_estimate(specs: List[AggSpec], views) -> int:
    """Per-request accounting estimate for the request breaker: bucket
    machinery scales with (aggs x segments x docs-touched)."""
    n_specs = sum(1 + len(s.subs) for s in specs)
    n_docs = sum(int(v.segment.nd_pad) for v in views)
    return n_specs * (n_docs * 4 + 4096)


def run_aggregations(specs: List[AggSpec], views: List[SegmentView]) -> dict:
    """Execute an agg tree over segment views; returns the response dict
    keyed by agg name (single-node path: segments of one or more shards)."""
    from elasticsearch_tpu.common.breaker import (
        CircuitBreaker,
        breaker_service,
    )

    breaker = breaker_service().get_breaker(CircuitBreaker.REQUEST)
    est = _agg_request_estimate(specs, views)
    breaker.add_estimate_bytes_and_maybe_break(est, "<agg_request>")
    try:
        out = {}
        pipeline_specs = [s for s in specs if s.type in PIPELINE_TYPES]
        for spec in specs:
            if spec.type in PIPELINE_TYPES:
                continue
            out[spec.name] = _run_one(spec, views)
        for spec in pipeline_specs:
            _apply_pipeline(spec, out)
        return out
    finally:
        breaker.add_without_breaking(-est)


def _run_one(spec: AggSpec, views: List[SegmentView]) -> dict:
    """Runs one agg; pipeline sub-aggs ("parent pipelines" — moving_avg /
    derivative / cumulative_sum / serial_diff / bucket_script / bucket_sort
    embedded INSIDE a bucket agg, the reference's canonical placement) are
    stripped first and applied across the finished buckets."""
    embedded = [s for s in (spec.subs or []) if s.type in PIPELINE_TYPES]
    if embedded:
        spec = AggSpec(spec.name, spec.type, spec.body,
                       [s for s in spec.subs if s.type not in PIPELINE_TYPES])
    result = _run_one_inner(spec, views)
    for p in embedded:
        _apply_embedded_pipeline(p, result)
    return result


def _apply_embedded_pipeline(spec: AggSpec, result: dict) -> None:
    """Apply a parent pipeline to its enclosing agg's reduced buckets by
    wrapping them as a synthetic sibling path."""
    wrapped = {"_b": result}
    body = dict(spec.body)
    if isinstance(body.get("buckets_path"), str):
        body["buckets_path"] = "_b>" + body["buckets_path"]
    elif isinstance(body.get("buckets_path"), dict):
        body["buckets_path"] = {k: "_b>" + v
                                for k, v in body["buckets_path"].items()}
    elif spec.type == "bucket_sort":
        pass  # sorts the parent's buckets; no path needed
    _apply_pipeline(AggSpec(spec.name, spec.type, body, spec.subs), wrapped)
    if spec.name in wrapped:  # sibling-output pipelines (avg_bucket family)
        result[spec.name] = wrapped[spec.name]


def _run_one_inner(spec: AggSpec, views: List[SegmentView]) -> dict:
    custom = CUSTOM_AGGS.get(spec.type)
    if custom is not None:
        return custom(spec, views)
    if spec.type in METRIC_TYPES:
        partials = [compute_partial(spec, v) for v in views]
        return _finalize_metric(spec, partials)

    if spec.type in ("filter", "global", "missing"):
        partials = [compute_partial(spec, v) for v in views]
        doc_count = sum(p["doc_count"] for p in partials)
        result = {"doc_count": doc_count}
        if spec.subs:
            sub_views = [v.with_mask(p["_mask"]) for v, p in zip(views, partials)]
            result.update(run_aggregations(spec.subs, sub_views))
        return result

    if spec.type == "filters":
        partials = [compute_partial(spec, v) for v in views]
        buckets = {}
        keys = partials[0]["filters"].keys() if partials else []
        for key in keys:
            doc_count = sum(p["filters"][key]["doc_count"] for p in partials)
            b = {"doc_count": doc_count}
            if spec.subs:
                sub_views = [v.with_mask(p["filters"][key]["_mask"])
                             for v, p in zip(views, partials)]
                b.update(run_aggregations(spec.subs, sub_views))
            buckets[key] = b
        return {"buckets": buckets}

    if spec.type == "terms":
        merged = _terms_global_merge(spec, views)
        if merged is None:  # numeric/missing field: string-keyed partials
            partials = [compute_partial(spec, v) for v in views]
            merged = {}
            for p in partials:
                for k, c in p["counts"].items():
                    merged[k] = merged.get(k, 0) + c
        sub_cb = None
        if spec.subs:
            def sub_cb(key):
                sub_views = [
                    v.with_mask(_term_bucket_mask(v, spec.body["field"], key))
                    for v in views
                ]
                return run_aggregations(spec.subs, sub_views)
        return finalize_terms(spec, merged, sub_cb)

    if spec.type in ("histogram", "date_histogram"):
        is_date = spec.type == "date_histogram"
        partials = [compute_partial(spec, v) for v in views]
        merged = {}
        for p in partials:
            for k, c in p["counts"].items():
                merged[k] = merged.get(k, 0) + c
        sub_cb = None
        if spec.subs:
            def sub_cb(key, count):
                if count > 0:
                    sub_views = [
                        v.with_mask(_histo_bucket_mask(v, spec, key, is_date))
                        for v in views
                    ]
                else:
                    sub_views = [v.with_mask(np.zeros_like(v.mask))
                                 for v in views]
                return run_aggregations(spec.subs, sub_views)
        return finalize_histogram(spec, merged, is_date, sub_cb)

    if spec.type == "nested":
        # nested agg (search/aggregations/bucket/nested/NestedAggregator):
        # flips the doc context from matched parents to their nested
        # objects at `path`; sub-aggs read the sub-segment's columns
        # (keyed by full field path)
        path = spec.body.get("path")
        sub_views = []
        doc_count = 0
        for v in views:
            nctx = v.segment.nested.get(path)
            if nctx is None or nctx.segment.num_docs == 0:
                continue
            n = nctx.parent_of.shape[0]
            nseg = nctx.segment
            m = np.zeros(nseg.nd_pad + 1, dtype=bool)
            m[:n] = v.mask[nctx.parent_of] & nseg.live[:n]
            doc_count += int(m.sum())
            sub_views.append(SegmentView(nseg, m, v.shard_ctx,
                                         nested_ctx=nctx, root_view=v))
        result = {"doc_count": doc_count}
        if spec.subs:
            result.update(run_aggregations(spec.subs, sub_views))
        return result

    if spec.type == "reverse_nested":
        # reverse_nested (bucket/nested/ReverseNestedAggregator): joins
        # back from nested objects to the enclosing root docs (optionally
        # re-descending into another nested `path`)
        target_path = spec.body.get("path")
        sub_views = []
        doc_count = 0
        for v in views:
            nctx, rv = v.nested_ctx, v.root_view
            if nctx is None or rv is None:
                raise ParsingException(
                    "Reverse nested aggregation must be nested in a nested "
                    "aggregation"
                )
            n = nctx.parent_of.shape[0]
            rm = np.zeros(rv.segment.nd_pad + 1, dtype=bool)
            objs = np.nonzero(v.mask[:n])[0]
            rm[nctx.parent_of[objs]] = True
            rm[: rv.segment.nd_pad] &= rv.segment.live
            if target_path is None:
                doc_count += int(rm.sum())
                sub_views.append(SegmentView(rv.segment, rm, rv.shard_ctx,
                                             rv.scores))
            else:
                tctx = rv.segment.nested.get(target_path)
                if tctx is None or tctx.segment.num_docs == 0:
                    continue
                tn = tctx.parent_of.shape[0]
                tseg = tctx.segment
                tm = np.zeros(tseg.nd_pad + 1, dtype=bool)
                tm[:tn] = rm[tctx.parent_of] & tseg.live[:tn]
                doc_count += int(tm.sum())
                sub_views.append(SegmentView(tseg, tm, rv.shard_ctx,
                                             nested_ctx=tctx, root_view=rv))
        result = {"doc_count": doc_count}
        if spec.subs:
            result.update(run_aggregations(spec.subs, sub_views))
        return result

    if spec.type == "children":
        # children agg (modules/parent-join — ChildrenAggregationBuilder):
        # flips the doc context from matched parents to their children of
        # the given join type (cross-segment: children may live in any
        # segment of the shard)
        from elasticsearch_tpu.mapper.field_types import join_field_of

        child_type = spec.body["type"]
        jf = None
        for v in views:
            if v.shard_ctx is not None:
                jf = join_field_of(v.shard_ctx.mapper_service)
                if jf is not None:
                    break
        parent_ids = set()
        if jf is not None:
            for v in views:
                seg = v.segment
                for local in np.nonzero(v.mask[: seg.nd_pad])[0]:
                    parent_ids.add(seg.doc_ids[int(local)])
        from elasticsearch_tpu.search.query_dsl import join_children

        sub_views = []
        total = 0
        for v in views:
            seg = v.segment
            mask = np.zeros_like(v.mask)
            if jf is not None:
                locals_, pids = join_children(seg, jf.name, [child_type])
                for local, pid in zip(locals_, pids):
                    if pid in parent_ids:
                        mask[int(local)] = True
            total += int(mask[: seg.nd_pad].sum())
            sub_views.append(v.with_mask(mask))
        result = {"doc_count": total}
        if spec.subs:
            result.update(run_aggregations(spec.subs, sub_views))
        return result

    if spec.type == "significant_terms":
        # foreground (matched) vs background (all live) term counts; JLH
        # score as in bucket/significant/heuristics/JLHScore.java
        fg_partials = [compute_partial(AggSpec(spec.name, "terms", spec.body, []), v)
                       for v in views]
        bg_views = [v.with_mask(np.concatenate([v.segment.live,
                                                np.zeros(1, bool)]))
                    for v in views]
        bg_partials = [compute_partial(AggSpec(spec.name, "terms", spec.body, []), v)
                       for v in bg_views]
        fg: Dict = {}
        bg: Dict = {}
        for p in fg_partials:
            for k, c in p["counts"].items():
                fg[k] = fg.get(k, 0) + c
        for p in bg_partials:
            for k, c in p["counts"].items():
                bg[k] = bg.get(k, 0) + c
        fg_total = sum(int(v.mask[: v.segment.nd_pad].sum()) for v in views)
        bg_total = sum(v.segment.live_doc_count for v in views)
        size = int(spec.body.get("size", 10))
        min_doc_count = int(spec.body.get("min_doc_count", 3))
        scored = []
        for key, fg_count in fg.items():
            if fg_count < min_doc_count or fg_total == 0 or bg_total == 0:
                continue
            fg_rate = fg_count / fg_total
            bg_rate = bg.get(key, fg_count) / bg_total
            if fg_rate <= bg_rate:
                continue
            score = (fg_rate - bg_rate) * (fg_rate / max(bg_rate, 1e-12))
            scored.append((score, key, fg_count, bg.get(key, fg_count)))
        scored.sort(reverse=True)
        buckets = []
        for score, key, fg_count, bg_count in scored[:size]:
            b = {"key": key, "doc_count": fg_count, "score": score,
                 "bg_count": bg_count}
            if spec.subs:
                sub_views = [
                    v.with_mask(_term_bucket_mask(v, spec.body["field"], key))
                    for v in views
                ]
                b.update(run_aggregations(spec.subs, sub_views))
            buckets.append(b)
        return {"doc_count": fg_total, "bg_count": bg_total, "buckets": buckets}

    if spec.type in ("sampler", "diversified_sampler"):
        # top-scoring shard_size matched docs per segment (bucket/sampler
        # SamplerAggregator, DiversifiedAggregatorFactory); diversified
        # additionally caps docs per distinct value of `field`
        shard_size = int(spec.body.get("shard_size", 100))
        max_per_value = int(spec.body.get("max_docs_per_value", 1))
        div_field = spec.body.get("field") if spec.type == "diversified_sampler" \
            else None
        sub_views = []
        total = 0
        for v in views:
            cand = np.nonzero(v.mask[: v.segment.nd_pad])[0]
            if v.scores is not None and cand.size:
                cand = cand[np.argsort(-v.scores[cand], kind="stable")]
            if div_field is not None and cand.size:
                col = _resolve_ordinal_field(v.segment, div_field)
                ncol = (v.segment.numeric_columns.get(div_field)
                        if col is None else None)
                per_value: Dict = {}
                kept = []
                for d in cand:
                    if col is not None and col.exists[d]:
                        key = int(col.first_ord[d])
                    elif ncol is not None and ncol.exists[d]:
                        key = float(ncol.first_value[d])
                    else:
                        key = None  # undiversified docs are not capped
                    if key is not None:
                        seen = per_value.get(key, 0)
                        if seen >= max_per_value:
                            continue
                        per_value[key] = seen + 1
                    kept.append(d)
                    if len(kept) >= shard_size:
                        break
                idx = np.asarray(kept, dtype=np.int64)
            else:
                idx = cand[:shard_size]
            mask = np.zeros_like(v.mask)
            mask[idx] = True
            total += int(idx.size)
            sub_views.append(v.with_mask(mask))
        out = {"doc_count": total}
        if spec.subs:
            out.update(run_aggregations(spec.subs, sub_views))
        return out

    if spec.type == "scripted_metric":
        # scripted_metric (metrics/scripted/): restricted to numeric
        # expressions (script/expression.py) — map_script computes a
        # per-doc value (vectorized over columns), partials sum per
        # segment, reduce_script (over `states` via params._agg) folds the
        # shard partials; painless-style stateful scripts are out of scope
        from elasticsearch_tpu.script.expression import (
            compile_script,
            segment_columns,
        )

        map_spec = spec.body.get("map_script")
        if map_spec is None:
            raise ParsingException("[scripted_metric] requires [map_script]")
        script = compile_script(map_spec)
        params = dict(spec.body.get("params") or {})
        partials = []
        for v in views:
            seg = v.segment
            nd = seg.nd_pad
            vals = script.execute_columns(segment_columns(seg, script.doc_fields),
                                          params)
            if vals is None:  # scalar division-by-zero contract
                continue
            vals = np.broadcast_to(np.asarray(vals, dtype=np.float64), (nd,))
            partials.append(float(np.where(v.mask[:nd], vals[:nd], 0.0).sum()))
        total = float(sum(partials))
        reduce_spec = spec.body.get("reduce_script")
        if reduce_spec is not None:
            rscript = compile_script(reduce_spec)
            total = rscript.execute({}, {**params, "_agg": total})
        return {"value": total}

    if spec.type == "adjacency_matrix":
        filters = spec.body["filters"]
        keys = list(filters.keys())
        # per-filter masks per view
        masks: Dict[str, List[np.ndarray]] = {}
        for key in keys:
            partials = [
                _partial_filter(AggSpec(key, "filter", filters[key], []), v)
                for v in views
            ]
            masks[key] = [p["_mask"] for p in partials]
        buckets = []
        sep = spec.body.get("separator", "&")
        for i, a in enumerate(keys):
            for j in range(i, len(keys)):
                b_key = keys[j]
                name = a if i == j else f"{a}{sep}{b_key}"
                count = 0
                combined_views = []
                for vi, v in enumerate(views):
                    m = masks[a][vi] & masks[b_key][vi]
                    count += int(m[: v.segment.nd_pad].sum())
                    combined_views.append(v.with_mask(m))
                if count == 0:
                    continue
                bucket = {"key": name, "doc_count": count}
                if spec.subs:
                    bucket.update(run_aggregations(spec.subs, combined_views))
                buckets.append(bucket)
        return {"buckets": buckets}

    if spec.type == "geohash_grid":
        partials = [compute_partial(spec, v) for v in views]
        merged = {}
        for p in partials:
            for k, c in p["counts"].items():
                merged[k] = merged.get(k, 0) + c
        size = int(spec.body.get("size", 10000))
        items = sorted(merged.items(), key=lambda kv: (-kv[1], kv[0]))[:size]
        return {"buckets": [{"key": k, "doc_count": c} for k, c in items]}

    if spec.type in ("range", "date_range"):
        is_date = spec.type == "date_range"
        partials = [compute_partial(spec, v) for v in views]
        ranges = spec.body["ranges"]
        buckets = []
        for i, r in enumerate(ranges):
            count = sum(p["range_counts"][i] for p in partials)
            key = r.get("key")
            if key is None:
                lo = r.get("from", "*")
                hi = r.get("to", "*")
                key = f"{lo}-{hi}"
            b = {"key": key, "doc_count": count}
            if "from" in r:
                b["from"] = parse_date(r["from"]) if is_date else float(r["from"])
            if "to" in r:
                b["to"] = parse_date(r["to"]) if is_date else float(r["to"])
            if spec.subs:
                sub_views = [
                    v.with_mask(_range_bucket_mask(v, spec.body["field"], r, is_date))
                    for v in views
                ]
                b.update(run_aggregations(spec.subs, sub_views))
            buckets.append(b)
        return {"buckets": buckets}

    raise ParsingException(f"Unsupported aggregation type [{spec.type}]")


def _sort_buckets(items: List[Tuple], order) -> List[Tuple]:
    if isinstance(order, list):
        order = order[0] if order else {"_count": "desc"}
    ((key, direction),) = order.items()
    reverse = str(direction).lower() == "desc"
    if key == "_count":
        return sorted(items, key=lambda kv: (-kv[1] if reverse else kv[1], str(kv[0])))
    if key in ("_key", "_term"):
        return sorted(items, key=lambda kv: kv[0], reverse=reverse)
    # sub-agg ordering unsupported pre-selection; fall back to count desc
    return sorted(items, key=lambda kv: (-kv[1], str(kv[0])))


def finalize_terms(spec: AggSpec, merged: Dict, sub_cb=None) -> dict:
    """Terms bucket selection/formatting from a merged {key: count} map.

    SHARED by the host reduce and the fused on-device plane
    (search/fused_aggs.py): both produce the same merged counts, so
    routing them through one assembly function makes ordering, size
    cutoff, sum_other and key formatting byte-identical by construction
    (docs/AGGS.md parity contract). ``sub_cb(key) -> dict`` attaches
    sub-aggregation results per surviving bucket (host path only — the
    fused plane excludes sub-aggs structurally)."""
    size = int(spec.body.get("size", 10))
    order = spec.body.get("order", {"_count": "desc"})
    items = _sort_buckets(list(merged.items()), order)
    selected = items[:size]
    sum_other = sum(c for _, c in items[size:])
    buckets = []
    for key, count in selected:
        b = {"key": key, "doc_count": count}
        if sub_cb is not None:
            b.update(sub_cb(key))
        buckets.append(b)
    return {
        "doc_count_error_upper_bound": 0,
        "sum_other_doc_count": sum_other,
        "buckets": buckets,
    }


def finalize_histogram(spec: AggSpec, merged: Dict, is_date: bool,
                       sub_cb=None) -> dict:
    """Histogram/date_histogram bucket assembly from merged {key: count}
    (min_doc_count filtering, empty-bucket fill, key_as_string) —
    SHARED by the host reduce and the fused on-device plane, same
    contract as finalize_terms. ``sub_cb(key, count) -> dict``."""
    min_doc_count = int(spec.body.get("min_doc_count",
                                      1 if not is_date else 0))
    keys = sorted(merged.keys())
    # date_histogram fills empty buckets between min and max (min_doc_count=0)
    if keys and min_doc_count == 0:
        interval = spec.body.get("interval") or spec.body.get(
            "calendar_interval") or spec.body.get("fixed_interval")
        ms = (_date_interval_ms(interval) if is_date
              else float(spec.body["interval"]))
        if ms is not None:
            full, k = [], keys[0]
            while k <= keys[-1] and len(full) < 10000:
                full.append(k)
                k += ms if not is_date else int(ms)
            keys = [k for k in full]
    buckets = []
    for key in keys:
        count = merged.get(key, 0)
        if count < min_doc_count:
            continue
        b = {"key": key, "doc_count": count}
        if is_date:
            b["key_as_string"] = format_epoch_millis(int(key))
        if sub_cb is not None:
            b.update(sub_cb(key, count))
        buckets.append(b)
    return {"buckets": buckets}


def _term_bucket_mask(view: SegmentView, field: str, key) -> np.ndarray:
    seg = view.segment
    ocol = _resolve_ordinal_field(seg, field)
    mask = np.zeros_like(view.mask)
    if ocol is not None:
        o = ocol.ord_of(str(key))
        if o < 0:
            return mask
        sel = ocol.flat_ords[: ocol.count] == o
        mask[ocol.flat_docs[: ocol.count][sel]] = True
        return mask & view.mask
    col = _resolve_value_field(seg, field)
    if col is None:
        return mask
    sel = col.flat_values[: col.count] == float(key)
    mask[col.flat_docs[: col.count][sel]] = True
    return mask & view.mask


def _histo_bucket_mask(view: SegmentView, spec: AggSpec, key, is_date: bool) -> np.ndarray:
    seg = view.segment
    col = _resolve_value_field(seg, spec.body["field"])
    mask = np.zeros_like(view.mask)
    if col is None:
        return mask
    vals = col.flat_values[: col.count]
    if is_date:
        interval = spec.body.get("interval") or spec.body.get(
            "calendar_interval") or spec.body.get("fixed_interval")
        ms = _date_interval_ms(interval)
        if ms is None:
            keys = _calendar_bucket_keys(vals.astype(np.int64), str(interval))
            sel = keys == int(key)
        else:
            offset = float(spec.body.get("offset", 0) or 0)
            sel = (np.floor((vals - offset) / ms) * ms + offset).astype(np.int64) == int(key)
    else:
        interval = float(spec.body["interval"])
        offset = float(spec.body.get("offset", 0.0))
        sel = (np.floor((vals - offset) / interval) * interval + offset) == float(key)
    mask[col.flat_docs[: col.count][sel]] = True
    return mask & view.mask


def _range_bucket_mask(view: SegmentView, field: str, r: dict, is_date: bool) -> np.ndarray:
    seg = view.segment
    col = _resolve_value_field(seg, field)
    mask = np.zeros_like(view.mask)
    if col is None:
        return mask
    conv = (lambda v: float(parse_date(v))) if is_date else float
    lo = conv(r["from"]) if "from" in r else -np.inf
    hi = conv(r["to"]) if "to" in r else np.inf
    vals = col.flat_values[: col.count]
    sel = (vals >= lo) & (vals < hi)
    mask[col.flat_docs[: col.count][sel]] = True
    return mask & view.mask


# ---------------------------------------------------------------------------
# Pipeline aggregations (post-process the reduced tree; search/aggregations/
# pipeline/ in the reference)
# ---------------------------------------------------------------------------


def _buckets_path_values(out: dict, path: str) -> List[Optional[float]]:
    """Resolve 'agg>metric' or 'agg' paths against reduced output."""
    parts = path.split(">")
    top = out.get(parts[0])
    if top is None or "buckets" not in top:
        raise ParsingException(f"No bucket aggregation found for path [{path}]")
    buckets = top["buckets"]
    if isinstance(buckets, dict):
        buckets = list(buckets.values())
    values = []
    for b in buckets:
        node = b
        ok = True
        for p in parts[1:]:
            if p == "_count":
                node = b["doc_count"]
                continue
            metric = p.split(".")
            node = node.get(metric[0])
            if node is None:
                ok = False
                break
            if isinstance(node, dict):
                if len(metric) > 1:
                    node = node.get(metric[1])
                elif "value" in node:
                    node = node["value"]
        if not ok:
            values.append(None)
        elif isinstance(node, dict):
            values.append(node.get("value"))
        else:
            values.append(b["doc_count"] if len(parts) == 1 else node)
    if len(parts) == 1:
        values = [b["doc_count"] for b in buckets]
    return values


def _apply_pipeline(spec: AggSpec, out: dict) -> None:
    t = spec.type
    path = spec.body.get("buckets_path")
    if t == "bucket_script" or t == "bucket_selector":
        _apply_bucket_script(spec, out)
        return
    if t == "bucket_sort":
        _apply_bucket_sort(spec, out)
        return
    values = _buckets_path_values(out, path)
    parent = path.split(">")[0]
    buckets = out[parent]["buckets"]
    if isinstance(buckets, dict):
        buckets = list(buckets.values())
    if t == "derivative":
        prev = None
        for b, v in zip(buckets, values):
            if prev is not None and v is not None:
                b[spec.name] = {"value": v - prev}
            prev = v
    elif t == "serial_diff":
        lag = int(spec.body.get("lag", 1))
        for i, b in enumerate(buckets):
            if i >= lag and values[i] is not None and values[i - lag] is not None:
                b[spec.name] = {"value": values[i] - values[i - lag]}
    elif t == "cumulative_sum":
        acc = 0.0
        for b, v in zip(buckets, values):
            acc += v or 0.0
            b[spec.name] = {"value": acc}
    elif t == "moving_avg":
        window = int(spec.body.get("window", 5))
        model = spec.body.get("model", "simple")
        settings = spec.body.get("settings") or {}
        for i, b in enumerate(buckets):
            if i == 0:
                continue
            w = [v for v in values[max(0, i - window): i] if v is not None]
            if w:
                b[spec.name] = {"value": _movavg_model(w, model, settings)}
        predict = int(spec.body.get("predict", 0))
        # predictions append real buckets — only meaningful for list-
        # shaped bucket aggs (histogram family)
        if predict > 0 and buckets and isinstance(out[parent]["buckets"], list):
            _movavg_predict(spec, buckets, values, window, model, settings,
                            predict)
    elif t in ("avg_bucket", "sum_bucket", "min_bucket", "max_bucket", "stats_bucket"):
        vals = [v for v in values if v is not None]
        if t == "avg_bucket":
            out[spec.name] = {"value": sum(vals) / len(vals) if vals else None}
        elif t == "sum_bucket":
            out[spec.name] = {"value": sum(vals)}
        elif t == "min_bucket":
            out[spec.name] = {"value": min(vals) if vals else None}
        elif t == "max_bucket":
            out[spec.name] = {"value": max(vals) if vals else None}
        else:
            out[spec.name] = {
                "count": len(vals),
                "min": min(vals) if vals else None,
                "max": max(vals) if vals else None,
                "avg": sum(vals) / len(vals) if vals else None,
                "sum": sum(vals),
            }


def _movavg_model(w: List[float], model: str, settings: dict,
                  predict_steps: int = 0):
    """Moving-average models (pipeline/movavg/models/ — SimpleModel,
    LinearModel, EwmaModel, HoltLinearModel, HoltWintersModel). With
    predict_steps > 0 returns a list of forecasts instead of the
    one-step smoothed value."""
    n = len(w)
    if model == "simple":
        v = sum(w) / n
        return [v] * predict_steps if predict_steps else v
    if model == "linear":
        num = sum((i + 1) * x for i, x in enumerate(w))
        den = n * (n + 1) / 2.0
        v = num / den
        return [v] * predict_steps if predict_steps else v
    alpha = float(settings.get("alpha", 0.3))
    if model == "ewma":
        s = w[0]
        for x in w[1:]:
            s = alpha * x + (1 - alpha) * s
        return [s] * predict_steps if predict_steps else s
    beta = float(settings.get("beta", 0.1))
    if model == "holt":
        s, prev_s = w[0], w[0]
        trend = (w[1] - w[0]) if n > 1 else 0.0
        for x in w[1:]:
            prev_s = s
            s = alpha * x + (1 - alpha) * (s + trend)
            trend = beta * (s - prev_s) + (1 - beta) * trend
        if predict_steps:
            return [s + (k + 1) * trend for k in range(predict_steps)]
        return s + trend
    if model == "holt_winters":
        gamma = float(settings.get("gamma", 0.3))
        period = int(settings.get("period", 1))
        mult = settings.get("type", "add") == "mult"
        if n < 2 * period:
            # not enough data to seed seasonality: degrade to holt
            return _movavg_model(w, "holt", settings, predict_steps)
        pad = float(settings.get("padding", 1e-10)) if mult else 0.0
        vals = [x + pad for x in w]
        # seed level/trend/seasonal from the first two periods
        s = sum(vals[:period]) / period
        trend = (sum(vals[period:2 * period]) - sum(vals[:period])) / (period ** 2)
        season = ([vals[i] / s for i in range(period)] if mult
                  else [vals[i] - s for i in range(period)])
        for i in range(period, n):
            x = vals[i]
            prev_s = s
            si = season[i % period]
            if mult:
                s = alpha * (x / max(si, 1e-12)) + (1 - alpha) * (s + trend)
            else:
                s = alpha * (x - si) + (1 - alpha) * (s + trend)
            trend = beta * (s - prev_s) + (1 - beta) * trend
            season[i % period] = (gamma * (x / max(s, 1e-12)) + (1 - gamma) * si
                                  if mult else gamma * (x - s) + (1 - gamma) * si)
        def forecast(k):
            si = season[(n + k) % period]
            base = s + (k + 1) * trend
            return base * si if mult else base + si
        if predict_steps:
            return [forecast(k) for k in range(predict_steps)]
        return forecast(0)
    raise ParsingException(f"Unknown MovAvg model [{model}]")


def _movavg_predict(spec: AggSpec, buckets: List[dict], values: List,
                    window: int, model: str, settings: dict,
                    predict: int) -> None:
    """Append `predict` forecast buckets past the series end (MovAvg
    predictions; keys extend at the trailing key interval when numeric)."""
    w = [v for v in values[max(0, len(values) - window):] if v is not None]
    if not w:
        return
    forecasts = _movavg_model(w, model, settings, predict_steps=predict)
    keys = [b.get("key") for b in buckets]
    interval = None
    if (len(keys) >= 2 and isinstance(keys[-1], (int, float))
            and isinstance(keys[-2], (int, float))):
        interval = keys[-1] - keys[-2]
    is_date = bool(buckets and "key_as_string" in buckets[-1])
    for k, fv in enumerate(forecasts):
        nb = {"doc_count": 0, spec.name: {"value": fv}}
        if interval is not None:
            nb["key"] = keys[-1] + (k + 1) * interval
            if is_date:
                nb["key_as_string"] = format_epoch_millis(int(nb["key"]))
        buckets.append(nb)


_SCRIPT_ALLOWED = set("0123456789.+-*/()% eE<>=! &|")


def _eval_bucket_script(script: str, params: Dict[str, Optional[float]]) -> Optional[float]:
    """Tiny safe arithmetic evaluator for bucket_script (the reference uses
    Painless; this accepts +-*/%() and params.<name> references)."""
    expr = script
    for name, value in sorted(params.items(), key=lambda kv: -len(kv[0])):
        if value is None:
            return None
        expr = expr.replace(f"params.{name}", repr(float(value)))
    if not all(c in _SCRIPT_ALLOWED for c in expr):
        raise ParsingException(f"unsupported bucket_script [{script}]")
    try:
        return float(eval(expr, {"__builtins__": {}}, {}))  # noqa: S307 — sanitized above
    except ZeroDivisionError:
        return None
    except Exception as e:
        raise ParsingException(f"failed to evaluate bucket_script [{script}]: {e}") from e


def _apply_bucket_script(spec: AggSpec, out: dict) -> None:
    paths = spec.body["buckets_path"]
    script = spec.body["script"]
    if isinstance(script, dict):
        script = script.get("source") or script.get("inline")
    parents = {p.split(">")[0] for p in paths.values()}
    if len(parents) != 1:
        raise ParsingException("bucket_script paths must share one parent")
    parent = parents.pop()
    per_param = {name: _buckets_path_values(out, path) for name, path in paths.items()}
    buckets = out[parent]["buckets"]
    if isinstance(buckets, dict):
        buckets = list(buckets.values())
    keep = []
    for i, b in enumerate(buckets):
        params = {name: vals[i] for name, vals in per_param.items()}
        value = _eval_bucket_script(script, params)
        if spec.type == "bucket_selector":
            if value:  # truthy keeps the bucket
                keep.append(b)
        else:
            if value is not None:
                b[spec.name] = {"value": value}
    if spec.type == "bucket_selector":
        out[parent]["buckets"] = keep


def _apply_bucket_sort(spec: AggSpec, out: dict) -> None:
    # operates on sibling buckets; sort keys limited to doc_count/_key/metrics
    sorts = spec.body.get("sort", [])
    size = spec.body.get("size")
    from_ = int(spec.body.get("from", 0))
    for parent_name, parent in out.items():
        if not isinstance(parent, dict) or "buckets" not in parent:
            continue
        buckets = parent["buckets"]
        if isinstance(buckets, dict):
            continue
        for s in reversed(sorts):
            if isinstance(s, str):
                key, direction = s, "asc"
            else:
                ((key, spec_dir),) = s.items()
                direction = spec_dir.get("order", "asc") if isinstance(spec_dir, dict) else spec_dir

            def sort_key(b, key=key):
                if key == "_key":
                    return b.get("key")
                if key == "doc_count":
                    return b.get("doc_count")
                node = b.get(key)
                return node.get("value") if isinstance(node, dict) else node

            buckets.sort(key=sort_key, reverse=(direction == "desc"))
        if size is not None:
            parent["buckets"] = buckets[from_: from_ + int(size)]
        elif from_:
            parent["buckets"] = buckets[from_:]
        break  # bucket_sort applies to its sibling context: first bucket agg
