"""Search templates: mustache-rendered search bodies.

Role model: ``modules/lang-mustache`` — ``TransportSearchTemplateAction``
(render {{params}} into a search source, then run it) and the _render API.
Supports {{var}}, {{#toJson}}var{{/toJson}}, {{var}}{{^var}}default
fallbacks are approximated with {{var}} only (the common subset).
"""

from __future__ import annotations

import json
import re
from typing import Optional

from elasticsearch_tpu.common.errors import (
    ParsingException,
    ResourceNotFoundException,
)

_TOJSON_RE = re.compile(r"\{\{#toJson\}\}(\w+)\{\{/toJson\}\}")
_VAR_RE = re.compile(r"\{\{([\w.]+)\}\}")


def render_template(source, params: Optional[dict]) -> dict:
    params = params or {}
    if isinstance(source, dict):
        template = json.dumps(source)
    else:
        template = str(source)

    def tojson(m):
        name = m.group(1)
        return json.dumps(params.get(name))

    template = _TOJSON_RE.sub(tojson, template)

    def sub(m):
        path = m.group(1)
        node = params
        for part in path.split("."):
            if isinstance(node, dict) and part in node:
                node = node[part]
            else:
                return ""
        if isinstance(node, str):
            return node
        return json.dumps(node)

    rendered = _VAR_RE.sub(sub, template)
    try:
        return json.loads(rendered)
    except json.JSONDecodeError as e:
        raise ParsingException(
            f"rendered search template is not valid JSON: {e}: {rendered[:200]}"
        ) from e


def resolve_template(node, body: dict):
    """-> (rendered_body, params) from inline or stored template."""
    params = body.get("params") or {}
    if "source" in body or "inline" in body:
        return render_template(body.get("source") or body.get("inline"), params)
    if "id" in body:
        stored = node.cluster_service.state.stored_scripts.get(body["id"])
        if stored is None:
            raise ResourceNotFoundException(
                f"unable to find script [{body['id']}]"
            )
        return render_template(stored.get("source") or stored.get("inline"), params)
    raise ParsingException("search template requires [source] or [id]")
