"""Fused on-device aggregations: the columnar doc-values plane (ISSUE 13).

Role model: the reference spends ~1/3 of its search subsystem on doc
values + the aggs framework (SURVEY §2.4 — ``index/fielddata/``,
``search/aggregations/``), collecting doc-at-a-time on the heap AFTER
the query phase returned candidates. Our inversion until this module
kept that shape on the accelerator: the mesh program scored tiles on
device, then shipped every slot's dense matched mask back to the host
(``with_views``) and re-read the doc-value columns there — an agg'd
query paid a full host round-trip plus a second corpus read.

This module moves eligible aggregations INTO the compiled mesh program
(``parallel/plan_exec._mesh_query_program`` and the batched dense
program): per-segment doc-value columns are sealed at segment build,
staged per slot as device arrays under the ``doc_values`` ledger kind
(``MeshPlanExecutor.stage_doc_value_columns`` — transactional,
budget-gated, evictable), and each slot's matched mask reduces into
tiny per-spec partial accumulators inside the same launch that scored
the corpus. Only the accumulators (a few KB) cross to the host; the
masks never leave the device.

Byte-identity with the host oracle (docs/AGGS.md) is engineered, not
hoped for:

- **bucket codes are precomputed host-side at staging time** with the
  exact arithmetic the host reduce uses (global-ordinal mapping for
  terms; the f64 ``floor((v - offset) / interval)`` bucket formula for
  histogram/date_histogram), cached per (field, interval, offset) on
  the executor — the device only counts int32 codes, so bucketing can
  never diverge by f32 rounding;
- **counts** accumulate in int32 (exact);
- **sums** ride an exact integer-digit decomposition: each value
  ``v`` (eligible only when every value is an integer with
  ``|v| < 2^48`` and the column's ``sum(|v|) < 2^53`` — epoch-millis
  dates, counters, prices) is offset to ``u = v + 2^49`` and split
  into six 9-bit digits staged as int16 columns; per-slot digit sums
  stay below 2^31 (int32-exact for any mask), and the host
  reconstructs the exact integer sum with Python bignums. The
  ``sum(|v|) < 2^53`` bound also makes the host's own f64 reduction
  exact, so both sides land on the same float;
- **min/max** split each value into ``(floor(v / 2^24), remainder)``
  f32 pairs (exact for the same integer range) and reduce
  lexicographically on device.

Anything outside the engineered-exact envelope — sub-aggregations,
multi-valued fields, calendar intervals, non-integer metric values,
text fielddata, bucket ranges past the caps — falls back STRUCTURALLY
to the host reduce over the program's matched views (the previous
behavior, and the parity oracle), counted per reason in
``agg_host_fallback_by_reason`` (docs/OBSERVABILITY.md).
"""

from __future__ import annotations

import math
from typing import Dict, List, Optional, Tuple

import numpy as np

from elasticsearch_tpu.search.aggregations import (
    AggSpec,
    _date_interval_ms,
    _finalize_metric,
    finalize_histogram,
    finalize_terms,
)

# metric sums: v is offset to u = v + VALUE_OFFSET and split into
# N_DIGITS base-2^DIGIT_BITS digits; 6 * 9 bits cover u < 2^54 and a
# per-slot digit sum stays < 512 * nd_pad < 2^31 for nd_pad <= 2^21
DIGIT_BITS = 9
DIGIT_BASE = 1 << DIGIT_BITS
N_DIGITS = 6
VALUE_OFFSET = 1 << 49
MAX_ABS_VALUE = 1 << 48
MAX_ABS_SUM = 1 << 53  # f64-exact bound for the host oracle's own sum
MAX_SLOT_DOCS = 1 << 21  # int32-exactness bound for per-slot digit sums
MM_SPLIT = float(1 << 24)  # min/max hi/lo split point (both halves f32-exact)

MAX_HIST_BUCKETS = 4096
MAX_TERMS_ORDS = 1 << 16

FUSED_BUCKET_TYPES = ("terms", "histogram", "date_histogram")
FUSED_METRIC_TYPES = ("min", "max", "sum", "avg", "stats", "value_count")

# request-body keys the fused formulation covers per agg type; anything
# else (missing, script, shard_size, calendar intervals, ...) keeps the
# host reduce, which owns the full surface
_ALLOWED_BODY = {
    "terms": {"field", "size", "order"},
    "histogram": {"field", "interval", "offset", "min_doc_count"},
    "date_histogram": {"field", "interval", "fixed_interval", "offset",
                       "min_doc_count"},
    "min": {"field"}, "max": {"field"}, "sum": {"field"},
    "avg": {"field"}, "stats": {"field"}, "value_count": {"field"},
}


class FusedAggPlan:
    """One query's resolved fused aggregation set.

    ``ops`` (aligned with ``specs``) are the STATIC per-spec descriptors
    baked into the compiled program's cache key:

      ("empty",)                      field absent everywhere — no device
                                      work, finalize emits the empty frame
      ("bucket", col_key, nb)         terms / histogram / date_histogram:
                                      count int32 codes into [nb] buckets
      ("metric", base, mm, dig)       stats family over base+".ex" /
                                      ".mm" / ".dig" columns

    ``metas`` carry the host-side finalize context (vocab, bucket-key
    reconstruction parameters)."""

    __slots__ = ("specs", "ops", "metas")

    def __init__(self, specs: List[AggSpec], ops: List[tuple],
                 metas: List[dict]):
        self.specs = specs
        self.ops = ops
        self.metas = metas

    @property
    def statics(self) -> tuple:
        return tuple(self.ops)

    def column_keys(self) -> List[str]:
        keys: List[str] = []
        for op in self.ops:
            if op[0] == "bucket":
                keys.append(op[1])
            elif op[0] == "metric":
                _, base, want_mm, want_dig = op
                keys.append(base + ".ex")
                if want_mm:
                    keys.append(base + ".mm")
                if want_dig:
                    keys.append(base + ".dig")
        return keys

    def staged_bytes(self, seg_staged: dict) -> int:
        return sum(int(seg_staged[k].nbytes) for k in self.column_keys()
                   if k in seg_staged)


def n_agg_outputs(statics: tuple) -> int:
    n = 0
    for op in statics:
        if op[0] == "bucket":
            n += 1
        elif op[0] == "metric":
            n += 1 + int(op[2]) + int(op[3])
    return n


# ---------------------------------------------------------------------------
# Device-side partial emission (traced inside the mesh programs)
# ---------------------------------------------------------------------------


def emit_agg_partials(statics: tuple, seg: dict, mask):
    """Per-slot partial accumulators for one (slot, mask) pair, traced
    into the mesh program. ``mask``: bool [nd1] — the agg-visible
    matched mask (post min_score/slice, pre post_filter, live applied).
    Output order matches ``n_agg_outputs``; every array is tiny (bucket
    counts / digit sums / min-max pairs), int32-exact or f32-exact per
    the module contract."""
    import jax.numpy as jnp

    outs = []
    for op in statics:
        if op[0] == "empty":
            continue
        if op[0] == "bucket":
            _, key, nb = op
            codes = seg[key]  # [nd1] int32, -1 = no value
            sel = mask & (codes >= 0)
            safe = jnp.where(sel, codes, jnp.int32(0))
            outs.append(jnp.zeros((nb,), jnp.int32).at[safe].add(
                sel.astype(jnp.int32)))
            continue
        _, base, want_mm, want_dig = op
        sel = mask & seg[base + ".ex"]
        outs.append(jnp.sum(sel.astype(jnp.int32))[None])  # [1] count
        if want_mm:
            mm = seg[base + ".mm"]  # [nd1, 2] f32: (floor(v/2^24), rest)
            hi, lo = mm[:, 0], mm[:, 1]
            inf = jnp.float32(jnp.inf)
            minhi = jnp.min(jnp.where(sel, hi, inf))
            minlo = jnp.min(jnp.where(sel & (hi == minhi), lo, inf))
            maxhi = jnp.max(jnp.where(sel, hi, -inf))
            maxlo = jnp.max(jnp.where(sel & (hi == maxhi), lo, -inf))
            outs.append(jnp.stack([minhi, minlo, maxhi, maxlo]))
        if want_dig:
            dig = seg[base + ".dig"].astype(jnp.int32)  # [nd1, N_DIGITS]
            outs.append(jnp.sum(jnp.where(sel[:, None], dig, 0), axis=0))
    return outs


# ---------------------------------------------------------------------------
# Eligibility + column builds (host side, once per executor generation)
# ---------------------------------------------------------------------------


def _metric_field_checks(executor, field: str) -> dict:
    """Column-wide eligibility facts for a numeric field, cached on the
    executor (one scan per field per staged generation)."""
    cache = getattr(executor, "_agg_field_checks", None)
    if cache is None:
        cache = executor._agg_field_checks = {}
    hit = cache.get(field)
    if hit is not None:
        return hit
    cols = [s.numeric_columns.get(field) for s in executor.segments]
    present = [c for c in cols if c is not None and c.count > 0]
    facts = {"present": bool(present), "single": True, "finite": True,
             "int48": True, "abs_sum_ok": True}
    abs_sum = 0.0
    for c in present:
        vals = c.flat_values[: c.count]
        if c.count != int(c.exists.sum()):
            facts["single"] = False
        if not np.all(np.isfinite(vals)):
            facts["finite"] = False
            continue
        if not (np.all(vals == np.floor(vals))
                and np.all(np.abs(vals) < MAX_ABS_VALUE)):
            facts["int48"] = False
        abs_sum += float(np.abs(vals).sum())
    if abs_sum >= MAX_ABS_SUM:
        facts["abs_sum_ok"] = False
    cache[field] = facts
    return facts


def _build_bucket_codes(executor, per_seg_codes) -> np.ndarray:
    """[n_slots, nd1] int32 codes column from per-segment local code
    arrays (length seg.nd_pad, -1 = no value)."""
    out = np.full((executor.n_slots, executor.nd1), -1, np.int32)
    for i, codes in enumerate(per_seg_codes):
        if codes is not None:
            out[i, : codes.shape[0]] = codes
    return out


def _resolve_terms(spec, executor, ops, metas, builds) -> Optional[str]:
    from elasticsearch_tpu.index.global_ordinals import global_ordinals

    field = spec.body.get("field")
    segs = executor.segments
    ocols = [s.ordinal_columns.get(field)
             or s.ordinal_columns.get(f"{field}.keyword") for s in segs]
    if all(o is None for o in ocols):
        if any(s.numeric_columns.get(field) is not None for s in segs):
            return "field_ineligible"  # numeric terms: host path
        if any(s.terms_for_field(field) for s in segs):
            # text fielddata builds lazily on the host (breaker-gated) —
            # the fused plane stages sealed keyword ordinals only
            return "field_ineligible"
        ops.append(("empty",))
        metas.append({"kind": "terms"})
        return None
    cache = getattr(executor, "_agg_field_checks", None)
    if cache is None:
        cache = executor._agg_field_checks = {}
    single = cache.get(("ord_single", field))
    if single is None:
        single = all(o is None or o.count == int(o.exists.sum())
                     for o in ocols)
        cache[("ord_single", field)] = single
    if not single:
        return "multi_valued"
    gords = global_ordinals(segs, field, columns=ocols)
    nb = len(gords.terms)
    if nb > MAX_TERMS_ORDS:
        return "bucket_range"
    if nb == 0:
        ops.append(("empty",))
        metas.append({"kind": "terms"})
        return None
    name = f"maggs.ord.{field}"
    if name not in executor._seg_staged and name not in builds:
        def build(gords=gords, ocols=list(ocols), name=name):
            per_seg = []
            for s, o in zip(segs, ocols):
                if o is None:
                    per_seg.append(None)
                    continue
                gmap = gords.seg_map(s)
                codes = np.where(
                    o.exists, gmap[np.clip(o.first_ord, 0, None)],
                    np.int32(-1)).astype(np.int32)
                per_seg.append(codes)
            return {name: _build_bucket_codes(executor, per_seg)}

        builds[name] = build
    ops.append(("bucket", name, nb))
    # read-only reference: the GlobalOrdinals cache owns the list
    metas.append({"kind": "terms", "vocab": gords.terms})
    return None


def _resolve_histogram(spec, executor, ops, metas, builds) -> Optional[str]:
    from elasticsearch_tpu.common.errors import ParsingException

    is_date = spec.type == "date_histogram"
    body = spec.body
    field = body.get("field")
    if is_date:
        interval_spec = body.get("interval") or body.get("fixed_interval")
        if interval_spec is None:
            return "unsupported_params"
        try:
            ms = _date_interval_ms(interval_spec)
        except ParsingException:
            return "field_ineligible"  # host path owns the 400
        if ms is None:
            return "unsupported_params"  # calendar interval
        interval = float(ms)
    else:
        try:
            interval = float(body["interval"])
        except (KeyError, TypeError, ValueError):
            return "field_ineligible"  # host path owns the 400
        if not (interval > 0):
            return "field_ineligible"
    offset = body.get("offset", 0) or 0
    if isinstance(offset, bool) or not isinstance(offset, (int, float)):
        return "unsupported_params"
    offset = float(offset)
    segs = executor.segments
    cols = [s.numeric_columns.get(field) for s in segs]
    if all(c is None or c.count == 0 for c in cols):
        ops.append(("empty",))
        metas.append({"kind": "hist", "is_date": is_date})
        return None
    facts = _metric_field_checks(executor, field)
    if not facts["single"]:
        return "multi_valued"
    if not facts["finite"]:
        return "values_not_fusable"
    # bucket-range resolution is an O(corpus) column scan: cache the
    # verdict per (field, interval, offset) on the executor generation
    # (zipfian dashboard traffic repeats the same histogram params), so
    # repeat queries pay a dict hit, not a corpus pass
    cache = getattr(executor, "_agg_field_checks", None)
    if cache is None:
        cache = executor._agg_field_checks = {}
    name = (f"maggs.hist.{field}.{spec.type}.{interval!r}.{offset!r}")
    cached = cache.get(("hist", name))
    if cached is None:
        b_min = b_max = None
        for c in cols:
            if c is None or c.count == 0:
                continue
            b = np.floor((c.first_value - offset)
                         / interval).astype(np.int64)
            bv = b[c.exists]
            if bv.size:
                lo, hi = int(bv.min()), int(bv.max())
                b_min = lo if b_min is None else min(b_min, lo)
                b_max = hi if b_max is None else max(b_max, hi)
        if b_min is None:
            cached = ("empty",)
        else:
            nb = b_max - b_min + 1
            if nb <= 0 or nb > MAX_HIST_BUCKETS:
                # <= 0 only under int64-overflowed bucket indices from
                # extreme values — same fallback as an oversized range
                cached = ("reason", "bucket_range")
            else:
                cached = ("ok", int(b_min), int(nb))
        cache[("hist", name)] = cached
    if cached[0] == "empty":
        ops.append(("empty",))
        metas.append({"kind": "hist", "is_date": is_date})
        return None
    if cached[0] == "reason":
        return cached[1]
    _tag, b_min, nb = cached
    if name not in executor._seg_staged and name not in builds:
        # exact HOST-side bucketing inside the build (the oracle's own
        # f64 formula) — runs once per staged generation, the device
        # only counts the precomputed int32 codes
        def build(cols=list(cols), b_min=b_min, name=name):
            per_seg = []
            for c in cols:
                if c is None or c.count == 0:
                    per_seg.append(None)
                    continue
                b = np.floor((c.first_value - offset)
                             / interval).astype(np.int64)
                codes = np.where(c.exists, b - b_min,
                                 np.int64(-1)).astype(np.int32)
                per_seg.append(codes)
            return {name: _build_bucket_codes(executor, per_seg)}

        builds[name] = build
    ops.append(("bucket", name, int(nb)))
    metas.append({"kind": "hist", "is_date": is_date, "interval": interval,
                  "offset": offset, "min_b": int(b_min)})
    return None


def _resolve_metric(spec, executor, ops, metas, builds) -> Optional[str]:
    field = spec.body.get("field")
    segs = executor.segments
    cols = [s.numeric_columns.get(field) for s in segs]
    if all(c is None or c.count == 0 for c in cols):
        if any(s.ordinal_columns.get(field) is not None
               or s.ordinal_columns.get(f"{field}.keyword") is not None
               or s.terms_for_field(field) for s in segs):
            # the host oracle computes metrics over the ORDINAL values
            # of a keyword/text field (search/aggregations.py
            # _metric_values) — keep that surface on the host reduce
            return "field_ineligible"
        ops.append(("empty",))
        metas.append({"kind": "metric"})
        return None
    want_mm = spec.type in ("min", "max", "stats")
    want_dig = spec.type in ("sum", "avg", "stats")
    facts = _metric_field_checks(executor, field)
    if not facts["single"]:
        return "multi_valued"
    if not facts["finite"]:
        return "values_not_fusable"
    if (want_mm or want_dig) and not facts["int48"]:
        return "values_not_fusable"
    if want_dig and not facts["abs_sum_ok"]:
        return "values_not_fusable"
    if executor.nd1 > MAX_SLOT_DOCS:
        return "values_not_fusable"  # per-slot digit sums exceed int32
    base = f"maggs.num.{field}"
    staged = executor._seg_staged
    needed = [base + ".ex"]
    if want_mm:
        needed.append(base + ".mm")
    if want_dig:
        needed.append(base + ".dig")
    missing = [n for n in needed if n not in staged]
    if missing:
        # ONE build closure per field, keyed by `base`: a second spec on
        # the same field with different component needs extends the
        # shared closure's name set instead of enqueueing a duplicate
        # build (the digit decomposition is the expensive part)
        entry = builds.get(base)
        if entry is not None:
            entry.names.update(missing)
        else:
            def build_all(cols=list(cols)):
                n_slots, nd1 = executor.n_slots, executor.nd1
                names = build_all.names
                out = {}
                if base + ".ex" in names:
                    out[base + ".ex"] = np.zeros((n_slots, nd1), bool)
                if base + ".mm" in names:
                    out[base + ".mm"] = np.zeros((n_slots, nd1, 2),
                                                 np.float32)
                if base + ".dig" in names:
                    out[base + ".dig"] = np.zeros(
                        (n_slots, nd1, N_DIGITS), np.int16)
                for i, c in enumerate(cols):
                    if c is None:
                        continue
                    n = c.exists.shape[0]
                    if base + ".ex" in out:
                        out[base + ".ex"][i, :n] = c.exists
                    v = c.first_value
                    if base + ".mm" in out:
                        hi = np.floor(v / MM_SPLIT)
                        out[base + ".mm"][i, :n, 0] = hi
                        out[base + ".mm"][i, :n, 1] = v - hi * MM_SPLIT
                    if base + ".dig" in out:
                        u = np.where(c.exists, v, 0.0).astype(np.int64) \
                            + np.int64(VALUE_OFFSET)
                        for k in range(N_DIGITS):
                            out[base + ".dig"][i, :n, k] = (
                                (u >> (DIGIT_BITS * k))
                                & (DIGIT_BASE - 1)).astype(np.int16)
                return out

            build_all.names = set(missing)
            builds[base] = build_all
    ops.append(("metric", base, want_mm, want_dig))
    metas.append({"kind": "metric"})
    return None


def resolve_fused_aggs(specs: List[AggSpec], executor
                       ) -> Tuple[Optional[FusedAggPlan], Optional[str]]:
    """Resolve a query's agg set against the staged segment set.

    Returns ``(plan, None)`` when EVERY spec is fused-eligible (staging
    any missing doc-value columns as a side effect), else
    ``(None, reason)`` — all-or-nothing, so a response never mixes
    fused and host-reduced frames. Reasons are the documented fallback
    vocabulary (docs/OBSERVABILITY.md). Budget denials return
    ``hbm_budget``; a terminal staging fault propagates to the caller
    (which reports ``staging_fault``)."""
    ops: List[tuple] = []
    metas: List[dict] = []
    builds: Dict[str, object] = {}
    for spec in specs:
        if spec.type in FUSED_BUCKET_TYPES:
            pass
        elif spec.type in FUSED_METRIC_TYPES:
            pass
        else:
            return None, "unsupported_agg"
        if spec.subs:
            return None, "sub_aggs"
        allowed = _ALLOWED_BODY[spec.type]
        if not isinstance(spec.body, dict) or set(spec.body) - allowed:
            return None, "unsupported_params"
        if not isinstance(spec.body.get("field"), str):
            return None, "field_ineligible"
        if spec.type == "terms":
            reason = _resolve_terms(spec, executor, ops, metas, builds)
        elif spec.type in ("histogram", "date_histogram"):
            reason = _resolve_histogram(spec, executor, ops, metas, builds)
        else:
            reason = _resolve_metric(spec, executor, ops, metas, builds)
        if reason is not None:
            return None, reason
    if builds:
        try:
            staged = executor.stage_doc_value_columns(builds)
        except Exception:  # noqa: BLE001 — classified terminal staging
            # fault (run_staged already retried/recorded): ONLY the
            # device staging step may report staging_fault — a
            # resolution bug must never masquerade as a device fault
            import logging

            logging.getLogger("elasticsearch_tpu.search.fused_aggs"
                              ).warning(
                "fused-agg doc-value staging failed; aggregations serve "
                "from the host reduce", exc_info=True)
            return None, "staging_fault"
        if not staged:
            return None, "hbm_budget"
    return FusedAggPlan(list(specs), ops, metas), None


# ---------------------------------------------------------------------------
# Host-side finalize (exact reconstruction + shared bucket assembly)
# ---------------------------------------------------------------------------


def finalize_fused(plan: FusedAggPlan, outs: List[np.ndarray],
                   n_real: int) -> dict:
    """Reduce the program's per-slot partials (``outs``: one
    [n_slots, ...] array per ``n_agg_outputs`` entry, only the first
    ``n_real`` slot rows are staged segments) into the response dict —
    byte-identical to the host oracle by the module's exactness
    contract (integer counts, bignum sum reconstruction, lexicographic
    min/max merge, shared bucket assembly)."""
    result: dict = {}
    pos = 0
    for spec, op, meta in zip(plan.specs, plan.ops, plan.metas):
        kind = meta["kind"]
        if op[0] == "empty":
            if kind == "terms":
                result[spec.name] = finalize_terms(spec, {})
            elif kind == "hist":
                result[spec.name] = finalize_histogram(
                    spec, {}, meta["is_date"])
            else:
                result[spec.name] = _finalize_metric(spec, [])
            continue
        if op[0] == "bucket":
            counts = np.asarray(outs[pos][:n_real],
                                np.int64).sum(axis=0)
            pos += 1
            if kind == "terms":
                vocab = meta["vocab"]
                merged = {vocab[i]: int(c)
                          for i, c in enumerate(counts.tolist()) if c > 0}
                result[spec.name] = finalize_terms(spec, merged)
            else:
                interval, offset = meta["interval"], meta["offset"]
                merged = {}
                for i, c in enumerate(counts.tolist()):
                    if c <= 0:
                        continue
                    b = np.float64(meta["min_b"] + i)
                    if meta["is_date"]:
                        # the oracle's per-value expression with the
                        # bucket index substituted — identical f64 ops
                        key = int(np.int64(b * interval + offset))
                    else:
                        key = float(b * interval + offset)
                    merged[key] = int(c)
                result[spec.name] = finalize_histogram(
                    spec, merged, meta["is_date"])
            continue
        # metric
        _, _base, want_mm, want_dig = op
        count = int(np.asarray(outs[pos][:n_real], np.int64).sum())
        pos += 1
        vmin, vmax, total = math.inf, -math.inf, 0.0
        if want_mm:
            mm = np.asarray(outs[pos][:n_real], np.float64)
            pos += 1
            # lexicographic (hi, lo) merge across slots; empty slots
            # carry inf/-inf sentinels and drop here
            mins = [(r[0], r[1]) for r in mm if np.isfinite(r[0])]
            maxs = [(r[2], r[3]) for r in mm if np.isfinite(r[2])]
            if mins:
                h, l = min(mins)
                vmin = float(h) * MM_SPLIT + float(l)
            if maxs:
                h, l = max(maxs)
                vmax = float(h) * MM_SPLIT + float(l)
        if want_dig:
            digs = np.asarray(outs[pos][:n_real], np.int64)
            pos += 1
            tot_u = 0
            for k in range(N_DIGITS):
                tot_u += int(digs[:, k].sum()) << (DIGIT_BITS * k)
            # exact integer sum via Python bignums; < 2^53 by the
            # eligibility bound, so the float conversion is exact
            total = float(tot_u - count * VALUE_OFFSET)
        result[spec.name] = _finalize_metric(spec, [{
            "count": count, "sum": total, "min": vmin, "max": vmax,
            "sq": 0.0}])
    return result
