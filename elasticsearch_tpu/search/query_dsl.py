"""The query DSL: JSON -> QueryBuilder tree -> per-segment PlanNode.

Role model: the 60+ builders under core/.../index/query/ (parsed via
``AbstractQueryBuilder``/``QueryShardContext``, two-phase rewrite). Each
builder here mirrors one reference builder's JSON shape and semantics;
``to_plan(shard_ctx, segment)`` replaces ``QueryBuilder.toQuery`` — it
resolves terms/ordinals against the segment and produces plan nodes
(search/plan.py) instead of Lucene Query objects.

Multi-term expansion (prefix/wildcard/fuzzy/regexp) happens host-side
against the segment's sorted term dictionary, exactly where Lucene expands
against its terms dict.
"""

from __future__ import annotations

import fnmatch
import re
from typing import Dict, List, Optional, Tuple

import numpy as np

from elasticsearch_tpu.common.errors import (
    IllegalArgumentException,
    ParsingException,
    QueryShardException,
)
from elasticsearch_tpu.mapper.field_types import (
    BooleanFieldType,
    DateFieldType,
    GeoPointFieldType,
    IpFieldType,
    KeywordFieldType,
    NumberFieldType,
    TextFieldType,
)
from elasticsearch_tpu.ops.scoring import B, K1, bm25_idf
from elasticsearch_tpu.search import plan as P

# default max_expansions for multi-term queries (MultiTermQuery rewrites)
MAX_EXPANSIONS = 1024

# SearchPlugin.getQueries extension point: {query_name: parser(qbody)}
CUSTOM_QUERY_PARSERS: Dict[str, "object"] = {}

# single source of the default BM25 constants for ctx-less callers
from elasticsearch_tpu.index.similarity import BM25Similarity  # noqa: E402
from elasticsearch_tpu.ops.scoring import B as _BM25_B, K1 as _BM25_K1  # noqa: E402

_DEFAULT_BM25 = BM25Similarity(k1=_BM25_K1, b=_BM25_B)


class ShardQueryContext:
    """Per-shard query context (≙ QueryShardContext): mapper + analyzers +
    (optionally) the engine, for queries that join across segments of the
    shard (has_child/has_parent — the reference resolves these through
    shard-wide global ordinals)."""

    def __init__(self, mapper_service, engine=None):
        self.mapper_service = mapper_service
        self.analyzers = mapper_service.analyzers
        self.engine = engine

    def field_type(self, name: str):
        return self.mapper_service.field_type(name)

    def similarity(self, field: str):
        """The similarity bound to a field (mapping ``similarity`` param,
        else the index default — SimilarityService.java semantics)."""
        svc = getattr(self.mapper_service, "similarity_service", None)
        if svc is None:
            return None
        ft = self.mapper_service.field_type(field)
        return svc.get(getattr(ft, "similarity_name", None))

    def all_segments(self, fallback_segment) -> List:
        """Every searchable segment of the shard (falls back to the one
        segment in contexts without an engine, e.g. percolation)."""
        if self.engine is not None:
            return list(self.engine.searchable_segments())
        return [fallback_segment]

    def default_fields(self) -> List[str]:
        # all text fields (the reference's `_all` is deprecated in 6.0; we
        # approximate all_fields mode: query every text field)
        return [
            f for f, ft in self.mapper_service.mapper.fields.items()
            if isinstance(ft, TextFieldType)
        ]


def _pad_pow2(lst, pad_value, min_len=8, dtype=None):
    n = max(min_len, 1)
    while n < len(lst):
        n *= 2
    arr = list(lst) + [pad_value] * (n - len(lst))
    return np.asarray(arr, dtype=dtype)


def term_blocks_arrays(segment, weighted_terms, ctx=None):
    """weighted_terms: list of (field, token, boost). Builds the gather
    arrays for ScoreTermsNode. When ``ctx`` is given, each field's mapped
    similarity folds its per-term constants into the lane params
    (index/similarity.py); without it, classic BM25 defaults apply."""
    blocks, weights, rows, avgdls = [], [], [], []
    p1s, p2s, p3s, kind_ids = [], [], [], []
    kinds: List[str] = []
    lanes_meta = []  # (block_start, block_count, weight, kernel_eligible)
    n_terms_present = 0
    for field, token, boost in weighted_terms:
        tid = segment.term_id(field, token)
        if tid < 0:
            continue
        n_terms_present += 1
        st = segment.field_stats.get(field, {})
        doc_count = st.get("doc_count", 0)
        row = segment.field_norm_idx.get(field, 0)
        avgdl = segment.field_avgdl(field)
        sim = (ctx.similarity(field) if ctx is not None else None) or _DEFAULT_BM25
        kind, w, p1, p2, p3 = sim.lane_params({
            "df": int(segment.term_doc_freq[tid]),
            # total term freq costs an O(postings) host pass — only the
            # DFR/IB/LM family reads it
            "ttf": segment.term_ttf(tid) if sim.needs_ttf else 0,
            "doc_count": doc_count,
            "sum_ttf": st.get("sum_ttf", 0),
            "avgdl": avgdl,
            "boost": boost,
        })
        if kind not in kinds:
            kinds.append(kind)
        kid = kinds.index(kind)
        start = int(segment.term_block_start[tid])
        # the pallas tile kernel precomputes per-posting norm factors
        # with default-constant BM25 and the segment's local stats; any
        # other similarity/params must take the scatter path. (No dfs-
        # adjusted avgdl reaches this builder today; if one ever does,
        # its lane must be marked ineligible here.)
        lanes_meta.append((start, int(segment.term_block_count[tid]),
                           float(w),
                           kind == "bm25" and p1 == K1 and p2 == B))
        for bi in range(start, start + int(segment.term_block_count[tid])):
            blocks.append(bi)
            weights.append(w)
            rows.append(row)
            avgdls.append(avgdl)
            p1s.append(p1)
            p2s.append(p2)
            p3s.append(p3)
            kind_ids.append(kid)
    return {
        "q_blocks": _pad_pow2(blocks, 0, dtype=np.int32),
        "q_weights": _pad_pow2(weights, 0.0, dtype=np.float32),
        "q_norm_rows": _pad_pow2(rows, 0, dtype=np.int32),
        "q_avgdl": _pad_pow2(avgdls, 1.0, dtype=np.float32),
        "q_valid": _pad_pow2([True] * len(blocks), False, dtype=bool),
        "q_p1": _pad_pow2(p1s, 1.0, dtype=np.float32),
        "q_p2": _pad_pow2(p2s, 1.0, dtype=np.float32),
        "q_p3": _pad_pow2(p3s, 0.0, dtype=np.float32),
        "q_kinds": _pad_pow2(kind_ids, 0, dtype=np.int32),
        "kinds": tuple(kinds) if kinds else ("bm25",),
        "n_present": n_terms_present,
        "lanes_meta": lanes_meta,
    }


def score_terms_node(segment, weighted_terms, min_match=1, ctx=None) -> P.PlanNode:
    arrs = term_blocks_arrays(segment, weighted_terms, ctx=ctx)
    if arrs["n_present"] == 0 or min_match > arrs["n_present"]:
        if not getattr(ctx, "for_mesh", False):
            return P.MatchNoneNode()
        # mesh plans must keep the SAME tree skeleton on every shard: a
        # term that happens to miss one shard's dictionary would turn
        # that shard's node into MatchNone and force the whole query off
        # the mesh (PlanStructureMismatch). An all-invalid-lane scorer
        # emits zero matches through the identical trace instead.
        if min_match > max(arrs["n_present"], 1):
            # unsatisfiable even with every lane valid: emit can never
            # match, but the skeleton must still line up — pin the
            # threshold above the padded lane count
            min_match = arrs["q_valid"].shape[0] + 1
    node = None
    if not getattr(ctx, "for_mesh", False):
        node = _pallas_score_terms_node(segment, arrs, min_match)
    elif getattr(ctx, "mesh_kernel", None) is not None:
        # mesh plane with the tile kernel staged: build the stackable
        # (deferred-geometry) kernel node; the executor harmonizes table
        # shapes across shards before stacking. Ineligible lane sets fall
        # through to the scatter node — a cross-shard pallas/scatter mix
        # then fails structure checks and the caller retries all-scatter.
        node = _mesh_pallas_score_terms_node(segment, arrs, min_match,
                                             ctx.mesh_kernel)
    if node is not None:
        return node
    return P.ScoreTermsNode(
        arrs["q_blocks"], arrs["q_weights"], arrs["q_norm_rows"],
        arrs["q_avgdl"], arrs["q_valid"], min_match,
        q_p1=arrs["q_p1"], q_p2=arrs["q_p2"], q_p3=arrs["q_p3"],
        q_kinds=arrs["q_kinds"], kinds=arrs["kinds"],
    )


def _pallas_score_terms_node(segment, arrs, min_match):
    """Route eligible BM25 disjunctions through the tile-scoring kernel:
    all lanes default-constant BM25 (positive weights for the score>0
    match rule unless counting), and the segment staged kernel arrays."""
    from elasticsearch_tpu.ops.aggs import _pallas_mode

    mode = _pallas_mode()
    if not mode:
        return None
    lanes = arrs["lanes_meta"]
    if not lanes or not all(ok for _, _, _, ok in lanes):
        return None
    # positive weights always: score>0 is the match rule for min_match<=1,
    # and zero-weight lanes would be dropped from the kernel's match
    # COUNTS too (build_tile_tables skips them) — the scatter path counts
    # them, so they must take it
    if not all(w > 0 for _, _, w, _ in lanes):
        return None
    segment.device_arrays()  # ensure kernel staging ran
    geom = getattr(segment, "kernel_geom", None)
    if geom is None:
        return None
    from elasticsearch_tpu.ops import pallas_scoring as psc

    qlanes = [psc.QueryLane(s, c, w) for s, c, w, _ in lanes]
    # geometry ladder: big tiles are fastest (per-grid-step overhead
    # dominates), but a dense term's per-tile covering window can exceed
    # the kernel bound there — retry with smaller tiles. Non-overlapping
    # sorted block ranges guarantee the window fits at tile_sub <= 32
    # (need <= sub + 2 blocks), so the ladder always terminates on the
    # kernel path for any well-formed segment.
    sub = geom.tile_sub
    while True:
        g = geom if sub == geom.tile_sub else psc.tile_geometry(
            geom.nd_pad, sub)
        try:
            row_lo, row_hi, kweights, cb = psc.build_tile_tables(
                qlanes, segment.kernel_bmin, segment.kernel_bmax, g)
            break
        except ValueError:
            if sub <= 32 or g.tile_sub < sub:
                return None  # malformed ranges; scatter path handles it
            sub //= 2
    live_key = ("k_live_t" if g.tile_sub == geom.tile_sub
                else segment.kernel_live_t_for(g.tile_sub))
    node = P.PallasScoreTermsNode(
        row_lo, row_hi, kweights, min_match,
        cb=cb, sub=g.tile_sub, interpret=(mode == "interpret"),
        live_key=live_key, tiles_per_step=psc.tiles_per_step_default(),
        codec=getattr(segment, "kernel_codec", "raw"))
    # the cross-query micro-batcher (search/batching.py) unions lane sets
    # across concurrent queries and re-derives shared tables, so the node
    # keeps its lane list alongside the already-built single-query tables
    node._host_lanes = qlanes
    return node


def _mesh_pallas_score_terms_node(segment, arrs, min_match, session):
    """Stackable tile-kernel node for the MESH data plane. ``session`` is
    the executor's staged-kernel context ({geom, meta: {id(segment):
    (bmin, bmax)}, mode}). Same lane eligibility rules as the host path
    (_pallas_score_terms_node), but an EMPTY lane set stays on the kernel:
    a term missing from one shard's dictionary must not flip that shard's
    node type (the skeleton must match across the mesh)."""
    from elasticsearch_tpu.ops import pallas_scoring as psc

    lanes = arrs["lanes_meta"]
    if not all(ok for _, _, _, ok in lanes):
        return None
    if not all(w > 0 for _, _, w, _ in lanes):
        return None  # see _pallas_score_terms_node: score>0 match rule
    meta = session["meta"].get(id(segment))
    if meta is None:
        return None  # segment not part of the staged mesh set
    qlanes = [psc.QueryLane(s, c, w) for s, c, w, _ in lanes]
    return P.PallasScoreTermsNode.mesh_deferred(
        qlanes, meta[0], meta[1], min_match,
        interpret=(session["mode"] == "interpret"),
        codec=session.get("codec", "raw"))


def _numeric_csr(segment, field):
    col = segment.numeric_columns.get(field)
    if col is None:
        return None
    docs = segment.device_column(f"num.{field}.docs", lambda: col.flat_docs)
    vals = segment.device_column(f"num.{field}.vals", lambda: col.flat_values)
    return docs, vals, col


def _ordinal_csr(segment, field):
    col = segment.ordinal_columns.get(field)
    if col is None:
        return None
    docs = segment.device_column(f"ord.{field}.docs", lambda: col.flat_docs)
    ords = segment.device_column(f"ord.{field}.ords", lambda: col.flat_ords)
    return docs, ords, col


# ---------------------------------------------------------------------------
# Builders
# ---------------------------------------------------------------------------


class QueryBuilder:
    name = "base"

    def __init__(self, boost: float = 1.0, _name: Optional[str] = None):
        self.boost = boost
        self.query_name = _name

    def to_plan(self, ctx: ShardQueryContext, segment) -> P.PlanNode:
        raise NotImplementedError

    def explain_terms(self, ctx) -> Optional[List[Tuple[str, str, float]]]:
        """(field, token, boost) lanes for the explain API's per-term BM25
        breakdown; None when this query type has no term-lane expansion
        (the explain response then stays summary-level)."""
        return None

    def _wrap_boost(self, node: P.PlanNode) -> P.PlanNode:
        if self.boost != 1.0:
            return P.BoostNode(node, self.boost)
        return node


class MatchAllQueryBuilder(QueryBuilder):
    name = "match_all"

    def to_plan(self, ctx, segment):
        return P.MatchAllNode(self.boost)


class MatchNoneQueryBuilder(QueryBuilder):
    name = "match_none"

    def to_plan(self, ctx, segment):
        return P.MatchNoneNode()


class KnnQueryBuilder(QueryBuilder):
    """Dense-vector kNN clause: score every live doc carrying the field
    by its embedding similarity to ``query_vector`` (the mapped field's
    ``similarity`` picks the metric). Mirrors the reference's knn search
    surface grown after 6.x (KnnSearchBuilder / the top-level ``knn``
    request section, which IndexService normalizes into this clause).

    Execution is exhaustive (exact, recall 1.0 — no ANN graph): the
    mesh_pallas rung scores the staged bf16 embedding matrix with the
    MXU kernel (ops/pallas_knn.py), the host rung with an identical XLA
    matmul (plan.KnnScoreNode). ``k`` sizes the result (the top-level
    knn section defaults the response size to it); ``num_candidates``
    is accepted for reference-API compatibility only — exhaustive exact
    scoring makes an ANN candidate bound moot, so it has no effect."""

    name = "knn"

    def __init__(self, field: str, query_vector, k: int = 10,
                 num_candidates: Optional[int] = None,
                 filter: Optional[list] = None, **kw):
        super().__init__(**kw)
        self.field = field
        self.query_vector = query_vector
        self.k = int(k)
        self.num_candidates = (int(num_candidates)
                               if num_candidates is not None else None)
        # pre-filter clauses (the reference's knn `filter`): restrict
        # WHICH docs may rank — under exhaustive scoring pre- and
        # post-filtering are equivalent, so they gate the matched mask
        self.filter = list(filter or [])

    def _field_type(self, ctx):
        from elasticsearch_tpu.mapper.field_types import DenseVectorFieldType

        ft = ctx.field_type(self.field)
        if ft is None:
            raise QueryShardException(
                f"failed to create query: field [{self.field}] does not "
                f"exist in the mapping")
        if not isinstance(ft, DenseVectorFieldType):
            raise QueryShardException(
                f"[knn] queries are only supported on [dense_vector] "
                f"fields; [{self.field}] is [{ft.type_name}]")
        qv = self.query_vector
        if (not isinstance(qv, (list, tuple))
                or len(qv) != ft.dims
                or any(isinstance(v, bool) or not isinstance(v, (int, float))
                       or not np.isfinite(v) for v in qv)):
            # finiteness matters: a NaN query poisons every score and
            # drives the kernel's tie-select out of the doc range —
            # reject with the same 400 the index path gives NaN vectors
            raise IllegalArgumentException(
                f"[knn] query_vector must be an array of {ft.dims} "
                f"finite numbers for field [{self.field}]")
        return ft

    def to_plan(self, ctx, segment):
        from elasticsearch_tpu.ops import pallas_knn as pkn

        ft = self._field_type(ctx)
        keys = segment.ensure_vector_staged(self.field, ft.similarity)
        if keys is None:
            # no doc of THIS segment carries the field: nothing can match
            return P.MatchNoneNode()
        emb_key, norm_key, exists_key, d_pad = keys
        qvec = pkn.normalize_query(
            np.asarray(self.query_vector, np.float32), ft.similarity,
            d_pad).reshape(1, d_pad)
        node = P.KnnScoreNode(self.field, qvec, ft.similarity, self.boost,
                              emb_key, norm_key, exists_key)
        if self.filter:
            # filtered kNN: the vector score ranks, the filter gates —
            # exact BoolQuery must+filter semantics (the mesh MXU
            # program doesn't cover filtered specs: knn_batch_spec
            # rejects them, so this plan always runs the host rung)
            node = P.BoolNode(
                must=[node],
                filter_=[f.to_plan(ctx, segment) for f in self.filter],
                should=[], must_not=[], min_should_match=0)
        return node


class MatchQueryBuilder(QueryBuilder):
    """Full-text match (index/query/MatchQueryBuilder): analyze the text
    with the field's search analyzer; OR (default) or AND over terms;
    minimum_should_match supported."""

    name = "match"

    def __init__(self, field: str, query, operator: str = "or",
                 minimum_should_match: Optional[str] = None,
                 analyzer: Optional[str] = None, **kw):
        super().__init__(**kw)
        self.field = field
        self.query = query
        self.operator = operator.lower()
        self.minimum_should_match = minimum_should_match
        # explicit search analyzer override (MatchQueryBuilder#analyzer)
        self.analyzer = analyzer

    def _analyzed_terms(self, ctx) -> List[str]:
        ft = ctx.field_type(self.field)
        if self.analyzer is not None:
            # explicit analyzer override beats the field's search analyzer
            return ctx.analyzers.get(self.analyzer).analyze(str(self.query))
        if ft is None:
            return [str(self.query)]
        if isinstance(ft, TextFieldType):
            return ft.query_terms(self.query, ctx.analyzers)
        return ft.index_terms(self.query, ctx.analyzers) or [
            ft.term_for_query(self.query, ctx.analyzers)
        ]

    def explain_terms(self, ctx):
        ft = ctx.field_type(self.field)
        if ft is None or not isinstance(ft, TextFieldType):
            return None
        return [(self.field, t, self.boost)
                for t in self._analyzed_terms(ctx)]

    def to_plan(self, ctx, segment):
        ft = ctx.field_type(self.field)
        if ft is not None and isinstance(ft, NumberFieldType):
            return TermQueryBuilder(self.field, self.query, boost=self.boost).to_plan(ctx, segment)
        if ft is not None and isinstance(ft, (DateFieldType, BooleanFieldType, IpFieldType)):
            return TermQueryBuilder(self.field, self.query, boost=self.boost).to_plan(ctx, segment)
        terms = self._analyzed_terms(ctx)
        if not terms:
            return P.MatchNoneNode()
        if self.operator == "and":
            min_match = len(terms)
        else:
            min_match = parse_min_should_match(self.minimum_should_match, len(terms)) or 1
        node = score_terms_node(
            segment, [(self.field, t, 1.0) for t in terms], min_match, ctx=ctx
        )
        return self._wrap_boost(node)


class MatchPhraseQueryBuilder(QueryBuilder):
    name = "match_phrase"

    def __init__(self, field: str, query, slop: int = 0,
                 analyzer: Optional[str] = None, **kw):
        super().__init__(**kw)
        self.field = field
        self.query = query
        self.slop = slop
        self.analyzer = analyzer

    def to_plan(self, ctx, segment):
        ft = ctx.field_type(self.field)
        if self.analyzer is not None:
            terms = ctx.analyzers.get(self.analyzer).analyze(str(self.query))
        elif isinstance(ft, TextFieldType):
            terms = ft.query_terms(self.query, ctx.analyzers)
        else:
            terms = [str(self.query)]
        if not terms:
            return P.MatchNoneNode()
        if len(terms) == 1:
            return MatchQueryBuilder(self.field, self.query, boost=self.boost).to_plan(ctx, segment)
        # host-side position intersection (SURVEY §7: strings/pointer-chasing
        # stay host-side); scored on device by phrase frequency
        tids = [segment.term_id(self.field, t) for t in terms]
        if any(t < 0 for t in tids):
            return P.MatchNoneNode()
        pos_maps = [segment.positions.get(t, {}) for t in tids]
        candidates = set(pos_maps[0])
        for pm in pos_maps[1:]:
            candidates &= set(pm)
        docs, freqs = [], []
        for doc in sorted(candidates):
            freq = _phrase_freq([pm[doc] for pm in pos_maps], self.slop)
            if freq > 0:
                docs.append(doc)
                freqs.append(float(freq))
        if not docs:
            return P.MatchNoneNode()
        # phrase weight under the field's similarity: sum of per-term
        # weights (Lucene PhraseQuery combines term stats similarly); the
        # non-weight lane params come from the rarest term (approximation
        # for the stat-dependent DFR/IB/LM params)
        st = segment.field_stats.get(self.field, {})
        doc_count = st.get("doc_count", 0)
        sim = (ctx.similarity(self.field) if ctx is not None else None) or _DEFAULT_BM25
        lanes = [
            sim.lane_params({
                "df": int(segment.term_doc_freq[t]),
                "ttf": segment.term_ttf(t) if sim.needs_ttf else 0,
                "doc_count": doc_count,
                "sum_ttf": st.get("sum_ttf", 0),
                "avgdl": segment.field_avgdl(self.field),
                "boost": 1.0,
            })
            for t in tids
        ]
        kind = lanes[0][0]
        weight = sum(l[1] for l in lanes) * self.boost
        _, _, p1, p2, p3 = max(lanes, key=lambda l: l[1])
        sentinel = segment.nd_pad
        return P.PhraseScoreNode(
            _pad_pow2(docs, sentinel, dtype=np.int32),
            _pad_pow2(freqs, 0.0, dtype=np.float32),
            weight,
            segment.field_norm_idx.get(self.field, 0),
            segment.field_avgdl(self.field),
            kind=kind, p1=p1, p2=p2, p3=p3,
        )


def _phrase_freq(positions_per_term: List[np.ndarray], slop: int) -> int:
    """Exact phrase (slop=0) or sloppy within-window match count."""
    first = positions_per_term[0]
    count = 0
    if slop == 0:
        others = [set(p.tolist()) for p in positions_per_term[1:]]
        for p in first.tolist():
            if all((p + i + 1) in s for i, s in enumerate(others)):
                count += 1
        return count
    # sloppy: greedy window check (approximation of Lucene's sloppy freq)
    for p in first.tolist():
        ok = True
        prev = p
        for i, arr in enumerate(positions_per_term[1:]):
            target = p + i + 1
            diffs = np.abs(arr - target)
            if diffs.size == 0 or diffs.min() > slop:
                ok = False
                break
        if ok:
            count += 1
    return count


class MatchPhrasePrefixQueryBuilder(QueryBuilder):
    name = "match_phrase_prefix"

    def __init__(self, field: str, query, max_expansions: int = 50, **kw):
        super().__init__(**kw)
        self.field = field
        self.query = query
        self.max_expansions = max_expansions

    def to_plan(self, ctx, segment):
        ft = ctx.field_type(self.field)
        terms = (ft.query_terms(self.query, ctx.analyzers)
                 if isinstance(ft, TextFieldType) else [str(self.query)])
        if not terms:
            return P.MatchNoneNode()
        prefix = terms[-1]
        expansions = [t for t, _ in segment.terms_for_field(self.field)
                      if t.startswith(prefix)][: self.max_expansions]
        if len(terms) == 1:
            if not expansions:
                return P.MatchNoneNode()
            return score_terms_node(
                segment, [(self.field, t, self.boost) for t in expansions], 1,
                ctx=ctx,
            )
        subs = []
        for exp in expansions:
            phrase_terms = terms[:-1] + [exp]
            subs.append(MatchPhraseQueryBuilder(
                self.field, " ".join(phrase_terms), boost=self.boost
            ))
        if not subs:
            return P.MatchNoneNode()
        return BoolQueryBuilder(should=subs).to_plan(ctx, segment)


class MultiMatchQueryBuilder(QueryBuilder):
    """multi_match (index/query/MultiMatchQueryBuilder): best_fields
    (dis_max over per-field match, default), most_fields (sum), and
    cross_fields (approximated as most_fields)."""

    name = "multi_match"

    def __init__(self, query, fields: List[str], type_: str = "best_fields",
                 operator: str = "or", tie_breaker: float = 0.0,
                 analyzer: Optional[str] = None, **kw):
        super().__init__(**kw)
        self.query = query
        self.fields = fields
        self.type = type_
        self.operator = operator
        self.tie_breaker = tie_breaker
        self.analyzer = analyzer

    def to_plan(self, ctx, segment):
        field_boosts = []
        for f in self.fields:
            if "^" in f:
                name, b = f.split("^", 1)
                for resolved in ctx.mapper_service.mapper.simple_match_to_fields(name) or [name]:
                    field_boosts.append((resolved, float(b)))
            else:
                for resolved in ctx.mapper_service.mapper.simple_match_to_fields(f) or [f]:
                    field_boosts.append((resolved, 1.0))
        per_field = [
            MatchQueryBuilder(f, self.query, operator=self.operator,
                              analyzer=self.analyzer, boost=b)
            .to_plan(ctx, segment)
            for f, b in field_boosts
        ]
        per_field = [n for n in per_field if not isinstance(n, P.MatchNoneNode)]
        if not per_field:
            return P.MatchNoneNode()
        if self.type in ("best_fields", "phrase", "phrase_prefix"):
            node = P.DisMaxNode(per_field, self.tie_breaker)
        else:  # most_fields / cross_fields: sum of field scores
            node = P.BoolNode([], [], per_field, [], 1)
        return self._wrap_boost(node)


class TermQueryBuilder(QueryBuilder):
    name = "term"

    def __init__(self, field: str, value, **kw):
        super().__init__(**kw)
        self.field = field
        self.value = value

    def to_plan(self, ctx, segment):
        if self.field == "_id":
            # term on the _id metadata field == ids query (the reference
            # routes both through IdFieldMapper's term query)
            vals = (self.value if isinstance(self.value, list)
                    else [self.value])
            return IdsQueryBuilder(
                [str(v) for v in vals], boost=self.boost).to_plan(
                    ctx, segment)
        ft = ctx.field_type(self.field)
        from elasticsearch_tpu.mapper.field_types import RangeFieldType

        if isinstance(ft, RangeFieldType):
            # point-containment: the stored range must contain the term
            v = ft.numeric_for_query(self.value)
            return _range_pair_node(segment, self.field, v, v, "intersects",
                                    self.boost)
        if isinstance(ft, NumberFieldType) or isinstance(ft, DateFieldType):
            csr = _numeric_csr(segment, self.field)
            if csr is None:
                return P.MatchNoneNode()
            docs, vals, _ = csr
            v = ft.numeric_for_query(self.value)
            return P.ConstantScoreNode(P.NumericTermsNode(
                docs, vals, _pad_pow2([v], np.nan, min_len=1, dtype=np.float64)
            ), self.boost)
        if isinstance(ft, IpFieldType):
            csr = _numeric_csr(segment, self.field)
            if csr is None:
                return P.MatchNoneNode()
            docs, vals, _ = csr
            from elasticsearch_tpu.mapper.field_types import parse_ip

            v = float(parse_ip(self.value))
            return P.ConstantScoreNode(P.NumericTermsNode(
                docs, vals, _pad_pow2([v], np.nan, min_len=1, dtype=np.float64)
            ), self.boost)
        # term against the inverted index (keyword/boolean/text-raw-token)
        token = (ft.term_for_query(self.value, ctx.analyzers)
                 if ft is not None and not isinstance(ft, TextFieldType)
                 else str(self.value))
        node = score_terms_node(segment, [(self.field, token, self.boost)], 1,
                                ctx=ctx)
        return node

    def explain_terms(self, ctx):
        ft = ctx.field_type(self.field)
        from elasticsearch_tpu.mapper.field_types import (
            BooleanFieldType,
            KeywordFieldType,
        )

        if isinstance(ft, (KeywordFieldType, BooleanFieldType)) or ft is None:
            token = (ft.term_for_query(self.value, ctx.analyzers)
                     if ft is not None else str(self.value))
            return [(self.field, token, self.boost)]
        if isinstance(ft, TextFieldType):
            return [(self.field, str(self.value), self.boost)]
        return None


class TermsQueryBuilder(QueryBuilder):
    name = "terms"

    def __init__(self, field: str, values: List, **kw):
        super().__init__(**kw)
        self.field = field
        self.values = values

    def to_plan(self, ctx, segment):
        if self.field == "_id":
            return IdsQueryBuilder(
                [str(v) for v in self.values], boost=self.boost).to_plan(
                    ctx, segment)
        ft = ctx.field_type(self.field)
        if isinstance(ft, (NumberFieldType, DateFieldType)):
            csr = _numeric_csr(segment, self.field)
            if csr is None:
                return P.MatchNoneNode()
            docs, vals, _ = csr
            nums = [ft.numeric_for_query(v) for v in self.values]
            return P.ConstantScoreNode(P.NumericTermsNode(
                docs, vals,
                _pad_pow2(nums, np.nan, min_len=1, dtype=np.float64),
            ), self.boost)
        # constant-score terms over ordinals if the field has them, else
        # inverted-index disjunction
        col = segment.ordinal_columns.get(self.field)
        if col is not None:
            csr = _ordinal_csr(segment, self.field)
            docs, ords, col = csr
            norm = (ft.term_for_query if ft is not None else (lambda v, a: str(v)))
            o = [col.ord_of(norm(v, ctx.analyzers)) for v in self.values]
            o = [x for x in o if x >= 0]
            if not o:
                return P.MatchNoneNode()
            return P.ConstantScoreNode(P.OrdTermsNode(
                docs, ords, _pad_pow2(o, -1, min_len=1, dtype=np.int32)
            ), self.boost)
        tokens = [
            (ft.term_for_query(v, ctx.analyzers) if ft is not None else str(v))
            for v in self.values
        ]
        node = score_terms_node(
            segment, [(self.field, t, self.boost) for t in tokens], 1, ctx=ctx
        )
        return P.ConstantScoreNode(node, self.boost)


def _range_pair_node(segment, field, q_lo, q_hi, relation, boost) -> P.PlanNode:
    """Build a RangePairNode against a range field's aligned #lo/#hi columns."""
    lo_col = segment.numeric_columns.get(f"{field}#lo")
    hi_col = segment.numeric_columns.get(f"{field}#hi")
    if lo_col is None or hi_col is None:
        return P.MatchNoneNode()
    docs = segment.device_column(f"num.{field}#lo.docs", lambda: lo_col.flat_docs)
    lo_vals = segment.device_column(f"num.{field}#lo.vals", lambda: lo_col.flat_values)
    hi_vals = segment.device_column(f"num.{field}#hi.vals", lambda: hi_col.flat_values)
    return P.ConstantScoreNode(
        P.RangePairNode(docs, lo_vals, hi_vals, q_lo, q_hi, relation), boost
    )


class RangeQueryBuilder(QueryBuilder):
    name = "range"

    def __init__(self, field: str, gte=None, gt=None, lte=None, lt=None,
                 format: Optional[str] = None, relation: str = "intersects", **kw):
        super().__init__(**kw)
        self.field = field
        self.gte, self.gt, self.lte, self.lt = gte, gt, lte, lt
        self.relation = str(relation).lower()
        if self.relation not in ("intersects", "within", "contains"):
            raise ParsingException(
                f"[range] query does not support relation [{relation}]"
            )

    def to_plan(self, ctx, segment):
        ft = ctx.field_type(self.field)
        from elasticsearch_tpu.mapper.field_types import RangeFieldType

        if isinstance(ft, RangeFieldType):
            spec = {}
            for k, v in (("gte", self.gte), ("gt", self.gt),
                         ("lte", self.lte), ("lt", self.lt)):
                if v is not None:
                    spec[k] = v
            q_lo, q_hi = ft.parse_range(spec)
            return _range_pair_node(segment, self.field, q_lo, q_hi,
                                    self.relation, self.boost)
        if isinstance(ft, (NumberFieldType, DateFieldType, BooleanFieldType, IpFieldType)) or (
            ft is None and segment.numeric_columns.get(self.field) is not None
        ):
            csr = _numeric_csr(segment, self.field)
            if csr is None:
                return P.MatchNoneNode()
            docs, vals, _ = csr
            conv = (ft.numeric_for_query if ft is not None else float)
            if isinstance(ft, IpFieldType):
                from elasticsearch_tpu.mapper.field_types import parse_ip
                conv = lambda v: float(parse_ip(v))  # noqa: E731
            lo = -np.inf
            hi = np.inf
            if self.gte is not None:
                lo = conv(self.gte)
            if self.gt is not None:
                lo = np.nextafter(conv(self.gt), np.inf)
            if self.lte is not None:
                hi = conv(self.lte)
            if self.lt is not None:
                hi = np.nextafter(conv(self.lt), -np.inf)
            return P.ConstantScoreNode(P.NumericRangeNode(docs, vals, lo, hi), self.boost)
        col = segment.ordinal_columns.get(self.field)
        if col is not None:
            docs, ords, col = _ordinal_csr(segment, self.field)
            lo_ord, hi_ord = col.ord_range(
                str(self.gte) if self.gte is not None else (
                    str(self.gt) if self.gt is not None else None),
                str(self.lte) if self.lte is not None else (
                    str(self.lt) if self.lt is not None else None),
                include_lo=self.gt is None,
                include_hi=self.lt is None,
            )
            return P.ConstantScoreNode(P.OrdRangeNode(docs, ords, lo_ord, hi_ord), self.boost)
        raise QueryShardException(
            f"field [{self.field}] does not support range queries "
            "(no doc values in this segment)"
        )


class ExistsQueryBuilder(QueryBuilder):
    name = "exists"

    def __init__(self, field: str, **kw):
        super().__init__(**kw)
        self.field = field

    def to_plan(self, ctx, segment):
        fields = ctx.mapper_service.mapper.simple_match_to_fields(self.field) or [self.field]
        masks = []
        for f in fields:
            if f in segment.exists_masks:
                masks.append(segment.device_column(
                    f"exists.{f}",
                    lambda f=f: np.concatenate(
                        [segment.exists_masks[f], np.zeros(1, dtype=bool)]
                    ),
                ))
        if not masks:
            return P.MatchNoneNode()
        combined = masks[0]
        for m in masks[1:]:
            combined = combined | m
        return P.ConstantScoreNode(P.DenseMaskNode(combined, f"exists:{self.field}"), self.boost)


class IdsQueryBuilder(QueryBuilder):
    name = "ids"

    def __init__(self, values: List[str], **kw):
        super().__init__(**kw)
        self.values = values

    def to_plan(self, ctx, segment):
        id_map = segment.id_to_doc()
        docs = [id_map[v] for v in self.values if v in id_map]
        if not docs:
            return P.MatchNoneNode()
        mask = np.zeros(segment.nd_pad + 1, dtype=bool)
        for d in docs:
            mask[d] = True
        return P.ConstantScoreNode(P.DenseMaskNode(mask, "ids"), self.boost)


class MultiTermExpandingBuilder(QueryBuilder):
    """Shared base for prefix/wildcard/regexp/fuzzy: expand against the
    segment term dictionary, then constant-score disjunction (Lucene
    MultiTermQuery CONSTANT_SCORE rewrite)."""

    def matches(self, token: str) -> bool:
        raise NotImplementedError

    def __init__(self, field: str, **kw):
        super().__init__(**kw)
        self.field = field

    def to_plan(self, ctx, segment):
        expansions = [
            t for t, _ in segment.terms_for_field(self.field) if self.matches(t)
        ][:MAX_EXPANSIONS]
        if not expansions:
            return P.MatchNoneNode()
        node = score_terms_node(
            segment, [(self.field, t, 1.0) for t in expansions], 1, ctx=ctx
        )
        return P.ConstantScoreNode(node, self.boost)


class PrefixQueryBuilder(MultiTermExpandingBuilder):
    name = "prefix"

    def __init__(self, field: str, value: str, **kw):
        super().__init__(field, **kw)
        self.value = str(value)

    def matches(self, token):
        return token.startswith(self.value)


class WildcardQueryBuilder(MultiTermExpandingBuilder):
    name = "wildcard"

    def __init__(self, field: str, value: str, **kw):
        super().__init__(field, **kw)
        self.value = str(value)

    def matches(self, token):
        return fnmatch.fnmatchcase(token, self.value)


class RegexpQueryBuilder(MultiTermExpandingBuilder):
    name = "regexp"

    def __init__(self, field: str, value: str, **kw):
        super().__init__(field, **kw)
        try:
            self._rx = re.compile(value)
        except re.error as e:
            raise ParsingException(f"failed to parse regexp [{value}]: {e}") from e

    def matches(self, token):
        return self._rx.fullmatch(token) is not None


def _levenshtein_leq(a: str, b: str, k: int) -> bool:
    """Edit distance <= k with early exit (banded DP)."""
    if abs(len(a) - len(b)) > k:
        return False
    prev = list(range(len(b) + 1))
    for i, ca in enumerate(a, 1):
        cur = [i] + [0] * len(b)
        row_min = i
        for j, cb in enumerate(b, 1):
            cur[j] = min(prev[j] + 1, cur[j - 1] + 1, prev[j - 1] + (ca != cb))
            row_min = min(row_min, cur[j])
        if row_min > k:
            return False
        prev = cur
    return prev[-1] <= k


class FuzzyQueryBuilder(MultiTermExpandingBuilder):
    name = "fuzzy"

    def __init__(self, field: str, value: str, fuzziness="AUTO",
                 prefix_length: int = 0, **kw):
        super().__init__(field, **kw)
        self.value = str(value)
        self.prefix_length = prefix_length
        if fuzziness in ("AUTO", "auto", None):
            n = len(self.value)
            self.max_edits = 0 if n <= 2 else (1 if n <= 5 else 2)
        else:
            self.max_edits = int(fuzziness)

    def matches(self, token):
        if self.prefix_length and not token.startswith(self.value[: self.prefix_length]):
            return False
        return _levenshtein_leq(token, self.value, self.max_edits)


class BoolQueryBuilder(QueryBuilder):
    name = "bool"

    def __init__(self, must=None, filter=None, should=None, must_not=None,
                 minimum_should_match=None, **kw):
        super().__init__(**kw)
        self.must = must or []
        self.filter = filter or []
        self.should = should or []
        self.must_not = must_not or []
        self.minimum_should_match = minimum_should_match

    def explain_terms(self, ctx):
        lanes = []
        for child in list(self.must) + list(self.should):
            sub = child.explain_terms(ctx)
            if sub:
                lanes.extend(sub)
        return lanes or None

    def to_plan(self, ctx, segment):
        must = [q.to_plan(ctx, segment) for q in self.must]
        filter_ = [q.to_plan(ctx, segment) for q in self.filter]
        should = [q.to_plan(ctx, segment) for q in self.should]
        must_not = [q.to_plan(ctx, segment) for q in self.must_not]
        if self.minimum_should_match is not None:
            msm = parse_min_should_match(self.minimum_should_match, len(should))
        elif not self.must and not self.filter:
            msm = 1 if should else 0
        else:
            msm = 0
        return P.BoolNode(must, filter_, should, must_not, msm, self.boost)


class ConstantScoreQueryBuilder(QueryBuilder):
    name = "constant_score"

    def __init__(self, filter: QueryBuilder, **kw):
        super().__init__(**kw)
        self.filter = filter

    def to_plan(self, ctx, segment):
        return P.ConstantScoreNode(self.filter.to_plan(ctx, segment), self.boost)


class DisMaxQueryBuilder(QueryBuilder):
    name = "dis_max"

    def __init__(self, queries: List[QueryBuilder], tie_breaker: float = 0.0, **kw):
        super().__init__(**kw)
        self.queries = queries
        self.tie_breaker = tie_breaker

    def to_plan(self, ctx, segment):
        nodes = [q.to_plan(ctx, segment) for q in self.queries]
        return self._wrap_boost(P.DisMaxNode(nodes, self.tie_breaker))


class FunctionScoreQueryBuilder(QueryBuilder):
    name = "function_score"

    def __init__(self, query: QueryBuilder, functions: List[dict],
                 boost_mode: str = "multiply", score_mode: str = "multiply", **kw):
        super().__init__(**kw)
        self.query = query
        self.functions = functions
        self.boost_mode = boost_mode
        self.score_mode = score_mode

    def to_plan(self, ctx, segment):
        child = self.query.to_plan(ctx, segment)
        weight = 1.0
        factor_columns = []
        for fn in self.functions:
            if "weight" in fn and len(fn) == 1:
                weight *= float(fn["weight"])
                continue
            if "field_value_factor" in fn:
                spec = fn["field_value_factor"]
                col = segment.numeric_columns.get(spec["field"])
                factor = float(spec.get("factor", 1.0))
                missing = float(spec.get("missing", 1.0))
                modifier = spec.get("modifier", "none")
                if col is None:
                    vals = np.full(segment.nd_pad + 1, missing, dtype=np.float32)
                else:
                    base = np.where(col.exists, col.first_value, missing)
                    vals = np.concatenate([base, [missing]]).astype(np.float32)
                vals = vals * factor
                if modifier == "log1p":
                    vals = np.log1p(np.maximum(vals, 0))
                elif modifier == "ln":
                    vals = np.log(np.maximum(vals, 1e-9))
                elif modifier == "sqrt":
                    vals = np.sqrt(np.maximum(vals, 0))
                elif modifier == "square":
                    vals = vals * vals
                elif modifier == "reciprocal":
                    vals = 1.0 / np.maximum(vals, 1e-9)
                factor_columns.append(vals.astype(np.float32))
                if "weight" in fn:
                    weight *= float(fn["weight"])
            elif "random_score" in fn:
                seed = int(fn["random_score"].get("seed", 0))
                rng = np.random.RandomState(seed if seed else 42)
                factor_columns.append(
                    rng.uniform(0, 1, segment.nd_pad + 1).astype(np.float32)
                )
            elif "weight" in fn:
                weight *= float(fn["weight"])
            else:
                raise ParsingException(
                    f"unsupported function_score function: {sorted(fn)}"
                )
        return self._wrap_boost(P.FunctionScoreNode(
            child, factor_columns, weight, self.boost_mode
        ))


class QueryStringQueryBuilder(QueryBuilder):
    """Simplified query_string: supports `field:value`, quoted phrases,
    AND/OR/NOT, +/-, wildcards in terms. (The reference's full Lucene
    syntax is larger; this covers the common subset. simple_query_string
    maps here too.)"""

    name = "query_string"

    def __init__(self, query: str, default_field: Optional[str] = None,
                 fields: Optional[List[str]] = None,
                 default_operator: str = "or",
                 analyzer: Optional[str] = None,
                 lenient: bool = False, **kw):
        super().__init__(**kw)
        self.query = query
        self.default_field = default_field
        self.fields = fields
        self.default_operator = default_operator.lower()
        self.analyzer = analyzer
        self.lenient = lenient

    def _leaf(self, field: Optional[str], text: str, is_phrase: bool, ctx) -> QueryBuilder:
        if field is None:
            fields = self.fields or (
                [self.default_field] if self.default_field else None
            )
            if fields is None:
                fields = ctx.default_fields() or ["*"]
            if len(fields) > 1:
                return MultiMatchQueryBuilder(text, fields,
                                              analyzer=self.analyzer)
            field = fields[0]
        if self.lenient:
            # lenient=true drops clauses whose value can't parse for the
            # field's type instead of failing the request
            ft = ctx.field_type(field) if field else None
            if ft is not None and not isinstance(ft, TextFieldType):
                try:
                    ft.term_for_query(text.strip('"'), ctx.analyzers)
                    if isinstance(ft, NumberFieldType):
                        float(text.strip('"'))
                except Exception:  # noqa: BLE001 — the lenient contract
                    return MatchNoneQueryBuilder()
        if is_phrase:
            return MatchPhraseQueryBuilder(field, text,
                                           analyzer=self.analyzer)
        if "*" in text or "?" in text:
            # analyzed (text) fields hold lowercased terms; the classic
            # query_string parser lowercases expanded terms to match
            ft = ctx.field_type(field)
            if ft is None or isinstance(ft, TextFieldType):
                text = text.lower()
            return WildcardQueryBuilder(field, text)
        return MatchQueryBuilder(field, text, analyzer=self.analyzer)

    def to_plan(self, ctx, segment):
        tokens = re.findall(r'\S*"[^"]*"|\S+', self.query)
        # first pass: clauses with modifiers; AND marks its neighbors as must
        clauses = []  # list of [builder, kind] where kind in must/should/must_not
        i = 0
        while i < len(tokens):
            tok = tokens[i]
            if tok.upper() == "AND":
                if clauses:
                    clauses[-1][1] = "must" if clauses[-1][1] == "should" else clauses[-1][1]
                # mark: next clause must too
                i += 1
                if i < len(tokens):
                    nxt, kind = self._clause(tokens[i], ctx)
                    if nxt is not None:
                        clauses.append([nxt, "must" if kind == "should" else kind])
                    i += 1
                continue
            if tok.upper() == "OR":
                i += 1
                continue
            if tok.upper() == "NOT":
                i += 1
                if i < len(tokens):
                    qb, _ = self._clause(tokens[i], ctx)
                    if qb is not None:
                        clauses.append([qb, "must_not"])
                    i += 1
                continue
            qb, kind = self._clause(tok, ctx)
            if qb is not None:
                clauses.append([qb, kind])
            i += 1
        must = [c for c, k in clauses if k == "must"]
        should = [c for c, k in clauses if k == "should"]
        must_not = [c for c, k in clauses if k == "must_not"]
        if self.default_operator == "and" and should:
            must.extend(should)
            should = []
        return BoolQueryBuilder(
            must=must, should=should, must_not=must_not, boost=self.boost
        ).to_plan(ctx, segment)

    def _clause(self, tok: str, ctx):
        """-> (builder or None, kind)."""
        kind = "should"
        if tok.startswith("+"):
            tok, kind = tok[1:], "must"
        elif tok.startswith("-"):
            tok, kind = tok[1:], "must_not"
        field = None
        if ":" in tok and not tok.startswith('"'):
            field, tok = tok.split(":", 1)
            if not tok:
                return None, kind
        is_phrase = tok.startswith('"') and tok.endswith('"') and len(tok) > 1
        text = tok.strip('"')
        if not text:
            return None, kind
        return self._leaf(field, text, is_phrase, ctx), kind


class GeoDistanceQueryBuilder(QueryBuilder):
    name = "geo_distance"

    def __init__(self, field: str, center, distance, **kw):
        super().__init__(**kw)
        self.field = field
        self.center = GeoPointFieldType.parse_point(center)
        self.distance_m = parse_distance(distance)

    def to_plan(self, ctx, segment):
        col = segment.geo_columns.get(self.field)
        if col is None:
            return P.MatchNoneNode()
        docs = segment.device_column(f"geo.{self.field}.docs", lambda: col.flat_docs)
        lat = segment.device_column(f"geo.{self.field}.lat", lambda: col.lat)
        lon = segment.device_column(f"geo.{self.field}.lon", lambda: col.lon)
        return P.ConstantScoreNode(P.GeoDistanceNode(
            docs, lat, lon, self.center[0], self.center[1], self.distance_m
        ), self.boost)


class GeoBoundingBoxQueryBuilder(QueryBuilder):
    name = "geo_bounding_box"

    def __init__(self, field: str, top_left, bottom_right, **kw):
        super().__init__(**kw)
        self.field = field
        tl = GeoPointFieldType.parse_point(top_left)
        br = GeoPointFieldType.parse_point(bottom_right)
        self.top, self.left = tl
        self.bottom, self.right = br

    def to_plan(self, ctx, segment):
        col = segment.geo_columns.get(self.field)
        if col is None:
            return P.MatchNoneNode()
        docs = segment.device_column(f"geo.{self.field}.docs", lambda: col.flat_docs)
        lat = segment.device_column(f"geo.{self.field}.lat", lambda: col.lat)
        lon = segment.device_column(f"geo.{self.field}.lon", lambda: col.lon)
        return P.ConstantScoreNode(P.GeoBoxNode(
            docs, lat, lon, self.top, self.left, self.bottom, self.right
        ), self.boost)


class GeoPolygonQueryBuilder(QueryBuilder):
    """geo_polygon (index/query/GeoPolygonQueryBuilder.java): docs whose
    point lies inside the polygon. Host-side vectorized ray casting over
    the geo column (a doc matches if ANY of its points is inside)."""

    name = "geo_polygon"

    def __init__(self, field: str, points, **kw):
        super().__init__(**kw)
        self.field = field
        if not points or len(points) < 3:
            raise ParsingException(
                "too few points defined for geo_polygon query"
            )
        self.points = [GeoPointFieldType.parse_point(p) for p in points]

    def to_plan(self, ctx, segment):
        col = segment.geo_columns.get(self.field)
        if col is None:
            return P.MatchNoneNode()
        n = col.count
        lat = col.lat[:n].astype(np.float64)
        lon = col.lon[:n].astype(np.float64)
        inside = np.zeros(n, dtype=bool)
        # ray casting: count edge crossings of a horizontal ray (vectorized
        # over all points per edge)
        pts = self.points + [self.points[0]]
        for (lat1, lon1), (lat2, lon2) in zip(pts[:-1], pts[1:]):
            cond = (lat1 > lat) != (lat2 > lat)
            with np.errstate(divide="ignore", invalid="ignore"):
                x = (lon2 - lon1) * (lat - lat1) / (lat2 - lat1) + lon1
            inside ^= cond & (lon < x)
        mask = np.zeros(segment.nd_pad + 1, dtype=bool)
        docs = col.flat_docs[:n][inside]
        mask[docs] = True
        mask[segment.nd_pad] = False
        return P.ConstantScoreNode(P.DenseMaskNode(mask, "geo_polygon"), self.boost)


class ScriptQueryBuilder(QueryBuilder):
    """script query (index/query/ScriptQueryBuilder.java): filter docs by
    a numeric expression over doc values. The reference compiles Painless
    per doc; here the expression evaluates ONCE over whole-segment
    columns (script/expression.py execute_columns)."""

    name = "script"

    def __init__(self, script_spec, **kw):
        super().__init__(**kw)
        from elasticsearch_tpu.script.expression import compile_script

        self.script = compile_script(script_spec)
        self.params = (script_spec.get("params") or {}
                       if isinstance(script_spec, dict) else {})

    def to_plan(self, ctx, segment):
        from elasticsearch_tpu.script.expression import segment_columns

        nd = segment.nd_pad
        columns = segment_columns(segment, self.script.doc_fields)
        result = self.script.execute_columns(columns, self.params)
        if result is None:
            return P.MatchNoneNode()
        result = np.asarray(result)
        mask = np.zeros(nd + 1, dtype=bool)
        if result.ndim == 0:  # constant expression
            mask[:nd] = bool(result)
        else:
            mask[:nd] = np.nan_to_num(result[:nd]) != 0
        return P.ConstantScoreNode(P.DenseMaskNode(mask, "script"), self.boost)


class MoreLikeThisQueryBuilder(QueryBuilder):
    """more_like_this (index/query/MoreLikeThisQueryBuilder): extract the
    top-idf terms from the liked text/docs and run a disjunction."""

    name = "more_like_this"

    def __init__(self, fields: List[str], like, max_query_terms: int = 25,
                 min_term_freq: int = 2, minimum_should_match: str = "30%", **kw):
        super().__init__(**kw)
        self.fields = fields
        self.like = like if isinstance(like, list) else [like]
        self.max_query_terms = max_query_terms
        self.min_term_freq = min_term_freq
        self.minimum_should_match = minimum_should_match

    def to_plan(self, ctx, segment):
        from collections import Counter

        texts: List[str] = []
        for item in self.like:
            if isinstance(item, str):
                texts.append(item)
            elif isinstance(item, dict) and "_id" in item:
                local = segment.id_to_doc().get(item["_id"])
                if local is not None:
                    src = segment.sources[local]
                    for f in self.fields:
                        v = src.get(f)
                        if isinstance(v, str):
                            texts.append(v)
        selected: List[tuple] = []
        for field in self.fields:
            ft = ctx.field_type(field)
            counts: Counter = Counter()
            for text in texts:
                if isinstance(ft, TextFieldType):
                    counts.update(ft.query_terms(text, ctx.analyzers))
                else:
                    counts.update(ctx.analyzers.get("standard").analyze(text))
            doc_count = segment.field_stats.get(field, {}).get("doc_count", 0)
            for tok, tf in counts.items():
                if tf < self.min_term_freq and len(texts) > 0 and len(counts) > 10:
                    continue
                tid = segment.term_id(field, tok)
                if tid < 0:
                    continue
                idf = bm25_idf(int(segment.term_doc_freq[tid]), doc_count)
                selected.append((idf, field, tok))
        selected.sort(reverse=True)
        selected = selected[: self.max_query_terms]
        if not selected:
            return P.MatchNoneNode()
        msm = parse_min_should_match(self.minimum_should_match, len(selected)) or 1
        return self._wrap_boost(score_terms_node(
            segment, [(f, t, 1.0) for _, f, t in selected], msm, ctx=ctx
        ))


class GeoShapeQueryBuilder(QueryBuilder):
    """geo_shape query (index/query/GeoShapeQueryBuilder.java): relate the
    query shape to each doc's indexed shapes — INTERSECTS (default),
    DISJOINT, WITHIN, CONTAINS. Vectorized bbox prefilter over the
    segment's dense bbox table, exact planar predicates on candidates
    (utils/geometry.py). ``indexed_shape`` references are resolved by a
    coordinator rewrite before shard execution (node.py)."""

    name = "geo_shape"

    def __init__(self, field: str, shape=None, relation: str = "intersects",
                 ignore_unmapped: bool = False, **kw):
        from elasticsearch_tpu.utils.geometry import parse_shape

        super().__init__(**kw)
        self.field = field
        self.shape = shape
        self.relation = str(relation).lower()
        self.ignore_unmapped = ignore_unmapped
        if self.relation not in ("intersects", "disjoint", "within", "contains"):
            raise ParsingException(
                f"Unknown geo_shape relation [{relation}]")
        if shape is None:
            raise ParsingException(
                "[geo_shape] requires a shape or indexed_shape")
        self._geom = parse_shape(shape)  # parse once per query, not per segment

    def to_plan(self, ctx, segment):
        from elasticsearch_tpu.mapper.field_types import GeoShapeFieldType

        ft = ctx.field_type(self.field)
        if not isinstance(ft, GeoShapeFieldType):
            if self.ignore_unmapped:
                return P.MatchNoneNode()
            raise QueryShardException(
                f"failed to find geo_shape field [{self.field}]")
        col = segment.shape_column(self.field)
        nd1 = segment.nd_pad + 1
        mask = np.zeros(nd1, dtype=bool)
        if col is not None:
            q = self._geom
            qb = q.bbox()
            bbox, exists = col["bbox"], col["exists"]
            with np.errstate(invalid="ignore"):
                overlap = exists & ~(
                    (bbox[:, 0] > qb[2]) | (qb[0] > bbox[:, 2])
                    | (bbox[:, 1] > qb[3]) | (qb[1] > bbox[:, 3])
                )
            if self.relation == "disjoint":
                # all docs with the field are candidates; non-overlapping
                # bboxes are immediately disjoint
                mask[: segment.nd_pad] = exists & ~overlap
                candidates = np.flatnonzero(overlap)
            elif self.relation == "contains":
                # a containing shape's own bbox covers the query bbox, so
                # the doc's combined bbox does too — safe prefilter
                with np.errstate(invalid="ignore"):
                    covers = exists & (
                        (bbox[:, 0] <= qb[0]) & (bbox[:, 1] <= qb[1])
                        & (bbox[:, 2] >= qb[2]) & (bbox[:, 3] >= qb[3])
                    )
                candidates = np.flatnonzero(covers)
            else:
                # intersects AND within use the overlap prefilter: within
                # matches if ANY doc shape sits inside the query shape, and
                # the doc's combined multi-shape bbox may exceed the query
                # bbox even when one shape qualifies
                candidates = np.flatnonzero(overlap)
            for doc in candidates:
                gs = col["geoms"][int(doc)]
                if self.relation == "disjoint":
                    mask[doc] = not any(g.intersects(q) for g in gs)
                else:
                    mask[doc] = any(g.relate(q, self.relation) for g in gs)
        return P.ConstantScoreNode(
            P.DenseMaskNode(mask, label=f"geo_shape.{self.field}"), self.boost)


class PercolateQueryBuilder(QueryBuilder):
    """Inverse search (modules/percolator — PercolateQueryBuilder:86): find
    stored queries (percolator-typed fields) matching a candidate document.
    The candidate is indexed into a one-doc in-memory segment; every stored
    query is planned against it and matched queries' docs become hits."""

    name = "percolate"

    def __init__(self, field: str, document: dict, **kw):
        super().__init__(**kw)
        self.field = field
        self.document = document

    def to_plan(self, ctx, segment):
        from elasticsearch_tpu.index.segment import SegmentBuilder

        # one-doc memory index of the candidate, parsed with a scratch
        # mapper (dynamic mapping) so stored queries see typed fields
        from elasticsearch_tpu.analysis.analyzers import AnalysisRegistry
        from elasticsearch_tpu.mapper.mapping import MapperService

        scratch = MapperService(AnalysisRegistry(),
                                ctx.mapper_service.mapping_dict())
        builder = SegmentBuilder("_percolate")
        builder.add_document(scratch.parse_document("_candidate", self.document), 0)
        temp_seg = builder.seal()
        temp_ctx = ShardQueryContext(scratch)
        temp_dev = temp_seg.device_arrays()

        from elasticsearch_tpu.search import plan as PL

        matching = []
        for local in range(segment.num_docs):
            if not segment.live[local]:
                continue
            stored = segment.sources[local].get(self.field)
            if not isinstance(stored, dict):
                continue
            try:
                qb = parse_query(stored)
                node = qb.to_plan(temp_ctx, temp_seg)
                _, m = PL.execute(temp_dev, node)
                if bool(np.asarray(m)[0]):
                    matching.append(local)
            except Exception:
                continue  # malformed stored query never matches
        if not matching:
            return P.MatchNoneNode()
        mask = np.zeros(segment.nd_pad + 1, dtype=bool)
        for d in matching:
            mask[d] = True
        return P.ConstantScoreNode(P.DenseMaskNode(mask, "percolate"), self.boost)


def _require_join_field(ctx):
    from elasticsearch_tpu.mapper.field_types import join_field_of

    jf = join_field_of(ctx.mapper_service)
    if jf is None:
        raise QueryShardException(
            "no [join] field declared in the mapping of this index"
        )
    return jf


def join_columns(segment, join_field: str):
    """(relation ordinal column, parent-id ordinal column) or None — the
    single place that knows the '<field>#parent' encoding."""
    col = segment.ordinal_columns.get(join_field)
    pcol = segment.ordinal_columns.get(f"{join_field}#parent")
    if col is None or pcol is None:
        return None
    return col, pcol


def join_children(segment, join_field: str, child_names) -> Tuple[np.ndarray, List[str]]:
    """Vectorized child-doc selection: live docs whose relation is one of
    child_names and that carry a parent id. -> (local docs, parent ids)."""
    cols = join_columns(segment, join_field)
    if cols is None:
        return np.empty(0, dtype=np.int64), []
    col, pcol = cols
    child_ords = [o for o in (col.ord_of(c) for c in child_names) if o >= 0]
    if not child_ords:
        return np.empty(0, dtype=np.int64), []
    sel = (np.isin(col.first_ord, child_ords) & pcol.exists
           & segment.live[: segment.nd_pad])
    locals_ = np.nonzero(sel)[0]
    pids = [pcol.terms[pcol.first_ord[int(d)]] for d in locals_]
    return locals_, pids


def parent_id_of(segment, join_field: str, local: int) -> Optional[str]:
    cols = join_columns(segment, join_field)
    if cols is None:
        return None
    _, pcol = cols
    if not pcol.exists[local]:
        return None
    return pcol.terms[pcol.first_ord[local]]


def _matched_by_relation(ctx, segment, query: QueryBuilder, jf,
                         relation_name: str):
    """Run `query` over every segment of the shard, restricted to docs of
    the given join relation. Yields (segment, local_doc, score)."""
    for seg2 in ctx.all_segments(segment):
        col = seg2.ordinal_columns.get(jf.name)
        if col is None:
            continue
        rel_ord = col.ord_of(relation_name)
        if rel_ord < 0:
            continue
        node = query.to_plan(ctx, seg2)
        scores_d, matched_d = P.execute(seg2.device_arrays(), node)
        scores = np.asarray(scores_d)
        matched = np.asarray(matched_d)[: seg2.nd_pad]
        sel = matched & seg2.live[: seg2.nd_pad] & (col.first_ord == rel_ord)
        for local in np.nonzero(sel)[0]:
            yield seg2, int(local), float(scores[local])


def _combine_child_scores(scores: List[float], mode: str) -> float:
    if mode == "min":
        return min(scores)
    if mode == "max":
        return max(scores)
    if mode == "sum":
        return sum(scores)
    if mode == "avg":
        return sum(scores) / len(scores)
    return 1.0  # none: constant


class HasChildQueryBuilder(QueryBuilder):
    """has_child (modules/parent-join — HasChildQueryBuilder:62): match
    parent docs having >=min_children..<=max_children children of `type`
    matching the inner query; child scores fold into the parent per
    score_mode. The reference joins via shard-global ordinals; here child
    hits map to parent _ids host-side and scatter into a dense parent
    score column."""

    name = "has_child"

    def __init__(self, type_: str, query: QueryBuilder, score_mode: str = "none",
                 min_children: int = 1, max_children: Optional[int] = None,
                 inner_hits: Optional[dict] = None, **kw):
        super().__init__(**kw)
        self.type = type_
        self.query = query
        if score_mode not in ("none", "min", "max", "sum", "avg"):
            raise ParsingException(
                f"[has_child] query does not support [score_mode] = [{score_mode}]"
            )
        self.score_mode = score_mode
        self.min_children = max(int(min_children), 1)
        self.max_children = int(max_children) if max_children else None
        self.inner_hits = inner_hits
        # pid -> list of (score, child segment, child local doc)
        self._cached_child_hits: Optional[Dict[str, List[tuple]]] = None

    def _child_hits(self, ctx, segment, jf) -> Dict[str, List[tuple]]:
        """Child-side pass, computed ONCE per query execution (builders are
        parsed fresh per request; to_plan runs per segment — memoizing here
        avoids O(segments^2) inner-query executions)."""
        if self._cached_child_hits is None:
            child_hits: Dict[str, List[tuple]] = {}
            for seg2, local, score in _matched_by_relation(
                    ctx, segment, self.query, jf, self.type):
                pid = parent_id_of(seg2, jf.name, local)
                if pid is not None:
                    child_hits.setdefault(pid, []).append((score, seg2, local))
            self._cached_child_hits = child_hits
        return self._cached_child_hits

    def inner_hits_for(self, ctx, segment, local_doc: int, index_name: str):
        """Matching child docs of one parent hit."""
        spec = self.inner_hits if isinstance(self.inner_hits, dict) else {}
        jf = _require_join_field(ctx)
        entries = self._child_hits(ctx, segment, jf).get(
            segment.doc_ids[local_doc], [])
        entries = sorted(entries, key=lambda e: (-e[0], e[2]))
        name = spec.get("name", self.type)
        frm = int(spec.get("from", 0) or 0)
        size = int(spec.get("size", 3) if spec.get("size") is not None else 3)
        hits = [
            {
                "_index": index_name,
                "_type": "_doc",
                "_id": seg2.doc_ids[loc],
                "_score": float(score),
                "_source": seg2.sources[loc],
            }
            for score, seg2, loc in entries[frm:frm + size]
        ]
        max_score = float(entries[0][0]) if entries else None
        return name, {"hits": {"total": len(entries), "max_score": max_score,
                               "hits": hits}}

    def to_plan(self, ctx, segment):
        jf = _require_join_field(ctx)
        parent_name = jf.parent_of(self.type)
        if parent_name is None:
            raise QueryShardException(
                f"[has_child] join relation [{self.type}] is not a child"
            )
        child_hits = self._child_hits(ctx, segment, jf)

        col = segment.ordinal_columns.get(jf.name)
        parent_ord = col.ord_of(parent_name) if col is not None else -1
        if parent_ord < 0:
            return P.MatchNoneNode()
        id_map = segment.id_to_doc()
        nd1 = segment.nd_pad + 1
        mask = np.zeros(nd1, dtype=bool)
        sc = np.zeros(nd1, dtype=np.float32)
        for pid, entries in child_hits.items():
            ss = [e[0] for e in entries]
            if len(ss) < self.min_children:
                continue
            if self.max_children is not None and len(ss) > self.max_children:
                continue
            local = id_map.get(pid)
            if local is None or col.first_ord[local] != parent_ord:
                continue
            mask[local] = True
            sc[local] = _combine_child_scores(ss, self.score_mode)
        if not mask.any():
            return P.MatchNoneNode()
        return self._wrap_boost(P.DenseScoreNode(sc, mask, "has_child"))


class HasParentQueryBuilder(QueryBuilder):
    """has_parent (modules/parent-join — HasParentQueryBuilder): match
    child docs whose parent matches the inner query; score=true copies the
    parent's score onto each child."""

    name = "has_parent"

    def __init__(self, parent_type: str, query: QueryBuilder,
                 score: bool = False, inner_hits: Optional[dict] = None, **kw):
        super().__init__(**kw)
        self.parent_type = parent_type
        self.query = query
        self.score = bool(score)
        self.inner_hits = inner_hits
        # pid -> (score, parent segment, parent local doc)
        self._cached_parent_hits: Optional[Dict[str, tuple]] = None

    def _parent_hits(self, ctx, segment, jf) -> Dict[str, tuple]:
        if self._cached_parent_hits is None:
            parent_hits: Dict[str, tuple] = {}
            for seg2, local, score in _matched_by_relation(
                    ctx, segment, self.query, jf, self.parent_type):
                parent_hits[seg2.doc_ids[local]] = (score, seg2, local)
            self._cached_parent_hits = parent_hits
        return self._cached_parent_hits

    def inner_hits_for(self, ctx, segment, local_doc: int, index_name: str):
        """The matched parent of one child hit."""
        spec = self.inner_hits if isinstance(self.inner_hits, dict) else {}
        jf = _require_join_field(ctx)
        name = spec.get("name", self.parent_type)
        pid = parent_id_of(segment, jf.name, local_doc)
        entry = self._parent_hits(ctx, segment, jf).get(pid) if pid else None
        if entry is None:
            return name, {"hits": {"total": 0, "max_score": None, "hits": []}}
        score, seg2, loc = entry
        hits = [{
            "_index": index_name,
            "_type": "_doc",
            "_id": seg2.doc_ids[loc],
            "_score": float(score),
            "_source": seg2.sources[loc],
        }]
        return name, {"hits": {"total": 1, "max_score": float(score), "hits": hits}}

    def to_plan(self, ctx, segment):
        jf = _require_join_field(ctx)
        if not jf.is_parent(self.parent_type):
            raise QueryShardException(
                f"[has_parent] join relation [{self.parent_type}] is not a parent"
            )
        parent_hits = self._parent_hits(ctx, segment, jf)
        if not parent_hits:
            return P.MatchNoneNode()
        child_names = jf.relations.get(self.parent_type, [])
        locals_, pids = join_children(segment, jf.name, child_names)
        nd1 = segment.nd_pad + 1
        mask = np.zeros(nd1, dtype=bool)
        sc = np.zeros(nd1, dtype=np.float32)
        for local, pid in zip(locals_, pids):
            if pid in parent_hits:
                mask[int(local)] = True
                sc[int(local)] = parent_hits[pid][0] if self.score else 1.0
        if not mask.any():
            return P.MatchNoneNode()
        return self._wrap_boost(P.DenseScoreNode(sc, mask, "has_parent"))


class ParentIdQueryBuilder(QueryBuilder):
    """parent_id (modules/parent-join — ParentIdQueryBuilder): children of
    `type` whose parent is exactly `id`."""

    name = "parent_id"

    def __init__(self, type_: str, id_: str, **kw):
        super().__init__(**kw)
        self.type = type_
        self.id = str(id_)

    def to_plan(self, ctx, segment):
        jf = _require_join_field(ctx)
        col = segment.ordinal_columns.get(jf.name)
        pcol = segment.ordinal_columns.get(f"{jf.name}#parent")
        if col is None or pcol is None:
            return P.MatchNoneNode()
        child_ord = col.ord_of(self.type)
        pid_ord = pcol.ord_of(self.id)
        if child_ord < 0 or pid_ord < 0:
            return P.MatchNoneNode()
        mask = np.zeros(segment.nd_pad + 1, dtype=bool)
        sel = ((col.first_ord == child_ord) & pcol.exists
               & (pcol.first_ord == pid_ord) & segment.live[: segment.nd_pad])
        mask[: segment.nd_pad] = sel
        return P.ConstantScoreNode(P.DenseMaskNode(mask, "parent_id"), self.boost)


class NestedQueryBuilder(QueryBuilder):
    """nested (index/query/NestedQueryBuilder.java): run the inner query
    over the nested objects of `path` and join matches to parent docs.

    The reference delegates to Lucene's ToParentBlockJoinQuery (child docs
    interleaved in the parent's block). TPU inversion: nested objects are a
    separate dense sub-segment with a ``parent_of`` pointer column
    (index/segment.py NestedContext); the child→parent join is a scatter
    by parent id — no cross-object match leakage (a bool must over two
    nested fields only matches when one *object* satisfies both)."""

    name = "nested"

    def __init__(self, path: str, query: QueryBuilder, score_mode: str = "avg",
                 ignore_unmapped: bool = False, inner_hits: Optional[dict] = None,
                 **kw):
        super().__init__(**kw)
        self.path = path
        self.query = query
        if score_mode not in ("none", "min", "max", "sum", "avg"):
            raise ParsingException(
                f"[nested] query does not support [score_mode] = [{score_mode}]"
            )
        self.score_mode = score_mode
        self.ignore_unmapped = bool(ignore_unmapped)
        self.inner_hits = inner_hits
        self._cache: Dict[str, tuple] = {}

    def _nested_matches(self, ctx, segment):
        """Inner-query pass over the path's sub-segment (once per segment
        per request): -> (NestedContext, matched bool[n_objs], scores) or
        None when the segment has no objects at the path."""
        if segment.name in self._cache:
            return self._cache[segment.name]
        nctx = segment.nested.get(self.path)
        if nctx is None or nctx.segment.num_docs == 0:
            self._cache[segment.name] = None
            return None
        nseg = nctx.segment
        node = self.query.to_plan(ShardQueryContext(ctx.mapper_service), nseg)
        scores_d, matched_d = P.execute(nseg.device_arrays(), node)
        n = nctx.parent_of.shape[0]
        scores = np.asarray(scores_d)[:n]
        matched = np.asarray(matched_d)[:n] & nseg.live[:n]
        # objects die with their parent
        matched = matched & segment.live[nctx.parent_of]
        out = (nctx, matched, scores)
        self._cache[segment.name] = out
        return out

    def to_plan(self, ctx, segment):
        if self.path not in ctx.mapper_service.mapper.nested_paths:
            if self.ignore_unmapped:
                return P.MatchNoneNode()
            raise QueryShardException(
                f"[nested] failed to find nested object under path [{self.path}]"
            )
        res = self._nested_matches(ctx, segment)
        if res is None:
            return P.MatchNoneNode()
        nctx, matched, scores = res
        objs = np.nonzero(matched)[0]
        if objs.size == 0:
            return P.MatchNoneNode()
        parents = nctx.parent_of[objs]
        nd1 = segment.nd_pad + 1
        mask = np.zeros(nd1, dtype=bool)
        mask[parents] = True
        sc = np.zeros(nd1, dtype=np.float32)
        obj_scores = scores[objs].astype(np.float32)
        if self.score_mode == "sum":
            np.add.at(sc, parents, obj_scores)
        elif self.score_mode == "avg":
            counts = np.zeros(nd1, dtype=np.float32)
            np.add.at(sc, parents, obj_scores)
            np.add.at(counts, parents, 1.0)
            sc = np.where(counts > 0, sc / np.maximum(counts, 1.0), 0.0)
        elif self.score_mode == "min":
            sc[:] = np.inf
            np.minimum.at(sc, parents, obj_scores)
            sc = np.where(mask, sc, 0.0).astype(np.float32)
        elif self.score_mode == "max":
            sc[:] = -np.inf
            np.maximum.at(sc, parents, obj_scores)
            sc = np.where(mask, sc, 0.0).astype(np.float32)
        # "none": parents score 0 (ToParentBlockJoinQuery ScoreMode.None)
        return self._wrap_boost(P.DenseScoreNode(sc.astype(np.float32), mask, "nested"))

    def inner_hits_for(self, ctx, segment, local_doc: int, index_name: str):
        """Matched nested objects of one parent hit, as an inner-hits
        entry (search/fetch/subphase/InnerHitsFetchSubPhase)."""
        spec = self.inner_hits if isinstance(self.inner_hits, dict) else {}
        res = self._nested_matches(ctx, segment) \
            if self.path in ctx.mapper_service.mapper.nested_paths else None
        name = spec.get("name", self.path)
        if res is None:
            return name, {"hits": {"total": 0, "max_score": None, "hits": []}}
        nctx, matched, scores = res
        objs = np.nonzero(matched & (nctx.parent_of == local_doc))[0]
        order = sorted(objs, key=lambda o: (-scores[o], nctx.offset_of[o]))
        total = len(order)
        frm = int(spec.get("from", 0) or 0)
        size = int(spec.get("size", 3) if spec.get("size") is not None else 3)
        sel = order[frm:frm + size]
        hits = [
            {
                "_index": index_name,
                "_type": "_doc",
                "_id": segment.doc_ids[local_doc],
                "_nested": {"field": self.path, "offset": int(nctx.offset_of[o])},
                "_score": float(scores[o]),
                "_source": nctx.segment.sources[o],
            }
            for o in sel
        ]
        max_score = float(scores[order[0]]) if order else None
        return name, {"hits": {"total": total, "max_score": max_score, "hits": hits}}


def sub_queries(qb: QueryBuilder) -> List[QueryBuilder]:
    """Immediate child builders of a compound query (for tree walks)."""
    if isinstance(qb, BoolQueryBuilder):
        return [*qb.must, *qb.filter, *qb.should, *qb.must_not]
    if isinstance(qb, ConstantScoreQueryBuilder):
        return [qb.filter]
    if isinstance(qb, DisMaxQueryBuilder):
        return list(qb.queries)
    if isinstance(qb, (FunctionScoreQueryBuilder, NestedQueryBuilder,
                       HasChildQueryBuilder, HasParentQueryBuilder)):
        return [qb.query]
    return []


def collect_inner_hits(qb: Optional[QueryBuilder]) -> List[QueryBuilder]:
    """Builders carrying an inner_hits spec anywhere in the query tree
    (the reference registers InnerHitContextBuilders during rewrite —
    index/query/InnerHitContextBuilder)."""
    if qb is None:
        return []
    out = []
    if getattr(qb, "inner_hits", None) is not None and hasattr(qb, "inner_hits_for"):
        out.append(qb)
    for child in sub_queries(qb):
        out.extend(collect_inner_hits(child))
    return out


# ---------------------------------------------------------------------------
# Parsing (JSON -> builders)
# ---------------------------------------------------------------------------


def parse_distance(d) -> float:
    """'10km', '500m', number (meters) -> meters. One unit table for
    geo_distance queries/sorts and geo_shape circle radii."""
    from elasticsearch_tpu.utils.geometry import _parse_radius

    return _parse_radius(d)


def parse_min_should_match(spec, n_clauses: int) -> int:
    """'2', '30%', '-25%' -> concrete clause count (Queries.calculateMinShouldMatch)."""
    if spec is None:
        return 0
    s = str(spec).strip()
    if s.endswith("%"):
        pct = float(s[:-1])
        if pct < 0:
            return n_clauses - int(-pct / 100.0 * n_clauses)
        return int(pct / 100.0 * n_clauses)
    v = int(s)
    if v < 0:
        return max(n_clauses + v, 0)
    return min(v, n_clauses)


def _field_and_params(body: dict, value_key: str):
    """Handle {"field": "val"} and {"field": {value_key: ..., opts}}."""
    if len(body) != 1:
        raise ParsingException(f"query body must reference one field, got {sorted(body)}")
    field, spec = next(iter(body.items()))
    if isinstance(spec, dict):
        params = dict(spec)
        value = params.pop(value_key, None)
        return field, value, params
    return field, spec, {}


def parse_query(body) -> QueryBuilder:
    """Parse the JSON query DSL (the ``"query": {...}`` object)."""
    if body is None:
        return MatchAllQueryBuilder()
    if not isinstance(body, dict) or len(body) != 1:
        raise ParsingException(
            "[query] malformed query, expected a single query clause object"
        )
    qtype, qbody = next(iter(body.items()))

    if qtype == "match_all":
        return MatchAllQueryBuilder(boost=float((qbody or {}).get("boost", 1.0)))
    if qtype == "match_none":
        return MatchNoneQueryBuilder()
    if qtype == "match":
        field, value, params = _field_and_params(qbody, "query")
        return MatchQueryBuilder(
            field, value, operator=params.get("operator", "or"),
            minimum_should_match=params.get("minimum_should_match"),
            analyzer=params.get("analyzer"),
            boost=float(params.get("boost", 1.0)),
        )
    if qtype == "knn":
        if not isinstance(qbody, dict) or "field" not in qbody:
            raise ParsingException("[knn] requires [field]")
        if "query_vector" not in qbody:
            raise ParsingException("[knn] requires [query_vector]")
        unknown = set(qbody) - {"field", "query_vector", "k",
                                "num_candidates", "filter", "boost",
                                "_name"}
        if unknown:
            # strict parsing (AbstractQueryBuilder contract): a
            # misspelled parameter must 400, never silently drop
            raise ParsingException(
                f"[knn] unknown parameter(s) {sorted(unknown)}")
        flt = qbody.get("filter")
        filters = ([parse_query(f) for f in flt]
                   if isinstance(flt, list)
                   else [parse_query(flt)] if flt is not None else [])
        return KnnQueryBuilder(
            qbody["field"], qbody["query_vector"],
            k=int(qbody.get("k", 10) or 10),
            num_candidates=qbody.get("num_candidates"),
            filter=filters,
            boost=float(qbody.get("boost", 1.0)),
        )
    if qtype == "match_phrase":
        field, value, params = _field_and_params(qbody, "query")
        return MatchPhraseQueryBuilder(
            field, value, slop=int(params.get("slop", 0)),
            boost=float(params.get("boost", 1.0)),
        )
    if qtype == "match_phrase_prefix":
        field, value, params = _field_and_params(qbody, "query")
        return MatchPhrasePrefixQueryBuilder(
            field, value, max_expansions=int(params.get("max_expansions", 50)),
            boost=float(params.get("boost", 1.0)),
        )
    if qtype == "multi_match":
        return MultiMatchQueryBuilder(
            qbody.get("query"), qbody.get("fields") or ["*"],
            type_=qbody.get("type", "best_fields"),
            operator=qbody.get("operator", "or"),
            tie_breaker=float(qbody.get("tie_breaker", 0.0)),
            boost=float(qbody.get("boost", 1.0)),
        )
    if qtype == "term":
        field, value, params = _field_and_params(qbody, "value")
        return TermQueryBuilder(field, value, boost=float(params.get("boost", 1.0)))
    if qtype == "terms":
        body2 = dict(qbody)
        boost = float(body2.pop("boost", 1.0))
        if len(body2) != 1:
            raise ParsingException("[terms] query requires exactly one field")
        field, values = next(iter(body2.items()))
        return TermsQueryBuilder(field, values, boost=boost)
    if qtype == "range":
        field, _, params = _field_and_params(qbody, "__none__")
        known = {k: params.get(k) for k in ("gte", "gt", "lte", "lt")}
        # legacy from/to/include_lower/include_upper
        if "from" in params:
            known["gte" if params.get("include_lower", True) else "gt"] = params["from"]
        if "to" in params:
            known["lte" if params.get("include_upper", True) else "lt"] = params["to"]
        return RangeQueryBuilder(
            field, boost=float(params.get("boost", 1.0)),
            relation=params.get("relation", "intersects"), **known,
        )
    if qtype == "exists":
        return ExistsQueryBuilder(qbody["field"], boost=float(qbody.get("boost", 1.0)))
    if qtype == "ids":
        return IdsQueryBuilder(qbody.get("values", []))
    if qtype == "prefix":
        field, value, params = _field_and_params(qbody, "value")
        return PrefixQueryBuilder(field, value, boost=float(params.get("boost", 1.0)))
    if qtype == "wildcard":
        field, value, params = _field_and_params(qbody, "value")
        if value is None:
            value = params.pop("wildcard", None)
        return WildcardQueryBuilder(field, value, boost=float(params.get("boost", 1.0)))
    if qtype == "regexp":
        field, value, params = _field_and_params(qbody, "value")
        return RegexpQueryBuilder(field, value, boost=float(params.get("boost", 1.0)))
    if qtype == "fuzzy":
        field, value, params = _field_and_params(qbody, "value")
        return FuzzyQueryBuilder(
            field, value, fuzziness=params.get("fuzziness", "AUTO"),
            prefix_length=int(params.get("prefix_length", 0)),
            boost=float(params.get("boost", 1.0)),
        )
    if qtype == "bool":
        def many(key):
            v = qbody.get(key)
            if v is None:
                return []
            if isinstance(v, list):
                return [parse_query(q) for q in v]
            return [parse_query(v)]

        return BoolQueryBuilder(
            must=many("must"), filter=many("filter"), should=many("should"),
            must_not=many("must_not"),
            minimum_should_match=qbody.get("minimum_should_match"),
            boost=float(qbody.get("boost", 1.0)),
        )
    if qtype == "constant_score":
        return ConstantScoreQueryBuilder(
            parse_query(qbody["filter"]), boost=float(qbody.get("boost", 1.0))
        )
    if qtype == "dis_max":
        return DisMaxQueryBuilder(
            [parse_query(q) for q in qbody.get("queries", [])],
            tie_breaker=float(qbody.get("tie_breaker", 0.0)),
            boost=float(qbody.get("boost", 1.0)),
        )
    if qtype == "function_score":
        inner = parse_query(qbody.get("query")) if qbody.get("query") else MatchAllQueryBuilder()
        functions = qbody.get("functions")
        if functions is None:
            functions = []
            for k in ("field_value_factor", "random_score", "script_score", "weight"):
                if k in qbody:
                    functions.append({k: qbody[k]})
        return FunctionScoreQueryBuilder(
            inner, functions, boost_mode=qbody.get("boost_mode", "multiply"),
            score_mode=qbody.get("score_mode", "multiply"),
            boost=float(qbody.get("boost", 1.0)),
        )
    if qtype in ("query_string", "simple_query_string"):
        return QueryStringQueryBuilder(
            qbody["query"], default_field=qbody.get("default_field"),
            fields=qbody.get("fields"),
            default_operator=qbody.get("default_operator", "or"),
            analyzer=qbody.get("analyzer"),
            lenient=bool(qbody.get("lenient", False)),
            boost=float(qbody.get("boost", 1.0)),
        )
    if qtype == "geo_distance":
        params = dict(qbody)
        distance = params.pop("distance")
        params.pop("distance_type", None)
        params.pop("validation_method", None)
        if len(params) != 1:
            raise ParsingException("[geo_distance] requires exactly one field")
        field, center = next(iter(params.items()))
        return GeoDistanceQueryBuilder(field, center, distance)
    if qtype == "geo_bounding_box":
        params = dict(qbody)
        params.pop("validation_method", None)
        params.pop("type", None)
        if len(params) != 1:
            raise ParsingException("[geo_bounding_box] requires exactly one field")
        field, box = next(iter(params.items()))
        return GeoBoundingBoxQueryBuilder(field, box["top_left"], box["bottom_right"])
    if qtype == "geo_shape":
        params = dict(qbody)
        ignore_unmapped = bool(params.pop("ignore_unmapped", False))
        boost = float(params.pop("boost", 1.0))
        if len(params) != 1:
            raise ParsingException("[geo_shape] requires exactly one field")
        field, spec = next(iter(params.items()))
        if "indexed_shape" in spec:
            raise ParsingException(
                "[geo_shape] indexed_shape must be resolved by the "
                "coordinator rewrite before shard execution")
        return GeoShapeQueryBuilder(
            field, shape=spec.get("shape"),
            relation=spec.get("relation", "intersects"),
            ignore_unmapped=ignore_unmapped, boost=boost)
    if qtype == "geo_polygon":
        params = dict(qbody)
        params.pop("validation_method", None)
        if len(params) != 1:
            raise ParsingException("[geo_polygon] requires exactly one field")
        field, spec = next(iter(params.items()))
        return GeoPolygonQueryBuilder(field, spec.get("points") or [])
    if qtype == "script":
        return ScriptQueryBuilder(
            qbody.get("script", qbody), boost=float(qbody.get("boost", 1.0))
        )
    if qtype == "more_like_this":
        return MoreLikeThisQueryBuilder(
            qbody.get("fields", []), qbody.get("like", []),
            max_query_terms=int(qbody.get("max_query_terms", 25)),
            min_term_freq=int(qbody.get("min_term_freq", 2)),
            minimum_should_match=qbody.get("minimum_should_match", "30%"),
        )
    if qtype == "percolate":
        doc = qbody.get("document")
        if doc is None and "documents" in qbody:
            doc = qbody["documents"][0]
        return PercolateQueryBuilder(qbody["field"], doc or {})
    if qtype == "has_child":
        return HasChildQueryBuilder(
            qbody["type"], parse_query(qbody.get("query")),
            score_mode=qbody.get("score_mode", "none"),
            min_children=int(qbody.get("min_children", 1) or 1),
            max_children=qbody.get("max_children"),
            inner_hits=qbody.get("inner_hits"),
            boost=float(qbody.get("boost", 1.0)),
        )
    if qtype == "has_parent":
        return HasParentQueryBuilder(
            qbody["parent_type"], parse_query(qbody.get("query")),
            score=bool(qbody.get("score", False)),
            inner_hits=qbody.get("inner_hits"),
            boost=float(qbody.get("boost", 1.0)),
        )
    if qtype == "parent_id":
        return ParentIdQueryBuilder(
            qbody["type"], qbody["id"], boost=float(qbody.get("boost", 1.0)),
        )
    if qtype == "nested":
        return NestedQueryBuilder(
            qbody["path"], parse_query(qbody["query"]),
            score_mode=qbody.get("score_mode", "avg"),
            ignore_unmapped=bool(qbody.get("ignore_unmapped", False)),
            inner_hits=qbody.get("inner_hits"),
            boost=float(qbody.get("boost", 1.0)),
        )
    if qtype == "type":
        return MatchAllQueryBuilder()  # single doc type in 6.x
    from elasticsearch_tpu.search.spans import SPAN_TYPES, parse_span_query

    if qtype in SPAN_TYPES:
        return parse_span_query(body)
    custom = CUSTOM_QUERY_PARSERS.get(qtype)
    if custom is not None:
        return custom(qbody)
    raise ParsingException(f"no [query] registered for [{qtype}]")
