"""Phase-attributed query telemetry (ISSUE 8, docs/OBSERVABILITY.md).

The headline query is bandwidth-bound (~35 GB/s effective, ~21 MB/query),
and every remaining tuning lever — packed-codec/pruning default flips,
the ICI serving loop, kNN tile tuning — needs to know WHERE a query's
sub-millisecond budget goes. The reference spends a whole subsystem on
exactly this (SURVEY §2.4: profile API, slowlog, node stats); here the
fast planes are compiled device programs, so the observable unit is the
PHASE around each program, not Lucene's per-scorer counters.

Three pieces:

- ``QueryTracer``: a low-overhead span tracer threaded through one
  query's execution. Monotonic clocks, a fixed phase taxonomy
  (``PHASES``), per-phase ACCUMULATORS (bounded by the taxonomy size —
  a thousand-segment shard still records at most one accumulator per
  phase) plus a small preallocated detail ring capped at ``MAX_SPANS``
  records. ``start``/``stop`` are two dict operations — no allocation
  beyond the capped ring tuples, no per-posting work, safe to leave
  always-on in the scoring hot path. ``NULL_TRACER`` is the disabled
  singleton (``search.telemetry.enabled`` kill switch): every call is a
  no-op so call sites stay unconditional.

- ``SearchTelemetry``: the per-index registry the tracers drain into —
  per-plane × per-phase log2-bucket latency histograms, byte counters
  (postings/embedding bytes staged/streamed/skipped), plane-ladder
  decision counters with reasons, exported as the ``search.phases``
  block of ``_stats`` and aggregated into ``_nodes/stats``.

- the ``X-Opaque-Id`` context: the REST layer stamps the request
  header into a contextvar; the search task, slowlog lines, and profile
  output read it back so a slow query joins to its client.
"""

from __future__ import annotations

import contextlib
import contextvars
import threading
import time
from typing import Dict, List, Optional

# Fixed phase taxonomy (docs/OBSERVABILITY.md). Every span a tracer
# records must use one of these names; the histograms are keyed by them.
#
#   parse_rewrite  query DSL parse + coordinator rewrites
#   plan_build     per-shard plan / kernel lane-table construction
#   staging        host->device transfer of plan arrays / union tables
#   kernel         device program dispatch -> block_until_ready
#                  (includes first-call compilation; fused on-device
#                  agg reduction executes inside this span)
#   merge          ICI/host top-k merge + DocRef assembly
#   aggregate      aggregation reduce OUTSIDE the device program: the
#                  host-path agg execution over segment views, the mesh
#                  with_views fallback reduce, and the fused plane's
#                  tiny partial-accumulator finalize (ISSUE 13 — what
#                  fusion removes shows up as this span collapsing)
#   batch_demux    micro-batch member demultiplex / response split
#   fetch          fetch phase (_source, highlight, sort values)
PHASES = ("parse_rewrite", "plan_build", "staging", "kernel", "merge",
          "aggregate", "batch_demux", "fetch")

_now_ns = time.monotonic_ns


class QueryTracer:
    """Span tracer for ONE query. Not thread-safe by design — a query's
    phases execute on one thread (the batch leader records into a batch
    tracer and ``merge_from`` folds it into each member's)."""

    MAX_SPANS = 32
    __slots__ = ("enabled", "_acc", "_counts", "_ring", "ring_dropped",
                 "_annotations")

    def __init__(self):
        self.enabled = True
        self._acc: Dict[str, int] = {}      # phase -> accumulated ns
        self._counts: Dict[str, int] = {}   # phase -> span count
        self._ring: List[tuple] = []        # capped detail records
        self.ring_dropped = 0
        self._annotations: Dict[str, object] = {}

    # -- hot path ------------------------------------------------------

    def start(self, phase: str) -> int:
        return _now_ns()

    def stop(self, phase: str, t0: int) -> None:
        dur = _now_ns() - t0
        self._acc[phase] = self._acc.get(phase, 0) + dur
        self._counts[phase] = self._counts.get(phase, 0) + 1
        if len(self._ring) < self.MAX_SPANS:
            self._ring.append((phase, dur))
        else:
            self.ring_dropped += 1

    # -- annotations ---------------------------------------------------

    def annotate(self, key: str, value) -> None:
        self._annotations[key] = value

    def merge_from(self, other: "QueryTracer") -> None:
        """Fold a shared (batch) tracer's accumulators into this one —
        every member of a batched launch is attributed the launch's
        phase durations (they all waited on it)."""
        for phase, ns in other._acc.items():
            self._acc[phase] = self._acc.get(phase, 0) + ns
            self._counts[phase] = (self._counts.get(phase, 0)
                                   + other._counts.get(phase, 1))
        self._annotations.update(other._annotations)

    # -- output --------------------------------------------------------

    def spans(self) -> List[dict]:
        """Per-phase accumulated spans in taxonomy order (the profile
        output's ``phases`` array)."""
        out = []
        for phase in PHASES:
            if phase in self._acc:
                out.append({"phase": phase,
                            "time_in_nanos": int(self._acc[phase]),
                            "count": int(self._counts.get(phase, 1))})
        return out

    def annotations(self) -> dict:
        out = dict(self._annotations)
        if self.ring_dropped:
            out["spans_dropped"] = self.ring_dropped
        return out

    def top_phases(self, n: int = 3) -> str:
        """``kernel:0.52ms, staging:0.11ms, merge:0.03ms`` — the slowlog
        enrichment string."""
        items = sorted(self._acc.items(), key=lambda kv: -kv[1])[:n]
        return ", ".join(f"{p}:{ns / 1e6:.2f}ms" for p, ns in items)


class _NullTracer:
    """Disabled tracer: every method a no-op, shared singleton."""

    __slots__ = ()
    enabled = False
    ring_dropped = 0
    _acc: Dict[str, int] = {}
    _annotations: Dict[str, object] = {}

    def start(self, phase: str) -> int:
        return 0

    def stop(self, phase: str, t0: int) -> None:
        pass

    def annotate(self, key: str, value) -> None:
        pass

    def merge_from(self, other) -> None:
        pass

    def spans(self) -> List[dict]:
        return []

    def annotations(self) -> dict:
        return {}

    def top_phases(self, n: int = 3) -> str:
        return ""


NULL_TRACER = _NullTracer()


def _bucket_label(ns: int) -> str:
    """log2 latency bucket: a duration in [2^(k-1), 2^k) microseconds
    lands in bucket ``le_2^k`` (``le_1`` = sub-microsecond). Integer
    bit_length — no float log on the recording path."""
    us = ns // 1000
    return f"le_{1 << max(us, 1).bit_length()}" if us > 0 else "le_1"


class SearchTelemetry:
    """Per-index phase-telemetry registry (thread-safe counters).

    Exported as the ``search.phases`` block of ``_stats`` and merged
    across indices into the ``_nodes/stats`` search section."""

    def __init__(self):
        self._lock = threading.Lock()
        # (plane, phase) -> {bucket_label: count}
        self._hist: Dict[tuple, Dict[str, int]] = {}
        self.counters: Dict[str, int] = {}
        self.decisions: Dict[str, int] = {}
        self.queries_recorded = 0

    def tracer(self, enabled: bool = True):
        return QueryTracer() if enabled else NULL_TRACER

    def record_query(self, plane: str, tracer) -> None:
        """Fold one finished query's spans into the per-plane × per-phase
        histograms (launch-level byte/tile totals arrive separately via
        ``add_counters`` — once per launch, never per member)."""
        if not getattr(tracer, "enabled", False):
            return
        with self._lock:
            self.queries_recorded += 1
            for phase, ns in tracer._acc.items():
                h = self._hist.setdefault((plane, phase), {})
                b = _bucket_label(ns)
                h[b] = h.get(b, 0) + 1

    def add_counters(self, mapping: Dict[str, int]) -> None:
        """Fold LAUNCH-level totals (bytes streamed/skipped, tiles) in
        exactly once — a batched launch must not multiply its byte
        counters by the number of members sharing it."""
        with self._lock:
            for key, n in mapping.items():
                total = key if key.endswith("_total") else key + "_total"
                self.counters[total] = self.counters.get(total, 0) + int(n)

    def note_decision(self, plane: str, reason: str, n: int = 1) -> None:
        """Plane-ladder decision counter: which plane a query landed on
        (or was turned away from) and WHY — ``mesh_pallas.served``,
        ``mesh_pallas.quarantined``, ``host.unsupported_body``, ...

        Units are PER QUERY: a batched launch's decision counts once per
        member (``n`` = batch size), so batch-path and serial-path counts
        stay comparable. A query descending the ladder may record more
        than one decision (``shape_mismatch`` then ``served``), so
        decision totals are not a partition of ``queries_recorded``."""
        key = f"{plane}.{reason}"
        with self._lock:
            self.decisions[key] = self.decisions.get(key, 0) + int(n)

    def phases_dict(self) -> dict:
        with self._lock:
            hist: Dict[str, Dict[str, dict]] = {}
            for (plane, phase), buckets in self._hist.items():
                hist.setdefault(plane, {})[phase] = {
                    b: c for b, c in sorted(
                        buckets.items(),
                        key=lambda kv: int(kv[0].split("_")[1]))}
            return {
                "taxonomy": list(PHASES),
                "queries_recorded": self.queries_recorded,
                "histogram_us": hist,
                "counters": dict(self.counters),
                "decisions": dict(sorted(self.decisions.items())),
            }


def merge_phase_stats(blocks: List[dict]) -> dict:
    """Merge per-index ``search`` stats blocks into one node-level block
    (histograms/counters sum; scalars sum; lists concatenate except the
    shared taxonomy; strings keep the first non-null value)."""

    def merge(a, b):
        if isinstance(a, dict) and isinstance(b, dict):
            out = dict(a)
            for k, v in b.items():
                out[k] = merge(out[k], v) if k in out else v
            return out
        if isinstance(a, bool) or isinstance(b, bool):
            return a or b
        if isinstance(a, (int, float)) and isinstance(b, (int, float)):
            return a + b
        if isinstance(a, list) and isinstance(b, list):
            return a if a == b else a + b
        return a if a is not None else b

    out: dict = {}
    for block in blocks:
        out = merge(out, block) if out else dict(block)
    return out


# ---------------------------------------------------------------------------
# X-Opaque-Id request context (Task headers / slowlog / profile join key)
# ---------------------------------------------------------------------------

_OPAQUE_ID: contextvars.ContextVar[Optional[str]] = contextvars.ContextVar(
    "es_tpu_x_opaque_id", default=None)


def set_opaque_id(value: Optional[str]) -> None:
    _OPAQUE_ID.set(value if value else None)


def get_opaque_id() -> Optional[str]:
    return _OPAQUE_ID.get()


@contextlib.contextmanager
def scoped_opaque_id(value: Optional[str]):
    """Stamp a MEMBER's X-Opaque-Id for the duration of the block and
    restore the previous (leader's) id on every exit path — the safe
    idiom for batch leaders building member results on their own
    thread. The contract-lint thread-local-hygiene pass flags bare
    ``set_opaque_id`` member stamps whose early returns skip the
    restore (the PR-9 stale-contextvar bug class); prefer this."""
    prev = _OPAQUE_ID.get()
    _OPAQUE_ID.set(value if value else None)
    try:
        yield
    finally:
        _OPAQUE_ID.set(prev)
