"""Multi-tenant search admission control + the brownout ladder (ISSUE 12).

Everything below this layer *degrades* correctly (hbm_budget demotion,
plane quarantine, partial results, deadlines — PRs 4/9/10) but nothing
*shapes* load. The reference makes overload a first-class contract: a
bounded search threadpool queue whose overflow is a clean
``es_rejected_execution_exception`` (HTTP 429), never a timeout, never a
5xx (SURVEY L0 threadpool/breaker model). This module is that contract
for the TPU query path, consulted at ``IndexService`` dispatch BEFORE
any staging/launch work, plus two things the reference does not have:

- **per-tenant fairness** — tenant identity is the request's
  ``X-Opaque-Id`` (threaded end-to-end since PR 8). In-flight and
  queued work is accounted per tenant and the admission queue drains by
  weighted deficit-round-robin, so a zipfian-hot tenant saturates only
  its share and a light tenant's p99 stays bounded by its own queue,
  not the hot tenant's;
- **the brownout ladder** — at configured queue-pressure thresholds
  the controller forces progressively cheaper execution *before*
  rejecting: (1) force pruned/gte-totals eligibility, (2) shed
  rescore, (3) shed aggs/suggest, (4) reject with Retry-After.
  Shedding is marked on the response (``_degraded: [...]``) and
  counted per step; a drained queue steps back DOWN the ladder in
  reverse order, returning subsequent queries to full-precision,
  full-feature responses.

Three structural rules keep the plane honest:

- every ``acquire`` ends in exactly ONE of {admitted, rejected,
  expired_in_queue} — counters are exact, there are no silent drops;
- a deadline that expires while the entry is QUEUED is shed before
  execution (the entry never reaches staging/launch work) and serves
  its partial timed-out response, mirroring the PR-4 contract;
- a rejection carries a computed ``Retry-After`` derived from the
  observed drain rate, so clients back off proportionally to the
  actual overload instead of thundering back.

See docs/OVERLOAD.md for the ladder semantics, the tenant model, and
the settings table; ``testing/disruption.QueuePressureScheme`` pins
synthetic occupancy / slows drain for deterministic overload tests.
"""

from __future__ import annotations

import contextvars
import math
import threading
import time
from collections import deque
from typing import Dict, List, Optional, Tuple

from elasticsearch_tpu.common.errors import (
    EsRejectedExecutionException,
    NodeDrainingException,
)

# tenant bucket for requests without an X-Opaque-Id header
DEFAULT_TENANT = "_anonymous"
# per-tenant accounting is bounded: an adversarial client minting a new
# opaque id per request must not grow the stats block without bound —
# tenants past the cap account under the shared overflow bucket (their
# queries still admit; only the ACCOUNTING coarsens)
MAX_TRACKED_TENANTS = 64
OVERFLOW_TENANT = "_other"

# brownout ladder steps, in escalation order (docs/OVERLOAD.md):
#   1 forced_pruned — force block-max pruned / gte-totals eligibility
#   2 shed_rescore  — drop the rescore phase
#   3 shed_features — drop aggs/aggregations/suggest
# step 4 (reject) is the queue-overflow 429, not a body transform
BROWNOUT_STEPS = ("forced_pruned", "shed_rescore", "shed_features")

# nested-search guard: collapse expansion / hybrid sides re-enter
# IndexService.search while the outer query already holds an admission
# slot — re-admitting would self-deadlock at max_concurrent=1. The
# contextvar survives the MicroBatcher's same-thread member execution.
_IN_ADMITTED_QUERY: contextvars.ContextVar[int] = contextvars.ContextVar(
    "es_tpu_in_admitted_query", default=0)


class _Entry:
    __slots__ = ("tenant", "deadline", "event", "state", "enqueued_at")

    def __init__(self, tenant: str, deadline):
        self.tenant = tenant
        self.deadline = deadline
        self.event = threading.Event()
        self.state = "queued"  # queued -> admitted | shed | closed
        self.enqueued_at = time.monotonic()


class AdmissionToken:
    """One admitted (or bypassed) query's handle: carries the brownout
    steps active at admission time and the release bookkeeping."""

    __slots__ = ("tenant", "steps", "shed_expired", "noop", "_cv_token",
                 "released")

    def __init__(self, tenant: str, steps=(False, False, False),
                 shed_expired: bool = False, noop: bool = False):
        self.tenant = tenant
        self.steps = steps
        self.shed_expired = shed_expired
        self.noop = noop
        self._cv_token = None
        self.released = False


def rejection(index_name: str, capacity: int, queued: int,
              retry_after_s: float) -> EsRejectedExecutionException:
    """The reference-shaped 429: ``type`` es_rejected_execution_exception
    and a ``reason`` naming the queue capacity. ``retry_after_s`` rides
    as an attribute (NOT body metadata) — the REST layer renders it as
    the ``Retry-After`` header, keeping the body byte-shape clean."""
    exc = EsRejectedExecutionException(
        f"rejected execution of search request on [{index_name}]: "
        f"search admission queue capacity [{capacity}] is full "
        f"(queued [{queued}])")
    exc.retry_after_s = float(retry_after_s)
    return exc


def drain_rejection(index_name: str,
                    retry_after_s: float) -> NodeDrainingException:
    """The graceful-drain 503 (ISSUE 14, docs/RESILIENCE.md "Rollout &
    drain"): the node is restarting — route around it and retry after
    the drain deadline. ``retry_after_s`` rides as an attribute and the
    REST layer renders the ``Retry-After`` header, exactly like the
    429 rejections."""
    exc = NodeDrainingException(
        f"rejected execution of search request on [{index_name}]: "
        f"node is draining for shutdown/rollout")
    exc.retry_after_s = float(retry_after_s)
    return exc


class SearchAdmissionController:
    """Bounded admission queue + DRR fairness + brownout ladder for one
    index's query path.

    Thread-safe; consulted once per top-level search dispatch. Config is
    read live from the index's ``Settings`` map with explicitly-set
    cluster overrides winning (``set_cluster_overrides`` — the same
    explicitness contract as search.pallas.pruning.*)."""

    _OVERRIDE_PREFIXES = ("search.queue.", "search.admission.",
                          "search.drain.",
                          "search.batch.max_window_ms")

    def __init__(self, index_name: str, settings=None):
        self.index_name = index_name
        self._settings = settings
        self._overrides = None  # Settings of explicit cluster values
        self._lock = threading.Lock()
        self._shut = False
        # graceful drain (ISSUE 14): while True, new acquires get the
        # clean 503 + Retry-After and queued entries were shed; in-flight
        # queries finish (await_drained) before the node flushes/closes
        self._draining = False
        self.drain_rejected_total = 0
        # signaled whenever in_flight reaches 0 (the drain waiter's cue)
        self._idle = threading.Condition(self._lock)
        # per-tenant FIFO queues + the weighted-round-robin cursor
        self._queues: Dict[str, deque] = {}
        self._rr_order: List[str] = []
        self._rr_ptr = 0
        self._turn_served = 0
        self.in_flight = 0
        self._queued_total = 0
        # completion timestamps ring: the observed drain rate behind the
        # computed Retry-After
        self._completions: deque = deque(maxlen=64)
        # counters (exported as the _stats `search.admission` block)
        self.admitted_total = 0
        self.rejected_total = 0
        self.expired_in_queue_total = 0
        self.brownout_counts = {step: 0 for step in BROWNOUT_STEPS}
        self._level = 0
        self._steps = (False, False, False)
        self._transitions = {"enter": {}, "exit": {}}
        self._weights: Dict[str, int] = {}
        self._weights_spec: Optional[str] = None
        self._last_retry_after_s = 0.0
        # tenant -> {admitted_total, rejected_total, expired_in_queue
        #            _total, in_flight, queued}
        self._tenants: Dict[str, Dict[str, int]] = {}
        # bounded admission-order ring (tests assert DRR interleaving)
        self.admission_log: deque = deque(maxlen=256)

    # -- configuration -------------------------------------------------

    def set_cluster_overrides(self, committed) -> None:
        """Install the committed cluster settings' EXPLICIT overload
        keys as overrides (cleared keys revert to the index's own
        Settings — the value-only update consumers can't see
        explicitness, so put_cluster_settings syncs this whole map)."""
        data = {}
        for key in committed.keys():
            if any(key.startswith(p) or key == p
                   for p in self._OVERRIDE_PREFIXES):
                data[key] = committed.get(key)
        from elasticsearch_tpu.common.settings import Settings

        self._overrides = Settings(data) if data else None

    def _cfg(self, getter: str, key: str, default):
        for source in (self._overrides, self._settings):
            if source is not None and source.get(key) is not None:
                return getattr(source, getter)(key, default)
        return default

    def _enabled(self) -> bool:
        return bool(self._cfg("get_bool", "search.admission.enabled", True))

    def _queue_size(self) -> int:
        return max(1, int(self._cfg("get_int", "search.queue.size", 1000)))

    def _max_concurrent(self) -> int:
        v = int(self._cfg("get_int", "search.admission.max_concurrent", 0))
        if v > 0:
            return v
        # auto: mirror the search threadpool's sizing, floored so small
        # hosts don't throttle below the micro-batcher's q_batch
        import os

        cores = os.cpu_count() or 4
        return max(16, 3 * cores // 2 + 1)

    def _weight(self, tenant: str) -> int:
        spec = self._cfg("get_str", "search.admission.weights", "") or ""
        if spec != self._weights_spec:
            # parse once per spec value — the dequeue loop consults
            # weights under the controller lock on the query hot path
            parsed: Dict[str, int] = {}
            for part in spec.split(","):
                if ":" in part:
                    name, _, w = part.strip().rpartition(":")
                    try:
                        parsed[name] = max(1, int(w))
                    except ValueError:
                        parsed[name] = 1
            self._weights = parsed
            self._weights_spec = spec
        return self._weights.get(tenant, 1)

    def _thresholds(self) -> Tuple[float, float, float]:
        return (
            float(self._cfg("get_float",
                            "search.admission.brownout.pruned_threshold",
                            0.25)),
            float(self._cfg("get_float",
                            "search.admission.brownout.rescore_threshold",
                            0.5)),
            float(self._cfg("get_float",
                            "search.admission.brownout.features_threshold",
                            0.75)),
        )

    # -- pressure / brownout -------------------------------------------

    def _synthetic_pressure(self, count_hit: bool = True):
        from elasticsearch_tpu.testing.disruption import queue_pressure

        return queue_pressure(self.index_name, count_hit=count_hit)

    def _pressure_locked(self, occupancy: int) -> float:
        return (self._queued_total + occupancy) / float(self._queue_size())

    def _active_steps(self, pressure: float):
        """Each ladder step activates against ITS OWN threshold — an
        operator may disable one step (threshold > 1) without skewing
        the others. With the default ordered thresholds this reduces to
        the classic monotonic ladder."""
        t1, t2, t3 = self._thresholds()
        return (pressure >= t1, pressure >= t2, pressure >= t3)

    def _update_level_locked(self, occupancy: int) -> int:
        steps = self._active_steps(self._pressure_locked(occupancy))
        self._steps = steps
        new = sum(steps)
        old = self._level
        if new != old:
            lo, hi = sorted((old, new))
            for step in range(lo + 1, hi + 1):
                bucket = "enter" if new > old else "exit"
                t = self._transitions[bucket]
                t[str(step)] = t.get(str(step), 0) + 1
            self._level = new
        return new

    @property
    def brownout_level(self) -> int:
        return self._level

    @property
    def brownout_forces_pruning(self) -> bool:
        """True while brownout step 1 is active: the mesh plane's
        ``_pruning_config`` ORs this in, forcing pruned / gte-totals
        eligibility for queries the pruned program can serve."""
        return self._steps[0] and self._enabled()

    def apply_brownout(self, body: dict, token) -> Tuple[dict, List[str]]:
        """Shape an admitted request per the token's active brownout
        steps: returns (possibly-stripped body, degraded markers).
        Counts each applied step per reason."""
        steps = token.steps if token is not None else (False,) * 3
        if not any(steps):
            return body, []
        degraded = []
        out = body

        def shed(step: str, marker: str) -> None:
            degraded.append(marker)
            with self._lock:
                self.brownout_counts[step] += 1

        if steps[0]:
            # step 1: pruned/gte-totals eligibility is forced via
            # brownout_forces_pruning (plan_exec._pruning_config); the
            # marker records the response ran under the forced mode
            shed("forced_pruned", "forced_pruned")
        if steps[1] and "rescore" in (out or {}):
            out = {k: v for k, v in out.items() if k != "rescore"}
            shed("shed_rescore", "rescore")
        if steps[2]:
            stripped = [k for k in ("aggs", "aggregations", "suggest")
                        if k in (out or {})]
            if stripped:
                out = {k: v for k, v in out.items() if k not in stripped}
                for key in stripped:
                    shed("shed_features", key)
        return out, degraded

    def effective_batch_window_s(self, base_s: float) -> float:
        """Adaptive micro-batch window: widens linearly with queue
        pressure from the configured base up to
        ``search.batch.max_window_ms``, trading p50 for throughput
        under load (docs/BATCHING.md). Unloaded indices keep the base
        window — the zero-added-latency contract is untouched."""
        if not self._enabled():
            return base_s
        max_s = float(self._cfg("get_float", "search.batch.max_window_ms",
                                5.0)) / 1000.0
        if max_s <= base_s:
            return base_s
        occupancy, _blocked, _delay = self._synthetic_pressure(
            count_hit=False)
        with self._lock:
            pressure = min(1.0, self._pressure_locked(occupancy))
        return base_s + (max_s - base_s) * pressure

    # -- admit / release -----------------------------------------------

    def _tenant_bucket(self, tenant: str) -> Dict[str, int]:
        b = self._tenants.get(tenant)
        if b is None:
            if (len(self._tenants) >= MAX_TRACKED_TENANTS
                    and tenant != OVERFLOW_TENANT):
                return self._tenant_bucket(OVERFLOW_TENANT)
            b = {"admitted_total": 0, "rejected_total": 0,
                 "expired_in_queue_total": 0, "in_flight": 0, "queued": 0}
            self._tenants[tenant] = b
        return b

    def _drain_rate_locked(self) -> float:
        """Completions per second over the recent completion ring."""
        now = time.monotonic()
        recent = [t for t in self._completions if now - t <= 5.0]
        if len(recent) < 2:
            return 0.0
        span = max(now - recent[0], 1e-6)
        return len(recent) / span

    def _retry_after_locked(self, occupancy: int) -> float:
        """Seconds until the queue has plausibly drained one slot for
        this client — the shared drain-rate estimator the thread-pool
        rejections use, so both 429 sources stay consistent."""
        from elasticsearch_tpu.common.thread_pool import (
            estimate_retry_after,
        )

        ra = estimate_retry_after(self._completions,
                                  self._queued_total + occupancy + 1)
        self._last_retry_after_s = ra
        return ra

    def acquire(self, deadline=None, tenant: Optional[str] = None):
        """Admit one search dispatch. Returns an :class:`AdmissionToken`
        (``shed_expired`` set when the entry's deadline expired while
        queued — the caller serves the partial timed-out response
        WITHOUT executing), or raises the 429 rejection on overflow.
        Every call must be paired with ``release`` via try/finally."""
        if _IN_ADMITTED_QUERY.get():
            return AdmissionToken(DEFAULT_TENANT, noop=True)
        if tenant is None:
            from elasticsearch_tpu.search.telemetry import get_opaque_id

            tenant = get_opaque_id() or DEFAULT_TENANT
        if self._draining:
            # rollout drain (docs/RESILIENCE.md): stop admitting — the
            # clean 503 + Retry-After, counted into the exact
            # admitted/rejected/expired partition (rejected side).
            # Checked BEFORE the enabled kill switch: disabling
            # admission must not void the drain contract (with the
            # switch off, in-flight work is untracked and await_drained
            # cannot wait for it — but new arrivals still get the 503)
            with self._lock:
                if self._draining:
                    self.rejected_total += 1
                    self.drain_rejected_total += 1
                    self._tenant_bucket(tenant)["rejected_total"] += 1
                    raise drain_rejection(self.index_name,
                                          self._drain_deadline_s())
        if not self._enabled():
            return AdmissionToken(DEFAULT_TENANT, noop=True)
        occupancy, blocked, _delay = self._synthetic_pressure()
        entry = None
        with self._lock:
            if self._draining:
                # re-check under the lock: a drain may have begun
                # between the fast check above and here
                self.rejected_total += 1
                self.drain_rejected_total += 1
                self._tenant_bucket(tenant)["rejected_total"] += 1
                raise drain_rejection(self.index_name,
                                      self._drain_deadline_s())
            limit = max(0, self._max_concurrent() - blocked)
            self._update_level_locked(occupancy)
            # opportunistic drain: queued entries stranded by a since-
            # raised limit (a removed QueuePressureScheme) admit here
            # instead of waiting for the next release
            self._dequeue_locked(blocked)
            if (self.in_flight < limit and self._queued_total == 0
                    and not self._shut):
                return self._grant_locked(tenant)
            if (self._queued_total + occupancy >= self._queue_size()
                    or self._shut):
                # fair-share queue displacement: the overflow check is
                # otherwise tenant-blind — a hot tenant's many clients
                # win the race to ENQUEUE and a light tenant would see
                # only 429s even though DRR would serve it. When the
                # arriving tenant sits under its fair slice of the
                # queue, the most-over-slice tenant's NEWEST entry is
                # displaced (it gets the clean 429 + Retry-After); the
                # light tenant takes the slot. Converges to at most a
                # fair slice per tenant under sustained contention.
                if self._shut or not self._displace_for_locked(tenant):
                    self.rejected_total += 1
                    self._tenant_bucket(tenant)["rejected_total"] += 1
                    raise rejection(self.index_name, self._queue_size(),
                                    self._queued_total,
                                    self._retry_after_locked(occupancy))
            entry = _Entry(tenant, deadline)
            q = self._queues.get(tenant)
            if q is None:
                q = deque()
                self._queues[tenant] = q
                self._rr_order.append(tenant)
            q.append(entry)
            self._queued_total += 1
            self._tenant_bucket(tenant)["queued"] += 1
        return self._wait(entry)

    def _grant_locked(self, tenant: str) -> AdmissionToken:
        self.in_flight += 1
        self.admitted_total += 1
        b = self._tenant_bucket(tenant)
        b["admitted_total"] += 1
        b["in_flight"] += 1
        self.admission_log.append(tenant)
        token = AdmissionToken(tenant, steps=self._steps)
        token._cv_token = _IN_ADMITTED_QUERY.set(1)
        return token

    def _wait(self, entry: _Entry) -> AdmissionToken:
        while True:
            timeout = None
            if entry.deadline is not None \
                    and entry.deadline.expires_at is not None:
                timeout = max(entry.deadline.expires_at - time.monotonic(),
                              0.0) + 0.005
            fired = entry.event.wait(timeout)
            with self._lock:
                if entry.state == "admitted":
                    # the dequeuer already did the grant bookkeeping;
                    # build the caller-side token here
                    token = AdmissionToken(entry.tenant,
                                           steps=self._steps)
                    token._cv_token = _IN_ADMITTED_QUERY.set(1)
                    return token
                if entry.state in ("shed", "closed", "displaced",
                                   "draining"):
                    if entry.state == "draining":
                        # the node began draining while this entry was
                        # queued: its clean 503 (counted by begin_drain)
                        raise drain_rejection(self.index_name,
                                              self._drain_deadline_s())
                    if entry.state in ("closed", "displaced"):
                        # displacement/shutdown: this entry's clean 429
                        # (already counted by the displacer)
                        raise rejection(
                            self.index_name, self._queue_size(),
                            self._queued_total,
                            self._last_retry_after_s or 1.0)
                    return AdmissionToken(entry.tenant, shed_expired=True)
                if not fired and entry.deadline is not None \
                        and entry.deadline.expired:
                    # self-wake on an expired deadline while still
                    # queued: shed pre-execution (no dequeuer needed)
                    self._remove_queued_locked(entry)
                    self._shed_locked(entry)
                    return AdmissionToken(entry.tenant, shed_expired=True)

    def _displace_for_locked(self, tenant: str) -> bool:
        """Try to free one queue slot for ``tenant`` by rejecting the
        newest queued entry of the tenant holding the most slots. Only
        fires when the arriver is UNDER its fair slice and the victim
        is OVER it (strictly above the arriver too, so displacement
        always reduces imbalance and cannot thrash between equals)."""
        if not self._queues:
            return False
        # the REAL queue depth, not the stats bucket: past the tenant-
        # tracking cap a tenant's counters accrue under _other, which
        # would read as 0 here and let an over-slice tenant keep
        # displacing others
        my_queued = len(self._queues.get(tenant, ()))
        n_active = len(self._queues) + (0 if tenant in self._queues
                                        else 1)
        fair_slice = max(1, self._queue_size() // max(1, n_active))
        if my_queued >= fair_slice:
            return False
        victim_tenant = max(self._queues, key=lambda t: len(self._queues[t]))
        victim_q = self._queues[victim_tenant]
        if len(victim_q) <= max(fair_slice, my_queued + 1):
            return False
        entry = victim_q.pop()  # newest: least sunk queue time
        self._queued_total -= 1
        self._tenant_bucket(victim_tenant)["queued"] -= 1
        if not victim_q:
            self._retire_tenant_locked(victim_tenant)
        entry.state = "displaced"
        self.rejected_total += 1
        self._tenant_bucket(victim_tenant)["rejected_total"] += 1
        entry.event.set()
        return True

    def _remove_queued_locked(self, entry: _Entry) -> None:
        q = self._queues.get(entry.tenant)
        if q is not None and entry in q:
            q.remove(entry)
            self._queued_total -= 1
            self._tenant_bucket(entry.tenant)["queued"] -= 1
            if not q:
                self._retire_tenant_locked(entry.tenant)

    def _retire_tenant_locked(self, tenant: str) -> None:
        self._queues.pop(tenant, None)
        if tenant in self._rr_order:
            idx = self._rr_order.index(tenant)
            self._rr_order.remove(tenant)
            if idx < self._rr_ptr:
                self._rr_ptr -= 1
            if self._rr_ptr >= len(self._rr_order):
                self._rr_ptr = 0
                self._turn_served = 0

    def _shed_locked(self, entry: _Entry) -> None:
        entry.state = "shed"
        self.expired_in_queue_total += 1
        self._tenant_bucket(entry.tenant)["expired_in_queue_total"] += 1
        entry.event.set()

    def _next_entry_locked(self) -> Optional[_Entry]:
        """Weighted round-robin pop: each tenant's turn serves up to its
        weight entries before the cursor advances — the deficit-round-
        robin schedule for unit-cost work items."""
        while self._rr_order:
            if self._rr_ptr >= len(self._rr_order):
                self._rr_ptr = 0
                self._turn_served = 0
            tenant = self._rr_order[self._rr_ptr]
            q = self._queues.get(tenant)
            if not q:
                self._retire_tenant_locked(tenant)
                self._turn_served = 0
                continue
            if self._turn_served >= self._weight(tenant):
                self._rr_ptr += 1
                self._turn_served = 0
                continue
            self._turn_served += 1
            entry = q.popleft()
            self._queued_total -= 1
            self._tenant_bucket(tenant)["queued"] -= 1
            if not q:
                self._retire_tenant_locked(tenant)
                self._turn_served = 0
            return entry
        return None

    def _dequeue_locked(self, blocked: int) -> None:
        limit = max(0, self._max_concurrent() - blocked)
        while self.in_flight < limit:
            entry = self._next_entry_locked()
            if entry is None:
                return
            if entry.deadline is not None and entry.deadline.expired:
                # shed BEFORE execution: the expired entry never
                # reaches staging/launch work
                self._shed_locked(entry)
                continue
            entry.state = "admitted"
            self.in_flight += 1
            self.admitted_total += 1
            b = self._tenant_bucket(entry.tenant)
            b["admitted_total"] += 1
            b["in_flight"] += 1
            self.admission_log.append(entry.tenant)
            entry.event.set()

    def release(self, token) -> None:
        if token is None or token.noop or token.shed_expired \
                or token.released:
            if token is not None and not token.released \
                    and token._cv_token is not None:
                _IN_ADMITTED_QUERY.reset(token._cv_token)
                token._cv_token = None
            if token is not None:
                token.released = True
            return
        token.released = True
        if token._cv_token is not None:
            _IN_ADMITTED_QUERY.reset(token._cv_token)
            token._cv_token = None
        occupancy, blocked, delay = self._synthetic_pressure(
            count_hit=False)
        if delay > 0:
            time.sleep(delay)  # QueuePressureScheme: slowed drain
        with self._lock:
            self.in_flight -= 1
            b = self._tenant_bucket(token.tenant)
            b["in_flight"] -= 1
            self._completions.append(time.monotonic())
            self._dequeue_locked(blocked)
            self._update_level_locked(occupancy)
            if self.in_flight == 0:
                self._idle.notify_all()  # drain waiters (await_drained)

    def refresh_level(self) -> int:
        """Recompute the brownout level from current pressure (queued +
        synthetic occupancy) without admitting anything — the consult
        point for tests and for pressure sources outside the
        acquire/release cycle."""
        occupancy, _blocked, _delay = self._synthetic_pressure(
            count_hit=False)
        with self._lock:
            return self._update_level_locked(occupancy)

    # -- graceful drain (ISSUE 14, docs/RESILIENCE.md) ------------------

    def _drain_deadline_s(self) -> float:
        v = self._cfg("get_time", "search.drain.deadline", 30.0)
        return float(v) if v is not None else 30.0

    @property
    def draining(self) -> bool:
        return self._draining

    def begin_drain(self) -> int:
        """Enter the draining state: new acquires get the clean 503 +
        Retry-After, every QUEUED entry is shed with the same contract
        (counted — no silent drops), and in-flight queries keep their
        slots until they finish (``await_drained``). Returns how many
        queued entries were shed. Idempotent."""
        with self._lock:
            if self._draining:
                return 0
            self._draining = True
            shed = 0
            for q in self._queues.values():
                for entry in q:
                    entry.state = "draining"
                    self.rejected_total += 1
                    self.drain_rejected_total += 1
                    self._tenant_bucket(entry.tenant)["rejected_total"] += 1
                    entry.event.set()
                    shed += 1
            self._queues.clear()
            self._rr_order = []
            self._queued_total = 0
            for b in self._tenants.values():
                b["queued"] = 0
            return shed

    def await_drained(self, timeout_s: Optional[float] = None) -> bool:
        """Block until every in-flight search released its slot (True)
        or the drain deadline passed (False — the caller proceeds with
        shutdown anyway; stragglers fail their shard the normal way)."""
        deadline = time.monotonic() + (timeout_s if timeout_s is not None
                                       else self._drain_deadline_s())
        with self._idle:
            while self.in_flight > 0:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._idle.wait(remaining)
            return True

    def end_drain(self) -> None:
        """Cancel a drain (rollout aborted): the node admits again."""
        with self._lock:
            self._draining = False

    def shutdown(self) -> None:
        """Index close: wake every queued waiter with a clean rejection
        (pool-shutdown semantics — nobody hangs on a closed index)."""
        with self._lock:
            self._shut = True
            for q in self._queues.values():
                for entry in q:
                    entry.state = "closed"
                    # counted here so admitted+rejected+expired still
                    # partitions offered exactly through a close
                    self.rejected_total += 1
                    self._tenant_bucket(entry.tenant)["rejected_total"] \
                        += 1
                    entry.event.set()
            self._queues.clear()
            self._rr_order = []
            self._queued_total = 0
            for b in self._tenants.values():
                b["queued"] = 0

    # -- stats ----------------------------------------------------------

    def stats_dict(self) -> dict:
        """The ``search.admission`` stats block (docs/OBSERVABILITY.md).
        Every key documented; the ``tenants`` subtree is keyed by
        client-chosen X-Opaque-Id values (cardinality-capped)."""
        with self._lock:
            return {
                "queue_capacity": self._queue_size(),
                "queued": self._queued_total,
                "in_flight": self.in_flight,
                "admitted_total": self.admitted_total,
                "rejected_total": self.rejected_total,
                "expired_in_queue_total": self.expired_in_queue_total,
                "draining": self._draining,
                "drain_rejected_total": self.drain_rejected_total,
                "brownout_level": self._level,
                "brownout": {f"{step}_total": n for step, n
                             in self.brownout_counts.items()},
                "brownout_transitions": {
                    k: dict(v) for k, v in self._transitions.items()},
                "retry_after_s": round(self._last_retry_after_s, 3),
                "drain_rate_qps": round(self._drain_rate_locked(), 3),
                "tenants": {t: dict(b)
                            for t, b in sorted(self._tenants.items())},
            }


def retry_after_header_value(seconds: float) -> str:
    """Integral-seconds Retry-After (RFC 7231 delay-seconds form),
    rounded UP so a client honoring it never retries early."""
    return str(max(1, int(math.ceil(float(seconds)))))
