"""Span query family over host-side position lists.

Role model: the span queries under core/.../index/query/ —
SpanTermQueryBuilder, SpanNearQueryBuilder, SpanFirstQueryBuilder,
SpanOrQueryBuilder, SpanNotQueryBuilder, SpanContainingQueryBuilder,
SpanWithinQueryBuilder, SpanMultiTermQueryBuilder, FieldMaskingSpanQueryBuilder
(each delegating to Lucene's SpanQuery/Spans enumeration).

TPU adaptation (SURVEY §7.3: pointer-chasing structures stay host-side):
positions live in ``segment.positions[term_id] -> {doc: np.ndarray}``;
span enumeration is host-side per segment, producing (doc, span_freq)
pairs that are scored on device via the same BM25-over-frequency node the
phrase query uses (plan.PhraseScoreNode).

A span is a half-open position interval (start, end). Matching docs and
their span lists are computed bottom-up through the builder tree.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

from elasticsearch_tpu.common.errors import ParsingException
from elasticsearch_tpu.ops.scoring import bm25_idf
from elasticsearch_tpu.search import plan as P

Span = Tuple[int, int]

# combination guard for span_near brute-force enumeration
_MAX_NEAR_COMBOS = 100_000


class SpanQueryBuilder:
    """Base: subclasses implement spans(segment) -> {doc: [(start, end)]}
    plus field() and terms() (for IDF weighting)."""

    name = "span_base"

    def __init__(self, boost: float = 1.0):
        self.boost = boost

    def field(self) -> str:
        raise NotImplementedError

    def terms(self, segment) -> List[int]:
        """Term ids involved (for the BM25 weight)."""
        return []

    def spans(self, segment) -> Dict[int, List[Span]]:
        raise NotImplementedError

    # SpanQueryBuilders are also plain QueryBuilders (usable at top level)
    def to_plan(self, ctx, segment) -> P.PlanNode:
        per_doc = self.spans(segment)
        per_doc = {d: s for d, s in per_doc.items() if s}
        if not per_doc:
            return P.MatchNoneNode()
        field = self.field()
        doc_count = segment.field_stats.get(field, {}).get("doc_count", 0)
        weight = sum(
            bm25_idf(int(segment.term_doc_freq[t]), doc_count)
            for t in set(self.terms(segment))
        ) or 1.0
        docs = sorted(per_doc)
        freqs = [float(len(per_doc[d])) for d in docs]
        sentinel = segment.nd_pad
        from elasticsearch_tpu.search.query_dsl import _pad_pow2

        return P.PhraseScoreNode(
            _pad_pow2(docs, sentinel, dtype=np.int32),
            _pad_pow2(freqs, 0.0, dtype=np.float32),
            weight * self.boost,
            segment.field_norm_idx.get(field, 0),
            segment.field_avgdl(field),
        )


class SpanTermQueryBuilder(SpanQueryBuilder):
    name = "span_term"

    def __init__(self, field: str, value: str, **kw):
        super().__init__(**kw)
        self._field = field
        self.value = str(value)

    def field(self):
        return self._field

    def terms(self, segment):
        tid = segment.term_id(self._field, self.value)
        return [tid] if tid >= 0 else []

    def spans(self, segment):
        tid = segment.term_id(self._field, self.value)
        if tid < 0:
            return {}
        return {
            doc: [(int(p), int(p) + 1) for p in pos.tolist()]
            for doc, pos in segment.positions.get(tid, {}).items()
        }


class SpanMultiTermQueryBuilder(SpanQueryBuilder):
    """span_multi: wraps prefix/wildcard/fuzzy/regexp; expands against the
    term dictionary into a span_or of span_terms."""

    name = "span_multi"

    def __init__(self, inner, **kw):
        # inner: a MultiTermExpandingBuilder (has .field and .matches)
        super().__init__(**kw)
        self.inner = inner

    def field(self):
        return self.inner.field

    def _expansions(self, segment) -> List[str]:
        return [t for t, _ in segment.terms_for_field(self.inner.field)
                if self.inner.matches(t)][:1024]

    def terms(self, segment):
        out = []
        for t in self._expansions(segment):
            tid = segment.term_id(self.inner.field, t)
            if tid >= 0:
                out.append(tid)
        return out

    def spans(self, segment):
        out: Dict[int, List[Span]] = {}
        for t in self._expansions(segment):
            sub = SpanTermQueryBuilder(self.inner.field, t).spans(segment)
            for doc, sp in sub.items():
                out.setdefault(doc, []).extend(sp)
        for sp in out.values():
            sp.sort()
        return out


class SpanOrQueryBuilder(SpanQueryBuilder):
    name = "span_or"

    def __init__(self, clauses: List[SpanQueryBuilder], **kw):
        super().__init__(**kw)
        if not clauses:
            raise ParsingException("[span_or] must include [clauses]")
        self.clauses = clauses

    def field(self):
        return self.clauses[0].field()

    def terms(self, segment):
        return [t for c in self.clauses for t in c.terms(segment)]

    def spans(self, segment):
        out: Dict[int, List[Span]] = {}
        for c in self.clauses:
            for doc, sp in c.spans(segment).items():
                out.setdefault(doc, []).extend(sp)
        for sp in out.values():
            sp.sort()
        return out


class SpanNearQueryBuilder(SpanQueryBuilder):
    """span_near: clause spans combine when total gap <= slop; in_order
    requires strictly ordered non-overlapping spans (Lucene NearSpans)."""

    name = "span_near"

    def __init__(self, clauses: List[SpanQueryBuilder], slop: int = 0,
                 in_order: bool = True, **kw):
        super().__init__(**kw)
        if not clauses:
            raise ParsingException("[span_near] must include [clauses]")
        self.clauses = clauses
        self.slop = int(slop)
        self.in_order = bool(in_order)

    def field(self):
        return self.clauses[0].field()

    def terms(self, segment):
        return [t for c in self.clauses for t in c.terms(segment)]

    def spans(self, segment):
        per_clause = [c.spans(segment) for c in self.clauses]
        if not per_clause:
            return {}
        docs = set(per_clause[0])
        for pc in per_clause[1:]:
            docs &= set(pc)
        out: Dict[int, List[Span]] = {}
        for doc in docs:
            lists = [pc[doc] for pc in per_clause]
            combos = 1
            for lst in lists:
                combos *= len(lst)
            if combos > _MAX_NEAR_COMBOS:
                lists = [lst[:16] for lst in lists]
            matches = []
            self._enum(lists, 0, [], matches)
            if matches:
                out[doc] = sorted(set(matches))
        return out

    def _enum(self, lists: List[List[Span]], i: int, chosen: List[Span],
              matches: List[Span]) -> None:
        if i == len(lists):
            starts = [s for s, _ in chosen]
            ends = [e for _, e in chosen]
            lo, hi = min(starts), max(ends)
            length = sum(e - s for s, e in chosen)
            if self.in_order:
                for a, b in zip(chosen, chosen[1:]):
                    if b[0] < a[1]:
                        return
            else:
                # overlapping spans never combine (Lucene semantics)
                ordered = sorted(chosen)
                for a, b in zip(ordered, ordered[1:]):
                    if b[0] < a[1]:
                        return
            if (hi - lo) - length <= self.slop:
                matches.append((lo, hi))
            return
        for sp in lists[i]:
            self._enum(lists, i + 1, chosen + [sp], matches)


class SpanFirstQueryBuilder(SpanQueryBuilder):
    name = "span_first"

    def __init__(self, match: SpanQueryBuilder, end: int, **kw):
        super().__init__(**kw)
        self.match = match
        self.end = int(end)

    def field(self):
        return self.match.field()

    def terms(self, segment):
        return self.match.terms(segment)

    def spans(self, segment):
        return {
            doc: [sp for sp in spans if sp[1] <= self.end]
            for doc, spans in self.match.spans(segment).items()
        }


class SpanNotQueryBuilder(SpanQueryBuilder):
    name = "span_not"

    def __init__(self, include: SpanQueryBuilder, exclude: SpanQueryBuilder,
                 pre: int = 0, post: int = 0, **kw):
        super().__init__(**kw)
        self.include = include
        self.exclude = exclude
        self.pre = int(pre)
        self.post = int(post)

    def field(self):
        return self.include.field()

    def terms(self, segment):
        return self.include.terms(segment)

    def spans(self, segment):
        inc = self.include.spans(segment)
        exc = self.exclude.spans(segment)
        out = {}
        for doc, spans in inc.items():
            bad = exc.get(doc, [])
            kept = [
                sp for sp in spans
                if not any(sp[0] - self.pre < e and b < sp[1] + self.post
                           for b, e in bad)
            ]
            out[doc] = kept
        return out


class SpanContainingQueryBuilder(SpanQueryBuilder):
    """big spans that contain at least one little span."""

    name = "span_containing"

    def __init__(self, little: SpanQueryBuilder, big: SpanQueryBuilder, **kw):
        super().__init__(**kw)
        self.little = little
        self.big = big

    def field(self):
        return self.big.field()

    def terms(self, segment):
        return self.big.terms(segment)

    def spans(self, segment):
        big = self.big.spans(segment)
        little = self.little.spans(segment)
        out = {}
        for doc, bspans in big.items():
            lspans = little.get(doc, [])
            out[doc] = [
                b for b in bspans
                if any(b[0] <= ls and le <= b[1] for ls, le in lspans)
            ]
        return out


class SpanWithinQueryBuilder(SpanQueryBuilder):
    """little spans enclosed by some big span."""

    name = "span_within"

    def __init__(self, little: SpanQueryBuilder, big: SpanQueryBuilder, **kw):
        super().__init__(**kw)
        self.little = little
        self.big = big

    def field(self):
        return self.little.field()

    def terms(self, segment):
        return self.little.terms(segment)

    def spans(self, segment):
        big = self.big.spans(segment)
        little = self.little.spans(segment)
        out = {}
        for doc, lspans in little.items():
            bspans = big.get(doc, [])
            out[doc] = [
                ls for ls in lspans
                if any(b[0] <= ls[0] and ls[1] <= b[1] for b in bspans)
            ]
        return out


class FieldMaskingSpanQueryBuilder(SpanQueryBuilder):
    """field_masking_span: reports a different field name so spans on an
    analyzed sub-field can combine with spans on the base field."""

    name = "field_masking_span"

    def __init__(self, query: SpanQueryBuilder, field: str, **kw):
        super().__init__(**kw)
        self.query = query
        self._field = field

    def field(self):
        return self._field

    def terms(self, segment):
        return self.query.terms(segment)

    def spans(self, segment):
        return self.query.spans(segment)


# ---------------------------------------------------------------------------
# Parsing
# ---------------------------------------------------------------------------

SPAN_TYPES = {"span_term", "span_near", "span_first", "span_or", "span_not",
              "span_containing", "span_within", "span_multi",
              "field_masking_span"}


def parse_span_query(body: dict) -> SpanQueryBuilder:
    if not isinstance(body, dict) or len(body) != 1:
        raise ParsingException("[span] malformed span query clause")
    qtype, qbody = next(iter(body.items()))
    if qtype not in SPAN_TYPES:
        raise ParsingException(
            f"[{qtype}] is not a span query (span clauses must be span queries)"
        )

    if qtype == "span_term":
        if len(qbody) != 1:
            raise ParsingException("[span_term] expects one field")
        field, spec = next(iter(qbody.items()))
        if isinstance(spec, dict):
            return SpanTermQueryBuilder(
                field, spec.get("value"), boost=float(spec.get("boost", 1.0))
            )
        return SpanTermQueryBuilder(field, spec)
    if qtype == "span_near":
        return SpanNearQueryBuilder(
            [parse_span_query(c) for c in qbody.get("clauses", [])],
            slop=int(qbody.get("slop", 0)),
            in_order=bool(qbody.get("in_order", True)),
            boost=float(qbody.get("boost", 1.0)),
        )
    if qtype == "span_first":
        return SpanFirstQueryBuilder(
            parse_span_query(qbody["match"]), qbody.get("end", 1),
            boost=float(qbody.get("boost", 1.0)),
        )
    if qtype == "span_or":
        return SpanOrQueryBuilder(
            [parse_span_query(c) for c in qbody.get("clauses", [])],
            boost=float(qbody.get("boost", 1.0)),
        )
    if qtype == "span_not":
        return SpanNotQueryBuilder(
            parse_span_query(qbody["include"]),
            parse_span_query(qbody["exclude"]),
            pre=int(qbody.get("pre", qbody.get("dist", 0))),
            post=int(qbody.get("post", qbody.get("dist", 0))),
            boost=float(qbody.get("boost", 1.0)),
        )
    if qtype == "span_containing":
        return SpanContainingQueryBuilder(
            parse_span_query(qbody["little"]), parse_span_query(qbody["big"]),
            boost=float(qbody.get("boost", 1.0)),
        )
    if qtype == "span_within":
        return SpanWithinQueryBuilder(
            parse_span_query(qbody["little"]), parse_span_query(qbody["big"]),
            boost=float(qbody.get("boost", 1.0)),
        )
    if qtype == "span_multi":
        from elasticsearch_tpu.search.query_dsl import (
            MultiTermExpandingBuilder,
            parse_query,
        )

        inner = parse_query(qbody["match"])
        if not isinstance(inner, MultiTermExpandingBuilder):
            raise ParsingException(
                "[span_multi] [match] must be a prefix, wildcard, fuzzy or "
                "regexp query"
            )
        return SpanMultiTermQueryBuilder(inner, boost=float(qbody.get("boost", 1.0)))
    if qtype == "field_masking_span":
        return FieldMaskingSpanQueryBuilder(
            parse_span_query(qbody["query"]), qbody["field"],
            boost=float(qbody.get("boost", 1.0)),
        )
    raise ParsingException(f"no [span] query registered for [{qtype}]")
