"""Search deadlines + cooperative cancellation checkpoints.

Role model: the reference's query-phase timeout plumbing —
``SearchContext.timeout()`` checked by ``CancellableBulkScorer`` /
``QueryPhase``'s timeout runnable (search/query/QueryPhase.java:265),
and ``CancellableTask`` checks from ``SearchService`` — plus the
Dean & Barroso "Tail at Scale" contract: a fan-out bounded by a deadline
returns what it has accumulated instead of stalling on stragglers.

One ``SearchDeadline`` is created per search request (node.search) and
threaded down through the coordinator fan-out, the per-shard query
phase, and the mesh plane ladder. Execution calls ``checkpoint()``
between units of work (shards, segments, plan/staging steps):

- a cancelled task raises ``TaskCancelledException`` (propagates to the
  REST layer as a clean error — the ``_tasks/_cancel`` contract);
- an expired timeout raises ``TimeExceededException``, an INTERNAL
  control-flow signal callers catch at the nearest partial-result
  boundary and convert into ``timed_out: true`` with accumulated hits.
"""

from __future__ import annotations

import time
from typing import Optional


class TimeExceededException(Exception):
    """Internal: the search deadline expired. Never surfaces to a
    client — the catcher returns partial results with timed_out=true
    (QueryPhase.TimeExceededException semantics)."""


class SearchDeadline:
    """Deadline + cancellation checkpoints for one search request.

    ``timeout_s``: None = no time bound. ``task``: the registered
    tasks/task_manager.Task whose cancellation trips the same
    checkpoints. The object is shared across the request's shards, so
    ``timed_out`` records whether ANY checkpoint expired (the response's
    top-level flag).
    """

    def __init__(self, timeout_s: Optional[float] = None, task=None):
        self.expires_at = (time.monotonic() + timeout_s
                           if timeout_s is not None and timeout_s > 0
                           else None)
        self.task = task
        self.timed_out = False
        self.checkpoints = 0

    @property
    def expired(self) -> bool:
        return (self.expires_at is not None
                and time.monotonic() >= self.expires_at)

    def checkpoint(self) -> None:
        """Between-units check: raises TaskCancelledException (cancel
        wins over timeout — the caller asked the work to STOP, not to
        degrade) or TimeExceededException."""
        self.checkpoints += 1
        if self.task is not None:
            self.task.ensure_not_cancelled()
        if self.expired:
            self.timed_out = True
            raise TimeExceededException()


def parse_search_timeout(body: dict, settings=None) -> Optional[float]:
    """Resolve a request's query-phase timeout in seconds: the `timeout`
    body/param value ("50ms", "2s", bare millis int) or the node's
    `search.default_search_timeout`; None = unbounded."""
    from elasticsearch_tpu.common.units import parse_time_value

    raw = (body or {}).get("timeout")
    if raw is not None:
        if isinstance(raw, (int, float)) and not isinstance(raw, bool):
            return float(raw) / 1000.0  # bare number = millis, like ES
        return parse_time_value(raw, "timeout")
    if settings is not None:
        from elasticsearch_tpu.common.settings import SEARCH_DEFAULT_TIMEOUT

        return SEARCH_DEFAULT_TIMEOUT.get(settings)
    return None
