"""Cross-query micro-batching for the Pallas scoring plane (ISSUE 5).

BENCH_r05 showed the tile kernel is bandwidth-bound: every query streams
the same corpus posting windows out of HBM (~21 MB/query against a
1.17 GB resident corpus), so at ~0.6 ms p50 a chip tops out near
1.7k qps even though per-query compute is tiny. The classic serving fix
(cf. Orca's iteration-level continuous batching for LLM serving, and
shared block-max traversal in IR) is to amortize one corpus-stream pass
across the queries that are in flight AT THE SAME TIME: score Q queries
per DMA window instead of 1.

Three pieces live here:

- ``MicroBatcher``: a bounded-window collector in front of the search
  path. A query arriving while no other search is in flight takes the
  existing unbatched path immediately (ZERO added latency — the
  batcher's hot check is one lock + one counter read). Under
  concurrency, the first arrival becomes the group leader and waits up
  to ``search.batch.window_ms`` (default 0.2 ms) for peers, bounded by
  ``search.batch.max_queries``; the leader then executes the batch and
  demultiplexes per-member results (a member's failure — cancellation,
  request error — is delivered to that member alone).
- ``BatchStats``: the ``search.batch`` observability block exported via
  ``_stats`` (batched_query_total, batch_size_histogram,
  batch_window_waits_total).
- ``batched_segment_scores``: the host-plane batched launch — given the
  per-query host kernel plans for ONE segment, it unions their term
  lanes (ops/pallas_scoring.union_query_lanes), walks the same geometry
  ladder as the single-query path, and runs ONE ``score_tiles`` call
  with ``q_batch=Q``, returning each query's dense (scores, matched)
  pair. ``ShardSearcher.query`` consumes those through its
  ``score_cache`` parameter, so every downstream per-query semantic
  (min_score, sort, aggs, post_filter, rescore, collapse) is byte-
  identical to serial execution.

The mesh-plane (``mesh_pallas``) batched rung lives in
``parallel/plan_exec.IndexMeshSearch.query_batch``; the rung selection
and per-member deadline/cancellation handling live in
``IndexService.search_batch``. See docs/BATCHING.md.
"""

from __future__ import annotations

import functools
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

# request-body keys the host batched path understands: batching only
# replaces the main query's scoring program with a cached per-query score
# vector — everything else (sort, aggs, post_filter, rescore, fetch-phase
# options) runs the normal per-query pipeline on top of it. profile IS
# batchable (ISSUE 8 plane-truthfulness): a profiled member must run on
# whatever plane would serve it unprofiled and report THAT plane's phase
# spans; on the host batched rung the member's per-segment score cache is
# skipped (ShardSearcher.query) so its engine/timing breakdown still
# reflects a real per-query execution. scroll/pit/collapse-expansion
# style keys stay excluded — their contexts are keyed to one request.
_BATCHABLE_KEYS = frozenset({
    "query", "size", "from", "sort", "aggs", "aggregations", "post_filter",
    "min_score", "timeout", "allow_partial_search_results", "stats",
    "terminate_after", "rescore", "search_after", "track_scores",
    "_source", "docvalue_fields", "stored_fields", "script_fields",
    "highlight", "version", "profile",
    # NB track_total_hits is deliberately NOT batchable: the mesh
    # batched rung rejects whole batches containing any unknown key, so
    # one flagged member would demote its 15 peers off the mesh_pallas
    # launch — it runs solo instead (exhaustive either way)
})


# pure-kNN request shapes the batched kNN MXU launch covers (the body
# either carries the top-level `knn` section alone or the bare `knn`
# query clause); hybrid (query + knn) requests run serially — each side
# then rides its own plane's batching
_KNN_BATCHABLE_KEYS = frozenset({
    "knn", "query", "size", "from", "timeout",
    "allow_partial_search_results", "stats", "_source", "profile",
})


# knn spec parameters the parser accepts (search/query_dsl.KnnQueryBuilder
# strict-parses the same set): the mesh gate must reject anything else so
# an unknown parameter gets the SAME 400 whichever plane is healthy
_KNN_SPEC_KEYS = frozenset({
    "field", "query_vector", "k", "num_candidates", "filter", "boost",
    "_name",
})


def _knn_shaped(body: dict) -> Optional[dict]:
    """The knn spec of a knn-SHAPED request (top-level section with no
    lexical query, or the sole knn query clause), eligible or not."""
    if isinstance(body.get("knn"), dict) and body.get("query") is None:
        return body["knn"]
    q = body.get("query")
    if (isinstance(q, dict) and set(q) == {"knn"}
            and isinstance(q["knn"], dict) and "knn" not in body):
        return q["knn"]
    return None


def knn_batch_spec(body: Optional[dict]) -> Optional[dict]:
    """The knn spec when this request is a pure top-k vector search a
    batched kNN launch could serve (same shape the mesh program covers),
    else None."""
    body = body or {}
    if any(key not in _KNN_BATCHABLE_KEYS for key in body):
        return None
    spec = _knn_shaped(body)
    if spec is None or float(spec.get("boost", 1.0)) != 1.0:
        return None
    if spec.get("filter"):
        return None  # filtered kNN runs the host plan rung (exact)
    if any(key not in _KNN_SPEC_KEYS for key in spec):
        return None  # unknown parameter: the parser owns the 400
    return spec


def batchable_body(body: Optional[dict]) -> bool:
    """Cheap body-shape precheck run at submit time: can this request
    ride a micro-batch at all? (Per-segment kernel eligibility is decided
    later, per query, by the plan builder — an ineligible member simply
    executes serially inside the batch.)"""
    body = body or {}
    if _knn_shaped(body) is not None:
        # pure kNN: batchable only when the MXU launch covers it — a
        # filtered/boosted/malformed spec runs SOLO rather than joining
        # the lexical batch and demoting its peers off the mesh rung
        return knn_batch_spec(body) is not None
    if not isinstance(body.get("query"), dict):
        return False  # match_all / missing query: nothing to amortize
    if body.get("knn") is not None:
        return False  # hybrid: each side batches on its own plane
    return all(key in _BATCHABLE_KEYS for key in body)


class BatchStats:
    """The ``search.batch`` stats block (thread-safe counters)."""

    def __init__(self):
        self._lock = threading.Lock()
        self.batched_query_total = 0
        self.batch_window_waits_total = 0
        self.batch_size_histogram: Dict[int, int] = {}
        # last collection window a leader actually used, in ms — the
        # adaptive-window observability gauge (docs/OVERLOAD.md): widens
        # under admission-queue pressure, narrows back as it drains
        self.batch_window_effective_ms = 0.0

    def note_window_wait(self) -> None:
        with self._lock:
            self.batch_window_waits_total += 1

    def note_effective_window(self, window_s: float) -> None:
        with self._lock:
            self.batch_window_effective_ms = round(window_s * 1000.0, 4)

    def note_batch(self, size: int) -> None:
        """One batched dispatch of ``size`` members served via a shared
        launch."""
        with self._lock:
            self.batched_query_total += size
            self.batch_size_histogram[size] = (
                self.batch_size_histogram.get(size, 0) + 1)

    def as_dict(self) -> dict:
        with self._lock:
            return {
                "batched_query_total": self.batched_query_total,
                "batch_window_waits_total": self.batch_window_waits_total,
                "batch_window_effective_ms": self.batch_window_effective_ms,
                "batch_size_histogram": {
                    str(size): count for size, count
                    in sorted(self.batch_size_histogram.items())},
            }


def counts_safe_for_union(node) -> bool:
    """False when a with_counts (minimum_should_match / operator:and)
    member names the same posting run in two lanes: the union dedupes the
    run (summing weights — exact for SCORES), so that member's match
    COUNT would see one lane where the serial kernel counts two and
    every matching doc could fall below its threshold. Such members
    execute serially; score-only members (min_match <= 1) are unaffected
    because summed weights reproduce their scores exactly."""
    if not node.with_counts:
        return True
    lanes = node._host_lanes
    return len({(l.block_start, l.block_count)
                for l in lanes}) == len(lanes)


class _Group:
    __slots__ = ("items", "results", "done", "sealed", "opened_at")

    def __init__(self):
        self.items: List[Any] = []
        self.results: Optional[List[Any]] = None
        self.done = threading.Event()
        self.sealed = False
        # window-wait telemetry anchor (docs/OBSERVABILITY.md): how long
        # the leader held the group open collecting peers
        self.opened_at = time.monotonic()


class MicroBatcher:
    """Bounded-window cross-query collector.

    ``run(key, item, single_fn, batch_fn)``:

    - no other search in flight -> ``single_fn(item)`` immediately (the
      zero-added-latency contract for unloaded indices);
    - otherwise the item joins (or opens) the pending group for ``key``;
      the group's first member leads: it waits up to ``window_s`` (or
      until ``max_queries`` members arrived), then executes
      ``batch_fn(items) -> [result|Exception, ...]`` and publishes each
      member's entry. Exception entries re-raise in their own caller's
      thread — one member's cancellation or request error never fails
      its peers.
    """

    def __init__(self, window_s: float = 0.0002, max_queries: int = 16,
                 enabled: bool = True,
                 stats: Optional[BatchStats] = None):
        self.window_s = float(window_s)
        self.max_queries = int(max_queries)
        self.enabled = bool(enabled)
        self.stats = stats or BatchStats()
        self._cv = threading.Condition()
        self._groups: Dict[Any, _Group] = {}
        self._inflight = 0
        # optional telemetry hook, called once per member right before
        # the leader dispatches: annotate(item, window_wait_s,
        # batch_size, member_index) — IndexService points it at each
        # member's QueryTracer (docs/OBSERVABILITY.md)
        self.annotate: Optional[Callable[[Any, float, int, int],
                                         None]] = None
        # adaptive collection window (docs/OVERLOAD.md): when set, the
        # leader sizes its wait from this callable instead of window_s —
        # IndexService points it at the admission controller, which
        # widens the window with queue pressure (bounded by
        # search.batch.max_window_ms). A lone query still never waits.
        self.window_fn: Optional[Callable[[], float]] = None

    def run(self, key, item, single_fn: Callable[[Any], Any],
            batch_fn: Callable[[List[Any]], List[Any]]):
        if not self.enabled or self.max_queries < 2:
            return single_fn(item)
        with self._cv:
            group = self._groups.get(key)
            if group is None and self._inflight == 0:
                # the common unloaded case: no concurrency, no window
                self._inflight += 1
                direct = True
                leader = False
                my_idx = 0
            elif group is None:
                group = _Group()
                group.items.append(item)
                self._groups[key] = group
                self._inflight += 1
                direct = False
                leader = True
                my_idx = 0
            else:
                group.items.append(item)
                my_idx = len(group.items) - 1
                self._inflight += 1
                direct = False
                leader = False
                if len(group.items) >= self.max_queries:
                    # full: seal so the leader dispatches now and new
                    # arrivals open a fresh group
                    group.sealed = True
                    self._groups.pop(key, None)
                    self._cv.notify_all()
        try:
            if direct:
                return single_fn(item)
            if leader:
                self.stats.note_window_wait()
                window_s = self.window_s
                if self.window_fn is not None:
                    try:
                        window_s = max(float(self.window_fn()), 0.0)
                    except Exception:  # noqa: BLE001 — sizing is
                        pass  # advisory; never fail the query
                self.stats.note_effective_window(window_s)
                deadline = time.monotonic() + window_s
                with self._cv:
                    while (not group.sealed
                           and len(group.items) < self.max_queries):
                        remaining = deadline - time.monotonic()
                        if remaining <= 0:
                            break
                        self._cv.wait(remaining)
                    group.sealed = True
                    # a filling member may have sealed+removed this group
                    # already AND a newer group may be pending under the
                    # same key — only remove OUR group, never evict the
                    # successor mid-collection
                    if self._groups.get(key) is group:
                        self._groups.pop(key)
                    items = list(group.items)
                if self.annotate is not None:
                    wait_s = time.monotonic() - group.opened_at
                    for idx, it in enumerate(items):
                        try:
                            self.annotate(it, wait_s, len(items), idx)
                        except Exception:  # noqa: BLE001 — telemetry
                            pass  # must never fail the query
                try:
                    if len(items) == 1:
                        # nobody joined: plain unbatched execution
                        try:
                            results = [single_fn(items[0])]
                        except Exception as e:  # noqa: BLE001
                            results = [e]
                    else:
                        results = list(batch_fn(items))
                        if len(results) != len(items):
                            raise RuntimeError(
                                f"batch_fn returned {len(results)} results "
                                f"for {len(items)} members")
                except BaseException as e:  # noqa: BLE001 — followers must
                    # never hang on a leader fault; every member sees it
                    results = [e] * len(items)
                group.results = results
                group.done.set()
                out = results[my_idx]
                if isinstance(out, BaseException):
                    raise out
                return out
            # follower: the leader publishes our result
            if not group.done.wait(timeout=300.0):
                # defensive: a wedged leader must not hang the caller
                return single_fn(item)
            out = group.results[my_idx]
            if isinstance(out, BaseException):
                raise out
            return out
        finally:
            with self._cv:
                self._inflight -= 1


# ----------------------------------------------------------------------
# Host-plane batched launch
# ----------------------------------------------------------------------


_FLAT_BATCH = None


def _flat_batch(dense):
    """[Q, n_tiles*LANE, sub] kernel layout -> [Q, nd_pad] doc order
    (jit specializes per input shape; built lazily so this module never
    imports jax at import time)."""
    global _FLAT_BATCH
    if _FLAT_BATCH is None:
        import jax

        from elasticsearch_tpu.ops import pallas_scoring as psc

        @jax.jit
        def flat(d):
            q, rows, s = d.shape
            n_tiles = rows // psc.LANE
            return d.reshape(q, n_tiles, psc.LANE, s).transpose(
                0, 1, 3, 2).reshape(q, -1)

        _FLAT_BATCH = flat
    return _FLAT_BATCH(dense)


def batched_segment_scores(segment, nodes: Sequence) -> Optional[
        List[Tuple[np.ndarray, np.ndarray]]]:
    """One batched ``score_tiles`` launch for Q queries over ONE segment.

    ``nodes``: the per-query host-built ``PallasScoreTermsNode``s (each
    carries its ``_host_lanes``). Returns one (scores [nd1] f32,
    matched [nd1] bool) numpy pair per query — exactly what
    ``PallasScoreTermsNode.emit`` + the live mask would have produced
    serially — or None when no shared geometry exists (callers fall back
    to serial execution; the same contract as the single-query ladder).
    """
    from elasticsearch_tpu.ops import pallas_scoring as psc

    from elasticsearch_tpu.index.segment import next_pow2

    geom = getattr(segment, "kernel_geom", None)
    if geom is None:
        return None
    lane_sets = [list(n._host_lanes) for n in nodes]
    # pad the batch to a power of two with empty (all-zero-weight) lane
    # sets: q_batch is a jit-static dim, and arrival timing would
    # otherwise compile one kernel variant per batch size
    q_pad = next_pow2(len(nodes))
    lane_sets.extend([] for _ in range(q_pad - len(nodes)))
    # collective geometry ladder (same walk as the single-query path in
    # query_dsl._pallas_score_terms_node): big tiles are fastest, but the
    # UNION's covering window must fit the kernel bound
    sub = geom.tile_sub
    while True:
        g = geom if sub == geom.tile_sub else psc.tile_geometry(
            geom.nd_pad, sub)
        try:
            row_lo, row_hi, weights, cb = psc.build_tile_tables_batched(
                lane_sets, segment.kernel_bmin, segment.kernel_bmax, g)
            break
        except ValueError:
            if sub <= 32 or g.tile_sub < sub:
                return None
            sub //= 2
    live_key = ("k_live_t" if g.tile_sub == geom.tile_sub
                else segment.kernel_live_t_for(g.tile_sub))
    dev = segment.device_arrays()
    codec = getattr(segment, "kernel_codec", "raw")
    if codec == "packed":
        if "k_packed" not in dev:
            return None
        corpus = (dev["k_packed"], None)
    else:
        if "k_docs" not in dev:
            return None
        corpus = (dev["k_docs"], dev["k_frac"])
    with_counts = any(n.with_counts for n in nodes)
    interpret = bool(nodes[0].interpret)
    outs = psc.score_tiles(
        corpus[0], corpus[1], dev[live_key],
        row_lo, row_hi, weights,
        t_pad=row_lo.shape[1], cb=cb, sub=g.tile_sub,
        dense=True, with_counts=with_counts, interpret=interpret,
        tiles_per_step=psc.tiles_per_step_default(),
        q_batch=q_pad, codec=codec)
    nd = segment.nd_pad
    scores_all = np.asarray(_flat_batch(outs[0]))[:, :nd]
    counts_all = (np.asarray(_flat_batch(outs[1]))[:, :nd]
                  if with_counts else None)
    results: List[Tuple[np.ndarray, np.ndarray]] = []
    zero = np.zeros(1, np.float32)
    for q, node in enumerate(nodes):
        scores = np.concatenate([scores_all[q], zero]).astype(np.float32)
        if node.with_counts:
            counts = np.concatenate([counts_all[q], zero])
            matched = counts >= float(node.min_match)
        else:
            matched = scores > 0.0
        results.append((scores, matched))
    return results
