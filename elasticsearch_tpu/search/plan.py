"""Query execution plans: a query tree compiled to ONE jitted XLA program.

Role model inversion: the reference executes a query as a virtual-call
tree of Lucene ``Weight``/``Scorer`` objects driven doc-at-a-time by a
collector (search/query/QueryPhase.java:272). Here the whole boolean/
scoring tree is *traced once* into a single XLA program operating on dense
``[nd1]`` score/match vectors (SURVEY.md §7.1): leaves gather posting
blocks or doc-value columns; combiners are elementwise ops; XLA fuses the
lot. Programs are cached by plan *structure* (node types + array shapes);
the same shaped query never recompiles.

Every node emits ``(scores f32[nd1], matched bool[nd1])``.
"""

from __future__ import annotations

import functools
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from elasticsearch_tpu.ops import masks as mask_ops
from elasticsearch_tpu.ops.scoring import B, K1


class PlanNode:
    """Base: subclasses define emit(ctx), structural key(), arrays()."""

    def emit(self, ctx: "EmitCtx"):
        raise NotImplementedError

    def key(self) -> str:
        raise NotImplementedError

    def arrays(self) -> List:
        return []

    def children(self) -> List["PlanNode"]:
        return []

    def flat_arrays(self) -> List:
        out = list(self.arrays())
        for c in self.children():
            out.extend(c.flat_arrays())
        return out

    def pad_kinds(self) -> List[str]:
        """How each entry of arrays() pads when per-shard plans for the
        SAME query are stacked onto a device mesh (parallel/plan_exec.py).
        Aligned with arrays(). Kinds:
          "s"     scalar — stacked to [n_dev], never padded
          "z"     pad with 0 / False
          "o"     pad with 1 (divisors: avgdl, similarity params)
          "n"     pad with nan (value columns: nan compares False)
          "m1"    pad with -1 (ordinal ids; -1 never matches a real ord)
          "d"     doc-id array — pad with the stacked sentinel doc
                  (nd1-1, dead in live1) and re-point the shard-local
                  sentinel to the stacked one
          "dense" dense-over-docs [local_nd1,...] — zero-extend to the
                  stacked nd1
        """
        return ["z"] * len(self.arrays())

    def trace_statics(self) -> tuple:
        """Static (non-array) attributes baked into the traced program.
        Per-shard plans for the same query may only be stacked onto one
        mesh template when these agree — array lengths may differ (they
        pad), but a differing static here would score non-template shards
        with the wrong formula."""
        return ()

    def flat_pad_kinds(self) -> List[str]:
        out = list(self.pad_kinds())
        for c in self.children():
            out.extend(c.flat_pad_kinds())
        return out

    def describe(self) -> dict:
        """Profile tree (search/profile/query/ProfileScorer.java analog).
        The whole plan executes as ONE fused XLA program, so child nodes
        carry structure, not separate timings — the root's breakdown owns
        the measured device time and children are marked fused."""
        return {
            "type": type(self).__name__,
            "description": self.key(),
            "children": [c.describe() for c in self.children()],
        }


class EmitCtx:
    """Carries the segment device arrays + the flat plan-array iterator
    during tracing."""

    def __init__(self, seg_arrays: dict, plan_arrays: List):
        self.seg = seg_arrays
        self._arrays = plan_arrays
        self._pos = 0

    def take(self, n: int) -> List:
        out = self._arrays[self._pos : self._pos + n]
        self._pos += n
        return out

    @property
    def nd1(self) -> int:
        return self.seg["norms"].shape[1]

    def zeros_f(self):
        return jnp.zeros((self.nd1,), jnp.float32)

    def zeros_b(self):
        return jnp.zeros((self.nd1,), bool)


# ---------------------------------------------------------------------------
# Leaves
# ---------------------------------------------------------------------------


class ScoreTermsNode(PlanNode):
    """Weighted disjunction of term posting blocks with per-lane similarity
    scoring (BM25 default) and a minimum-distinct-match threshold
    (match/term/multi_match leaves).

    Each posting-block lane carries its similarity's host-folded constants
    (weight + p1..p3, see index/similarity.py); the traced formula set is
    selected statically by the node's distinct ``kinds`` tuple, so a plain
    BM25 query compiles exactly the BM25 arithmetic."""

    def __init__(self, q_blocks, q_weights, q_norm_rows, q_avgdl, q_valid,
                 min_match, k1: float = K1, b: float = B,
                 q_p1=None, q_p2=None, q_p3=None, q_kinds=None,
                 kinds: tuple = ("bm25",)):
        from elasticsearch_tpu.index.similarity import STRICTLY_POSITIVE_KINDS

        n = len(q_blocks)
        self.q_blocks = q_blocks
        self.q_weights = q_weights
        self.q_norm_rows = q_norm_rows
        self.q_avgdl = q_avgdl
        self.q_valid = q_valid
        self.min_match = np.float32(min_match)
        # default lane params reproduce classic BM25(k1, b)
        self.q_p1 = q_p1 if q_p1 is not None else np.full(n, k1, np.float32)
        self.q_p2 = q_p2 if q_p2 is not None else np.full(n, b, np.float32)
        self.q_p3 = q_p3 if q_p3 is not None else np.zeros(n, np.float32)
        self.q_kinds = q_kinds if q_kinds is not None else np.zeros(n, np.int32)
        self.kinds = tuple(kinds)
        # single-scatter fast path: only when "matched == score > 0" holds,
        # i.e. plain disjunction AND every live weight strictly positive
        # (a boost of 0 would make a matching doc score 0) AND every
        # similarity in play yields strictly positive contributions
        self._fast = (
            bool(min_match <= 1)
            and bool((np.asarray(q_weights)[np.asarray(q_valid)] > 0).all())
            and all(k in STRICTLY_POSITIVE_KINDS for k in self.kinds)
        )

    def key(self):
        # the fast path + similarity set change the traced program
        return f"terms[{len(self.q_blocks)},{','.join(self.kinds)},{self._fast}]"

    def trace_statics(self):
        return (self.kinds, self._fast)

    def arrays(self):
        return [self.q_blocks, self.q_weights, self.q_norm_rows, self.q_avgdl,
                self.q_valid, self.min_match, self.q_p1, self.q_p2, self.q_p3,
                self.q_kinds]

    def pad_kinds(self):
        return ["z", "z", "z", "o", "z", "s", "o", "o", "z", "z"]

    def emit(self, ctx):
        from elasticsearch_tpu.index.similarity import emit_contrib

        (q_blocks, q_weights, q_norm_rows, q_avgdl, q_valid, min_match,
         q_p1, q_p2, q_p3, q_kinds) = ctx.take(10)
        docs = ctx.seg["block_docs"][q_blocks]
        tfs = ctx.seg["block_tfs"][q_blocks]
        # flat 1-D gather (2-D advanced indexing lowers to a slower general
        # gather on TPU)
        norms = ctx.seg["norms"]
        nd1 = norms.shape[1]
        flat_idx = (q_norm_rows[:, None] * nd1 + docs).ravel()
        doc_len = norms.ravel()[flat_idx].reshape(docs.shape)
        matched = (tfs > 0.0) & q_valid[:, None]
        w = q_weights[:, None]
        avgdl = q_avgdl[:, None]
        p1, p2, p3 = q_p1[:, None], q_p2[:, None], q_p3[:, None]
        if len(self.kinds) == 1:
            contrib = emit_contrib(self.kinds[0], tfs, doc_len, w, avgdl,
                                   p1, p2, p3)
        else:
            contrib = jnp.zeros_like(tfs)
            for i, kind in enumerate(self.kinds):
                lane = (q_kinds == i)[:, None]
                val = emit_contrib(kind, tfs, doc_len, w, avgdl, p1, p2, p3)
                contrib = contrib + jnp.where(lane, val, 0.0)
        contrib = jnp.where(matched, contrib, 0.0)
        scores = ctx.zeros_f().at[docs].add(contrib)
        if self._fast:
            # contributions are strictly positive, so scores > 0 is
            # exactly "any term matched" — saves the second scatter
            return scores, scores > 0.0
        counts = ctx.zeros_f().at[docs].add(matched.astype(jnp.float32))
        return scores, counts >= min_match


class PallasScoreTermsNode(PlanNode):
    """BM25 disjunction executed by the tile-scoring pallas kernel
    (ops/pallas_scoring.py) instead of the XLA scatter-add — the TPU
    replacement for the reference's BulkScorer loop
    (search/query/QueryPhase.java:272). Chosen by score_terms_node when
    every lane is default-constant BM25 and the segment staged kernel
    arrays; the query carries per-(tile, lane) covering-block windows
    computed host-side from per-block doc ranges.

    Mesh form: ``mesh_deferred`` builds the node with the per-shard lane
    set but NO tables; the mesh executor's ``harmonize_kernel_nodes``
    calls ``finalize_mesh`` with the geometry shared by every shard so the
    stacked tables have identical shapes and ONE trace serves all devices
    (the reference runs the same BulkScorer loop on every shard — this is
    that property on a TPU mesh)."""

    def __init__(self, row_lo, row_hi, kweights, min_match, *, cb: int,
                 sub: int, interpret: bool, live_key: str = "k_live_t",
                 tiles_per_step: int = 1, codec: str = "raw"):
        self.row_lo = row_lo  # [n_tiles, t_pad] i32
        self.row_hi = row_hi
        self.kweights = kweights  # [1, t_pad] f32
        self.min_match = np.float32(min_match)
        self.cb = cb
        self.sub = sub
        self.t_pad = int(row_lo.shape[1])
        self.n_tiles = int(row_lo.shape[0])
        self.interpret = interpret
        self.with_counts = min_match > 1
        # live-mask layout key in the segment device dict: the geometry
        # ladder stages per-sub variants for dense-term queries
        self.live_key = live_key
        self.tiles_per_step = tiles_per_step
        # postings codec the segment staged (docs/PRUNING.md): "packed"
        # reads the bit-packed word array and decodes in-kernel
        self.codec = codec
        self._mesh_lanes = None
        self._mesh_bmin = None
        self._mesh_bmax = None

    @classmethod
    def mesh_deferred(cls, lanes, bmin, bmax, min_match, *,
                      interpret: bool,
                      codec: str = "raw") -> "PallasScoreTermsNode":
        """Node for the MESH plane with table building deferred: lanes are
        shard-local, but table geometry (tile count, t_pad, cb, sub) must
        be uniform across the whole stacked segment set and is only known
        once every shard's plan exists. ``bmin``/``bmax`` are the shard
        segment's per-block doc ranges (tile-size independent)."""
        self = cls.__new__(cls)
        self.row_lo = self.row_hi = self.kweights = None
        self.min_match = np.float32(min_match)
        self.cb = self.sub = self.t_pad = self.n_tiles = None
        self.interpret = interpret
        self.with_counts = min_match > 1
        self.live_key = "k_live_t"
        self.tiles_per_step = 1
        self.codec = codec
        self._mesh_lanes = list(lanes)
        self._mesh_bmin = bmin
        self._mesh_bmax = bmax
        return self

    def finalize_mesh(self, row_lo, row_hi, kweights, *, cb: int, sub: int,
                      live_key: str, tiles_per_step: int = 1) -> None:
        self.row_lo = row_lo
        self.row_hi = row_hi
        self.kweights = kweights
        self.cb = cb
        self.sub = sub
        self.t_pad = int(row_lo.shape[1])
        self.n_tiles = int(row_lo.shape[0])
        self.live_key = live_key
        self.tiles_per_step = tiles_per_step

    def key(self):
        return (f"pterms[{self.n_tiles},{self.t_pad},{self.cb},{self.sub},"
                f"{self.with_counts},{self.interpret},{self.live_key},"
                f"{self.tiles_per_step},{self.codec}]")

    def trace_statics(self):
        return (self.cb, self.sub, self.t_pad, self.with_counts,
                self.interpret, self.live_key, self.tiles_per_step,
                self.codec)

    def arrays(self):
        if self.row_lo is None:
            # a mesh_deferred node escaped harmonization — refuse to trace
            # a half-built plan (callers treat this as "no plan form")
            raise NotImplementedError(
                "mesh pallas node used before finalize_mesh")
        return [self.row_lo, self.row_hi, self.kweights, self.min_match]

    def pad_kinds(self):
        # "k": kernel tables — stackable onto a mesh template only when
        # every shard's tables share one shape (harmonize_kernel_nodes
        # guarantees it for mesh-built plans; host-built per-segment
        # geometries differ and fail the stack, keeping the host path)
        return ["k", "k", "k", "s"]

    def emit(self, ctx):
        from elasticsearch_tpu.ops import pallas_scoring as psc

        row_lo, row_hi, kweights, min_match = ctx.take(4)
        if self.codec == "packed":
            corpus = (ctx.seg["k_packed"], None)
        else:
            corpus = (ctx.seg["k_docs"], ctx.seg["k_frac"])
        outs = psc.score_tiles(
            corpus[0], corpus[1], ctx.seg[self.live_key],
            row_lo, row_hi, kweights,
            t_pad=self.t_pad, cb=self.cb, sub=self.sub,
            dense=True, with_counts=self.with_counts,
            interpret=self.interpret,
            tiles_per_step=self.tiles_per_step, codec=self.codec)
        nd = ctx.nd1 - 1
        scores = psc.dense_to_flat(outs[0], self.sub)[:nd]
        scores = jnp.concatenate([scores, jnp.zeros(1, jnp.float32)])
        if self.with_counts:
            counts = psc.dense_to_flat(outs[1], self.sub)[:nd]
            counts = jnp.concatenate([counts, jnp.zeros(1, jnp.float32)])
            return scores, counts >= min_match
        return scores, scores > 0.0


class KnnScoreNode(PlanNode):
    """Dense-vector similarity scoring against a staged embedding matrix
    (the host rung of the kNN plane ladder; the mesh_pallas rung runs
    the MXU kernel in ops/pallas_knn.py with identical arithmetic).

    score = (dot(x, q) * scale) / 2 + 1/2 with q pre-normalized for
    cosine and scale the staged per-doc inverse norm (ones for
    dot_product) — the reference's (1 + sim) / 2 convention. Every live
    doc carrying the vector field "matches"; ranking is the whole query.

    The embedding matrix is segment-local device state (ctx.seg keys
    staged by Segment.ensure_vector_staged), NOT a plan array — so the
    node cannot stack onto a mesh template (pad kind "x"): the generic
    mesh path cleanly mismatches and the dedicated kNN mesh program
    (IndexMeshSearch.query_knn) owns the distributed form."""

    def __init__(self, field: str, qvec, metric: str, boost: float,
                 emb_key: str, norm_key: str, exists_key: str):
        self.field = field
        self.qvec = qvec  # [1, d_pad] f32 (normalize_query row)
        self.metric = metric
        self.boost = np.float32(boost)
        self.emb_key = emb_key
        self.norm_key = norm_key
        self.exists_key = exists_key

    def key(self):
        return (f"knn[{self.field},{self.metric},{self.qvec.shape[1]},"
                f"{self.emb_key}]")

    def trace_statics(self):
        return (self.field, self.metric, self.emb_key)

    def arrays(self):
        return [self.qvec, self.boost]

    def pad_kinds(self):
        # "x": segment-keyed device state can't stack onto a mesh
        # template — the executor raises PlanStructureMismatch and the
        # ladder serves this query from the host (or the kNN program)
        return ["x", "s"]

    def emit(self, ctx):
        qvec, boost = ctx.take(2)
        emb = ctx.seg[self.emb_key].astype(jnp.float32)  # [nd_pad, d_pad]
        # same contraction shape + HIGHEST precision as the MXU kernel so
        # host and mesh rungs score identical bits (dryrun phase 5 gate)
        s = jax.lax.dot_general(
            emb, qvec, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.HIGHEST)[:, 0]
        if self.metric == "cosine":
            s = s * ctx.seg[self.norm_key]
        s = s * jnp.float32(0.5) + jnp.float32(0.5)
        scores = jnp.concatenate([s, jnp.zeros(1, jnp.float32)])
        matched = ctx.seg[self.exists_key]
        return jnp.where(matched, scores * boost,
                         jnp.float32(0.0)).astype(jnp.float32), matched


class PhraseScoreNode(PlanNode):
    """Pre-verified phrase matches (host position intersection) scored by
    the field's similarity over the phrase frequency — MatchPhraseQuery
    semantics. docs/freqs are [K]-padded (doc = nd1-1 sentinel, freq = 0)."""

    def __init__(self, docs, freqs, weight, norm_row, avgdl,
                 k1: float = K1, b: float = B, kind: str = "bm25",
                 p1=None, p2=None, p3=0.0):
        self.docs = docs
        self.freqs = freqs
        self.weight = np.float32(weight)
        self.norm_row = int(norm_row)
        self.avgdl = np.float32(avgdl)
        self.kind = kind
        # default params reproduce classic BM25(k1, b)
        self.p1 = np.float32(k1 if p1 is None else p1)
        self.p2 = np.float32(b if p2 is None else p2)
        self.p3 = np.float32(p3)

    def key(self):
        return f"phrase[{len(self.docs)},{self.norm_row},{self.kind}]"

    def trace_statics(self):
        return (self.norm_row, self.kind)

    def arrays(self):
        return [self.docs, self.freqs, self.weight, self.avgdl,
                self.p1, self.p2, self.p3]

    def pad_kinds(self):
        return ["d", "z", "s", "s", "s", "s", "s"]

    def emit(self, ctx):
        from elasticsearch_tpu.index.similarity import emit_contrib

        docs, freqs, weight, avgdl, p1, p2, p3 = ctx.take(7)
        doc_len = ctx.seg["norms"][self.norm_row][docs]
        matched_v = freqs > 0
        contrib = jnp.where(
            matched_v,
            emit_contrib(self.kind, freqs, doc_len, weight, avgdl, p1, p2, p3),
            0.0,
        )
        scores = ctx.zeros_f().at[docs].add(contrib)
        matched = ctx.zeros_b().at[docs].max(matched_v)
        return scores, matched


class MatchAllNode(PlanNode):
    def __init__(self, boost: float = 1.0):
        self.boost = np.float32(boost)

    def key(self):
        return "all"

    def arrays(self):
        return [self.boost]

    def pad_kinds(self):
        return ["s"]

    def emit(self, ctx):
        (boost,) = ctx.take(1)
        matched = ctx.seg["live1"]
        return jnp.where(matched, boost, 0.0).astype(jnp.float32), matched


class MatchNoneNode(PlanNode):
    def key(self):
        return "none"

    def emit(self, ctx):
        return ctx.zeros_f(), ctx.zeros_b()


class NumericRangeNode(PlanNode):
    def __init__(self, flat_docs, flat_values, lo: float, hi: float):
        self.flat_docs = flat_docs
        self.flat_values = flat_values
        self.lo = np.float64(lo)
        self.hi = np.float64(hi)

    def key(self):
        return f"nrange[{len(self.flat_docs)}]"

    def arrays(self):
        return [self.flat_docs, self.flat_values, self.lo, self.hi]

    def pad_kinds(self):
        return ["d", "n", "s", "s"]

    def emit(self, ctx):
        flat_docs, flat_values, lo, hi = ctx.take(4)
        cond = (flat_values >= lo) & (flat_values <= hi)
        return ctx.zeros_f(), ctx.zeros_b().at[flat_docs].max(cond)


class NumericTermsNode(PlanNode):
    def __init__(self, flat_docs, flat_values, values):
        self.flat_docs = flat_docs
        self.flat_values = flat_values
        self.values = values  # [K] f64 padded with nan

    def key(self):
        return f"nterms[{len(self.flat_docs)},{len(self.values)}]"

    def arrays(self):
        return [self.flat_docs, self.flat_values, self.values]

    def pad_kinds(self):
        return ["d", "n", "n"]

    def emit(self, ctx):
        flat_docs, flat_values, values = ctx.take(3)
        cond = (flat_values[:, None] == values[None, :]).any(axis=1)
        return ctx.zeros_f(), ctx.zeros_b().at[flat_docs].max(cond)


class OrdTermsNode(PlanNode):
    def __init__(self, flat_docs, flat_ords, ords):
        self.flat_docs = flat_docs
        self.flat_ords = flat_ords
        self.ords = ords  # [K] int32 padded with -1

    def key(self):
        return f"oterms[{len(self.flat_docs)},{len(self.ords)}]"

    def arrays(self):
        return [self.flat_docs, self.flat_ords, self.ords]

    def pad_kinds(self):
        return ["d", "m1", "m1"]

    def emit(self, ctx):
        flat_docs, flat_ords, ords = ctx.take(3)
        cond = (flat_ords[:, None] == ords[None, :]).any(axis=1)
        return ctx.zeros_f(), ctx.zeros_b().at[flat_docs].max(cond)


class OrdRangeNode(PlanNode):
    def __init__(self, flat_docs, flat_ords, lo_ord: int, hi_ord: int):
        self.flat_docs = flat_docs
        self.flat_ords = flat_ords
        self.lo_ord = np.int32(lo_ord)
        self.hi_ord = np.int32(hi_ord)

    def key(self):
        return f"orange[{len(self.flat_docs)}]"

    def arrays(self):
        return [self.flat_docs, self.flat_ords, self.lo_ord, self.hi_ord]

    def pad_kinds(self):
        return ["d", "m1", "s", "s"]

    def emit(self, ctx):
        flat_docs, flat_ords, lo, hi = ctx.take(4)
        cond = (flat_ords >= lo) & (flat_ords < hi)
        return ctx.zeros_f(), ctx.zeros_b().at[flat_docs].max(cond)


class RangePairNode(PlanNode):
    """Query against a range *field* (index/mapper/RangeFieldMapper.java
    relation semantics): doc values are (lo, hi) pairs in aligned CSR
    columns; the relation picks the predicate vs the query interval."""

    def __init__(self, flat_docs, lo_vals, hi_vals, q_lo: float, q_hi: float,
                 relation: str = "intersects"):
        self.flat_docs = flat_docs
        self.lo_vals = lo_vals
        self.hi_vals = hi_vals
        self.q_lo = np.float64(q_lo)
        self.q_hi = np.float64(q_hi)
        self.relation = relation

    def key(self):
        return f"rpair[{len(self.flat_docs)},{self.relation}]"

    def trace_statics(self):
        return (self.relation,)

    def arrays(self):
        return [self.flat_docs, self.lo_vals, self.hi_vals, self.q_lo, self.q_hi]

    def pad_kinds(self):
        return ["d", "n", "n", "s", "s"]

    def emit(self, ctx):
        flat_docs, lo_vals, hi_vals, q_lo, q_hi = ctx.take(5)
        if self.relation == "within":
            cond = (lo_vals >= q_lo) & (hi_vals <= q_hi)
        elif self.relation == "contains":
            cond = (lo_vals <= q_lo) & (hi_vals >= q_hi)
        else:  # intersects (default)
            cond = (lo_vals <= q_hi) & (hi_vals >= q_lo)
        return ctx.zeros_f(), ctx.zeros_b().at[flat_docs].max(cond)


class DenseMaskNode(PlanNode):
    """A precomputed [nd1] bool mask (exists query, ids query)."""

    def __init__(self, mask, label: str = "mask"):
        self.mask = mask
        self.label = label

    def key(self):
        return f"dense[{len(self.mask)}]"

    def arrays(self):
        return [self.mask]

    def pad_kinds(self):
        return ["dense"]

    def emit(self, ctx):
        (mask,) = ctx.take(1)
        return ctx.zeros_f(), mask


class DenseScoreNode(PlanNode):
    """Precomputed dense [nd1] scores + match mask (join queries: scores
    aggregated host-side from the other side of the relation)."""

    def __init__(self, scores, mask, label: str = "join"):
        self.scores = scores
        self.mask = mask
        self.label = label

    def key(self):
        return f"densescore[{len(self.mask)}]"

    def arrays(self):
        return [self.scores, self.mask]

    def pad_kinds(self):
        return ["dense", "dense"]

    def emit(self, ctx):
        scores, mask = ctx.take(2)
        return jnp.where(mask, scores, 0.0).astype(jnp.float32), mask


class GeoDistanceNode(PlanNode):
    def __init__(self, flat_docs, lat, lon, center_lat, center_lon, radius_m):
        self.flat_docs = flat_docs
        self.lat = lat
        self.lon = lon
        self.center_lat = np.float32(center_lat)
        self.center_lon = np.float32(center_lon)
        self.radius_m = np.float32(radius_m)

    def key(self):
        return f"geodist[{len(self.flat_docs)}]"

    def arrays(self):
        return [self.flat_docs, self.lat, self.lon, self.center_lat,
                self.center_lon, self.radius_m]

    def pad_kinds(self):
        return ["d", "z", "z", "s", "s", "s"]

    def emit(self, ctx):
        flat_docs, lat, lon, clat, clon, radius = ctx.take(6)
        d = mask_ops.haversine_distance_m(lat, lon, clat, clon)
        return ctx.zeros_f(), ctx.zeros_b().at[flat_docs].max(d <= radius)


class GeoBoxNode(PlanNode):
    def __init__(self, flat_docs, lat, lon, top, left, bottom, right):
        self.flat_docs = flat_docs
        self.lat = lat
        self.lon = lon
        self.box = np.asarray([top, left, bottom, right], dtype=np.float32)

    def key(self):
        return f"geobox[{len(self.flat_docs)}]"

    def arrays(self):
        return [self.flat_docs, self.lat, self.lon, self.box]

    def pad_kinds(self):
        return ["d", "z", "z", "z"]

    def emit(self, ctx):
        flat_docs, lat, lon, box = ctx.take(4)
        top, left, bottom, right = box[0], box[1], box[2], box[3]
        in_lat = (lat <= top) & (lat >= bottom)
        crosses = left > right
        in_lon = jnp.where(crosses, (lon >= left) | (lon <= right),
                           (lon >= left) & (lon <= right))
        return ctx.zeros_f(), ctx.zeros_b().at[flat_docs].max(in_lat & in_lon)


# ---------------------------------------------------------------------------
# Combiners
# ---------------------------------------------------------------------------


class BoolNode(PlanNode):
    """BooleanQuery semantics (org.apache.lucene.search.BooleanQuery as used
    by index/query/BoolQueryBuilder): score = sum of matching scoring
    clauses; filters gate without scoring; minimum_should_match applies to
    should when must/filter present (default 0) else 1."""

    def __init__(self, must: List[PlanNode], filter_: List[PlanNode],
                 should: List[PlanNode], must_not: List[PlanNode],
                 min_should_match: int, boost: float = 1.0):
        self.must = must
        self.filter = filter_
        self.should = should
        self.must_not = must_not
        self.msm = np.float32(min_should_match)
        self.boost = np.float32(boost)

    def key(self):
        return (f"bool[{len(self.must)},{len(self.filter)},{len(self.should)},"
                f"{len(self.must_not)}](" +
                ",".join(c.key() for c in self.children()) + ")")

    def children(self):
        return self.must + self.filter + self.should + self.must_not

    def arrays(self):
        return [self.msm, self.boost]

    def pad_kinds(self):
        return ["s", "s"]

    def emit(self, ctx):
        msm, boost = ctx.take(2)
        matched = ctx.seg["live1"]
        scores = ctx.zeros_f()
        for c in self.must:
            s, m = c.emit(ctx)
            scores = scores + s
            matched = matched & m
        for c in self.filter:
            _, m = c.emit(ctx)
            matched = matched & m
        if self.should:
            s_count = ctx.zeros_f()
            for c in self.should:
                s, m = c.emit(ctx)
                scores = scores + jnp.where(m, s, 0.0)
                s_count = s_count + m.astype(jnp.float32)
            matched = matched & (s_count >= msm)
        for c in self.must_not:
            _, m = c.emit(ctx)
            matched = matched & ~m
        return jnp.where(matched, scores * boost, 0.0).astype(jnp.float32), matched


class ConstantScoreNode(PlanNode):
    def __init__(self, child: PlanNode, boost: float = 1.0):
        self.child = child
        self.boost = np.float32(boost)

    def key(self):
        return f"const({self.child.key()})"

    def children(self):
        return [self.child]

    def arrays(self):
        return [self.boost]

    def pad_kinds(self):
        return ["s"]

    def emit(self, ctx):
        (boost,) = ctx.take(1)
        _, m = self.child.emit(ctx)
        return jnp.where(m, boost, 0.0).astype(jnp.float32), m


class BoostNode(PlanNode):
    def __init__(self, child: PlanNode, boost: float):
        self.child = child
        self.boost = np.float32(boost)

    def key(self):
        return f"boost({self.child.key()})"

    def children(self):
        return [self.child]

    def arrays(self):
        return [self.boost]

    def pad_kinds(self):
        return ["s"]

    def emit(self, ctx):
        (boost,) = ctx.take(1)
        s, m = self.child.emit(ctx)
        return s * boost, m


class DisMaxNode(PlanNode):
    def __init__(self, nodes: List[PlanNode], tie_breaker: float = 0.0):
        self.nodes = nodes
        self.tie_breaker = np.float32(tie_breaker)

    def key(self):
        return "dismax(" + ",".join(c.key() for c in self.nodes) + ")"

    def children(self):
        return self.nodes

    def arrays(self):
        return [self.tie_breaker]

    def pad_kinds(self):
        return ["s"]

    def emit(self, ctx):
        (tie,) = ctx.take(1)
        best = None
        total = ctx.zeros_f()
        matched = ctx.zeros_b()
        for c in self.nodes:
            s, m = c.emit(ctx)
            s = jnp.where(m, s, 0.0)
            best = s if best is None else jnp.maximum(best, s)
            total = total + s
            matched = matched | m
        scores = best + tie * (total - best)
        return scores, matched


class FunctionScoreNode(PlanNode):
    """function_score (index/query/functionscore/): child score combined
    with functions. Round-1 functions: weight, field_value_factor,
    random_score (deterministic hash) — combined multiplicatively; boost_mode
    multiply/replace/sum."""

    MODES = ("multiply", "replace", "sum", "avg", "max", "min")

    def __init__(self, child: PlanNode, factor_columns: List, weight: float,
                 boost_mode: str = "multiply"):
        self.child = child
        self.factor_columns = factor_columns  # list of dense [nd1] f32 factors
        self.weight = np.float32(weight)
        self.boost_mode = boost_mode

    def key(self):
        return f"fscore[{len(self.factor_columns)},{self.boost_mode}]({self.child.key()})"

    def trace_statics(self):
        return (self.boost_mode,)

    def children(self):
        return [self.child]

    def arrays(self):
        return [self.weight] + list(self.factor_columns)

    def pad_kinds(self):
        return ["s"] + ["dense"] * len(self.factor_columns)

    def emit(self, ctx):
        taken = ctx.take(1 + len(self.factor_columns))
        weight, cols = taken[0], taken[1:]
        s, m = self.child.emit(ctx)
        fn = jnp.full_like(s, 1.0) * weight
        for col in cols:
            fn = fn * col
        if self.boost_mode == "multiply":
            out = s * fn
        elif self.boost_mode == "replace":
            out = fn
        elif self.boost_mode == "sum":
            out = s + fn
        elif self.boost_mode == "avg":
            out = (s + fn) / 2.0
        elif self.boost_mode == "max":
            out = jnp.maximum(s, fn)
        else:
            out = jnp.minimum(s, fn)
        return jnp.where(m, out, 0.0).astype(jnp.float32), m


# ---------------------------------------------------------------------------
# Compile + run
# ---------------------------------------------------------------------------


@functools.lru_cache(maxsize=256)
def _compiled_for(structure_key: str, plan_holder) -> "jax.stages.Wrapped":
    plan = plan_holder.plan

    @jax.jit
    def run(seg_arrays, plan_arrays):
        ctx = EmitCtx(seg_arrays, plan_arrays)
        scores, matched = plan.emit(ctx)
        matched = matched & ctx.seg["live1"]
        return scores, matched

    return run


class _PlanHolder:
    """Hashable wrapper so lru_cache keys on the structure string only; the
    held plan is the FIRST plan seen with that structure (same trace)."""

    __slots__ = ("plan", "_key")

    def __init__(self, plan: PlanNode):
        self.plan = plan
        self._key = plan.key()

    def __hash__(self):
        return hash(self._key)

    def __eq__(self, other):
        return isinstance(other, _PlanHolder) and self._key == other._key


def execute(seg_device: dict, plan: PlanNode):
    """Run a plan against one segment's device arrays.

    seg_device must contain block_docs, block_tfs, norms, live1.
    Returns (scores f32[nd1], matched bool[nd1]) on device.
    """
    shape_sig = f"@nd{seg_device['norms'].shape}"
    run = _compiled_for(plan.key() + shape_sig, _PlanHolder(plan))
    return run(seg_device, plan.flat_arrays())
