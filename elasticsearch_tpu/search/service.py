"""Per-shard search execution + cross-shard merge.

Role model: ``SearchService.executeQueryPhase/executeFetchPhase``
(search/SearchService.java:284,459), ``QueryPhase`` (collector assembly),
``FetchPhase`` (+12 sub-phases), and ``SearchPhaseController``
(sortDocs:156, reducedQueryPhase:408, merge:309).

Shapes:
- ``ShardSearcher.query(source)`` runs the query phase on one shard:
  plan -> jitted program per segment -> top-k / sort-key selection ->
  agg partials; returns a ``ShardQueryResult`` (doc refs only, no
  _source — the same contract as QuerySearchResult).
- ``reduce_shard_results`` is the coordinator merge: global top-k across
  shard results + agg tree already reduced via aggregations.run_aggregations.
- ``fetch`` materializes hits (_source filtering, docvalue_fields,
  highlight, sort values).
"""

from __future__ import annotations

import fnmatch
import re
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from elasticsearch_tpu.common.errors import (
    IllegalArgumentException,
    ParsingException,
)
from elasticsearch_tpu.mapper.field_types import TextFieldType
from elasticsearch_tpu.search import plan as P
from elasticsearch_tpu.search.aggregations import (
    SegmentView,
    parse_aggs,
    run_aggregations,
)
from elasticsearch_tpu.search.query_dsl import (
    ShardQueryContext,
    collect_inner_hits,
    parse_query,
)
from elasticsearch_tpu.utils.murmur3 import hash_routing


@dataclass
class DocRef:
    """A hit before fetch: which shard/segment/local doc + ranking keys."""

    shard_id: int
    segment_name: str
    local_doc: int
    score: float
    sort_values: Tuple = ()
    collapse_value: Any = None


@dataclass
class ShardQueryResult:
    shard_id: int
    total_hits: int
    refs: List[DocRef]
    max_score: Optional[float] = None
    # segment views kept for agg execution at reduce time (single-process)
    agg_views: List[SegmentView] = field(default_factory=list)
    # per-segment timing breakdowns when "profile": true
    profile: Optional[List[dict]] = None
    # set (true/false) only when terminate_after was requested
    terminated_early: Optional[bool] = None
    # the shard's deadline expired mid-scan: refs/total cover only the
    # segments finished before the cut (partial, reported timed_out)
    timed_out: bool = False


import logging

_slow_logger = logging.getLogger("elasticsearch_tpu.index.search.slowlog")


def _request_opaque_id(tracer=None) -> Optional[str]:
    """The request's X-Opaque-Id: the tracer annotation when threaded
    (batch members carry it across the leader's thread hop), else the
    REST layer's contextvar."""
    if tracer is not None:
        oid = getattr(tracer, "_annotations", {}).get("opaque_id")
        if oid:
            return str(oid)
    from elasticsearch_tpu.search.telemetry import get_opaque_id

    return get_opaque_id()


def emit_search_slowlog(warn_s, info_s, took_s: float, scope: str,
                        scope_id, plane: str, tracer, source) -> None:
    """The ONE search-slowlog line format (docs/OBSERVABILITY.md):
    shard-level host lines and index-level mesh-plane lines differ only
    in their scope field. Thresholds: warn wins, None = disabled."""
    warn = warn_s is not None and took_s >= warn_s
    info = not warn and info_s is not None and took_s >= info_s
    if not (warn or info):
        return
    log = _slow_logger.warning if warn else _slow_logger.info
    log("took[%dms], %s[%s], plane[%s], id[%s], phases[%s], source[%s]",
        int(took_s * 1000), scope, scope_id, plane,
        _request_opaque_id(tracer) or "",
        tracer.top_phases() if tracer is not None else "",
        str(source)[:512])


def _plan_uses_pallas(node) -> bool:
    """True when any node of the plan scores through the pallas tile
    kernel (vs the XLA scatter program) — the per-segment engine marker
    for the execution-plane counters and the profiler."""
    from elasticsearch_tpu.search.plan import PallasScoreTermsNode

    if isinstance(node, PallasScoreTermsNode):
        return True
    return any(_plan_uses_pallas(c) for c in node.children())


def _mark_fused(tree: dict) -> None:
    """Child nodes of a fused program carry structure only."""
    tree["time_in_nanos"] = 0
    tree["breakdown"] = {"fused_into_parent_program": 0}
    for child in tree.get("children", []):
        _mark_fused(child)


class ShardSearcher:
    """Query-phase execution for one shard."""

    def __init__(self, shard_id: int, engine, mapper_service,
                 slowlog_warn_s: Optional[float] = None,
                 slowlog_info_s: Optional[float] = None,
                 index_name: str = ""):
        import threading

        self.shard_id = shard_id
        self.index_name = index_name
        self.engine = engine
        self.mapper_service = mapper_service
        self.ctx = ShardQueryContext(mapper_service, engine=engine)
        # counter updates must not lose increments under concurrent
        # searches (host threads + mesh/batch leaders all attribute
        # per-shard stats here — docs/OBSERVABILITY.md)
        self._stats_lock = threading.Lock()
        self.query_total = 0
        self.query_time = 0.0
        self.fetch_total = 0
        # execution-plane observability (VERDICT r4 weak 3): which engine
        # scored each segment — the pallas tile kernel or the XLA scatter
        # program — exported via _stats/_nodes/stats and the profiler
        self.pallas_segments_total = 0
        self.scatter_segments_total = 0
        # per-group search stats ("stats": ["grp"] in request bodies —
        # index/search/stats/SearchStats groupStats)
        self.group_stats: Dict[str, dict] = {}
        # search slow log (index/SearchSlowLog.java): per-shard thresholds;
        # negative = disabled (the "-1" sentinel)
        self.slowlog_warn_s = (
            slowlog_warn_s if slowlog_warn_s is not None and slowlog_warn_s >= 0
            else None
        )
        self.slowlog_info_s = (
            slowlog_info_s if slowlog_info_s is not None and slowlog_info_s >= 0
            else None
        )

    def record_query_groups(self, groups) -> None:
        """Count one query against each requested stats group (shared by
        the host path and the mesh path)."""
        with self._stats_lock:
            for g in groups or []:
                gs = self.group_stats.setdefault(str(g), {
                    "query_total": 0, "query_time_in_millis": 0,
                    "fetch_total": 0, "fetch_time_in_millis": 0})
                gs["query_total"] += 1

    def note_query(self, groups=None) -> None:
        """Attribute one mesh/batch-served query to this shard's stats
        (the mesh executes all shards as one program, but per-shard
        SearchStats stay truthful); lost-increment-safe under the
        concurrent batch leaders of ISSUE 5/8."""
        with self._stats_lock:
            self.query_total += 1
        self.record_query_groups(groups)

    def _maybe_slowlog(self, took_s: float, source: dict,
                       tracer=None, plane: str = "host") -> None:
        emit_search_slowlog(self.slowlog_warn_s, self.slowlog_info_s,
                            took_s, "shard", self.shard_id, plane,
                            tracer, source)

    # ------------------------------------------------------------------

    def query(self, source: dict, size_hint: Optional[int] = None,
              segments=None, deadline=None,
              score_cache: Optional[Dict[str, Tuple]] = None,
              tracer=None,
              ) -> ShardQueryResult:
        """segments: optional explicit segment list (point-in-time views
        pinned by an open scroll context — search/internal/ScrollContext);
        None searches the engine's current NRT segment set.
        deadline: optional SearchDeadline — checkpointed between segments;
        expiry stops the scan and returns the accumulated partial result
        with timed_out=True, cancellation raises TaskCancelledException.
        score_cache: {segment_name: (scores [nd1] f32, matched [nd1]
        bool)} precomputed by a cross-query batched kernel launch
        (search/batching.py) — a cached segment skips plan execution and
        feeds the identical per-query downstream pipeline (min_score,
        selection, aggs, post_filter, rescore).
        tracer: QueryTracer — host-plane phase spans (parse_rewrite,
        staging, plan_build, kernel, merge) accumulated per segment;
        always-on and bounded (docs/OBSERVABILITY.md)."""
        from elasticsearch_tpu.search.telemetry import NULL_TRACER
        from elasticsearch_tpu.testing.disruption import on_shard_search

        if tracer is None:
            tracer = NULL_TRACER
        t0 = time.monotonic()
        with self._stats_lock:
            self.query_total += 1
        # query-path fault injection (SearchDelayScheme / SearchFailScheme)
        on_shard_search(self.index_name, self.shard_id)
        source = source or {}
        self.record_query_groups(source.get("stats"))
        t_parse = tracer.start("parse_rewrite")
        from_ = int(source.get("from", 0) or 0)
        size = int(source.get("size", 10) if source.get("size") is not None else 10)
        k = size_hint if size_hint is not None else from_ + size
        k = max(k, 1)
        qb = parse_query(source.get("query"))
        post_qb = parse_query(source["post_filter"]) if source.get("post_filter") else None
        min_score = source.get("min_score")
        sort_spec = normalize_sort(source.get("sort"))
        search_after = source.get("search_after")
        # shard-level collapse (CollapsingTopDocsCollector analog): every
        # group's shard-best must survive to the coordinator, so selection
        # is uncapped and collapsed to k groups per shard
        collapse_field = validate_collapse(source)
        slice_spec = source.get("slice")
        rescore_specs = _normalize_rescore(source.get("rescore"))
        profile = bool(source.get("profile", False))
        k_select = k
        if rescore_specs:
            k_select = max(k, max(r["window_size"] for r in rescore_specs))
        tracer.stop("parse_rewrite", t_parse)

        # sorted-index early termination (QueryPhase.java:107): when the
        # query sort is a prefix of the index sort, segment doc order IS
        # sort order — select the first k matching docs instead of a
        # keyed top-k pass
        from elasticsearch_tpu.index.index_sort import query_sort_matches_index_sort

        index_sorted = (
            search_after is None
            and query_sort_matches_index_sort(
                sort_spec, getattr(self.engine, "index_sort", None),
                mapper_service=self.mapper_service)
        )

        refs: List[DocRef] = []
        total = 0
        max_score = None
        agg_views: List[SegmentView] = []
        agg_specs = parse_aggs(source.get("aggs") or source.get("aggregations"))
        profile_shards = []

        timed_out = False
        for seg in (segments if segments is not None
                    else self.engine.searchable_segments()):
            if deadline is not None:
                from elasticsearch_tpu.search.cancellation import (
                    TimeExceededException,
                )

                try:
                    deadline.checkpoint()
                except TimeExceededException:
                    # accumulated segments stand; the scan stops here
                    # (QueryPhase timeout contract: partial + timed_out)
                    timed_out = True
                    break
            t_seg = time.monotonic()
            t_stage = tracer.start("staging")
            dev = seg.device_arrays()
            tracer.stop("staging", t_stage)
            cached = (score_cache.get(seg.name)
                      if score_cache and not profile else None)
            if cached is not None:
                # scored by a batched kernel launch shared with the other
                # members of this query's micro-batch (the batched analog
                # of the pallas plane below)
                scores, matched = cached
                with self._stats_lock:
                    self.pallas_segments_total += 1
                t_build = t_exec = time.monotonic()
            else:
                t_plan = tracer.start("plan_build")
                node = qb.to_plan(self.ctx, seg)
                tracer.stop("plan_build", t_plan)
                used_pallas = _plan_uses_pallas(node)
                with self._stats_lock:
                    if used_pallas:
                        self.pallas_segments_total += 1
                    else:
                        self.scatter_segments_total += 1
                t_build = time.monotonic()
                t_kernel = tracer.start("kernel")
                scores_d, matched_d = P.execute(dev, node)
                scores = np.asarray(scores_d)
                matched = np.asarray(matched_d)
                tracer.stop("kernel", t_kernel)
                t_exec = time.monotonic()
            live1 = np.concatenate([seg.live, np.zeros(1, bool)])
            matched = matched & live1
            if min_score is not None:
                matched = matched & (scores >= float(min_score))
            if slice_spec is not None:
                resolved = resolve_slice(
                    dict(slice_spec,
                         _limit=getattr(self, "max_slices", 1024)),
                    self.shard_id, getattr(self, "num_shards", 1))
                if resolved == "skip":
                    matched = np.zeros_like(matched)
                elif resolved is not None:
                    matched = matched & self._slice_mask(seg, resolved)
            if agg_views is not None and agg_specs:
                agg_views.append(SegmentView(seg, matched.copy(), self.ctx, scores))
            if post_qb is not None:
                _, post_m = P.execute(dev, post_qb.to_plan(self.ctx, seg))
                matched = matched & np.asarray(post_m)
            total += int(matched[: seg.num_docs].sum())
            t_merge = tracer.start("merge")
            if collapse_field:
                seg_refs = self._select_all(seg, scores, matched, sort_spec)
            else:
                seg_refs = self._select(seg, scores, matched, sort_spec,
                                        search_after, k_select,
                                        index_sorted=index_sorted)
            if rescore_specs and sort_spec is None:
                seg_refs = self._rescore(seg, dev, seg_refs, rescore_specs)
            tracer.stop("merge", t_merge)
            refs.extend(seg_refs)
            if seg_refs and sort_spec is None:
                m = max(r.score for r in seg_refs)
                max_score = m if max_score is None else max(max_score, m)
            if profile:
                t_end = time.monotonic()
                tree = node.describe()
                for child in tree.get("children", []):
                    _mark_fused(child)
                tree.update({
                    # which engine scored this segment (SURVEY §5.1:
                    # per-kernel observability)
                    "engine": ("pallas_tile_kernel" if used_pallas
                               else "xla_scatter"),
                    "description": str(source.get("query",
                                                  {"match_all": {}})),
                    "time_in_nanos": int((t_exec - t_build) * 1e9),
                    "breakdown": {
                        # the plan is ONE fused device program; these are
                        # the real pipeline stages around it (SURVEY §5.1:
                        # per-kernel timing in place of the reference's
                        # create_weight/next_doc/score counters)
                        "build_plan": int((t_build - t_seg) * 1e9),
                        "execute_program": int((t_exec - t_build) * 1e9),
                        "select_topk": int((t_end - t_exec) * 1e9),
                    },
                })
                profile_shards.append({
                    "id": f"[{self.shard_id}][{seg.name}]",
                    # the data plane that served this shard's query phase
                    # (profile requests always run host-merge; the mesh
                    # plane's usage is visible in _stats planes counters)
                    "plane": "host",
                    "searches": [{
                        "query": [tree],
                        "collector": [{
                            "name": "TopKSelector",
                            "reason": "search_top_hits",
                            "time_in_nanos": int((t_end - t_exec) * 1e9),
                        }],
                    }],
                })

        t_merge = tracer.start("merge")
        if collapse_field:
            refs = merge_refs(refs, sort_spec, len(refs))
            refs = collapse_refs(refs, collapse_field, {self.shard_id: self})[:k]
        else:
            refs = merge_refs(refs, sort_spec, k_select if rescore_specs else k)
        tracer.stop("merge", t_merge)
        if rescore_specs and sort_spec is None:
            refs.sort(key=lambda r: (-r.score, r.local_doc))
            refs = refs[:k]
            if refs:
                max_score = refs[0].score
        terminate_after = source.get("terminate_after")
        terminated_early = None
        if terminate_after:
            # exhaustive execution cannot stop mid-scan; cap the reported
            # total + set terminated_early (the observable contract)
            terminated_early = total >= int(terminate_after)
            total = min(total, int(terminate_after))
        elif index_sorted and total > k:
            # index-sort early termination: collection stopped after k
            # docs per segment. Unlike the reference, the dense-mask
            # execution knows the exact total for free, so it stays
            # accurate while terminated_early is reported.
            terminated_early = True
        result = ShardQueryResult(self.shard_id, total, refs, max_score, agg_views,
                                  terminated_early=terminated_early,
                                  timed_out=timed_out)
        if profile:
            result.profile = profile_shards
        took = time.monotonic() - t0
        with self._stats_lock:
            self.query_time += took
        self._maybe_slowlog(took, source, tracer=tracer, plane="host")
        return result

    def _rescore(self, seg, dev, seg_refs: List[DocRef],
                 rescore_specs: List[dict]) -> List[DocRef]:
        """QueryRescorer (search/rescore/QueryRescorer.java): re-rank the
        top-window hits by combining the original score with the rescore
        query's score. Window applies per shard, like the reference."""
        for spec in rescore_specs:
            window = spec["window_size"]
            rqb = parse_query(spec["rescore_query"])
            r_scores = np.asarray(P.execute(dev, rqb.to_plan(self.ctx, seg))[0])
            qw, rqw = spec["query_weight"], spec["rescore_query_weight"]
            mode = spec["score_mode"]
            for ref in seg_refs[:window]:
                rs = float(r_scores[ref.local_doc])
                base = ref.score * qw
                resc = rs * rqw
                if mode == "total":
                    ref.score = base + resc
                elif mode == "multiply":
                    ref.score = base * rs if rs else base
                elif mode == "avg":
                    ref.score = (base + resc) / 2.0
                elif mode == "max":
                    ref.score = max(base, resc)
                elif mode == "min":
                    ref.score = min(base, resc)
                ref.sort_values = (ref.score,)
        seg_refs.sort(key=lambda r: (-r.score, r.local_doc))
        return seg_refs

    # ------------------------------------------------------------------

    def _slice_mask(self, seg, slice_spec: dict) -> np.ndarray:
        """Sliced scroll partitions (search/slice/SliceBuilder): docs
        partitioned by murmur3(_id) % max == id."""
        sid = int(slice_spec["id"])
        smax = int(slice_spec["max"])
        key = f"slice.{smax}.{sid}"
        if key not in seg.dev_cache:
            from elasticsearch_tpu.utils.murmur3 import hash_slice_id

            mask = np.zeros(seg.nd_pad + 1, dtype=bool)
            for local, doc_id in enumerate(seg.doc_ids):
                if hash_slice_id(doc_id) % smax == sid:
                    mask[local] = True
            seg.dev_cache[key] = mask
        return seg.dev_cache[key]

    def _select_all(self, seg, scores, matched, sort_spec) -> List[DocRef]:
        """Uncapped selection of every matching doc, ordered by the
        request's sort — the collapse path needs the full candidate set so
        no group's best doc is cut by a top-k window. (search_after is
        rejected with collapse upstream, so no cursor masking here.)"""
        live_matched = matched[: seg.nd_pad] & seg.live
        idx = np.flatnonzero(live_matched)
        if sort_spec is None:
            out = [DocRef(self.shard_id, seg.name, int(d), float(scores[d]),
                          (float(scores[d]),)) for d in idx]
            out.sort(key=lambda r: (-r.score, r.local_doc))
            return out
        keys, all_key_arrays = self._sort_keys(seg, scores, sort_spec)
        out = [DocRef(self.shard_id, seg.name, int(d), float(scores[d]),
                      tuple(arr[d] for arr in all_key_arrays)) for d in idx]
        sort_refs(out, sort_spec)
        return out

    def _select(self, seg, scores, matched, sort_spec, search_after, k,
                index_sorted: bool = False) -> List[DocRef]:
        import jax.numpy as jnp

        nd = seg.num_docs
        if index_sorted and sort_spec is not None:
            # doc order is sort order: take the first k matching docs;
            # sort values still materialize for the cross-segment merge
            live_matched = matched[: seg.nd_pad] & seg.live
            idx = np.flatnonzero(live_matched)[:k]
            _, all_key_arrays = self._sort_keys(seg, scores, sort_spec)
            return [
                DocRef(self.shard_id, seg.name, int(d), float(scores[d]),
                       tuple(arr[d] for arr in all_key_arrays))
                for d in idx
            ]
        if sort_spec is None:
            # relevance: device top-k by score
            if search_after is not None:
                cutoff = float(search_after[0])
                matched = matched & (scores < cutoff)
            top_scores, top_docs = P_select_topk(scores, matched, k)
            out = []
            for s, d in zip(np.asarray(top_scores), np.asarray(top_docs)):
                if s == -np.inf:
                    break
                out.append(DocRef(self.shard_id, seg.name, int(d), float(s), (float(s),)))
            return out

        # field sort: build primary key vector; select by key; host refine
        keys, all_key_arrays = self._sort_keys(seg, scores, sort_spec)
        primary = keys[0]
        if search_after is not None:
            matched = matched & _search_after_mask(all_key_arrays, sort_spec, search_after)
        masked = np.where(matched[: seg.nd_pad] & seg.live, primary, -np.inf)
        kk = min(k, masked.size)
        idx = np.argpartition(-masked, kk - 1)[:kk] if kk < masked.size else np.arange(masked.size)
        cand = [(int(d),) for d in idx if masked[d] != -np.inf]
        out = []
        for (d,) in cand:
            sv = tuple(arr[d] for arr in all_key_arrays)
            out.append(DocRef(self.shard_id, seg.name, d, float(scores[d]), sv))
        sort_refs(out, sort_spec)
        return out[:k]

    def _sort_keys(self, seg, scores, sort_spec):
        """Returns (oriented primary key array [nd_pad], raw per-field value
        arrays for sort_values output)."""
        raw_arrays = []
        oriented = []
        for entry in sort_spec:
            field_name, order, missing = entry
            if field_name == "_score":
                raw = scores[: seg.nd_pad].astype(np.float64)
            elif field_name == "_doc":
                raw = np.arange(seg.nd_pad, dtype=np.float64)
            elif field_name == "_geo_distance":
                raw = _geo_distance_sort_values(seg, missing)
            else:
                col = seg.numeric_columns.get(field_name)
                nested_raw = (None if col is not None
                              else _nested_sort_values(seg, field_name, order, missing))
                if col is not None:
                    base = col.min_value if order == "asc" else col.max_value
                    fill = _missing_fill(missing, order)
                    raw = np.where(col.exists, base, fill)
                elif nested_raw is not None:
                    raw = nested_raw
                else:
                    ocol = seg.ordinal_columns.get(field_name) or seg.ordinal_columns.get(
                        f"{field_name}.keyword"
                    )
                    ft = self.mapper_service.field_type(field_name)
                    string_typed = (ocol is not None or (
                        ft is not None
                        and getattr(ft, "ordinal_doc_values", False)))
                    if not string_typed:
                        # numeric/unmapped: float fill (custom missing must
                        # be a number here)
                        fill = _missing_fill(missing, order)
                        raw = np.full(seg.nd_pad, fill, dtype=np.float64)
                    elif ocol is None:
                        # keyword-typed field with NO column in this
                        # segment: every doc is missing — values must stay
                        # STRINGS so the cross-segment merge never mixes
                        # floats into a string sort
                        sfill = _missing_fill_str(missing, order)
                        raw = np.full(seg.nd_pad, sfill, dtype=object)
                        fillf = (np.inf if sfill == _STR_SENTINEL_HIGH
                                 else -np.inf)
                        key = fillf if order == "desc" else -fillf
                        oriented.append(np.full(
                            seg.nd_pad, float(np.clip(key, -1e300, 1e300))))
                        raw_arrays.append(raw)
                        continue
                    else:
                        # ordinals order the SELECTION within this segment
                        # (local ord order == string order), but the merge
                        # across segments/shards must compare the STRINGS:
                        # ordinal spaces are per-segment, so an ordinal
                        # sort value from one segment is meaningless next
                        # to another's (the global-ordinals problem).
                        # A custom string `missing` ranks at its bisect
                        # position between ordinals (exactly where the
                        # string sorts).
                        if missing in (None, "_last", "_first"):
                            fill = _missing_fill(missing, order)
                        else:
                            import bisect as _bisect

                            pos = _bisect.bisect_left(ocol.terms,
                                                      str(missing))
                            fill = pos - 0.5
                        ord_key = np.where(
                            ocol.exists, ocol.first_ord.astype(np.float64),
                            fill)
                        sfill = _missing_fill_str(missing, order)
                        cache_key = (f"sortstr.{field_name}.{order}."
                                     f"{missing!r}")
                        raw = seg.dev_cache.get(cache_key)
                        if raw is None:
                            terms_arr = np.asarray(ocol.terms + [sfill],
                                                   dtype=object)
                            raw = terms_arr[np.where(
                                ocol.exists, ocol.first_ord,
                                len(ocol.terms))]
                            seg.dev_cache[cache_key] = raw
                        raw_arrays.append(raw)
                        oriented.append(np.clip(
                            ord_key if order == "desc" else -ord_key,
                            -1e300, 1e300))
                        continue
            raw_arrays.append(raw)
            # clamp ±inf (missing-value fills) to large finite sentinels:
            # -inf in the oriented key is reserved for "not matched", and a
            # missing-value doc in an asc sort must still be selectable
            oriented.append(np.clip(raw if order == "desc" else -raw,
                                    -1e300, 1e300))
        return oriented, raw_arrays


def _geo_distance_sort_values(seg, spec: dict) -> np.ndarray:
    """Per-doc haversine distance to the reference point(s), multi-values
    reduced per `mode` (GeoDistanceSortBuilder semantics, arc distance);
    over multiple reference points the min distance per value is used;
    docs without the field sort last (+inf)."""
    col = seg.geo_columns.get(spec["field"])
    mode = spec.get("mode", "min")
    out = np.full(seg.nd_pad, np.inf, dtype=np.float64)
    if col is not None:
        n = col.count
        lat = np.radians(col.lat[:n].astype(np.float64))
        lon = np.radians(col.lon[:n].astype(np.float64))
        # per stored value: min distance over the reference points
        per_val = np.full(n, np.inf, dtype=np.float64)
        for plat, plon in spec["points"]:
            plat_r, plon_r = np.radians(plat), np.radians(plon)
            a = (np.sin((lat - plat_r) / 2.0) ** 2
                 + np.cos(lat) * np.cos(plat_r) * np.sin((lon - plon_r) / 2.0) ** 2)
            d = 2.0 * 6371008.7714 * np.arcsin(np.sqrt(np.clip(a, 0.0, 1.0)))
            per_val = np.minimum(per_val, d)
        docs = col.flat_docs[:n]
        if mode == "min":
            np.minimum.at(out, docs, per_val)
        elif mode == "max":
            neg = np.full(seg.nd_pad, -np.inf, dtype=np.float64)
            np.maximum.at(neg, docs, per_val)
            out = np.where(np.isfinite(neg), neg, np.inf)
        else:  # sum / avg
            tot = np.zeros(seg.nd_pad, dtype=np.float64)
            cnt = np.zeros(seg.nd_pad, dtype=np.float64)
            np.add.at(tot, docs, per_val)
            np.add.at(cnt, docs, 1.0)
            vals = tot / np.maximum(cnt, 1.0) if mode == "avg" else tot
            out = np.where(cnt > 0, vals, np.inf)
    return out / float(spec["unit_m"])


def _nested_sort_values(seg, field_name: str, order: str, missing):
    """Sort key for a field that lives under a nested path: reduce each
    parent's nested-object values with min (asc) / max (desc) — the
    reference's nested sort with the default mode (FieldSortBuilder
    nested handling). The nested path is auto-detected from the field
    prefix (the 6.x `nested_path` spec is accepted and implied)."""
    for path, nctx in seg.nested.items():
        if not field_name.startswith(path + "."):
            continue
        ncol = nctx.segment.numeric_columns.get(field_name)
        if ncol is None:
            return None
        n = nctx.parent_of.shape[0]
        fill = _missing_fill(missing, order)
        vals = (ncol.min_value if order == "asc" else ncol.max_value)[:n]
        sel = ncol.exists[:n] & nctx.segment.live[:n]
        out = np.full(seg.nd_pad, np.inf if order == "asc" else -np.inf,
                      dtype=np.float64)
        if order == "asc":
            np.minimum.at(out, nctx.parent_of[sel], vals[sel])
        else:
            np.maximum.at(out, nctx.parent_of[sel], vals[sel])
        has = np.zeros(seg.nd_pad, dtype=bool)
        has[nctx.parent_of[sel]] = True
        return np.where(has, out, fill)
    return None


def P_select_topk(scores, matched, k):
    import jax.numpy as jnp

    from elasticsearch_tpu.ops.scoring import select_topk

    live1 = jnp.ones(scores.shape, bool)  # matched already includes live
    return select_topk(jnp.asarray(scores), jnp.asarray(matched), live1, int(k))


def _sort_value_out(v):
    """Sort value -> response form: missing fills (inf floats / string
    sentinels) render as null."""
    if isinstance(v, str):
        return None if v in (_STR_SENTINEL_HIGH, _STR_SENTINEL_LOW) else v
    return v if not np.isinf(v) else None


def _missing_fill(missing, order) -> float:
    if missing in (None, "_last"):
        return -np.inf if order == "desc" else np.inf
    if missing == "_first":
        return np.inf if order == "desc" else -np.inf
    return float(missing)


# string-sort missing sentinels: HIGH sorts after every practical term,
# LOW (a NUL) before; both render as null in sort-value output
_STR_SENTINEL_HIGH = "\U0010ffff\U0010ffff\U0010ffff\U0010ffff"
_STR_SENTINEL_LOW = "\x00"


def _missing_fill_str(missing, order) -> str:
    if missing in (None, "_last"):
        # "_last" = end of the RESULT order: largest for asc, smallest
        # for desc
        return _STR_SENTINEL_HIGH if order == "asc" else _STR_SENTINEL_LOW
    if missing == "_first":
        return _STR_SENTINEL_LOW if order == "asc" else _STR_SENTINEL_HIGH
    return str(missing)


def multi_pass_sort(items, sort_spec, values_of, tiebreak=None):
    """Stable multi-pass sort over per-field sort values.

    Strings can't be negated for desc the way floats can (and per-
    segment ORDINALS must never be merge keys — spaces differ), so
    instead of one composite key the list is sorted once per field from
    the least-significant up, relying on sort stability — every pass
    keeps O(n) key extraction. A tiebreak key, when given, runs first
    (least significant). Mixed value types within one field (keyword in
    one index, numeric/unmapped in another) are a request error, as in
    the reference."""
    if tiebreak is not None:
        items.sort(key=tiebreak)
    try:
        for i in reversed(range(len(sort_spec))):
            _f, order, _m = sort_spec[i]
            items.sort(key=lambda x, i=i: values_of(x)[i],
                       reverse=order == "desc")
    except TypeError:
        raise IllegalArgumentException(
            "can't sort across indices mapping the sort field to "
            "different types (string vs numeric)") from None


def sort_refs(refs: List[DocRef], sort_spec,
              with_shard: bool = False) -> None:
    multi_pass_sort(
        refs, sort_spec, lambda r: r.sort_values,
        tiebreak=(lambda r: (r.shard_id, r.local_doc)) if with_shard
        else (lambda r: r.local_doc))


def _search_after_mask(key_arrays, sort_spec, after_values) -> np.ndarray:
    """Strict lexicographic 'after' filter over full sort tuples."""
    n = key_arrays[0].shape[0]
    gt = np.zeros(n, dtype=bool)
    eq = np.ones(n, dtype=bool)
    for arr, (fname, order, missing), after in zip(key_arrays, sort_spec,
                                                   after_values):
        # a null cursor value is a missing-value doc's sort key (fetch
        # serializes the fill as null): map back to the fill
        if arr.dtype == object:  # keyword sort: string comparisons
            a = (_missing_fill_str(missing, order) if after is None
                 else str(after))
        elif isinstance(missing, dict):
            # _geo_distance: the missing slot carries the geo spec, and
            # missing-geo docs ALWAYS fill +inf regardless of order
            a = np.inf if after is None else float(after)
        else:
            a = (_missing_fill(missing, order)
                 if after is None else float(after))
        if order == "desc":
            gt |= eq & (arr < a)
        else:
            gt |= eq & (arr > a)
        eq &= arr == a
    mask = np.concatenate([gt, np.zeros(1, dtype=bool)])
    return mask


def resolve_slice(spec: dict, shard_id: int, num_shards: int):
    """SliceBuilder.toFilter's shard-aware slice resolution
    (search/slice/SliceBuilder.java:195-255). Returns:
    - "skip": this shard is not part of the slice (MatchNoDocsQuery)
    - None: the whole shard belongs to the slice (MatchAllDocsQuery)
    - {"id", "max"}: doc-hash partition to apply within the shard
    The three regimes: single shard → plain doc hash; max >= shards →
    shards round-robin over slices with an intra-shard sub-partition;
    max < shards → whole shards grouped per slice, no doc hashing."""
    sid, smax = int(spec["id"]), int(spec["max"])
    if smax <= 1:
        raise IllegalArgumentException("max must be greater than 1")
    if sid < 0 or sid >= smax:
        raise IllegalArgumentException(
            f"id must be in [0, {smax}), got {sid}")
    limit = int(spec.get("_limit", 1024))
    if smax > limit:
        from elasticsearch_tpu.common.errors import (
            QueryPhaseExecutionException,
        )

        raise QueryPhaseExecutionException(
            f"The number of slices [{smax}] is too large. It must be "
            f"less than [{limit}]. This limit can be set by changing "
            f"the [index.max_slices_per_scroll] index level setting.")
    if num_shards == 1:
        return {"id": sid, "max": smax}
    if smax >= num_shards:
        target = sid % num_shards
        if target != shard_id:
            return "skip"
        n_in_shard = smax // num_shards + (
            1 if smax % num_shards > target else 0)
        if n_in_shard == 1:
            return None
        return {"id": sid // num_shards, "max": n_in_shard}
    return None if shard_id % smax == sid else "skip"


def _normalize_rescore(body) -> List[dict]:
    """rescore body -> list of {window_size, rescore_query, weights, mode}."""
    if body is None:
        return []
    specs = body if isinstance(body, list) else [body]
    out = []
    for spec in specs:
        q = spec.get("query") or {}
        out.append({
            "window_size": int(spec.get("window_size", 10)),
            "rescore_query": q.get("rescore_query"),
            "query_weight": float(q.get("query_weight", 1.0)),
            "rescore_query_weight": float(q.get("rescore_query_weight", 1.0)),
            "score_mode": q.get("score_mode", "total"),
        })
    return out


def collapse_refs(refs: List["DocRef"], field_name: str, shards: Dict) -> List["DocRef"]:
    """Field collapsing (search/collapse/CollapseContext): keep the best hit
    per distinct field value, preserving result order."""
    seen = set()
    out = []
    for ref in refs:
        shard = shards[ref.shard_id]
        seg = next((s for s in shard.engine.segments if s.name == ref.segment_name), None)
        if seg is None:
            continue
        value = None
        col = seg.numeric_columns.get(field_name)
        if col is not None and col.exists[ref.local_doc]:
            value = float(col.first_value[ref.local_doc])
        else:
            ocol = seg.ordinal_columns.get(field_name) or seg.ordinal_columns.get(
                f"{field_name}.keyword"
            )
            if ocol is not None and ocol.exists[ref.local_doc]:
                value = ocol.terms[ocol.first_ord[ref.local_doc]]
        if value in seen:
            continue
        seen.add(value)
        ref.collapse_value = value
        out.append(ref)
    return out


def expand_collapsed_hits(hits: List[dict], refs: List["DocRef"],
                          collapse_body: dict, body: dict, search_fn) -> None:
    """ExpandSearchPhase (action/search/ExpandSearchPhase.java:44): attach
    the collapse value to each hit's fields and, when the collapse declares
    inner_hits, run one group sub-search (original query AND group-value
    filter) per top hit per spec via ``search_fn(sub_body) -> response``."""
    from elasticsearch_tpu.common.errors import IllegalArgumentException

    field = collapse_body["field"]
    specs = collapse_body.get("inner_hits")
    if isinstance(specs, dict):
        specs = [specs]
    if specs:
        names = [spec.get("name", field) for spec in specs]
        dupes = {n for n in names if names.count(n) > 1}
        if dupes:
            raise IllegalArgumentException(
                f"[inner_hits] already contains an entry for key [{dupes.pop()}]")
    orig_query = body.get("query") or {"match_all": {}}
    for hit, ref in zip(hits, refs):
        value = ref.collapse_value
        hit.setdefault("fields", {})[field] = [value]
        if not specs:
            continue
        if value is None:
            group_filter = {"bool": {"must_not": [{"exists": {"field": field}}]}}
        else:
            group_filter = {"term": {field: value}}
        for spec in specs:
            name = spec.get("name", field)
            sub = {
                "query": {"bool": {"must": [orig_query],
                                   "filter": [group_filter]}},
                "from": int(spec.get("from", 0)),
                # InnerHitBuilder default size = 3
                "size": int(spec.get("size", 3)),
            }
            for key in ("sort", "_source", "docvalue_fields", "script_fields",
                        "stored_fields", "version", "highlight"):
                if key in spec:
                    sub[key] = spec[key]
            hit.setdefault("inner_hits", {})[name] = {
                "hits": search_fn(sub)["hits"]}


def validate_collapse(body: dict) -> Optional[str]:
    """Body-shape validation for collapse, run BEFORE shard execution
    (SearchService createContext checks). Returns the collapse field or
    None."""
    from elasticsearch_tpu.common.errors import IllegalArgumentException

    collapse_field = (body.get("collapse") or {}).get("field")
    if collapse_field and body.get("search_after") is not None:
        raise IllegalArgumentException(
            "cannot use `collapse` in conjunction with `search_after`")
    if collapse_field and body.get("rescore"):
        raise IllegalArgumentException(
            "cannot use `collapse` in conjunction with `rescore`")
    return collapse_field


def normalize_sort(sort_body) -> Optional[List[Tuple[str, str, Any]]]:
    """-> list of (field, order, missing), or None for relevance."""
    if sort_body is None:
        return None
    if not isinstance(sort_body, list):
        sort_body = [sort_body]
    out = []
    for entry in sort_body:
        if isinstance(entry, str):
            if entry == "_score":
                out.append(("_score", "desc", None))
            else:
                out.append((entry, "asc" if entry != "_score" else "desc", None))
        elif isinstance(entry, dict):
            ((fname, spec),) = entry.items()
            if fname == "_geo_distance":
                # geo-distance sort (search/sort/GeoDistanceSortBuilder):
                # the geo spec rides in the missing slot of the tuple
                from elasticsearch_tpu.mapper.field_types import GeoPointFieldType
                from elasticsearch_tpu.search.query_dsl import parse_distance

                params = dict(spec)
                order = params.pop("order", "asc")
                unit = params.pop("unit", "m")
                # multi-valued reduce mode: the reference defaults to MIN
                # for asc, MAX for desc (GeoDistanceSortBuilder.build)
                mode = params.pop("mode", "min" if order == "asc" else "max")
                if mode not in ("min", "max", "sum", "avg"):
                    raise ParsingException(
                        f"Unsupported sort mode [{mode}] for [_geo_distance]")
                for k in ("distance_type", "validation_method",
                          "ignore_unmapped", "nested_path", "nested"):
                    params.pop(k, None)
                if len(params) != 1:
                    raise ParsingException(
                        "[_geo_distance] sort requires exactly one field")
                ((gfield, pts),) = params.items()
                if not isinstance(pts, list) or (
                        pts and isinstance(pts[0], (int, float))):
                    pts = [pts]
                out.append(("_geo_distance", order, {
                    "field": gfield,
                    "points": [GeoPointFieldType.parse_point(p) for p in pts],
                    "unit_m": parse_distance(f"1{unit}"),
                    "mode": mode,
                }))
            elif isinstance(spec, str):
                out.append((fname, spec, None))
            else:
                out.append((
                    fname,
                    spec.get("order", "desc" if fname == "_score" else "asc"),
                    spec.get("missing"),
                ))
        else:
            raise ParsingException(f"malformed sort entry {entry!r}")
    if len(out) == 1 and out[0][0] == "_score":
        return None  # plain relevance
    return out


def allow_partial_results(body: dict) -> bool:
    """Request-level allow_partial_search_results. The coordinator
    injects the node default (`search.default_allow_partial_results`)
    when the request leaves it unset; bare shard-level callers default
    to the reference's true."""
    v = (body or {}).get("allow_partial_search_results")
    if v is None:
        return True
    if isinstance(v, str):
        return v.lower() != "false"
    return bool(v)


def expired_queue_response(index_name: str, n_shards: int,
                           body: dict) -> dict:
    """The partial response for a search whose deadline expired while
    it was still QUEUED in the admission plane (docs/OVERLOAD.md): it
    is shed before execution — no staging, no launch, no shard work —
    and serves the same timed-out degradation the query phase would
    have produced at its first checkpoint (the PR-4 contract). Shards
    count successful: none failed, none ran. allow_partial_search_
    results=false keeps its error contract instead."""
    if not allow_partial_results(body):
        from elasticsearch_tpu.common.errors import (
            SearchPhaseExecutionException,
        )

        raise SearchPhaseExecutionException(
            "query",
            "Partial shards failure (request timed out in the search "
            "admission queue)", [])
    return {
        "took": 0,
        "timed_out": True,
        "_plane": "none",
        "_degraded": ["expired_in_queue"],
        "_shards": {"total": n_shards, "successful": n_shards,
                    "skipped": 0, "failed": 0},
        "hits": {"total": 0, "max_score": None, "hits": []},
    }


def shard_failure_entry(index: str, shard_id, exc: Exception,
                        node: Optional[str] = None) -> dict:
    """One failures[] entry (ShardSearchFailure.toXContent shape): the
    per-shard exception serialized with its type + reason so a partial
    response still explains WHICH shard failed and why."""
    from elasticsearch_tpu.common.errors import (
        ElasticsearchTpuException,
        es_type_name,
    )

    if isinstance(exc, ElasticsearchTpuException):
        reason = {"type": exc.error_type, "reason": exc.reason}
    else:
        reason = {"type": es_type_name(type(exc).__name__),
                  "reason": str(exc)}
    entry = {"shard": shard_id, "index": index, "reason": reason}
    if node is not None:
        entry["node"] = node
    return entry


def merge_refs(refs: List[DocRef], sort_spec, k: int) -> List[DocRef]:
    """Coordinator-side top-k merge (SearchPhaseController.sortDocs)."""
    if sort_spec is None:
        refs.sort(key=lambda r: (-r.score, r.shard_id, r.local_doc))
    else:
        sort_refs(refs, sort_spec, with_shard=True)
    return refs[:k]


# ---------------------------------------------------------------------------
# Fetch phase
# ---------------------------------------------------------------------------


def filter_source(source: dict, includes: List[str], excludes: List[str]) -> dict:
    """_source filtering (fetch/subphase/FetchSourceSubPhase semantics):
    a pattern matching a path or any of its ancestors covers the subtree."""

    def ancestor_match(path: str, patterns: List[str]) -> bool:
        parts = path.split(".")
        for i in range(1, len(parts) + 1):
            prefix = ".".join(parts[:i])
            if any(fnmatch.fnmatchcase(prefix, p) for p in patterns):
                return True
        return False

    def walk(obj: dict, prefix: str) -> dict:
        out = {}
        for key, value in obj.items():
            path = f"{prefix}{key}"
            if excludes and ancestor_match(path, excludes):
                continue
            if isinstance(value, dict):
                child = walk(value, path + ".")
                if child:
                    out[key] = child
            elif isinstance(value, list) and value and all(
                isinstance(x, dict) for x in value
            ):
                items = [walk(x, path + ".") for x in value]
                items = [x for x in items if x]
                if items:
                    out[key] = items
            else:
                if includes and not ancestor_match(path, includes):
                    continue
                out[key] = value
        return out

    return walk(source, "")


_HL_PRE = "<em>"
_HL_POST = "</em>"


def highlight_fields(source: dict, mapper_service, query_terms: Dict[str, set],
                     highlight_body: dict) -> Dict[str, List[str]]:
    """Highlight sub-phase. Two highlighters, selected per field by
    ``type`` (subphase/highlight/):

    - "unified" (the 6.x default, UnifiedHighlighter): sentence-bounded
      passages scored like Lucene's PassageScorer (unique-term coverage
      with log tf saturation), top passages selected and term-wrapped.
    - "plain" (PlainHighlighter): token-window fragments around matches.
    """
    out = {}
    fields_spec = highlight_body.get("fields", {})
    pre = (highlight_body.get("pre_tags") or [_HL_PRE])[0]
    post = (highlight_body.get("post_tags") or [_HL_POST])[0]
    require_match = highlight_body.get("require_field_match", True)
    default_type = highlight_body.get("type", "unified")
    all_terms = set().union(*query_terms.values()) if query_terms else set()
    for fname, fspec in fields_spec.items():
        fspec = fspec or {}
        fragment_size = int(fspec.get("fragment_size", 100))
        n_frags = int(fspec.get("number_of_fragments", 5))
        hl_type = fspec.get("type", default_type)
        order = fspec.get("order", highlight_body.get("order", "none"))
        for resolved in mapper_service.mapper.simple_match_to_fields(fname) or [fname]:
            value = _source_value(source, resolved)
            if value is None:
                continue
            text = value if isinstance(value, str) else str(value)
            ft = mapper_service.field_type(resolved)
            analyzer_name = ft.analyzer if isinstance(ft, TextFieldType) else "keyword"
            analyzer = mapper_service.analyzers.get(analyzer_name)
            terms = query_terms.get(resolved, set()) if require_match else all_terms
            if not terms:
                continue
            spans = [
                (s, e, tok) for tok, s, e in analyzer.analyze_tokens(text)
                if tok in terms
            ]
            if not spans:
                continue
            if hl_type == "plain":
                fragments = _build_fragments(
                    text, [(s, e) for s, e, _ in spans], fragment_size,
                    n_frags, pre, post)
            else:
                fragments = _unified_fragments(
                    text, spans, fragment_size, n_frags, pre, post, order)
            if fragments:
                out[resolved] = fragments
    return out


_SENTENCE_BREAK = None  # compiled lazily


def _split_passages(text: str, max_len: int) -> List[tuple]:
    """Sentence-bounded passages [(start, end)], long sentences split at
    max_len word boundaries (java.text.BreakIterator analog)."""
    import re as _re

    global _SENTENCE_BREAK
    if _SENTENCE_BREAK is None:
        _SENTENCE_BREAK = _re.compile(r"(?<=[.!?])\s+|\n+")
    bounds = []
    start = 0
    for m in _SENTENCE_BREAK.finditer(text):
        bounds.append((start, m.start()))
        start = m.end()
    if start < len(text):
        bounds.append((start, len(text)))
    out = []
    for s, e in bounds:
        while e - s > max_len * 2:
            cut = text.rfind(" ", s, s + max_len)
            if cut <= s:
                cut = s + max_len
            out.append((s, cut))
            s = cut + 1
        if e > s:
            out.append((s, e))
    return out


def _unified_fragments(text, spans, fragment_size, n_frags, pre, post,
                       order) -> List[str]:
    """UnifiedHighlighter: score sentence passages by unique-term coverage
    with log tf saturation (PassageScorer semantics), take the top
    passages, wrap their matches."""
    import math

    passages = _split_passages(text, fragment_size)
    scored = []
    for idx, (ps, pe) in enumerate(passages):
        inside = [(s, e) for s, e, _tok in spans if s >= ps and e <= pe]
        if not inside:
            continue
        tfs: Dict[str, int] = {}
        for s, e, tok in spans:
            if s >= ps and e <= pe:
                tfs[tok] = tfs.get(tok, 0) + 1
        score = sum(1.0 + math.log1p(tf) for tf in tfs.values())
        scored.append((score, idx, ps, pe, inside))
    if not scored:
        return []
    scored.sort(key=lambda t: (-t[0], t[1]))
    chosen = scored[:n_frags]
    if order != "score":
        chosen.sort(key=lambda t: t[1])  # document order (6.x default)
    fragments = []
    for _score, _idx, ps, pe, inside in chosen:
        frag = []
        pos = ps
        for a, b in sorted(inside):
            frag.append(text[pos:a])
            frag.append(pre + text[a:b] + post)
            pos = b
        frag.append(text[pos:pe])
        fragments.append("".join(frag))
    return fragments


def _source_value(source: dict, path: str):
    node = source
    for part in path.split("."):
        if not isinstance(node, dict) or part not in node:
            return None
        node = node[part]
    return node


def _build_fragments(text, spans, fragment_size, n_frags, pre, post):
    spans = sorted(spans)
    fragments = []
    used = set()
    for s, e in spans:
        frag_start = max(0, s - fragment_size // 2)
        frag_id = frag_start // max(fragment_size, 1)
        if frag_id in used:
            continue
        used.add(frag_id)
        frag_end = min(len(text), frag_start + fragment_size)
        in_frag = [(a, b) for a, b in spans if a >= frag_start and b <= frag_end]
        frag = []
        pos = frag_start
        for a, b in in_frag:
            frag.append(text[pos:a])
            frag.append(pre + text[a:b] + post)
            pos = b
        frag.append(text[pos:frag_end])
        fragments.append("".join(frag))
        if len(fragments) >= n_frags:
            break
    return fragments


def extract_query_terms(qb, ctx, terms: Optional[Dict[str, set]] = None) -> Dict[str, set]:
    """Collect (field -> tokens) from a builder tree for highlighting."""
    from elasticsearch_tpu.search import query_dsl as Q

    if terms is None:
        terms = {}

    def add(field, toks):
        terms.setdefault(field, set()).update(toks)

    if isinstance(qb, Q.MatchQueryBuilder):
        ft = ctx.field_type(qb.field)
        if isinstance(ft, TextFieldType):
            add(qb.field, ft.query_terms(qb.query, ctx.analyzers))
        else:
            add(qb.field, [str(qb.query)])
    elif isinstance(qb, Q.MatchPhraseQueryBuilder):
        ft = ctx.field_type(qb.field)
        if isinstance(ft, TextFieldType):
            add(qb.field, ft.query_terms(qb.query, ctx.analyzers))
    elif isinstance(qb, Q.TermQueryBuilder):
        add(qb.field, [str(qb.value)])
    elif isinstance(qb, Q.TermsQueryBuilder):
        add(qb.field, [str(v) for v in qb.values])
    elif isinstance(qb, Q.MultiMatchQueryBuilder):
        for f in qb.fields:
            name = f.split("^")[0]
            for resolved in ctx.mapper_service.mapper.simple_match_to_fields(name) or [name]:
                ft = ctx.field_type(resolved)
                if isinstance(ft, TextFieldType):
                    add(resolved, ft.query_terms(qb.query, ctx.analyzers))
    elif isinstance(qb, Q.BoolQueryBuilder):
        for sub in qb.must + qb.should + qb.filter:
            extract_query_terms(sub, ctx, terms)
    elif isinstance(qb, (Q.ConstantScoreQueryBuilder,)):
        extract_query_terms(qb.filter, ctx, terms)
    elif isinstance(qb, Q.DisMaxQueryBuilder):
        for sub in qb.queries:
            extract_query_terms(sub, ctx, terms)
    elif isinstance(qb, Q.FunctionScoreQueryBuilder):
        extract_query_terms(qb.query, ctx, terms)
    return terms


def fetch_hits(refs: List[DocRef], shards: Dict[int, "Any"], source_body: dict,
               index_name: str,
               pinned_segments: Optional[Dict[int, list]] = None,
               ) -> List[dict]:
    """Fetch phase: materialize hits from doc refs.

    shards: shard_id -> object with .engine and .mapper_service.
    pinned_segments: {shard_id: [segment views]} from an open scroll
    context — refs from a pinned query phase must fetch from the SAME
    views (a concurrent merge may have dropped the segment from the
    engine's live list).
    """
    source_body = source_body or {}
    src_spec = source_body.get("_source", True)
    includes, excludes, enabled = _parse_source_spec(src_spec)
    docvalue_fields = source_body.get("docvalue_fields") or []
    stored_fields = source_body.get("stored_fields")
    want_version = bool(source_body.get("version", False))
    highlight_body = source_body.get("highlight")
    sort_spec = normalize_sort(source_body.get("sort"))
    script_fields = source_body.get("script_fields") or {}
    compiled_scripts = {}
    if script_fields:
        from elasticsearch_tpu.script.expression import compile_script

        for fname, spec in script_fields.items():
            sc = spec.get("script", spec)
            compiled_scripts[fname] = (
                compile_script(sc),
                (sc.get("params") if isinstance(sc, dict) else None) or {},
            )

    query_terms: Dict[str, set] = {}
    # probe the query ONCE for inner_hits; if none, skip the per-shard
    # builder setup entirely (the common case)
    has_inner_hits = bool(
        source_body.get("query")
        and collect_inner_hits(parse_query(source_body["query"]))
    )
    # per-shard builders (memoized): the child/nested pass runs once per
    # shard per request, not once per hit
    inner_hits_cache: Dict[int, Tuple] = {}
    hits = []
    for ref in refs:
        shard = shards[ref.shard_id]
        seg = None
        if pinned_segments is not None:
            seg = next((s for s in pinned_segments.get(ref.shard_id, [])
                        if s.name == ref.segment_name), None)
        if seg is None:
            seg = next(
                (s for s in shard.engine.segments
                 if s.name == ref.segment_name), None)
        if seg is None:
            continue
        d = ref.local_doc
        hit = {
            "_index": index_name,
            "_type": "_doc",
            "_id": seg.doc_ids[d],
            "_score": None if sort_spec is not None else ref.score,
        }
        if enabled and stored_fields != "_none_":
            src = seg.sources[d]
            if includes or excludes:
                src = filter_source(src, includes, excludes)
            hit["_source"] = src
        if want_version:
            hit["_version"] = int(seg.versions[d])
        if docvalue_fields:
            fields_out = {}
            for fspec in docvalue_fields:
                fname = fspec if isinstance(fspec, str) else fspec.get("field")
                col = seg.numeric_columns.get(fname)
                if col is not None and col.exists[d]:
                    vals = col.flat_values[: col.count][
                        col.flat_docs[: col.count] == d
                    ]
                    fields_out[fname] = [float(v) for v in vals]
                else:
                    ocol = seg.ordinal_columns.get(fname) or seg.ordinal_columns.get(
                        f"{fname}.keyword"
                    )
                    if ocol is not None and ocol.exists[d]:
                        sel = ocol.flat_docs[: ocol.count] == d
                        fields_out[fname] = [
                            ocol.terms[o] for o in ocol.flat_ords[: ocol.count][sel]
                        ]
            if fields_out:
                hit["fields"] = fields_out
        if compiled_scripts:
            from elasticsearch_tpu.script.expression import doc_values_for

            fields_out = hit.setdefault("fields", {})
            for fname, (script, sparams) in compiled_scripts.items():
                if hasattr(script, "run"):
                    # painless: typed doc values (strings stay strings)
                    from elasticsearch_tpu.script.painless import (
                        DocMap,
                        segment_doc_resolver,
                    )

                    val = script.run({
                        "doc": DocMap(segment_doc_resolver(seg, d)),
                        "params": dict(sparams),
                        "_score": ref.score or 0.0,
                    })
                else:
                    dv = doc_values_for(seg, d, script.doc_fields)
                    val = script.execute(dv, sparams, ref.score or 0.0)
                fields_out[fname] = [val]
        if sort_spec is not None:
            hit["sort"] = [_sort_value_out(v) for v in ref.sort_values]
        if highlight_body:
            if not query_terms:
                qb = parse_query(source_body.get("query"))
                query_terms = extract_query_terms(
                    qb, ShardQueryContext(shard.mapper_service)
                )
            hl = highlight_fields(
                seg.sources[d], shard.mapper_service, query_terms, highlight_body
            )
            if hl:
                hit["highlight"] = hl
        if has_inner_hits:
            if ref.shard_id not in inner_hits_cache:
                ih_ctx = ShardQueryContext(shard.mapper_service, engine=shard.engine)
                ih_builders = collect_inner_hits(parse_query(source_body["query"]))
                inner_hits_cache[ref.shard_id] = (ih_ctx, ih_builders)
            ih_ctx, ih_builders = inner_hits_cache[ref.shard_id]
            ih_out = {}
            for b in ih_builders:
                name, payload = b.inner_hits_for(ih_ctx, seg, d, index_name)
                ih_out[name] = payload
            if ih_out:
                hit["inner_hits"] = ih_out
        hits.append(hit)
    return hits


def _parse_source_spec(spec):
    """-> (includes, excludes, enabled)."""
    if spec is True or spec is None:
        return [], [], True
    if spec is False:
        return [], [], False
    if isinstance(spec, str):
        return [spec], [], True
    if isinstance(spec, list):
        return list(spec), [], True
    if isinstance(spec, dict):
        return (
            list(spec.get("includes") or spec.get("include") or []),
            list(spec.get("excludes") or spec.get("exclude") or []),
            True,
        )
    raise ParsingException(f"unsupported _source spec {spec!r}")
