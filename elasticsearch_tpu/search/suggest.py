"""Suggesters: term (edit distance), phrase (candidate rescoring),
completion (prefix index).

Role model: search/suggest/ in the reference — ``TermSuggester``
(DirectSpellChecker over the terms dict), ``PhraseSuggester`` (n-gram LM +
candidate generation), ``CompletionSuggester`` (FST with weights;
completion inputs here live in a sorted host-side list per segment, the
pointer-chasing structure that stays off-device per SURVEY.md §7.3).
"""

from __future__ import annotations

import bisect
from typing import Dict, List, Optional, Tuple

from elasticsearch_tpu.common.errors import ParsingException
from elasticsearch_tpu.search.query_dsl import _levenshtein_leq


def _edit_distance(a: str, b: str, cap: int = 3) -> int:
    for k in range(cap + 1):
        if _levenshtein_leq(a, b, k):
            return k
    return cap + 1


def _field_term_freqs(segments, field: str) -> Dict[str, int]:
    freqs: Dict[str, int] = {}
    for seg in segments:
        for token, tid in seg.terms_for_field(field):
            freqs[token] = freqs.get(token, 0) + int(seg.term_doc_freq[tid])
    return freqs


def term_suggest(segments, field: str, text: str, analyzer,
                 max_edits: int = 2, size: int = 5,
                 min_word_length: int = 4, prefix_length: int = 1) -> List[dict]:
    """Per-token spelling candidates ranked by (distance, -freq)."""
    freqs = _field_term_freqs(segments, field)
    out = []
    for token, start, end in analyzer.analyze_tokens(text):
        options: List[Tuple[int, int, str]] = []
        exists = token in freqs
        for cand, freq in freqs.items():
            if cand == token:
                continue
            if len(token) >= min_word_length and prefix_length and \
                    cand[:prefix_length] != token[:prefix_length]:
                continue
            if abs(len(cand) - len(token)) > max_edits:
                continue
            d = _edit_distance(token, cand, max_edits)
            if d <= max_edits:
                options.append((d, -freq, cand))
        options.sort()
        out.append({
            "text": token,
            "offset": start,
            "length": end - start,
            "options": [] if exists else [
                {"text": c, "score": round(1.0 - d / (max_edits + 1), 3), "freq": -nf}
                for d, nf, c in options[:size]
            ],
        })
    return out


def phrase_suggest(segments, field: str, text: str, analyzer,
                   size: int = 5, max_errors: float = 1.0) -> List[dict]:
    """Whole-phrase correction: per-token candidates (incl. the token
    itself), best combinations scored by a unigram LM over the corpus
    (the reference defaults to a bigram LM; unigram is the documented
    round-1 model)."""
    freqs = _field_term_freqs(segments, field)
    total = sum(freqs.values()) or 1
    tokens = [t for t, _, _ in analyzer.analyze_tokens(text)]
    if not tokens:
        return []
    per_token: List[List[Tuple[str, float]]] = []
    for tok in tokens:
        cands: List[Tuple[str, float]] = []
        if tok in freqs:
            cands.append((tok, freqs[tok] / total))
        for cand, freq in freqs.items():
            if cand != tok and _levenshtein_leq(cand, tok, 1):
                cands.append((cand, freq / total * 0.5))  # error discount
        if not cands:
            cands.append((tok, 1e-9))
        cands.sort(key=lambda cf: -cf[1])
        per_token.append(cands[:4])

    # beam over combinations, bounded error count
    max_err = int(max_errors) if max_errors >= 1 else max(1, int(max_errors * len(tokens)))
    beams: List[Tuple[float, List[str], int]] = [(1.0, [], 0)]
    for i, cands in enumerate(per_token):
        nxt = []
        for score, words, errs in beams:
            for cand, p in cands:
                e = errs + (cand != tokens[i])
                if e > max_err:
                    continue
                nxt.append((score * p, words + [cand], e))
        nxt.sort(key=lambda b: -b[0])
        beams = nxt[:16]
    options = []
    seen = set()
    for score, words, errs in beams:
        phrase = " ".join(words)
        if phrase in seen or errs == 0:
            continue
        seen.add(phrase)
        options.append({"text": phrase, "score": round(score, 9)})
        if len(options) >= size:
            break
    return [{
        "text": text,
        "offset": 0,
        "length": len(text),
        "options": options,
    }]


def completion_suggest(segments, field: str, prefix: str, size: int = 5,
                       skip_duplicates: bool = False) -> List[dict]:
    """Prefix completion over indexed completion inputs.

    Inputs are stored as the field's ordinal column (sorted — the FST
    analog); weights come from a parallel '<field>#weight' numeric column
    when present."""
    options = []
    seen = set()
    for seg in segments:
        col = seg.ordinal_columns.get(field)
        if col is None:
            continue
        wcol = seg.numeric_columns.get(f"{field}#weight")
        lo = bisect.bisect_left(col.terms, prefix)
        hi = bisect.bisect_left(col.terms, prefix + "￿")
        for o in range(lo, hi):
            term = col.terms[o]
            # find docs holding this ordinal (host scan of CSR)
            sel = col.flat_ords[: col.count] == o
            for local in col.flat_docs[: col.count][sel]:
                if not seg.live[local]:
                    continue
                weight = 1.0
                if wcol is not None and wcol.exists[local]:
                    weight = float(wcol.first_value[local])
                if skip_duplicates and term in seen:
                    continue
                seen.add(term)
                options.append({
                    "text": term,
                    "_id": seg.doc_ids[local],
                    "_score": weight,
                    "_source": seg.sources[local],
                })
    options.sort(key=lambda opt: (-opt["_score"], opt["text"]))
    return [{
        "text": prefix,
        "offset": 0,
        "length": len(prefix),
        "options": options[:size],
    }]


def run_suggest(suggest_body: dict, shards, mapper_service) -> dict:
    """Execute the ``"suggest"`` section (SuggestPhase)."""
    out = {}
    global_text = suggest_body.get("text")
    segments = [
        seg for shard in shards.values()
        for seg in shard.engine.searchable_segments()
    ]
    for name, spec in suggest_body.items():
        if name == "text":
            continue
        text = spec.get("text") or spec.get("prefix") or global_text
        if "term" in spec:
            cfg = spec["term"]
            field = cfg["field"]
            analyzer = mapper_service.analyzers.get(
                getattr(mapper_service.field_type(field), "analyzer", None) or "standard"
            )
            out[name] = term_suggest(
                segments, field, text, analyzer,
                max_edits=int(cfg.get("max_edits", 2)),
                size=int(cfg.get("size", 5)),
                min_word_length=int(cfg.get("min_word_length", 4)),
                prefix_length=int(cfg.get("prefix_length", 1)),
            )
        elif "phrase" in spec:
            cfg = spec["phrase"]
            field = cfg["field"]
            analyzer = mapper_service.analyzers.get(
                getattr(mapper_service.field_type(field), "analyzer", None) or "standard"
            )
            out[name] = phrase_suggest(
                segments, field, text, analyzer,
                size=int(cfg.get("size", 5)),
                max_errors=float(cfg.get("max_errors", 1.0)),
            )
        elif "completion" in spec:
            cfg = spec["completion"]
            out[name] = completion_suggest(
                segments, cfg["field"], text,
                size=int(cfg.get("size", 5)),
                skip_duplicates=bool(cfg.get("skip_duplicates", False)),
            )
        else:
            raise ParsingException(
                f"suggestion [{name}] must specify one of [term, phrase, completion]"
            )
    return out
