"""Suggesters: term (edit distance), phrase (candidate rescoring),
completion (prefix index).

Role model: search/suggest/ in the reference — ``TermSuggester``
(DirectSpellChecker over the terms dict), ``PhraseSuggester`` (n-gram LM +
candidate generation), ``CompletionSuggester`` (FST with weights;
completion inputs here live in a sorted host-side list per segment, the
pointer-chasing structure that stays off-device per SURVEY.md §7.3).
"""

from __future__ import annotations

import bisect
from typing import Dict, List, Optional, Tuple

from elasticsearch_tpu.common.errors import ParsingException
from elasticsearch_tpu.search.query_dsl import _levenshtein_leq


def _edit_distance(a: str, b: str, cap: int = 3) -> int:
    for k in range(cap + 1):
        if _levenshtein_leq(a, b, k):
            return k
    return cap + 1


def _field_term_freqs(segments, field: str) -> Dict[str, int]:
    freqs: Dict[str, int] = {}
    for seg in segments:
        for token, tid in seg.terms_for_field(field):
            freqs[token] = freqs.get(token, 0) + int(seg.term_doc_freq[tid])
    return freqs


def term_suggest(segments, field: str, text: str, analyzer,
                 max_edits: int = 2, size: int = 5,
                 min_word_length: int = 4, prefix_length: int = 1) -> List[dict]:
    """Per-token spelling candidates ranked by (distance, -freq)."""
    freqs = _field_term_freqs(segments, field)
    out = []
    for token, start, end in analyzer.analyze_tokens(text):
        options: List[Tuple[int, int, str]] = []
        exists = token in freqs
        for cand, freq in freqs.items():
            if cand == token:
                continue
            if len(token) >= min_word_length and prefix_length and \
                    cand[:prefix_length] != token[:prefix_length]:
                continue
            if abs(len(cand) - len(token)) > max_edits:
                continue
            d = _edit_distance(token, cand, max_edits)
            if d <= max_edits:
                options.append((d, -freq, cand))
        options.sort()
        out.append({
            "text": token,
            "offset": start,
            "length": end - start,
            "options": [] if exists else [
                {"text": c, "score": round(1.0 - d / (max_edits + 1), 3), "freq": -nf}
                for d, nf, c in options[:size]
            ],
        })
    return out


def _field_bigram_counts(segments, field: str) -> Dict[Tuple[str, str], int]:
    """Consecutive-token pair counts over the field, reconstructed from
    the host-side position lists (the shingle-field analog the reference's
    phrase suggester reads its bigram stats from). Cached per segment."""
    out: Dict[Tuple[str, str], int] = {}
    for seg in segments:
        cached = seg.dev_cache.get(f"bigrams.{field}")
        if cached is None:
            # doc -> {position: token}
            per_doc: Dict[int, Dict[int, str]] = {}
            for term, tid in seg.terms_for_field(field):
                for doc, positions in seg.positions.get(tid, {}).items():
                    slots = per_doc.setdefault(doc, {})
                    for p in positions:
                        slots[p] = term
            cached = {}
            for slots in per_doc.values():
                for p, tok in slots.items():
                    nxt = slots.get(p + 1)
                    if nxt is not None:
                        key = (tok, nxt)
                        cached[key] = cached.get(key, 0) + 1
            seg.dev_cache[f"bigrams.{field}"] = cached
        for key, n in cached.items():
            out[key] = out.get(key, 0) + n
    return out


def phrase_suggest(segments, field: str, text: str, analyzer,
                   size: int = 5, max_errors: float = 1.0) -> List[dict]:
    """Whole-phrase correction: per-token candidates (incl. the token
    itself), best combinations scored by a bigram language model with
    Stupid Backoff smoothing (the reference phrase suggester's default
    model — search/suggest/phrase/StupidBackoffScorer.java, discount
    0.4)."""
    freqs = _field_term_freqs(segments, field)
    bigrams = _field_bigram_counts(segments, field)
    total = sum(freqs.values()) or 1
    tokens = [t for t, _, _ in analyzer.analyze_tokens(text)]
    if not tokens:
        return []
    per_token: List[List[Tuple[str, float]]] = []
    for tok in tokens:
        cands: List[Tuple[str, float]] = []
        if tok in freqs:
            cands.append((tok, freqs[tok] / total))
        for cand, freq in freqs.items():
            if cand != tok and _levenshtein_leq(cand, tok, 1):
                cands.append((cand, freq / total * 0.5))  # error discount
        if not cands:
            cands.append((tok, 1e-9))
        cands.sort(key=lambda cf: -cf[1])
        per_token.append(cands[:4])

    DISCOUNT = 0.4  # Stupid Backoff alpha

    def transition_p(prev: Optional[str], word: str, unigram_p: float) -> float:
        if prev is None:
            return unigram_p
        bi = bigrams.get((prev, word), 0)
        if bi > 0 and freqs.get(prev):
            return bi / freqs[prev]
        return DISCOUNT * unigram_p

    # beam over combinations, bounded error count
    max_err = int(max_errors) if max_errors >= 1 else max(1, int(max_errors * len(tokens)))
    beams: List[Tuple[float, List[str], int]] = [(1.0, [], 0)]
    for i, cands in enumerate(per_token):
        nxt = []
        for score, words, errs in beams:
            prev = words[-1] if words else None
            for cand, p in cands:
                e = errs + (cand != tokens[i])
                if e > max_err:
                    continue
                nxt.append((score * transition_p(prev, cand, p),
                            words + [cand], e))
        nxt.sort(key=lambda b: -b[0])
        beams = nxt[:16]
    options = []
    seen = set()
    for score, words, errs in beams:
        phrase = " ".join(words)
        if phrase in seen or errs == 0:
            continue
        seen.add(phrase)
        options.append({"text": phrase, "score": round(score, 9)})
        if len(options) >= size:
            break
    return [{
        "text": text,
        "offset": 0,
        "length": len(text),
        "options": options,
    }]


def _doc_context_values(seg, field: str, cname: str, local: int) -> List[str]:
    ccol = seg.ordinal_columns.get(f"{field}#ctx.{cname}")
    if ccol is None or not ccol.exists[local]:
        return []
    sel = ccol.flat_docs[: ccol.count] == local
    return [ccol.terms[o] for o in ccol.flat_ords[: ccol.count][sel]]


def _context_boost(seg, field: str, local: int, contexts: dict,
                   ctx_defs: dict) -> Optional[float]:
    """None = filtered out; otherwise the multiplicative boost
    (ContextMappings.ContextQuery: docs must match at least one value per
    queried context; boosts multiply the suggestion weight)."""
    total_boost = 1.0
    for cname, wanted in contexts.items():
        cdef = ctx_defs.get(cname)
        if cdef is None:
            raise ParsingException(
                f"Unknown context name [{cname}], must be one of "
                f"{sorted(ctx_defs)}")
        have = _doc_context_values(seg, field, cname, local)
        if not isinstance(wanted, list):
            wanted = [wanted]
        is_geo = cdef.get("type", "category") == "geo"
        best = None
        for w in wanted:
            if is_geo:
                from elasticsearch_tpu.utils.geohash import encode

                boost = 1.0
                precision = int(cdef.get("precision", 6))
                if isinstance(w, dict):
                    pt = w.get("context") or w
                    precision = int(w.get("precision", precision))
                    boost = float(w.get("boost", 1.0))
                else:
                    pt = w
                if isinstance(pt, dict):
                    want_prefix = encode(float(pt["lat"]), float(pt["lon"]),
                                         precision)
                elif isinstance(pt, str) and "," in pt:
                    lat, lon = pt.split(",", 1)
                    want_prefix = encode(float(lat), float(lon), precision)
                else:
                    want_prefix = str(pt)  # raw geohash prefix
                if any(h.startswith(want_prefix) for h in have):
                    best = max(best or 0.0, boost)
            else:
                if isinstance(w, dict):
                    if "context" not in w:
                        raise ParsingException(
                            f"context query for [{cname}] requires [context]")
                    value = str(w["context"])
                    boost = float(w.get("boost", 1.0))
                else:
                    value, boost = str(w), 1.0
                if value in have:
                    best = max(best or 0.0, boost)
        if best is None:
            return None
        total_boost *= best
    return total_boost


def completion_suggest(segments, field: str, prefix: str, size: int = 5,
                       skip_duplicates: bool = False,
                       contexts: Optional[dict] = None,
                       ctx_defs: Optional[dict] = None) -> List[dict]:
    """Prefix completion over indexed completion inputs.

    Inputs are stored as the field's ordinal column (sorted — the FST
    analog); weights come from a parallel '<field>#weight' numeric column;
    context values (category or geohash-encoded geo) live in parallel
    '<field>#ctx.<name>' columns (the reference's ContextMappings encode
    contexts into the FST paths — search/suggest/completion/context/)."""
    options = []
    seen = set()
    for seg in segments:
        col = seg.ordinal_columns.get(field)
        if col is None:
            continue
        wcol = seg.numeric_columns.get(f"{field}#weight")
        lo = bisect.bisect_left(col.terms, prefix)
        hi = bisect.bisect_left(col.terms, prefix + "￿")
        for o in range(lo, hi):
            term = col.terms[o]
            # find docs holding this ordinal (host scan of CSR)
            sel = col.flat_ords[: col.count] == o
            for local in col.flat_docs[: col.count][sel]:
                if not seg.live[local]:
                    continue
                weight = 1.0
                if wcol is not None and wcol.exists[local]:
                    weight = float(wcol.first_value[local])
                if contexts:
                    boost = _context_boost(seg, field, int(local), contexts,
                                           ctx_defs or {})
                    if boost is None:
                        continue
                    weight *= boost
                if skip_duplicates and term in seen:
                    continue
                seen.add(term)
                options.append({
                    "text": term,
                    "_id": seg.doc_ids[local],
                    "_score": weight,
                    "_source": seg.sources[local],
                })
    options.sort(key=lambda opt: (-opt["_score"], opt["text"]))
    return [{
        "text": prefix,
        "offset": 0,
        "length": len(prefix),
        "options": options[:size],
    }]


def run_suggest(suggest_body: dict, shards, mapper_service) -> dict:
    """Execute the ``"suggest"`` section (SuggestPhase)."""
    out = {}
    global_text = suggest_body.get("text")
    segments = [
        seg for shard in shards.values()
        for seg in shard.engine.searchable_segments()
    ]
    for name, spec in suggest_body.items():
        if name == "text":
            continue
        text = spec.get("text") or spec.get("prefix") or global_text
        if "term" in spec:
            cfg = spec["term"]
            field = cfg["field"]
            analyzer = mapper_service.analyzers.get(
                getattr(mapper_service.field_type(field), "analyzer", None) or "standard"
            )
            out[name] = term_suggest(
                segments, field, text, analyzer,
                max_edits=int(cfg.get("max_edits", 2)),
                size=int(cfg.get("size", 5)),
                min_word_length=int(cfg.get("min_word_length", 4)),
                prefix_length=int(cfg.get("prefix_length", 1)),
            )
        elif "phrase" in spec:
            cfg = spec["phrase"]
            field = cfg["field"]
            analyzer = mapper_service.analyzers.get(
                getattr(mapper_service.field_type(field), "analyzer", None) or "standard"
            )
            out[name] = phrase_suggest(
                segments, field, text, analyzer,
                size=int(cfg.get("size", 5)),
                max_errors=float(cfg.get("max_errors", 1.0)),
            )
        elif "completion" in spec:
            cfg = spec["completion"]
            ft = mapper_service.field_type(cfg["field"])
            out[name] = completion_suggest(
                segments, cfg["field"], text,
                size=int(cfg.get("size", 5)),
                skip_duplicates=bool(cfg.get("skip_duplicates", False)),
                contexts=cfg.get("contexts"),
                ctx_defs=getattr(ft, "contexts", None) or {},
            )
        else:
            raise ParsingException(
                f"suggestion [{name}] must specify one of [term, phrase, completion]"
            )
    return out
