"""In-process node-to-node transport with fault injection.

Role model: ``TransportService``/``TcpTransport`` (core/.../transport/) for
the request/handler contract, and the test framework's
``MockTransportService`` + ``NetworkDisruption``
(test/framework/.../test/transport/MockTransportService.java:91,
disruption/NetworkDisruption.java:49) for programmable faults. The
reference's production data plane is Netty sockets; ours is ICI
collectives inside compiled programs (parallel/distributed.py) — this
transport carries the *control plane* (cluster state publish, shard-level
requests between hosts) and is the seam where a gRPC/DCN implementation
slots in for real multi-host deployments.

Requests are synchronous in-process calls; payloads are JSON-able dicts
(enforced in strict mode) so the handler contract stays wire-serializable.

Resilience layer (this module's second half):

- ``send_request`` accepts a per-attempt ``timeout``: the delivery runs on
  a worker thread and the caller gives up with
  ``ReceiveTimeoutTransportException`` when the deadline passes — an
  unresponsive peer can no longer hang a coordination path. Handlers
  already run thread-per-request over the TCP transport, so the threading
  model is identical across both hubs.
- ``RetryPolicy`` is the ``RetryableAction`` analog: exponential backoff
  between attempts, a retryable-exception classification (connection-level
  failures and backpressure retry; remote handler failures do not), and an
  optional overall deadline.
- ``ConnectionHealth`` tracks consecutive per-node failures and fast-fails
  (``ConnectTransportException``) to nodes past the failure threshold
  while inside a short quarantine window, with a half-open probe after it
  expires. ``TransportHub.heal``/``clear_disruptions`` reset it so tests
  reconnect deterministically.
- ``TransportHub`` hosts pluggable ``DisruptionScheme``s
  (testing/disruption.py): delay, probabilistic drop, one-way partition,
  unresponsive node, action blackhole — applied per delivery, outside the
  hub lock.
"""

from __future__ import annotations

import json
import logging
import threading
import time
import weakref
from typing import Any, Callable, Dict, Optional, Set, Tuple

from elasticsearch_tpu.common.errors import (
    CircuitBreakingException,
    ConnectTransportException,
    ElasticsearchTpuException,
    EsRejectedExecutionException,
    NodeNotConnectedException,
    ReceiveTimeoutTransportException,
)

logger = logging.getLogger("elasticsearch_tpu.transport")

# every TransportService ever created in this process (weakly held):
# the PR-2 resilience counters (retries, backoff waits, send timeouts,
# ConnectionHealth fast-fails) existed per service but were never
# exported — _nodes/stats aggregates them from here (docs/RESILIENCE.md)
_ALL_TRANSPORTS: "weakref.WeakSet" = weakref.WeakSet()
# guards registry mutation vs the stats snapshot: a node starting up
# concurrently with GET /_nodes/stats would otherwise race the WeakSet
# iteration ("Set changed size during iteration" -> 500)
_ALL_TRANSPORTS_LOCK = threading.Lock()


def aggregate_transport_stats() -> Dict[str, int]:
    """Process-wide transport resilience counters, summed over every
    live TransportService (the in-process hub spawns one per node; a
    single-node REST process reports zeros). Exported as the
    ``transport`` block of ``_nodes/stats``."""
    out: Dict[str, int] = {
        "services": 0, "requests_sent": 0, "retries": 0, "timeouts": 0,
        "fast_fails": 0, "failures": 0,
    }
    with _ALL_TRANSPORTS_LOCK:
        services = list(_ALL_TRANSPORTS)
    for svc in services:
        out["services"] += 1
        with svc._stats_lock:
            for key, v in svc.stats.items():
                out[key] = out.get(key, 0) + v
    return out


class RemoteActionException(ElasticsearchTpuException):
    """Wraps a failure raised by a remote handler."""

    status_code = 500


# connection-level trouble and backpressure are worth retrying; a handler
# that executed and failed (RemoteActionException etc.) is not — the op may
# have applied (RetryableAction.shouldRetry draws the same line)
DEFAULT_RETRYABLE = (
    NodeNotConnectedException,
    EsRejectedExecutionException,
    CircuitBreakingException,
)


class RetryPolicy:
    """``RetryableAction`` analog: exponential backoff between attempts.

    ``initial_backoff`` doubles (``backoff_multiplier``) per attempt up to
    ``max_backoff``; ``overall_timeout`` (optional) bounds the whole retry
    loop including backoff sleeps. ``retryable`` is the exception
    classification — only instances of these classes re-attempt.
    """

    def __init__(self, max_attempts: int = 3, initial_backoff: float = 0.05,
                 backoff_multiplier: float = 2.0, max_backoff: float = 2.0,
                 overall_timeout: Optional[float] = None,
                 retryable: Tuple[type, ...] = DEFAULT_RETRYABLE):
        if max_attempts < 1:
            raise ValueError("max_attempts must be >= 1")
        self.max_attempts = int(max_attempts)
        self.initial_backoff = float(initial_backoff)
        self.backoff_multiplier = float(backoff_multiplier)
        self.max_backoff = float(max_backoff)
        self.overall_timeout = overall_timeout
        self.retryable = tuple(retryable)

    def is_retryable(self, exc: BaseException) -> bool:
        # a fast-fail never hit the wire: retrying it inside the same
        # send would just spin on the tracker — let the caller's own
        # failover/backoff handle it
        if isinstance(exc, ConnectTransportException):
            return False
        return isinstance(exc, self.retryable)

    def backoff(self, attempt: int) -> float:
        """Backoff before attempt ``attempt + 1`` (0-based)."""
        return min(self.max_backoff,
                   self.initial_backoff * (self.backoff_multiplier ** attempt))

    def derive(self, **overrides) -> "RetryPolicy":
        """Copy of this policy with some fields overridden — keeps
        derived policies in sync when RetryPolicy grows a field."""
        base = dict(max_attempts=self.max_attempts,
                    initial_backoff=self.initial_backoff,
                    backoff_multiplier=self.backoff_multiplier,
                    max_backoff=self.max_backoff,
                    overall_timeout=self.overall_timeout,
                    retryable=self.retryable)
        base.update(overrides)
        return RetryPolicy(**base)


class ConnectionHealth:
    """Per-node connection health with fast-fail (circuit-breaker shape).

    After ``failure_threshold`` consecutive failures the breaker OPENS
    for ``quarantine_s``: sends inside the window fast-fail with
    ``ConnectTransportException`` without touching the wire. At expiry
    the state fully resets — the next sends go to the wire and a fresh
    run of consecutive failures is needed to re-open. (The window is
    anchored at open time, NOT at the last failure: re-arming on every
    failed probe would starve a lossy-but-alive link — a 30% drop rate
    must degrade throughput, not permanently open the breaker.) A dead
    node still fast-fails for most of every window because its re-probes
    fail instantly and re-open the breaker.
    """

    def __init__(self, failure_threshold: int = 3, quarantine_s: float = 1.0):
        self.failure_threshold = int(failure_threshold)
        self.quarantine_s = float(quarantine_s)
        self._lock = threading.Lock()
        # node -> [consecutive_failures, breaker_open_monotonic]
        self._state: Dict[str, list] = {}

    def should_fast_fail(self, node: str) -> bool:
        with self._lock:
            st = self._state.get(node)
            if st is None or st[0] < self.failure_threshold:
                return False
            if time.monotonic() - st[1] >= self.quarantine_s:
                self._state.pop(node, None)  # expiry: full reset
                return False
            return True

    def on_success(self, node: str) -> None:
        with self._lock:
            self._state.pop(node, None)

    def on_failure(self, node: str) -> None:
        with self._lock:
            st = self._state.setdefault(node, [0, 0.0])
            st[0] += 1
            if st[0] <= self.failure_threshold:
                # the open timestamp freezes when the breaker trips; late
                # wire failures (in-flight when it tripped) don't extend
                # the window
                st[1] = time.monotonic()

    def failures(self, node: str) -> int:
        with self._lock:
            st = self._state.get(node)
            return st[0] if st else 0

    def reset(self, node: Optional[str] = None) -> None:
        with self._lock:
            if node is None:
                self._state.clear()
            else:
                self._state.pop(node, None)


class TransportHub:
    """The shared 'network': node registry + disruption rules."""

    def __init__(self, strict_serialization: bool = False):
        self._nodes: Dict[str, "TransportService"] = {}
        self._disconnected: Set[Tuple[str, str]] = set()
        self._delays: Dict[Tuple[str, str], float] = {}
        self._disruptions: list = []  # DisruptionScheme instances
        self._lock = threading.Lock()
        self.strict_serialization = strict_serialization
        self.requests_log: list = []  # (src, dst, action) — CapturingTransport

    def register(self, service: "TransportService") -> None:
        with self._lock:
            self._nodes[service.node_id] = service

    def unregister(self, node_id: str) -> None:
        with self._lock:
            self._nodes.pop(node_id, None)

    def nodes(self) -> Dict[str, "TransportService"]:
        with self._lock:
            return dict(self._nodes)

    # --- disruption schemes (NetworkDisruption behaviors) ---

    def disconnect(self, a: str, b: Optional[str] = None) -> None:
        """Break a<->b, or isolate `a` from everyone."""
        with self._lock:
            targets = [b] if b else [n for n in self._nodes if n != a]
            for t in targets:
                self._disconnected.add((a, t))
                self._disconnected.add((t, a))

    def heal(self, a: Optional[str] = None) -> None:
        with self._lock:
            if a is None:
                self._disconnected.clear()
                self._delays.clear()
            else:
                self._disconnected = {
                    (x, y) for x, y in self._disconnected if a not in (x, y)
                }
        self._reset_health(a)

    def add_disruption(self, scheme) -> None:
        """Install a ``DisruptionScheme`` (testing/disruption.py); applied
        to every subsequent delivery until removed."""
        with self._lock:
            if scheme not in self._disruptions:
                self._disruptions.append(scheme)

    def remove_disruption(self, scheme) -> None:
        with self._lock:
            if scheme in self._disruptions:
                self._disruptions.remove(scheme)
        self._reset_health(None)

    def clear_disruptions(self) -> None:
        with self._lock:
            self._disruptions.clear()
        self._reset_health(None)

    def _reset_health(self, node: Optional[str]) -> None:
        """The network just changed shape: forget learned per-node health
        so healed links are usable immediately (tests rely on heal() being
        deterministic, not racing a quarantine window). Healing ``node``
        clears every link touching it: its entry in every peer's tracker
        AND everything in its own."""
        for svc in self.nodes().values():
            if node is None or svc.node_id == node:
                svc.connection_health.reset()
            else:
                svc.connection_health.reset(node)

    def add_delay(self, a: str, b: str, seconds: float) -> None:
        with self._lock:
            self._delays[(a, b)] = seconds

    def deliver(self, src: str, dst: str, action: str, payload: Any) -> Any:
        with self._lock:
            if (src, dst) in self._disconnected:
                raise NodeNotConnectedException(
                    f"[{dst}] disconnected from [{src}]"
                )
            service = self._nodes.get(dst)
            delay = self._delays.get((src, dst), 0.0)
            schemes = [s for s in self._disruptions
                       if s.applies(src, dst, action)]
            self.requests_log.append((src, dst, action))
        # disruption effects run OUTSIDE the hub lock: a scheme may sleep
        # (delay / unresponsive node) and must not stall unrelated links
        for scheme in schemes:
            scheme.disrupt(src, dst, action)
        if service is None:
            raise NodeNotConnectedException(f"node [{dst}] is not in the cluster")
        if delay:
            time.sleep(delay)
        if self.strict_serialization:
            payload = json.loads(json.dumps(payload))
        return service.handle(action, payload, src)


class TransportService:
    def __init__(self, node_id: str, hub: TransportHub,
                 health: Optional[ConnectionHealth] = None):
        self.node_id = node_id
        self.hub = hub
        self._handlers: Dict[str, Callable[[Any, str], Any]] = {}
        self.connection_health = health or ConnectionHealth()
        # observability: retries/timeouts/fast-fails must be visible in
        # stats so disruption tests can assert the resilient path actually
        # exercised (the reference exposes the same through TransportStats)
        self._stats_lock = threading.Lock()
        self.stats: Dict[str, int] = {
            "requests_sent": 0, "retries": 0, "timeouts": 0,
            "fast_fails": 0, "failures": 0,
        }
        hub.register(self)
        with _ALL_TRANSPORTS_LOCK:
            _ALL_TRANSPORTS.add(self)

    def _bump(self, key: str, n: int = 1) -> None:
        with self._stats_lock:
            self.stats[key] += n

    def register_handler(self, action: str, handler: Callable[[Any, str], Any]) -> None:
        """handler(payload, source_node_id) -> response."""
        self._handlers[action] = handler

    def handle(self, action: str, payload: Any, src: str) -> Any:
        handler = self._handlers.get(action)
        if handler is None:
            raise RemoteActionException(
                f"node [{self.node_id}] has no handler for action [{action}]"
            )
        return handler(payload, src)

    # ------------------------------------------------------------------

    def _deliver(self, target: str, action: str, payload: Any,
                 timeout: Optional[float]) -> Any:
        """One delivery attempt; with a timeout the call runs on a worker
        thread and is abandoned at the deadline (the late response is
        dropped, exactly like a real network)."""
        if timeout is None:
            return self.hub.deliver(self.node_id, target, action, payload)
        box: Dict[str, Any] = {}
        done = threading.Event()

        def run():
            try:
                box["value"] = self.hub.deliver(
                    self.node_id, target, action, payload)
            except BaseException as e:  # noqa: BLE001 — re-raised below
                box["error"] = e
            finally:
                done.set()

        threading.Thread(target=run, daemon=True,
                         name=f"transport[{self.node_id}->{target}]").start()
        if not done.wait(timeout):
            self._bump("timeouts")
            raise ReceiveTimeoutTransportException(
                f"[{target}][{action}] request timed out after {timeout}s")
        if "error" in box:
            raise box["error"]
        return box["value"]

    def send_request(self, target: str, action: str, payload: Any,
                     timeout: Optional[float] = None,
                     retry: Optional[RetryPolicy] = None) -> Any:
        """Send ``action`` to ``target``.

        ``timeout``: per-attempt deadline (seconds); None = wait forever
        (the pre-resilience behavior, kept for local same-thread calls).
        ``retry``: a RetryPolicy; None = single attempt.
        """
        if target == self.node_id:
            # local fast path: same-thread dispatch keeps RLock
            # reentrancy for nested master-service updates
            return self.handle(action, payload, self.node_id)
        if self.connection_health.should_fast_fail(target):
            self._bump("fast_fails")
            raise ConnectTransportException(
                f"[{target}] fast-fail: node is quarantined after "
                f"{self.connection_health.failures(target)} consecutive "
                f"failures")
        attempts = retry.max_attempts if retry else 1
        deadline = None
        if retry is not None and retry.overall_timeout is not None:
            deadline = time.monotonic() + retry.overall_timeout
        last: Optional[BaseException] = None
        for attempt in range(attempts):
            self._bump("requests_sent")
            try:
                resp = self._deliver(target, action, payload, timeout)
                self.connection_health.on_success(target)
                return resp
            except Exception as e:  # noqa: BLE001 — classified below
                last = e
                if isinstance(e, NodeNotConnectedException):
                    self.connection_health.on_failure(target)
                self._bump("failures")
                if retry is None or not retry.is_retryable(e):
                    raise
                if attempt + 1 >= attempts:
                    raise
                pause = retry.backoff(attempt)
                if deadline is not None:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        raise
                    pause = min(pause, remaining)
                self._bump("retries")
                logger.info(
                    "retrying [%s] to [%s] after %s (attempt %d/%d, "
                    "backoff %.3fs)", action, target,
                    type(e).__name__, attempt + 1, attempts, pause)
                time.sleep(pause)
        raise last  # pragma: no cover — loop always returns or raises

    def close(self) -> None:
        self.hub.unregister(self.node_id)
