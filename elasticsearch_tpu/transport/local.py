"""In-process node-to-node transport with fault injection.

Role model: ``TransportService``/``TcpTransport`` (core/.../transport/) for
the request/handler contract, and the test framework's
``MockTransportService`` + ``NetworkDisruption``
(test/framework/.../test/transport/MockTransportService.java:91,
disruption/NetworkDisruption.java:49) for programmable faults. The
reference's production data plane is Netty sockets; ours is ICI
collectives inside compiled programs (parallel/distributed.py) — this
transport carries the *control plane* (cluster state publish, shard-level
requests between hosts) and is the seam where a gRPC/DCN implementation
slots in for real multi-host deployments.

Requests are synchronous in-process calls; payloads are JSON-able dicts
(enforced in strict mode) so the handler contract stays wire-serializable.
"""

from __future__ import annotations

import json
import threading
import time
from typing import Any, Callable, Dict, Optional, Set, Tuple

from elasticsearch_tpu.common.errors import (
    ElasticsearchTpuException,
    NodeNotConnectedException,
)


class RemoteActionException(ElasticsearchTpuException):
    """Wraps a failure raised by a remote handler."""

    status_code = 500


class TransportHub:
    """The shared 'network': node registry + disruption rules."""

    def __init__(self, strict_serialization: bool = False):
        self._nodes: Dict[str, "TransportService"] = {}
        self._disconnected: Set[Tuple[str, str]] = set()
        self._delays: Dict[Tuple[str, str], float] = {}
        self._lock = threading.Lock()
        self.strict_serialization = strict_serialization
        self.requests_log: list = []  # (src, dst, action) — CapturingTransport

    def register(self, service: "TransportService") -> None:
        with self._lock:
            self._nodes[service.node_id] = service

    def unregister(self, node_id: str) -> None:
        with self._lock:
            self._nodes.pop(node_id, None)

    def nodes(self) -> Dict[str, "TransportService"]:
        with self._lock:
            return dict(self._nodes)

    # --- disruption schemes (NetworkDisruption behaviors) ---

    def disconnect(self, a: str, b: Optional[str] = None) -> None:
        """Break a<->b, or isolate `a` from everyone."""
        with self._lock:
            targets = [b] if b else [n for n in self._nodes if n != a]
            for t in targets:
                self._disconnected.add((a, t))
                self._disconnected.add((t, a))

    def heal(self, a: Optional[str] = None) -> None:
        with self._lock:
            if a is None:
                self._disconnected.clear()
                self._delays.clear()
            else:
                self._disconnected = {
                    (x, y) for x, y in self._disconnected if a not in (x, y)
                }

    def add_delay(self, a: str, b: str, seconds: float) -> None:
        with self._lock:
            self._delays[(a, b)] = seconds

    def deliver(self, src: str, dst: str, action: str, payload: Any) -> Any:
        with self._lock:
            if (src, dst) in self._disconnected:
                raise NodeNotConnectedException(
                    f"[{dst}] disconnected from [{src}]"
                )
            service = self._nodes.get(dst)
            delay = self._delays.get((src, dst), 0.0)
            self.requests_log.append((src, dst, action))
        if service is None:
            raise NodeNotConnectedException(f"node [{dst}] is not in the cluster")
        if delay:
            time.sleep(delay)
        if self.strict_serialization:
            payload = json.loads(json.dumps(payload))
        return service.handle(action, payload, src)


class TransportService:
    def __init__(self, node_id: str, hub: TransportHub):
        self.node_id = node_id
        self.hub = hub
        self._handlers: Dict[str, Callable[[Any, str], Any]] = {}
        hub.register(self)

    def register_handler(self, action: str, handler: Callable[[Any, str], Any]) -> None:
        """handler(payload, source_node_id) -> response."""
        self._handlers[action] = handler

    def handle(self, action: str, payload: Any, src: str) -> Any:
        handler = self._handlers.get(action)
        if handler is None:
            raise RemoteActionException(
                f"node [{self.node_id}] has no handler for action [{action}]"
            )
        return handler(payload, src)

    def send_request(self, target: str, action: str, payload: Any) -> Any:
        if target == self.node_id:
            return self.handle(action, payload, self.node_id)
        return self.hub.deliver(self.node_id, target, action, payload)

    def close(self) -> None:
        self.hub.unregister(self.node_id)
