"""TCP node-to-node transport: the real-socket control plane.

Role model: ``TcpTransport`` (core/src/main/java/org/elasticsearch/
transport/TcpTransport.java:121) with its length-prefixed, versioned
frames (TcpHeader.java:30-38 writes 'E','S', message length, request id,
status byte, version) and request/response correlation; here the header is
``b'ET' | version u8 | kind u8 | request_id u64 | length u32`` and the
body is versioned JSON (the reference's StreamOutput binary protocol maps
to an explicit wire version byte + JSON payload — a v2 can switch codecs
per version without changing the framing).

``TcpTransportHub`` is interface-compatible with the in-process
``TransportHub`` (transport/local.py): ``TransportService`` and everything
above it (cluster/multinode.py — join, publish, replication, recovery)
run over sockets unchanged. Peers are an explicit address book (the
unicast seed-hosts analog of discovery.zen.ping.unicast.hosts).

Concurrency model mirrors the reference's: one persistent connection per
peer direction, concurrent requests correlated by id; inbound requests
are handled on their own threads so a handler may issue nested RPCs
(join -> publish back) without deadlocking the reader loop.
"""

from __future__ import annotations

import json
import socket
import struct
import threading
from typing import Any, Callable, Dict, Optional, Set, Tuple

import numpy as np

from elasticsearch_tpu.common import errors as es_errors
from elasticsearch_tpu.common.errors import (
    ElasticsearchTpuException,
    NodeNotConnectedException,
    ReceiveTimeoutTransportException,
)
from elasticsearch_tpu.transport.local import RemoteActionException

MAGIC = b"ET"
WIRE_VERSION = 1
KIND_REQUEST = 1
KIND_RESPONSE = 2
KIND_ERROR = 3
HEADER = struct.Struct(">2sBBQI")  # magic, version, kind, req_id, body len
MAX_FRAME = 512 * 1024 * 1024


def _json_default(o):
    if isinstance(o, (np.integer,)):
        return int(o)
    if isinstance(o, (np.floating,)):
        return float(o)
    if isinstance(o, np.ndarray):
        return o.tolist()
    if isinstance(o, (set, frozenset)):
        return sorted(o)
    raise TypeError(f"not wire-serializable: {type(o).__name__}")


def _encode(kind: int, req_id: int, body: dict) -> bytes:
    payload = json.dumps(body, default=_json_default).encode("utf-8")
    return HEADER.pack(MAGIC, WIRE_VERSION, kind, req_id, len(payload)) + payload


def _read_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed")
        buf.extend(chunk)
    return bytes(buf)


def _read_frame(sock: socket.socket) -> Tuple[int, int, dict]:
    head = _read_exact(sock, HEADER.size)
    magic, version, kind, req_id, length = HEADER.unpack(head)
    if magic != MAGIC:
        raise ConnectionError(f"bad frame magic {magic!r}")
    if version > WIRE_VERSION:
        raise ConnectionError(f"unsupported wire version {version}")
    if length > MAX_FRAME:
        raise ConnectionError(f"frame too large: {length}")
    body = json.loads(_read_exact(sock, length).decode("utf-8"))
    return kind, req_id, body


def _raise_remote(body: dict) -> None:
    """Rebuild the remote exception class when it is one of ours."""
    etype = body.get("etype", "RemoteActionException")
    reason = body.get("reason", "remote failure")
    cls = getattr(es_errors, etype, None)
    if isinstance(cls, type) and issubclass(cls, ElasticsearchTpuException):
        raise cls(reason)
    raise RemoteActionException(f"{etype}: {reason}")


class _PeerConnection:
    """One outbound socket to a peer: frame writer + response reader."""

    def __init__(self, host: str, port: int, timeout: float):
        self.sock = socket.create_connection((host, port), timeout=5.0)
        self.sock.settimeout(None)
        self.timeout = timeout
        self.wlock = threading.Lock()
        self.pending: Dict[int, dict] = {}
        self.plock = threading.Lock()
        self.closed = False
        self._next_id = 0
        self.reader = threading.Thread(target=self._read_loop, daemon=True)
        self.reader.start()

    def _read_loop(self) -> None:
        try:
            while True:
                kind, req_id, body = _read_frame(self.sock)
                with self.plock:
                    slot = self.pending.pop(req_id, None)
                if slot is not None:
                    slot["kind"] = kind
                    slot["body"] = body
                    slot["event"].set()
        except (ConnectionError, OSError):
            pass
        finally:
            self.closed = True
            with self.plock:
                for slot in self.pending.values():
                    slot["kind"] = KIND_ERROR
                    slot["body"] = {"etype": "NodeNotConnectedException",
                                    "reason": "connection closed"}
                    slot["event"].set()
                self.pending.clear()

    def request(self, body: dict) -> dict:
        slot = {"event": threading.Event(), "kind": None, "body": None}
        with self.plock:
            if self.closed:
                raise NodeNotConnectedException("connection closed")
            self._next_id += 1
            req_id = self._next_id
            self.pending[req_id] = slot
        try:
            frame = _encode(KIND_REQUEST, req_id, body)
            with self.wlock:
                self.sock.sendall(frame)
        except OSError as e:
            with self.plock:
                self.pending.pop(req_id, None)
            raise NodeNotConnectedException(f"send failed: {e}") from e
        if not slot["event"].wait(self.timeout):
            with self.plock:
                self.pending.pop(req_id, None)
            raise ReceiveTimeoutTransportException(
                f"request timed out after {self.timeout}s")
        if slot["kind"] == KIND_ERROR:
            _raise_remote(slot["body"])
        return slot["body"]

    def close(self) -> None:
        self.closed = True
        try:
            self.sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        self.sock.close()


class TcpTransportHub:
    """Socket-backed drop-in for transport/local.TransportHub.

    One hub per process; local services register directly, remote node ids
    resolve through the peer address book. Handlers run on per-request
    threads so nested RPCs can't deadlock a connection's reader.
    """

    def __init__(self, bind_host: str = "127.0.0.1", port: int = 0,
                 request_timeout: float = 30.0):
        self._services: Dict[str, Any] = {}
        self._peers: Dict[str, Tuple[str, int]] = {}
        self._conns: Dict[str, _PeerConnection] = {}
        self._disconnected: Set[Tuple[str, str]] = set()
        self._disruptions: list = []  # DisruptionScheme parity
        self._lock = threading.Lock()
        self.request_timeout = request_timeout
        self.requests_log: list = []
        self._server = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._server.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._server.bind((bind_host, port))
        self._server.listen(64)
        self.host, self.port = self._server.getsockname()
        self._running = True
        self._accept_thread = threading.Thread(target=self._accept_loop,
                                               daemon=True)
        self._accept_thread.start()

    # --- address book -------------------------------------------------

    def add_peer(self, node_id: str, host: str, port: int) -> None:
        with self._lock:
            self._peers[node_id] = (host, port)

    # --- TransportHub interface ---------------------------------------

    def register(self, service) -> None:
        with self._lock:
            self._services[service.node_id] = service

    def unregister(self, node_id: str) -> None:
        with self._lock:
            self._services.pop(node_id, None)

    def disconnect(self, a: str, b: Optional[str] = None) -> None:
        """Test-only fault injection parity with the local hub."""
        with self._lock:
            targets = [b] if b else [n for n in set(self._peers)
                                     | set(self._services) if n != a]
            for t in targets:
                self._disconnected.add((a, t))
                self._disconnected.add((t, a))

    def heal(self, a: Optional[str] = None) -> None:
        with self._lock:
            if a is None:
                self._disconnected.clear()
            else:
                self._disconnected = {
                    (x, y) for x, y in self._disconnected if a not in (x, y)}
        self._reset_health(a)

    def add_disruption(self, scheme) -> None:
        with self._lock:
            if scheme not in self._disruptions:
                self._disruptions.append(scheme)

    def remove_disruption(self, scheme) -> None:
        with self._lock:
            if scheme in self._disruptions:
                self._disruptions.remove(scheme)
        self._reset_health(None)

    def clear_disruptions(self) -> None:
        with self._lock:
            self._disruptions.clear()
        self._reset_health(None)

    def _reset_health(self, node: Optional[str]) -> None:
        with self._lock:
            services = list(self._services.values())
        for svc in services:
            health = getattr(svc, "connection_health", None)
            if health is None:
                continue
            if node is None or svc.node_id == node:
                health.reset()
            else:
                health.reset(node)

    def deliver(self, src: str, dst: str, action: str, payload: Any) -> Any:
        with self._lock:
            if (src, dst) in self._disconnected:
                raise NodeNotConnectedException(
                    f"[{dst}] disconnected from [{src}]")
            local = self._services.get(dst)
            schemes = [s for s in self._disruptions
                       if s.applies(src, dst, action)]
            self.requests_log.append((src, dst, action))
        for scheme in schemes:  # outside the lock: schemes may sleep
            scheme.disrupt(src, dst, action)
        if local is not None:
            return local.handle(action, payload, src)
        conn = self._connection(dst)
        resp = conn.request({"src": src, "dst": dst, "action": action,
                             "payload": payload})
        return resp.get("result")

    # --- internals ----------------------------------------------------

    def _connection(self, dst: str) -> _PeerConnection:
        with self._lock:
            conn = self._conns.get(dst)
            if conn is not None and not conn.closed:
                return conn
            addr = self._peers.get(dst)
        if addr is None:
            raise NodeNotConnectedException(
                f"node [{dst}] is not in the cluster")
        try:
            conn = _PeerConnection(addr[0], addr[1], self.request_timeout)
        except OSError as e:
            raise NodeNotConnectedException(
                f"connect to [{dst}] {addr} failed: {e}") from e
        with self._lock:
            self._conns[dst] = conn
        return conn

    def _accept_loop(self) -> None:
        while self._running:
            try:
                sock, _ = self._server.accept()
            except OSError:
                break
            threading.Thread(target=self._serve_connection, args=(sock,),
                             daemon=True).start()

    def _serve_connection(self, sock: socket.socket) -> None:
        wlock = threading.Lock()
        try:
            while True:
                kind, req_id, body = _read_frame(sock)
                if kind != KIND_REQUEST:
                    continue
                threading.Thread(
                    target=self._handle_request,
                    args=(sock, wlock, req_id, body), daemon=True).start()
        except (ConnectionError, OSError):
            pass
        finally:
            try:
                sock.close()
            except OSError:
                pass

    def _handle_request(self, sock, wlock, req_id: int, body: dict) -> None:
        src = body.get("src", "?")
        action = body.get("action", "?")
        try:
            with self._lock:
                if (src, "*") in self._disconnected:
                    raise NodeNotConnectedException("disconnected")
                services = list(self._services.values())
            if not services:
                raise NodeNotConnectedException("no local services")
            # a process hosts one node in practice; dispatch to it (or the
            # addressed one if several are registered)
            service = self._services.get(body.get("dst")) or services[0]
            result = service.handle(action, body.get("payload"), src)
            frame = _encode(KIND_RESPONSE, req_id, {"result": result})
        except Exception as e:  # noqa: BLE001 — becomes a wire error frame
            frame = _encode(KIND_ERROR, req_id, {
                "etype": type(e).__name__, "reason": str(e)})
        try:
            with wlock:
                sock.sendall(frame)
        except OSError:
            pass

    def close(self) -> None:
        self._running = False
        try:
            # close() alone does not wake a thread blocked in accept() on
            # linux — the kernel socket stays listening via the blocked
            # thread's reference; shutdown() interrupts it
            self._server.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._server.close()
        except OSError:
            pass
        with self._lock:
            conns = list(self._conns.values())
            self._conns.clear()
        for c in conns:
            c.close()
