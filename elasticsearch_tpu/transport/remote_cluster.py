"""Cross-cluster search: remote cluster registry + index-expression split.

Role model: ``RemoteClusterService`` (reference:
core/src/main/java/org/elasticsearch/transport/RemoteClusterService.java:60)
— remote clusters declared via ``search.remote.<alias>.seeds`` settings,
``alias:index`` expressions in search/msearch/field_caps, per-alias
``skip_unavailable``, and the ``_remote/info`` API. The reference relays
shard-level requests through gateway nodes (``TransportActionProxy``); in
this single-process framework the relay is a direct handle to the remote
``Node``, so remote shards join the coordinator's shard-level merge
exactly like local ones (true cross-cluster aggregation reduce).

Seeds resolve through a process-level node registry (every ``Node``
registers by node_name), the in-process stand-in for DNS + transport
handshake.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Tuple

from elasticsearch_tpu.common.errors import IllegalArgumentException

# process-level registry: node_name -> Node (the "network")
_NODE_REGISTRY: Dict[str, object] = {}
_LOCK = threading.Lock()

REMOTE_CLUSTERS_SEEDS_PREFIX = "search.remote."


def register_node(node) -> None:
    with _LOCK:
        _NODE_REGISTRY[node.node_name] = node


def unregister_node(node) -> None:
    with _LOCK:
        if _NODE_REGISTRY.get(node.node_name) is node:
            _NODE_REGISTRY.pop(node.node_name, None)


class RemoteClusterService:
    """Per-node registry of remote clusters."""

    def __init__(self, node, settings=None):
        self._node = node
        # alias -> (remote Node | None, seeds, skip_unavailable)
        self._remotes: Dict[str, dict] = {}
        if settings is not None:
            self.apply_settings(settings)

    # -- configuration ------------------------------------------------

    def apply_settings(self, settings) -> None:
        """Parse ``search.remote.<alias>.seeds`` / ``.skip_unavailable``
        (dynamic: re-applied on cluster-settings updates; empty seeds
        remove the alias, like the reference)."""
        aliases = {}
        for key in settings.keys():
            if not key.startswith(REMOTE_CLUSTERS_SEEDS_PREFIX):
                continue
            rest = key[len(REMOTE_CLUSTERS_SEEDS_PREFIX):]
            alias, _, param = rest.partition(".")
            if alias and param:
                aliases.setdefault(alias, {})[param] = settings.get(key)
        for alias, cfg in aliases.items():
            if "seeds" in cfg:
                seeds = cfg["seeds"]
                if isinstance(seeds, str):
                    seeds = [s for s in seeds.split(",") if s]
                if not seeds:
                    self._remotes.pop(alias, None)
                    continue
                entry = self._remotes.setdefault(
                    alias, {"node": None, "seeds": [], "skip_unavailable": False})
                if entry["seeds"] != list(seeds):
                    entry["node"] = None  # re-resolve after a seed change
                    entry["seeds"] = list(seeds)
            if "skip_unavailable" in cfg and alias in self._remotes:
                self._remotes[alias]["skip_unavailable"] = (
                    str(cfg["skip_unavailable"]).lower() == "true")

    def attach(self, alias: str, remote_node, skip_unavailable: bool = False) -> None:
        """Programmatic registration (a resolved connection)."""
        self._remotes[alias] = {
            "node": remote_node,
            "seeds": [getattr(remote_node, "node_name", alias)],
            "skip_unavailable": skip_unavailable,
        }

    def remove(self, alias: str) -> None:
        self._remotes.pop(alias, None)

    # -- resolution ---------------------------------------------------

    def is_remote_cluster_registered(self, alias: str) -> bool:
        return alias in self._remotes

    def _connect(self, alias: str):
        entry = self._remotes[alias]
        if entry["node"] is not None and not getattr(entry["node"], "_closed", False):
            return entry["node"]
        # resolve seeds through the process registry (re-resolve every
        # call: the sniffed-gateway refresh analog)
        with _LOCK:
            for seed in entry["seeds"]:
                name = seed.split(":")[0]  # accept "name" or "name:port"
                node = _NODE_REGISTRY.get(name)
                if node is not None and not getattr(node, "_closed", False):
                    entry["node"] = node
                    return node
        return None

    def get_remote(self, alias: str):
        """-> (remote Node or None, skip_unavailable)."""
        if alias not in self._remotes:
            raise IllegalArgumentException(f"no such remote cluster: [{alias}]")
        return self._connect(alias), self._remotes[alias]["skip_unavailable"]

    def group_indices(self, expression: str) -> List[Tuple[Optional[str], str]]:
        """Split a comma-separated index expression into (cluster_alias,
        sub_expression) groups; alias None = local. ``alias:idx`` only
        routes remotely when the alias is a registered remote cluster
        (RemoteClusterService.groupClusterIndices semantics — unregistered
        prefixes stay local index names)."""
        groups: Dict[Optional[str], List[str]] = {}
        for part in (expression or "_all").split(","):
            part = part.strip()
            if not part:
                continue
            if ":" in part:
                alias, _, idx = part.partition(":")
                if alias in self._remotes:
                    groups.setdefault(alias, []).append(idx or "_all")
                    continue
            groups.setdefault(None, []).append(part)
        return [(alias, ",".join(parts)) for alias, parts in groups.items()]

    # -- info API -----------------------------------------------------

    def info(self) -> dict:
        """GET /_remote/info (RemoteInfo / TransportRemoteInfoAction)."""
        out = {}
        for alias, entry in self._remotes.items():
            node = self._connect(alias)
            out[alias] = {
                "seeds": entry["seeds"],
                "connected": node is not None,
                "num_nodes_connected": 1 if node is not None else 0,
                "max_connections_per_cluster": 3,
                "initial_connect_timeout": "30s",
                "skip_unavailable": entry["skip_unavailable"],
            }
        return out
