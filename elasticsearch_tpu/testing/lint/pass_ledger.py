"""Pass 2 — ledger balance (ISSUE 15).

The device-memory accounting contract (ISSUE 9/10, docs/RESILIENCE.md
"Device-plane faults"): every ``DeviceMemoryAccountant.register`` call
site must leave the ledger RECLAIMABLE — registered bytes that nothing
can ever release (an "orphan register") grow ``staged_bytes`` forever
and starve the HBM budget gate. PRs 9-13 enforced this by review
("register-then-commit", "transactional staging"); this pass mechanizes
the two structural halves of the invariant:

1. the register call passes an ``evict=`` callback, so the accountant
   itself can reclaim the scope under budget pressure; and
2. the enclosing class owns a release path — some method calls
   ``release_scope``/``release_index`` — pairing every register with a
   reachable rollback (module-level registers need a module-level
   release call).

Call sites are matched structurally: ``<expr>.register(...)`` where the
receiver involves ``memory_accountant()`` (directly, or via a local
alias assigned from it in the same function). Registries with the same
method name (settings, tasks, transport hubs, REST routes) never match.
"""

from __future__ import annotations

import ast
from typing import Iterable, Optional, Set

from elasticsearch_tpu.testing.lint.core import (
    Finding,
    LintPass,
    SourceTree,
    register_pass,
)

RELEASE_CALLS = {"release_scope", "release_index"}


def _aliases_of_accountant(func: ast.AST) -> Set[str]:
    """Local names assigned from ``memory_accountant()`` in ``func``."""
    out: Set[str] = set()
    for node in ast.walk(func):
        if isinstance(node, ast.Assign) and isinstance(node.value,
                                                       ast.Call):
            callee = node.value.func
            name = (callee.id if isinstance(callee, ast.Name)
                    else getattr(callee, "attr", None))
            if name == "memory_accountant":
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        out.add(t.id)
    return out


def _is_accountant_register(call: ast.Call,
                            aliases: Set[str]) -> bool:
    f = call.func
    if not (isinstance(f, ast.Attribute) and f.attr == "register"):
        return False
    recv = f.value
    if isinstance(recv, ast.Call):
        callee = recv.func
        name = (callee.id if isinstance(callee, ast.Name)
                else getattr(callee, "attr", None))
        return name == "memory_accountant"
    if isinstance(recv, ast.Name):
        return recv.id in aliases
    return False


def _enclosing_class(sf, node: ast.AST) -> Optional[str]:
    qual = sf.qualname_at(node)
    return qual.rsplit(".", 1)[0] if "." in qual else None


def _scope_has_release(sf, cls: Optional[str]) -> bool:
    """The class (or the whole module, for free functions) contains a
    reachable ``release_scope``/``release_index`` call."""
    scope = sf.defs.get(cls) if cls else sf.tree
    if scope is None:
        scope = sf.tree
    for node in ast.walk(scope):
        if isinstance(node, ast.Call) and isinstance(node.func,
                                                     ast.Attribute):
            if node.func.attr in RELEASE_CALLS:
                return True
    return False


@register_pass
class LedgerBalancePass(LintPass):
    name = "ledger-balance"
    description = ("every memory-accountant register site must pass an "
                   "evict= callback and sit in a scope owning a "
                   "release_scope/release_index rollback path")
    targets = None  # whole tree: new register sites anywhere must comply

    def run(self, tree: SourceTree) -> Iterable[Finding]:
        for rel, sf in tree.files.items():
            if rel.startswith("testing/lint/"):
                continue  # the analyzer's own pattern tables
            func_aliases: dict = {}
            for node in ast.walk(sf.tree):
                if isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                    func_aliases[node] = _aliases_of_accountant(node)
            for node in ast.walk(sf.tree):
                if not isinstance(node, ast.Call):
                    continue
                aliases: Set[str] = set()
                for fn, al in func_aliases.items():
                    if fn.lineno <= node.lineno <= getattr(
                            fn, "end_lineno", fn.lineno):
                        aliases |= al
                if not _is_accountant_register(node, aliases):
                    continue
                qual = sf.qualname_at(node)
                kwargs = {k.arg for k in node.keywords}
                if "evict" not in kwargs:
                    yield Finding(
                        self.name, rel, qual, node.lineno,
                        "accountant.register without an evict= callback:"
                        " the HBM budget gate cannot reclaim this scope "
                        "— pass the generation's eviction hook",
                        key="evict")
                cls = _enclosing_class(sf, node)
                if not _scope_has_release(sf, cls):
                    yield Finding(
                        self.name, rel, qual, node.lineno,
                        "orphan register: no release_scope/release_index"
                        " call anywhere in the enclosing "
                        f"{'class ' + cls if cls else 'module'} — "
                        "registered bytes could never be returned to "
                        "the ledger",
                        key="release")
