"""Pass 6 — settings/docs cross-check (ISSUE 15 satellite).

Extends the tests/test_settings_registry.py lint (every settings LOOKUP
must be registered) with the documentation half: every registered
``search.*`` / ``index.search.*`` key must appear in EXACTLY ONE
settings table across docs/*.md, and every settings-table row must name
a registered key. This catches the two recurring drift shapes the
review logs kept fixing: "registered but undocumented" (a knob ships
with no operator surface) and duplicate rows that rot independently.

A settings-table ROW is a markdown table line whose FIRST cell is the
backticked key (``| `search.foo` | ...``) — keys mentioned mid-row or
in prose are cross-references, not the documenting row, and don't
count. The docs may intentionally cross-reference a key from several
subsystem pages; only one page owns its row.
"""

from __future__ import annotations

import os
import re
from typing import Dict, Iterable, List, Tuple

from elasticsearch_tpu.testing.lint.core import (
    Finding,
    LintPass,
    SourceTree,
    register_pass,
    repo_root,
)

# settings keys are lowercase dotted words — the case restriction keeps
# generated artifacts like LOCK_ORDER.md (whose site ids embed
# CamelCase class names under a `search.` module prefix) out of scope
_ROW_RE = re.compile(r"^\|\s*`((?:index\.)?search\.[a-z0-9_.]+)`\s*\|")


def doc_rows(docs_dir: str) -> Dict[str, List[Tuple[str, int]]]:
    """key -> [(doc relname, lineno)] for every settings-table row."""
    rows: Dict[str, List[Tuple[str, int]]] = {}
    for fname in sorted(os.listdir(docs_dir)):
        if not fname.endswith(".md") or fname == "LOCK_ORDER.md":
            # LOCK_ORDER.md is the GENERATED pass-5 artifact, never a
            # settings page — its site ids live under a `search.`
            # module prefix and a future lowercase module-level lock
            # in search/ would otherwise read as an unregistered key
            continue
        with open(os.path.join(docs_dir, fname), encoding="utf-8") as f:
            for n, line in enumerate(f, 1):
                m = _ROW_RE.match(line.strip())
                if m:
                    rows.setdefault(m.group(1), []).append((fname, n))
    return rows


def registered_search_keys() -> set:
    from elasticsearch_tpu.common.settings import (
        cluster_settings,
        index_scoped_settings,
    )

    keys = set()
    for registry in (cluster_settings(), index_scoped_settings()):
        keys.update(k for k in registry._settings
                    if k.startswith("search.")
                    or k.startswith("index.search."))
    return keys


def cross_check(keys: set, rows: Dict[str, List[Tuple[str, int]]],
                pass_name: str) -> Iterable[Finding]:
    """The testable core: findings for undocumented / duplicated /
    unregistered keys (docs path is symbolic — the finding id anchors
    on the key, so allowlist entries survive doc reflows)."""
    for key in sorted(keys):
        sites = rows.get(key, [])
        if not sites:
            yield Finding(
                pass_name, "common/settings.py", "<registry>", 1,
                f"registered setting [{key}] has no settings-table row "
                f"in docs/*.md — document it (catches the 'registered "
                f"but undocumented' drift)",
                key=key)
        elif len(sites) > 1:
            where = ", ".join(f"{d}:{n}" for d, n in sites)
            yield Finding(
                pass_name, "common/settings.py", "<registry>", 1,
                f"setting [{key}] documented in {len(sites)} tables "
                f"({where}) — exactly one page owns a key's row; turn "
                f"the others into cross-references",
                key=key)
    for key in sorted(rows):
        if key not in keys:
            d, n = rows[key][0]
            yield Finding(
                pass_name, "common/settings.py", "<registry>", 1,
                f"docs table row for [{key}] ({d}:{n}) names a key the "
                f"settings registries don't know — register it or drop "
                f"the row",
                key=key)


@register_pass
class SettingsDocsPass(LintPass):
    name = "settings-docs"
    description = ("every registered search.*/index.search.* setting "
                   "appears in exactly one docs/*.md settings table, "
                   "and vice versa")
    targets = None

    def run(self, tree: SourceTree) -> Iterable[Finding]:
        if tree.fixture_mode:
            # self-test drives cross_check() directly with synthetic
            # inputs; a fixtures tree has no registry to import
            return
        docs_dir = os.path.join(repo_root(), "docs")
        if not os.path.isdir(docs_dir):
            return
        yield from cross_check(registered_search_keys(),
                               doc_rows(docs_dir), self.name)
