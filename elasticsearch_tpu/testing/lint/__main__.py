"""``python -m elasticsearch_tpu.testing.lint`` — the pre-PR contract
gate (scripts/check.sh wraps it together with the registry lints).

Exit status 0 iff every finding is allowlisted (with justification),
no allowlist entry is stale, and — unless ``--no-doc-check`` — the
checked-in docs/LOCK_ORDER.md matches the current source tree.
"""

from __future__ import annotations

import argparse
import os
import sys

from elasticsearch_tpu.testing.lint.core import (
    Allowlist,
    SourceTree,
    all_passes,
    repo_root,
    run_lint,
)
from elasticsearch_tpu.testing.lint.pass_lockorder import (
    lock_graph_for,
    render_lock_order,
)

LOCK_ORDER_DOC = os.path.join(repo_root(), "docs", "LOCK_ORDER.md")


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m elasticsearch_tpu.testing.lint",
        description="AST contract lints + lock-discipline analyzer")
    parser.add_argument("--list", action="store_true",
                        help="list registered passes and exit")
    parser.add_argument("--pass", dest="passes", action="append",
                        metavar="NAME",
                        help="run only this pass (repeatable; disables "
                             "the stale-allowlist check)")
    parser.add_argument("--allowlist", default=None,
                        help="alternate allowlist file")
    parser.add_argument("--emit-lock-order", nargs="?", metavar="PATH",
                        const=LOCK_ORDER_DOC, default=None,
                        help=f"write the lock-order artifact (default "
                             f"{os.path.relpath(LOCK_ORDER_DOC, repo_root())}"
                             f") and exit")
    parser.add_argument("--no-doc-check", action="store_true",
                        help="skip the docs/LOCK_ORDER.md freshness check")
    args = parser.parse_args(argv)

    registry = all_passes()
    if args.list:
        for name in sorted(registry):
            print(f"{name}: {registry[name].description}")
        return 0

    tree = SourceTree()
    if args.emit_lock_order:
        content = render_lock_order(lock_graph_for(tree))
        with open(args.emit_lock_order, "w", encoding="utf-8") as f:
            f.write(content)
        print(f"wrote {args.emit_lock_order}")
        return 0

    unknown = [p for p in (args.passes or []) if p not in registry]
    if unknown:
        print(f"unknown pass(es): {unknown}; "
              f"known: {sorted(registry)}", file=sys.stderr)
        return 2
    allow = (Allowlist.load(args.allowlist) if args.allowlist
             else None)
    result = run_lint(tree, passes=args.passes, allowlist=allow)

    allowlisted = len(result.findings) - len(result.unallowlisted)
    for f in result.unallowlisted:
        print(f.render())
    for err in result.allowlist_errors:
        print(f"ALLOWLIST ERROR: {err}")
    for entry in result.stale_entries:
        print(f"STALE ALLOWLIST ENTRY (no finding matches — remove it): "
              f"{entry}")

    doc_ok = True
    if args.passes is None and not args.no_doc_check:
        # reuses the LockGraph the lock-order pass already built on
        # this tree (lock_graph_for cache)
        current = render_lock_order(lock_graph_for(tree))
        try:
            with open(LOCK_ORDER_DOC, encoding="utf-8") as f:
                on_disk = f.read()
        except OSError:
            on_disk = ""
        if on_disk != current:
            doc_ok = False
            print("docs/LOCK_ORDER.md is stale — regenerate with "
                  "`python -m elasticsearch_tpu.testing.lint "
                  "--emit-lock-order`")

    print(f"contract-lint: {len(result.findings)} finding(s), "
          f"{allowlisted} allowlisted, "
          f"{len(result.unallowlisted)} unallowlisted, "
          f"{len(result.stale_entries)} stale allowlist entr(ies), "
          f"{len(result.allowlist_errors)} allowlist error(s)")
    return 0 if (result.ok and doc_ok) else 1


if __name__ == "__main__":
    sys.exit(main())
