"""Pass 1 — cancellation-passthrough (ISSUE 15).

The PR-4/PR-10 contract: ``TimeExceededException`` /
``TaskCancelledException`` / ``StagingBail`` must pass THROUGH the
plane-ladder, fault-recording and staging-retry ``except`` blocks — a
broad handler that records a fault (quarantine, staging-fault
accounting, ladder decision) or swallows the error entirely would turn
a clean cancellation into a bogus plane quarantine or a silently-eaten
timeout. Review logs re-fixed this class in PRs 4, 10, 11 and 13; this
pass mechanizes it.

Rule, per ``try`` in the target files: a broad handler (bare ``except``,
``except Exception``/``BaseException``) is flagged when

- its body calls a fault-recording function (``record_failure``,
  ``note_staging_fault``, ``_note``, ``_note_agg_fallback``,
  ``note_decision``, ``shard_failure_entry``), OR
- the ``try`` body can raise a cancellation (it checkpoints a deadline
  or blocks on a device program),

UNLESS the cancellation types are re-raised first: an earlier handler
in the same ``try`` catches one of the passthrough types and re-raises,
or the broad handler itself re-raises unconditionally as its LAST
statement (the ``run_staged`` record-then-re-raise shape still
propagates the exception; recording a cancellation as a device fault is
noisy telemetry, so target files should prefer the explicit
passthrough handler).
"""

from __future__ import annotations

import ast
from typing import Iterable, List

from elasticsearch_tpu.testing.lint.callgraph import call_name
from elasticsearch_tpu.testing.lint.core import (
    Finding,
    LintPass,
    SourceTree,
    register_pass,
)

PASSTHROUGH_TYPES = {
    "TaskCancelledException",
    "TimeExceededException",
    "StagingBail",
}

FAULT_CALLS = {
    "record_failure",
    "note_staging_fault",
    "_note",
    "_note_agg_fallback",
    "note_decision",
    "shard_failure_entry",
}

# calls whose presence in a try body means a cancellation can surface
# inside it (deadline checkpoints; device-program completion points sit
# behind them on every ladder path)
CANCELLATION_SOURCES = {"checkpoint"}


def _handler_names(handler: ast.ExceptHandler) -> List[str]:
    t = handler.type
    if t is None:
        return ["<bare>"]
    out = []
    for node in ([t.elts] if isinstance(t, ast.Tuple) else [[t]])[0]:
        if isinstance(node, ast.Name):
            out.append(node.id)
        elif isinstance(node, ast.Attribute):
            out.append(node.attr)
    return out


def _is_broad(handler: ast.ExceptHandler) -> bool:
    names = _handler_names(handler)
    return any(n in ("<bare>", "Exception", "BaseException")
               for n in names)


def _records_fault(handler: ast.ExceptHandler) -> bool:
    for node in ast.walk(handler):
        if isinstance(node, ast.Call):
            if call_name(node) in FAULT_CALLS:
                return True
    return False


def _reraises_unconditionally(handler: ast.ExceptHandler) -> bool:
    """Last top-level statement of the handler is a bare ``raise``."""
    body = handler.body
    return bool(body) and isinstance(body[-1], ast.Raise) \
        and body[-1].exc is None


def _try_can_cancel(node: ast.Try) -> bool:
    for stmt in node.body:
        for sub in ast.walk(stmt):
            if isinstance(sub, ast.Call):
                if call_name(sub) in CANCELLATION_SOURCES:
                    return True
    return False


REQUIRED_PASSTHROUGH = {"TaskCancelledException", "TimeExceededException"}


def _passthrough_before(node: ast.Try,
                        broad: ast.ExceptHandler) -> bool:
    """Earlier handlers HANDLE both cancellation types — re-raising
    (the ladder shape) or converting deliberately (the per-shard
    partial-results shape turns TimeExceeded into ``timed_out``); what
    the contract forbids is the BROAD handler ever seeing them.
    (StagingBail passthrough is accepted as a bonus but not required —
    it only has meaning at staging-retry sites, and those must still
    let the two cancellation types through.)"""
    covered: set = set()
    for handler in node.handlers:
        if handler is broad:
            break
        covered |= set(_handler_names(handler)) & PASSTHROUGH_TYPES
    return covered >= REQUIRED_PASSTHROUGH


@register_pass
class CancellationPassthroughPass(LintPass):
    name = "cancellation-passthrough"
    description = ("broad except blocks on plane-ladder / fault-recording"
                   " / staging-retry paths must re-raise TimeExceeded/"
                   "TaskCancelled/StagingBail before recording a fault")
    targets = {
        "parallel/plan_exec.py",
        "common/staging.py",
        "index/index_service.py",
        "search/batching.py",
        "index/segment.py",
        "search/fused_aggs.py",
    }

    def run(self, tree: SourceTree) -> Iterable[Finding]:
        for rel, sf in tree.files.items():
            if not tree.applies(rel, self.targets):
                continue
            counters: dict = {}
            for node in ast.walk(sf.tree):
                if not isinstance(node, ast.Try):
                    continue
                for handler in node.handlers:
                    if not _is_broad(handler):
                        continue
                    records = _records_fault(handler)
                    cancellable = _try_can_cancel(node)
                    if not (records or cancellable):
                        continue
                    if _passthrough_before(node, handler):
                        continue
                    if not records and _reraises_unconditionally(handler):
                        # pure rethrow shapes propagate cancellation fine
                        continue
                    qual = sf.qualname_at(handler)
                    n = counters.get(qual, 0) + 1
                    counters[qual] = n
                    what = ("records a fault" if records
                            else "guards a cancellable body")
                    yield Finding(
                        self.name, rel, qual, handler.lineno,
                        f"broad except {what} without re-raising "
                        f"TimeExceeded/TaskCancelled/StagingBail first "
                        f"— add an `except (TaskCancelledException, "
                        f"TimeExceededException): raise` arm before it",
                        key=f"h{n}" if n > 1 else "")
