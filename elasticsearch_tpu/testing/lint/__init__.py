"""Contract-lint subsystem (ISSUE 15, docs/STATIC_ANALYSIS.md).

Entry point: ``python -m elasticsearch_tpu.testing.lint`` — runs every
registered pass over the source tree and exits non-zero on any
unallowlisted finding. Tier-1 coverage: tests/test_contract_lint.py.
"""

from elasticsearch_tpu.testing.lint.core import (  # noqa: F401
    Allowlist,
    Finding,
    LintPass,
    LintResult,
    SourceTree,
    all_passes,
    register_pass,
    run_lint,
)
