"""Pass 3 — counter lock discipline (ISSUE 15).

The PR-8 stats-consistency contract (docs/OBSERVABILITY.md): exported
counters are mutated from concurrent query/ingest threads, and a bare
``self.x_total += 1`` is a read-modify-write race that silently loses
increments — the exact class the PR-8 concurrency hardening fixed by
hand across plan_exec/telemetry/admission. This pass flags every
augmented assignment to a ``self.*_total`` attribute (and every write
through a ``self.*_by_reason`` mapping) that is not covered by one of
the repo's synchronization idioms:

- lexically inside ``with self.<lock>:`` / ``with <module lock>:``
  where the context expression names a lock/condition (attribute or
  global whose name contains ``lock``, ``_cv``, or ``cond``);
- in a function whose name ends in ``_locked`` (the caller-holds-lock
  naming convention); or
- in a function whose docstring states the convention explicitly
  ("caller holds", "lock held", or "single-threaded by design").

Counters on local variables don't race and are ignored.
"""

from __future__ import annotations

import ast
from typing import Iterable, List, Optional

from elasticsearch_tpu.testing.lint.core import (
    Finding,
    LintPass,
    SourceTree,
    register_pass,
)

_DOC_MARKERS = ("caller holds", "lock held", "single-threaded by design")


def _expr_names(expr: ast.AST) -> List[str]:
    out = []
    for node in ast.walk(expr):
        if isinstance(node, ast.Attribute):
            out.append(node.attr)
        elif isinstance(node, ast.Name):
            out.append(node.id)
    return out


def _is_lock_expr(expr: ast.AST) -> bool:
    return any("lock" in n.lower() or n in ("_cv", "cv")
               or "cond" in n.lower()
               for n in _expr_names(expr))


def _counter_target(node: ast.AST) -> Optional[str]:
    """The counter name when ``node`` writes a tracked counter."""
    if isinstance(node, ast.AugAssign):
        t = node.target
        if (isinstance(t, ast.Attribute)
                and isinstance(t.value, ast.Name) and t.value.id == "self"
                and t.attr.endswith("_total")):
            return t.attr
        if isinstance(t, ast.Subscript):
            v = t.value
            if (isinstance(v, ast.Attribute)
                    and isinstance(v.value, ast.Name)
                    and v.value.id == "self"
                    and v.attr.endswith("_by_reason")):
                return v.attr
    if isinstance(node, ast.Assign):
        for t in node.targets:
            if isinstance(t, ast.Subscript):
                v = t.value
                if (isinstance(v, ast.Attribute)
                        and isinstance(v.value, ast.Name)
                        and v.value.id == "self"
                        and v.attr.endswith("_by_reason")):
                    return v.attr
    return None


class _Scanner(ast.NodeVisitor):
    def __init__(self):
        self.findings: List[tuple] = []  # (node, counter, func)
        self._with_lock_depth = 0
        self._func_stack: List[ast.FunctionDef] = []

    def visit_With(self, node: ast.With) -> None:
        locked = any(_is_lock_expr(item.context_expr)
                     for item in node.items)
        if locked:
            self._with_lock_depth += 1
        self.generic_visit(node)
        if locked:
            self._with_lock_depth -= 1

    def _visit_func(self, node) -> None:
        self._func_stack.append(node)
        self.generic_visit(node)
        self._func_stack.pop()

    visit_FunctionDef = _visit_func
    visit_AsyncFunctionDef = _visit_func

    def _func_exempt(self) -> bool:
        if not self._func_stack:
            return False
        fn = self._func_stack[-1]
        if fn.name.endswith("_locked"):
            return True
        doc = (ast.get_docstring(fn) or "").lower()
        return any(marker in doc for marker in _DOC_MARKERS)

    def _check(self, node) -> None:
        counter = _counter_target(node)
        if counter and not self._with_lock_depth \
                and not self._func_exempt():
            fn = self._func_stack[-1] if self._func_stack else None
            self.findings.append((node, counter, fn))

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        self._check(node)
        self.generic_visit(node)

    def visit_Assign(self, node: ast.Assign) -> None:
        self._check(node)
        self.generic_visit(node)


@register_pass
class CounterLockPass(LintPass):
    name = "counter-lock-discipline"
    description = ("self.*_total / self.*_by_reason counter writes must "
                   "happen under a lock (or in a function documented as "
                   "caller-holds-lock)")
    targets = None  # whole tree

    def run(self, tree: SourceTree) -> Iterable[Finding]:
        for rel, sf in tree.files.items():
            if rel.startswith("testing/lint/"):
                continue
            scanner = _Scanner()
            scanner.visit(sf.tree)
            per_qual: dict = {}
            for node, counter, _fn in scanner.findings:
                qual = sf.qualname_at(node)
                n = per_qual.get((qual, counter), 0) + 1
                per_qual[(qual, counter)] = n
                yield Finding(
                    self.name, rel, qual, node.lineno,
                    f"unsynchronized write to self.{counter}: wrap it in"
                    f" the owning lock (concurrent increments lose "
                    f"updates — the PR-8 race class), name the function "
                    f"*_locked, or document 'caller holds' the lock",
                    key=(counter if n == 1 else f"{counter}{n}"))
