"""Contract-lint framework core (ISSUE 15, docs/STATIC_ANALYSIS.md).

The reference enforces its cross-cutting contracts (circuit-breaker
accounting balance, cancellable-task propagation, settings registration)
with dedicated infrastructure; this package is our reproduction's
equivalent: AST-based lint passes that encode the invariants the PR 2-14
review logs kept re-fixing by hand, run over the whole source tree by
``python -m elasticsearch_tpu.testing.lint`` and by the tier-1 test
``tests/test_contract_lint.py``.

Three pieces:

- :class:`SourceTree` — the parsed source universe (one ``ast.parse``
  per file, shared by every pass) plus the qualname index the passes
  key their findings on.
- :class:`LintPass` / :func:`register_pass` — the pass registry. A pass
  receives the tree and yields :class:`Finding`s; its ``targets`` set
  (when not None) restricts it to the files whose contracts it encodes.
- :class:`Allowlist` — the per-finding allowlist. Every entry carries a
  MANDATORY justification string; entries that no longer match any
  finding are themselves reported (a stale allowlist hides regressions),
  so the file can only ever shrink truthfully.

Finding identity is ``pass:relpath:qualname[:key]`` — stable across
line-number drift so allowlist entries survive unrelated edits.
"""

from __future__ import annotations

import ast
import os
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Set, Tuple


def package_root() -> str:
    """Absolute path of the ``elasticsearch_tpu`` package directory."""
    import elasticsearch_tpu

    return os.path.dirname(os.path.abspath(elasticsearch_tpu.__file__))


def repo_root() -> str:
    return os.path.dirname(package_root())


# ---------------------------------------------------------------------------
# Findings
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class Finding:
    """One contract violation (or justified-false-positive candidate)."""

    pass_name: str
    path: str          # relative to the package root, '/'-separated
    qualname: str      # Class.method / function / '<module>'
    lineno: int
    message: str
    key: str = ""      # disambiguator when one symbol yields several

    @property
    def id(self) -> str:
        base = f"{self.pass_name}:{self.path}:{self.qualname}"
        return f"{base}:{self.key}" if self.key else base

    def render(self) -> str:
        return (f"{self.path}:{self.lineno}: [{self.pass_name}] "
                f"{self.message}\n    id: {self.id}")


# ---------------------------------------------------------------------------
# Parsed-source universe
# ---------------------------------------------------------------------------


class SourceFile:
    def __init__(self, relpath: str, source: str):
        self.relpath = relpath
        self.source = source
        self.tree = ast.parse(source)
        # node -> qualname ('Class.method', nested functions dotted)
        self.qualnames: Dict[ast.AST, str] = {}
        # function/class defs by qualname
        self.defs: Dict[str, ast.AST] = {}
        self._index()

    def _index(self) -> None:
        def walk(node: ast.AST, prefix: str) -> None:
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                      ast.ClassDef)):
                    qual = (f"{prefix}.{child.name}" if prefix
                            else child.name)
                    self.qualnames[child] = qual
                    self.defs[qual] = child
                    walk(child, qual)
                else:
                    walk(child, prefix)

        walk(self.tree, "")

    def qualname_at(self, node: ast.AST) -> str:
        """Qualname of the innermost def/class enclosing ``node`` (by
        position), or '<module>'."""
        best = "<module>"
        best_span = None
        for d, qual in self.qualnames.items():
            if not isinstance(d, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            end = getattr(d, "end_lineno", d.lineno)
            if d.lineno <= node.lineno <= end:
                span = end - d.lineno
                if best_span is None or span < best_span:
                    best, best_span = qual, span
        return best


class SourceTree:
    """Every ``.py`` file under ``root``, parsed once.

    ``fixture_mode`` lifts per-pass ``targets`` restrictions so the
    lint_fixtures self-test snippets exercise every pass regardless of
    their file names."""

    def __init__(self, root: Optional[str] = None,
                 fixture_mode: bool = False):
        self.root = root or package_root()
        self.fixture_mode = fixture_mode
        self.files: Dict[str, SourceFile] = {}
        for dirpath, dirnames, filenames in os.walk(self.root):
            dirnames[:] = [d for d in dirnames
                           if d not in ("__pycache__",)]
            for fname in sorted(filenames):
                if not fname.endswith(".py"):
                    continue
                path = os.path.join(dirpath, fname)
                rel = os.path.relpath(path, self.root).replace(os.sep, "/")
                with open(path, encoding="utf-8") as f:
                    self.files[rel] = SourceFile(rel, f.read())

    def applies(self, relpath: str,
                targets: Optional[Set[str]]) -> bool:
        return self.fixture_mode or targets is None or relpath in targets


# ---------------------------------------------------------------------------
# Pass registry
# ---------------------------------------------------------------------------


class LintPass:
    """Base class: subclasses set ``name``/``description`` (and
    optionally ``targets``) and implement :meth:`run`."""

    name: str = ""
    description: str = ""
    # None = whole tree; otherwise the set of relpaths whose contracts
    # this pass encodes
    targets: Optional[Set[str]] = None

    def run(self, tree: SourceTree) -> Iterable[Finding]:
        raise NotImplementedError


_REGISTRY: Dict[str, LintPass] = {}


def register_pass(cls):
    """Class decorator adding a pass (by its ``name``) to the registry."""
    inst = cls()
    assert inst.name and inst.name not in _REGISTRY, inst.name
    _REGISTRY[inst.name] = inst
    return cls


def all_passes() -> Dict[str, LintPass]:
    # importing the pass modules registers them; keep the import here so
    # `from ...lint.core import ...` stays cycle-free
    from elasticsearch_tpu.testing.lint import (  # noqa: F401
        pass_cancellation,
        pass_counters,
        pass_ledger,
        pass_lockorder,
        pass_quarantine,
        pass_settings_docs,
        pass_threadlocal,
    )

    return dict(_REGISTRY)


# ---------------------------------------------------------------------------
# Allowlist
# ---------------------------------------------------------------------------

DEFAULT_ALLOWLIST = os.path.join(os.path.dirname(__file__), "allowlist.txt")


@dataclass
class Allowlist:
    """``finding-id | justification`` lines; '#' comments; justification
    is mandatory — an entry without one is a lint failure itself."""

    entries: Dict[str, str] = field(default_factory=dict)
    errors: List[str] = field(default_factory=list)

    @classmethod
    def load(cls, path: str = DEFAULT_ALLOWLIST) -> "Allowlist":
        out = cls()
        if not os.path.exists(path):
            return out
        with open(path, encoding="utf-8") as f:
            for n, raw in enumerate(f, 1):
                line = raw.strip()
                if not line or line.startswith("#"):
                    continue
                if "|" not in line:
                    out.errors.append(
                        f"allowlist line {n}: missing '| justification' "
                        f"— every entry must say WHY it is a false "
                        f"positive: {line}")
                    continue
                fid, just = (s.strip() for s in line.split("|", 1))
                if not just:
                    out.errors.append(
                        f"allowlist line {n}: empty justification for "
                        f"[{fid}]")
                    continue
                if fid in out.entries:
                    out.errors.append(
                        f"allowlist line {n}: duplicate entry [{fid}]")
                    continue
                out.entries[fid] = just
        return out


# ---------------------------------------------------------------------------
# Driver
# ---------------------------------------------------------------------------


@dataclass
class LintResult:
    findings: List[Finding]
    unallowlisted: List[Finding]
    stale_entries: List[str]
    allowlist_errors: List[str]

    @property
    def ok(self) -> bool:
        return not (self.unallowlisted or self.stale_entries
                    or self.allowlist_errors)


def run_lint(tree: Optional[SourceTree] = None,
             passes: Optional[List[str]] = None,
             allowlist: Optional[Allowlist] = None) -> LintResult:
    tree = tree or SourceTree()
    registry = all_passes()
    names = passes or sorted(registry)
    allow = allowlist if allowlist is not None else Allowlist.load()
    findings: List[Finding] = []
    for name in names:
        findings.extend(registry[name].run(tree))
    findings.sort(key=lambda f: (f.path, f.lineno, f.pass_name, f.key))
    seen_ids = {f.id for f in findings}
    unallow = [f for f in findings if f.id not in allow.entries]
    # stale check only makes sense on a full default run: a restricted
    # pass list would report every other pass's entries as stale
    stale = ([e for e in sorted(allow.entries)
              if e not in seen_ids] if passes is None else [])
    return LintResult(findings, unallow, stale, allow.errors)
