"""Pass 4 — thread-local / contextvar hygiene (ISSUE 15).

Two PR-9 review-history bug classes, mechanized:

A. **Denial-reason reset-first.** The plane-ladder denial reasons
   (``staging_denied_reason`` / ``kernel_denied_reason``) are
   thread-local by design: each query reads the reason ITS OWN ensure_*
   call produced. The invariant that kept regressing: any function that
   writes a non-None reason must reset the attribute to ``None`` BEFORE
   its first early return — otherwise a thread whose last call was a
   budget denial keeps reporting ``hbm_budget`` for what is now a mode
   gap or staging fault. Tracked attributes: ``*denied_reason``.

B. **Opaque-id restore.** ``set_opaque_id`` stamps the per-request
   ``X-Opaque-Id`` contextvar; batch leaders stamp each MEMBER's id
   while building its result and must restore their own snapshot
   (``leader_oid = get_opaque_id()``) before every return — a stale
   member id attributes the leader's subsequent slowlog/profile lines
   to the wrong client. The pass walks each function's statements in
   source order: a ``set_opaque_id(<non-snapshot>)`` marks the context
   dirty, ``set_opaque_id(<snapshot var>)`` cleans it, and any
   ``return`` (or falling off the end) while dirty is a finding. A
   ``try/finally`` whose finally restores the snapshot makes the whole
   function compliant.
"""

from __future__ import annotations

import ast
from typing import Iterable, List, Optional, Set

from elasticsearch_tpu.testing.lint.core import (
    Finding,
    LintPass,
    SourceTree,
    register_pass,
)

TRACKED_SUFFIX = "denied_reason"


def _writes_tracked(node: ast.Assign) -> Optional[tuple]:
    """(attr, is_none) when ``node`` writes self.*denied_reason."""
    for t in node.targets:
        if (isinstance(t, ast.Attribute)
                and isinstance(t.value, ast.Name)
                and t.value.id == "self"
                and t.attr.endswith(TRACKED_SUFFIX)):
            is_none = (isinstance(node.value, ast.Constant)
                       and node.value.value is None)
            return t.attr, is_none
    return None


def _check_reset_first(fn: ast.FunctionDef, rel: str, qual: str,
                       pass_name: str) -> Iterable[Finding]:
    """Rule A for one function: collect tracked writes in source order;
    a non-None write is only legal after a None reset in the same
    function (property setters — one-statement passthroughs — are the
    storage shim itself and exempt)."""
    if any(isinstance(d, ast.Name) and d.id in ("property", "setter")
           or isinstance(d, ast.Attribute) and d.attr == "setter"
           for d in fn.decorator_list):
        return
    writes: List[tuple] = []  # (lineno, attr, is_none)
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign):
            w = _writes_tracked(node)
            if w:
                writes.append((node.lineno, w[0], w[1]))
    writes.sort()
    reset_seen: Set[str] = set()
    flagged: Set[str] = set()
    for lineno, attr, is_none in writes:
        if is_none:
            reset_seen.add(attr)
        elif attr not in reset_seen and attr not in flagged:
            flagged.add(attr)
            yield Finding(
                pass_name, rel, qual, lineno,
                f"self.{attr} set to a non-None reason without a "
                f"reset-to-None earlier in the same function: a stale "
                f"thread-local from a previous call relabels this "
                f"thread's next denial (PR-9 bug class) — reset FIRST, "
                f"before every early return, or justify that every "
                f"caller resets",
                key=attr)


# ---------------------------------------------------------------------------
# Rule B: opaque-id restore
# ---------------------------------------------------------------------------


def _snapshot_vars(fn: ast.FunctionDef) -> Set[str]:
    """Names assigned from get_opaque_id() anywhere in the function."""
    out: Set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign) and isinstance(node.value,
                                                       ast.Call):
            callee = node.value.func
            name = (callee.id if isinstance(callee, ast.Name)
                    else getattr(callee, "attr", None))
            if name == "get_opaque_id":
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        out.add(t.id)
    return out


def _is_set_opaque(node: ast.AST) -> Optional[ast.Call]:
    if isinstance(node, ast.Expr) and isinstance(node.value, ast.Call):
        callee = node.value.func
        name = (callee.id if isinstance(callee, ast.Name)
                else getattr(callee, "attr", None))
        if name == "set_opaque_id":
            return node.value
    return None


def _finally_restores(fn: ast.FunctionDef, snaps: Set[str]) -> bool:
    for node in ast.walk(fn):
        if isinstance(node, ast.Try) and node.finalbody:
            for stmt in node.finalbody:
                call = _is_set_opaque(stmt)
                if call and call.args and isinstance(call.args[0],
                                                     ast.Name) \
                        and call.args[0].id in snaps:
                    return True
    return False


class _OpaqueScan:
    """Source-order scan (a linear approximation of dominance — good
    enough for the straight-line set/restore shapes the codebase uses,
    and wrong answers land in the allowlist with a justification)."""

    def __init__(self, snaps: Set[str]):
        self.snaps = snaps
        self.dirty_since: Optional[int] = None
        self.dirty_returns: List[int] = []

    def scan(self, stmts) -> None:
        for stmt in stmts:
            call = _is_set_opaque(stmt)
            if call is not None:
                arg = call.args[0] if call.args else None
                if isinstance(arg, ast.Name) and arg.id in self.snaps:
                    self.dirty_since = None
                else:
                    self.dirty_since = stmt.lineno
                continue
            if isinstance(stmt, ast.Return):
                if self.dirty_since is not None:
                    self.dirty_returns.append(stmt.lineno)
                continue
            for body in (getattr(stmt, "body", None),
                         getattr(stmt, "orelse", None),
                         getattr(stmt, "finalbody", None)):
                if body:
                    self.scan(body)
            for handler in getattr(stmt, "handlers", []) or []:
                self.scan(handler.body)


@register_pass
class ThreadLocalHygienePass(LintPass):
    name = "thread-local-hygiene"
    description = ("thread-local denial reasons must reset-first; "
                   "set_opaque_id must restore the leader's snapshot on "
                   "every return path")
    targets = None  # whole tree

    def run(self, tree: SourceTree) -> Iterable[Finding]:
        for rel, sf in tree.files.items():
            if rel.startswith("testing/lint/"):
                continue
            for qual, fn in sf.defs.items():
                if not isinstance(fn, (ast.FunctionDef,
                                       ast.AsyncFunctionDef)):
                    continue
                yield from _check_reset_first(fn, rel, qual, self.name)
                # rule B — only functions that stamp a foreign id
                sets = [n for n in ast.walk(fn)
                        if _is_set_opaque(n) is not None]
                if not sets:
                    continue
                snaps = _snapshot_vars(fn)
                if (not snaps and len(sets) == 1
                        and sets[0] in fn.body):
                    # the request-entry stamp (REST dispatch): ONE
                    # top-level set, no snapshot taken — each request
                    # overwrites it on arrival, nothing later on the
                    # thread reads the old value; the restore contract
                    # is for leaders that stamp MEMBER ids
                    continue
                scan = _OpaqueScan(snaps)
                scan.scan(fn.body)
                if scan.dirty_since is None and not scan.dirty_returns:
                    continue
                if _finally_restores(fn, snaps):
                    continue
                lines = scan.dirty_returns or [scan.dirty_since]
                for i, lineno in enumerate(lines, 1):
                    where = ("return" if scan.dirty_returns
                             else "function end")
                    yield Finding(
                        self.name, rel, qual, lineno,
                        f"set_opaque_id stamped a member id but the "
                        f"{where} is reached without restoring the "
                        f"snapshot (leader_oid = get_opaque_id()) — "
                        f"the stale id mis-attributes later slowlog/"
                        f"profile lines (PR-9 bug class)",
                        key=f"oid{i}" if len(lines) > 1 else "oid")
