"""Pass 7 — quarantine release discipline (ISSUE 16).

The corruption-quarantine contract (docs/RESILIENCE.md "Data
integrity"): flipping a shard copy's ``store_corrupted`` flag is only
legal as the last step of a full quarantine — the same scope must also

1. write the durable ``corrupted_*`` marker (``mark_corrupted``) so the
   quarantine survives restart and the allocator can see it;
2. record the detection (``record_corruption``) so the integrity
   counters never undercount a corruption the cluster acted on; and
3. release the copy's device staging through the PR-9 accountant
   (``release_device_staging``, or a ``release_scope``/``release_index``
   sweep) — a quarantined copy must not pin HBM, and the ledger must
   return to baseline exactly.

A flag flip missing any leg is the bug class ISSUE 16's chaos phase
exists to catch at runtime (silent-unmarked copies, leaked staged
bytes, undercounted detections); this pass catches it at lint time.
Sites that provably have nothing staged (a copy that was never opened)
belong in the allowlist with that justification.
"""

from __future__ import annotations

import ast
from typing import Iterable, Set

from elasticsearch_tpu.testing.lint.core import (
    Finding,
    LintPass,
    SourceTree,
    register_pass,
)

RELEASE_CALLS = {"release_device_staging", "release_scope",
                 "release_index"}


def _called_names(scope: ast.AST) -> Set[str]:
    out: Set[str] = set()
    for node in ast.walk(scope):
        if isinstance(node, ast.Call):
            f = node.func
            if isinstance(f, ast.Attribute):
                out.add(f.attr)
            elif isinstance(f, ast.Name):
                out.add(f.id)
    return out


def _is_quarantine_flip(node: ast.AST) -> bool:
    return (isinstance(node, ast.Assign)
            and any(isinstance(t, ast.Attribute)
                    and t.attr == "store_corrupted"
                    for t in node.targets)
            and isinstance(node.value, ast.Constant)
            and node.value.value is True)


@register_pass
class QuarantineReleasePass(LintPass):
    name = "quarantine-release"
    description = ("every store_corrupted = True site must mark the "
                   "store, record the detection, and release the "
                   "copy's device staging in the same scope")
    targets = None  # whole tree: new quarantine sites must comply

    def run(self, tree: SourceTree) -> Iterable[Finding]:
        for rel, sf in tree.files.items():
            if rel.startswith("testing/lint/"):
                continue  # the analyzer's own pattern tables
            for node in ast.walk(sf.tree):
                if not _is_quarantine_flip(node):
                    continue
                qual = sf.qualname_at(node)
                scope = sf.defs.get(qual, sf.tree)
                called = _called_names(scope)
                if "mark_corrupted" not in called:
                    yield Finding(
                        self.name, rel, qual, node.lineno,
                        "store_corrupted flipped without writing the "
                        "durable corrupted_* marker (mark_corrupted) — "
                        "the quarantine would not survive restart",
                        key="marker")
                if "record_corruption" not in called:
                    yield Finding(
                        self.name, rel, qual, node.lineno,
                        "store_corrupted flipped without "
                        "record_corruption — the integrity counters "
                        "would undercount an acted-on detection",
                        key="record")
                if not (RELEASE_CALLS & called):
                    yield Finding(
                        self.name, rel, qual, node.lineno,
                        "store_corrupted flipped without releasing the "
                        "copy's device staging (release_device_staging/"
                        "release_scope/release_index) — a quarantined "
                        "copy must not pin HBM (ledger exactness)",
                        key="staging-release")
