"""Method-level call-graph approximation shared by the lock-order and
thread-local-hygiene passes (ISSUE 15, docs/STATIC_ANALYSIS.md).

Resolution is BY BARE NAME, package-wide: a call ``self.m()`` /
``obj.m()`` / ``m()`` maps to every function named ``m`` anywhere in the
tree (``self.m()`` prefers methods of the lexically-enclosing class when
any exist). This over-approximates — the price of not running a type
checker — which is the right direction for a deadlock lint (extra edges
can only ADD candidate cycles, and candidate cycles are triaged against
the allowlist with a mandatory justification) and is documented as the
analyzer's precision bound in docs/STATIC_ANALYSIS.md.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from elasticsearch_tpu.testing.lint.core import SourceTree

# calls that never acquire package locks and only blow up the graph
_IGNORED_CALLEES = {
    "append", "extend", "pop", "get", "set", "add", "items", "keys",
    "values", "update", "join", "split", "strip", "format", "sort",
    "sorted", "len", "int", "float", "str", "bool", "list", "dict",
    "tuple", "range", "isinstance", "getattr", "setattr", "hasattr",
    "min", "max", "sum", "abs", "repr", "print", "enumerate", "zip",
    "copy", "deepcopy", "monotonic", "time", "sleep", "wait", "notify",
    "notify_all", "warning", "info", "debug", "error", "exception",
    # standard container-protocol names: a call like
    # ``self._entries.clear()`` must not resolve to a same-named method
    # of the enclosing class (the OrderedDict is not the class)
    "clear", "popitem", "move_to_end", "discard", "setdefault",
    "appendleft", "popleft", "count", "index", "remove", "insert",
}


def ignored_callee(name: Optional[str]) -> bool:
    return name is None or name in _IGNORED_CALLEES


def call_name(call: ast.Call) -> Optional[str]:
    """Bare callee name of a Call node, or None when unresolvable."""
    f = call.func
    if isinstance(f, ast.Name):
        return f.id
    if isinstance(f, ast.Attribute):
        return f.attr
    return None


def call_is_self(call: ast.Call) -> bool:
    f = call.func
    return (isinstance(f, ast.Attribute)
            and isinstance(f.value, ast.Name) and f.value.id == "self")


# a bare name defined in more places than this is too ambiguous to
# resolve on a non-self receiver — edges through it would be noise
# (``close``/``stats``/``run`` exist on a dozen classes); the runtime
# witness covers what this precision bound drops
MAX_AMBIGUITY = 3


class CallGraph:
    """funcqual ('relpath::Class.method') -> (called name, self-recv)
    pairs, plus the reverse index bare name -> defining funcquals."""

    def __init__(self, tree: SourceTree):
        self.tree = tree
        self.calls: Dict[str, Set[Tuple[str, bool]]] = {}
        self.defs_by_name: Dict[str, List[str]] = {}
        self.class_of: Dict[str, Optional[str]] = {}
        for rel, sf in tree.files.items():
            for qual, node in sf.defs.items():
                if not isinstance(node, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                    continue
                fq = f"{rel}::{qual}"
                bare = qual.rsplit(".", 1)[-1]
                self.defs_by_name.setdefault(bare, []).append(fq)
                self.class_of[fq] = (qual.rsplit(".", 1)[0]
                                     if "." in qual else None)
                called: Set[Tuple[str, bool]] = set()
                for sub in ast.walk(node):
                    if isinstance(sub, ast.Call):
                        name = call_name(sub)
                        if name and name not in _IGNORED_CALLEES:
                            called.add((name, call_is_self(sub)))
                self.calls[fq] = called

    def resolve(self, caller: str, name: str,
                is_self: bool = False) -> List[str]:
        """Callees a bare name may refer to, from ``caller``'s view.

        ``self.m()`` resolves within the enclosing class when it defines
        ``m`` (exactly). Any OTHER receiver must NOT take that shortcut
        — ``shard.refresh()`` inside ``IndexService.refresh`` is the
        shard's method, and binding it to the enclosing class would
        silently DROP the real callee (hiding its lock acquisitions,
        the one direction a deadlock lint must never err). Non-self
        receivers use the package-wide by-name candidates, dropped
        entirely when the name is defined in more than MAX_AMBIGUITY
        places (precision over recall; see module docstring)."""
        cands = self.defs_by_name.get(name, [])
        cls = self.class_of.get(caller)
        if is_self and cls is not None:
            rel = caller.split("::", 1)[0]
            same = [c for c in cands
                    if c.startswith(f"{rel}::{cls}.")]
            if same:
                return same
        if len(cands) > MAX_AMBIGUITY:
            return []
        return cands

    def transitive_closure(
            self, seed: Dict[str, Set[str]]) -> Dict[str, Set[str]]:
        """Fixed point of ``seed`` (funcqual -> facts) propagated from
        callee to caller: a caller accumulates every fact of every
        function its bare-name calls may resolve to."""
        facts: Dict[str, Set[str]] = {fq: set(v)
                                      for fq, v in seed.items()}
        for fq in self.calls:
            facts.setdefault(fq, set())
        changed = True
        while changed:
            changed = False
            for fq, called in self.calls.items():
                acc = facts[fq]
                before = len(acc)
                for name, is_self in called:
                    for callee in self.resolve(fq, name, is_self):
                        acc |= facts.get(callee, set())
                if len(acc) != before:
                    changed = True
        return facts


# ---------------------------------------------------------------------------
# Lock-site discovery (shared vocabulary for pass 5 and the witness docs)
# ---------------------------------------------------------------------------

_LOCK_CTORS = {"Lock", "RLock", "Condition"}


def _lock_ctor_kind(call: ast.Call) -> Optional[str]:
    f = call.func
    if isinstance(f, ast.Attribute) and f.attr in _LOCK_CTORS:
        if isinstance(f.value, ast.Name) and f.value.id == "threading":
            return f.attr
        return None
    if isinstance(f, ast.Name) and f.id in _LOCK_CTORS:
        return f.id
    return None


def lock_sites(tree: SourceTree) -> Dict[str, Tuple[str, int, str]]:
    """site-id -> (relpath, lineno, kind) for every
    ``threading.Lock/RLock/Condition`` creation in the tree.

    Site ids are stable across line drift: ``module.Class.attr`` for
    ``self.attr = threading.Lock()`` in a class body / __init__,
    ``module.NAME`` for module globals, ``module.func.NAME`` for
    function locals."""
    sites: Dict[str, Tuple[str, int, str]] = {}
    for rel, sf in tree.files.items():
        mod = rel[:-3].replace("/", ".")
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Assign):
                continue
            if not isinstance(node.value, ast.Call):
                continue
            kind = _lock_ctor_kind(node.value)
            if kind is None:
                continue
            for target in node.targets:
                qual = sf.qualname_at(node)
                if (isinstance(target, ast.Attribute)
                        and isinstance(target.value, ast.Name)
                        and target.value.id == "self"):
                    cls = qual.rsplit(".", 1)[0] if "." in qual else qual
                    sites[f"{mod}.{cls}.{target.attr}"] = (rel, node.lineno,
                                                           kind)
                elif isinstance(target, ast.Name):
                    if qual == "<module>":
                        sites[f"{mod}.{target.id}"] = (rel, node.lineno,
                                                       kind)
                    else:
                        sites[f"{mod}.{qual}.{target.id}"] = (
                            rel, node.lineno, kind)
    return sites


def with_lock_site(item: ast.withitem, rel: str, qualname: str,
                   known: Dict[str, Tuple[str, int]]) -> Optional[str]:
    """Resolve one ``with <expr>:`` item to a known lock site id.

    Handles ``self._x`` (own class first, then any class declaring the
    attr), bare module-global names, and ``obj._x`` attribute reads
    (matched against every class declaring ``_x`` — the by-name
    over-approximation again)."""
    expr = item.context_expr
    mod = rel[:-3].replace("/", ".")
    if isinstance(expr, ast.Attribute):
        attr = expr.attr
        if isinstance(expr.value, ast.Name) and expr.value.id == "self":
            cls = (qualname.rsplit(".", 1)[0]
                   if "." in qualname else qualname)
            own = f"{mod}.{cls}.{attr}"
            if own in known:
                return own
        matches = [s for s in known if s.endswith(f".{attr}")]
        if len(matches) == 1:
            return matches[0]
        if matches:
            # ambiguous attr name across classes: pick deterministically
            # (documented approximation; distinct classes sharing a lock
            # attr name collapse into one graph node, which only merges
            # orderings — never hides an edge)
            return sorted(matches)[0]
        return None
    if isinstance(expr, ast.Name):
        own = f"{mod}.{expr.id}"
        if own in known:
            return own
        matches = [s for s in known if s.endswith(f".{expr.id}")]
        return sorted(matches)[0] if matches else None
    return None
