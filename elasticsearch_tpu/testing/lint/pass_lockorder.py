"""Pass 5 — static lock-ordering (ISSUE 15, docs/LOCK_ORDER.md).

Builds the acquired-while-holding graph over every
``threading.Lock/RLock/Condition`` site in the package (method-level
call-graph approximation — see callgraph.py for the precision bound)
and flags cycles as potential deadlocks. The same graph renders as the
checked-in ``docs/LOCK_ORDER.md`` artifact
(``python -m elasticsearch_tpu.testing.lint --emit-lock-order``), and
the runtime witness (testing/lockwitness.py) confirms the ordering
dynamically during the chaos soaks.

Edge semantics: ``A -> B`` means "some code path may acquire B while
holding A" — a ``with`` on site B nested (lexically, or through any
chain of bare-name-resolved calls) inside a ``with`` on site A. A cycle
among DISTINCT sites is a deadlock candidate. A self-edge on a plain
``Lock`` site (the site's own closure re-acquires it) is flagged too —
that is a single-thread deadlock unless the inner acquisition is on a
different instance; self-edges on ``RLock``/``Condition`` sites are
reentrancy by design and pass.

Known precision limits (all covered by the runtime witness instead):
callback-mediated acquisition (a lock held while invoking a stored
callable — e.g. the accountant's evict callbacks) is invisible to the
static graph; conversely, bare-name call resolution can fabricate
edges between unrelated classes sharing a method name. Fabricated
cycles are allowlisted with justification, never silently dropped.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set, Tuple

from elasticsearch_tpu.testing.lint.callgraph import (
    CallGraph,
    call_is_self,
    call_name,
    ignored_callee,
    lock_sites,
    with_lock_site,
)
from elasticsearch_tpu.testing.lint.core import (
    Finding,
    LintPass,
    SourceTree,
    register_pass,
)


def _function_withs(fn: ast.AST) -> List[ast.With]:
    return [n for n in ast.walk(fn) if isinstance(n, ast.With)]


def lock_graph_for(tree: SourceTree) -> "LockGraph":
    """The tree's LockGraph, built once — the call-graph closure is the
    linter's heaviest analysis and both the pass and the LOCK_ORDER.md
    renderer need it per run."""
    lg = getattr(tree, "_lock_graph", None)
    if lg is None:
        lg = LockGraph(tree)
        tree._lock_graph = lg
    return lg


class LockGraph:
    """The full static analysis result, reused by the doc emitter."""

    def __init__(self, tree: SourceTree):
        self.tree = tree
        self.sites = lock_sites(tree)
        self.graph = CallGraph(tree)
        # funcqual -> sites directly acquired in its body
        self.direct: Dict[str, Set[str]] = {}
        self._withs: Dict[str, List[Tuple[ast.With, str]]] = {}
        for rel, sf in tree.files.items():
            for qual, fn in sf.defs.items():
                if not isinstance(fn, (ast.FunctionDef,
                                       ast.AsyncFunctionDef)):
                    continue
                fq = f"{rel}::{qual}"
                acq: Set[str] = set()
                pairs: List[Tuple[ast.With, str]] = []
                for w in _function_withs(fn):
                    for item in w.items:
                        site = with_lock_site(item, rel, qual, self.sites)
                        if site is not None:
                            acq.add(site)
                            pairs.append((w, site))
                self.direct[fq] = acq
                self._withs[fq] = pairs
        self.may_acquire = self.graph.transitive_closure(self.direct)
        # (A, B) -> sorted example locations "rel::qual"
        self.edges: Dict[Tuple[str, str], Set[str]] = {}
        self._build_edges()

    def _build_edges(self) -> None:
        for fq, pairs in self._withs.items():
            for w, held in pairs:
                for stmt in w.body:
                    for sub in ast.walk(stmt):
                        if isinstance(sub, ast.With):
                            for item in sub.items:
                                rel, qual = fq.split("::", 1)
                                inner = with_lock_site(
                                    item, rel, qual, self.sites)
                                if inner is not None:
                                    self._edge(held, inner, fq)
                        elif isinstance(sub, ast.Call):
                            name = call_name(sub)
                            if not name or ignored_callee(name):
                                continue
                            for callee in self.graph.resolve(
                                    fq, name, call_is_self(sub)):
                                for site in self.may_acquire.get(
                                        callee, ()):
                                    if site == held and site not in \
                                            self.direct.get(callee, ()):
                                        # self-edges keep only DIRECT
                                        # re-acquisition: a transitive
                                        # by-name chain ending back at
                                        # the held site is noise at this
                                        # precision (different
                                        # instances / name collisions);
                                        # the runtime witness owns the
                                        # instance-accurate check
                                        continue
                                    self._edge(held, site,
                                               f"{fq} -> {callee}")

    def _edge(self, a: str, b: str, where: str) -> None:
        self.edges.setdefault((a, b), set()).add(where)

    # -- cycle analysis -------------------------------------------------

    def cycles(self) -> List[List[str]]:
        """SCCs with more than one site, plus plain-Lock self-loops."""
        adj: Dict[str, Set[str]] = {}
        for (a, b) in self.edges:
            adj.setdefault(a, set()).add(b)
            adj.setdefault(b, set())
        out: List[List[str]] = []
        # Tarjan
        index: Dict[str, int] = {}
        low: Dict[str, int] = {}
        stack: List[str] = []
        on: Set[str] = set()
        counter = [0]

        def strongconnect(v: str) -> None:
            index[v] = low[v] = counter[0]
            counter[0] += 1
            stack.append(v)
            on.add(v)
            for w in adj.get(v, ()):
                if w not in index:
                    strongconnect(w)
                    low[v] = min(low[v], low[w])
                elif w in on:
                    low[v] = min(low[v], index[w])
            if low[v] == index[v]:
                comp = []
                while True:
                    w = stack.pop()
                    on.discard(w)
                    comp.append(w)
                    if w == v:
                        break
                if len(comp) > 1:
                    out.append(sorted(comp))

        for v in sorted(adj):
            if v not in index:
                strongconnect(v)
        for (a, b) in sorted(self.edges):
            if a == b and self.sites.get(a, ("", 0, "Lock"))[2] == "Lock":
                out.append([a])
        return out

    def topo_order(self) -> List[str]:
        """Deterministic acquisition order over the condensation (cycle
        members sort together); the documented 'acquire in this order'
        artifact."""
        adj: Dict[str, Set[str]] = {s: set() for s in self.sites}
        indeg: Dict[str, int] = {s: 0 for s in self.sites}
        for (a, b) in self.edges:
            if a != b and b not in adj.setdefault(a, set()):
                adj[a].add(b)
                indeg[b] = indeg.get(b, 0) + 1
            adj.setdefault(b, set())
            indeg.setdefault(a, 0)
        order: List[str] = []
        ready = sorted(s for s, d in indeg.items() if d == 0)
        seen: Set[str] = set()
        while ready:
            v = ready.pop(0)
            order.append(v)
            seen.add(v)
            for w in sorted(adj.get(v, ())):
                indeg[w] -= 1
                if indeg[w] == 0:
                    ready.append(w)
            ready.sort()
        # cycle members (never reach indeg 0) appended sorted, marked
        # in the doc
        order.extend(sorted(s for s in adj if s not in seen))
        return order


def render_lock_order(lg: LockGraph) -> str:
    """docs/LOCK_ORDER.md content — regenerate with
    ``python -m elasticsearch_tpu.testing.lint --emit-lock-order``."""
    lines = [
        "# Lock acquisition order",
        "",
        "GENERATED by `python -m elasticsearch_tpu.testing.lint "
        "--emit-lock-order` (pass 5, docs/STATIC_ANALYSIS.md) — do not "
        "edit by hand; the tier-1 contract-lint test fails when this "
        "file drifts from the source tree.",
        "",
        "`A -> B` means some code path may acquire B while holding A "
        "(lexical nesting, or nesting through the bare-name call-graph "
        "approximation). New code must not add an edge that reverses "
        "an existing path; the runtime witness "
        "(`elasticsearch_tpu/testing/lockwitness.py`) asserts the same "
        "property dynamically during the chaos soaks.",
        "",
        "## Lock sites",
        "",
        "| site | kind | file |",
        "|---|---|---|",
    ]
    for site in sorted(lg.sites):
        rel, _lineno, kind = lg.sites[site]
        lines.append(f"| `{site}` | {kind} | `{rel}` |")
    lines += [
        "",
        "## Acquired-while-holding edges",
        "",
        "| held | acquired | via |",
        "|---|---|---|",
    ]
    for (a, b) in sorted(lg.edges):
        wheres = sorted(lg.edges[(a, b)])
        shown = wheres[0] + (f" (+{len(wheres) - 1} more)"
                             if len(wheres) > 1 else "")
        lines.append(f"| `{a}` | `{b}` | `{shown}` |")
    cycles = lg.cycles()
    lines += ["", "## Cycles", ""]
    if cycles:
        lines.append("Candidate deadlock cycles (each must be fixed or "
                     "allowlisted with justification):")
        lines.append("")
        for cyc in cycles:
            lines.append("- " + " -> ".join(f"`{s}`" for s in cyc)
                         + (" -> `" + cyc[0] + "`" if len(cyc) > 1
                            else " (self-edge on a plain Lock)"))
    else:
        lines.append("None — the static graph is acyclic.")
    lines += [
        "",
        "## Global acquisition order",
        "",
        "Acquire in this order (topological over the edge graph; "
        "unordered sites sort lexicographically):",
        "",
    ]
    for i, site in enumerate(lg.topo_order(), 1):
        lines.append(f"{i}. `{site}`")
    lines.append("")
    return "\n".join(lines)


@register_pass
class LockOrderPass(LintPass):
    name = "lock-order"
    description = ("acquired-while-holding graph over every threading "
                   "lock site must be acyclic (candidate deadlocks)")
    targets = None

    def run(self, tree: SourceTree) -> Iterable[Finding]:
        lg = lock_graph_for(tree)
        for cyc in lg.cycles():
            if len(cyc) == 1:
                site = cyc[0]
                rel, lineno, _kind = lg.sites[site]
                yield Finding(
                    self.name, rel, site, lineno,
                    f"self-edge on plain Lock site `{site}`: its "
                    f"holder's call closure may re-acquire it — a "
                    f"single-thread deadlock unless the inner "
                    f"acquisition is provably a different instance",
                    key="self-edge")
            else:
                anchor = cyc[0]
                rel, lineno, _kind = lg.sites.get(anchor,
                                                  ("<unknown>", 0, ""))
                yield Finding(
                    self.name, rel, anchor, lineno,
                    "candidate deadlock cycle: "
                    + " -> ".join(cyc) + f" -> {anchor}",
                    key="cycle:" + "|".join(cyc))
