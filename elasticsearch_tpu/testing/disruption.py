"""Injectable network disruption schemes for the transport hubs.

Role model: the reference test framework's ``ServiceDisruptionScheme``
family (test/framework/.../test/disruption/): ``NetworkDisruption`` with
its ``NetworkDelay`` / ``NetworkDisconnect`` / ``NetworkUnresponsive``
link behaviors, ``SlowClusterStateProcessing``, and
``MockTransportService``'s per-action request blackholing.

A scheme is installed on a hub (``TransportHub`` or ``TcpTransportHub``)
with ``apply_to(hub)`` and applied to every delivery it matches:
``applies(src, dst, action)`` filters, ``disrupt(src, dst, action)``
executes the effect — sleep (delay), raise ``NodeNotConnectedException``
(drop/partition), or block until the caller's request deadline fires
(unresponsive/blackhole). Randomized schemes take an explicit ``seed`` so
disruption tests are reproducible.

Usage::

    drop = NetworkDrop(0.3, seed=7).apply_to(hub)
    delay = NetworkDelay(0.2).apply_to(hub)
    ...drive the cluster...
    drop.remove(); delay.remove()    # or hub.clear_disruptions()

Schemes compose: every installed scheme whose filter matches runs, in
installation order.
"""

from __future__ import annotations

import fnmatch
import random
import threading
from typing import Iterable, Optional, Sequence

from elasticsearch_tpu.common.errors import NodeNotConnectedException


class DisruptionScheme:
    """Base scheme: optional link/action filters + the disruption hook.

    ``src``/``dst``: restrict to deliveries from/to these node ids (None =
    any). ``nodes``: restrict to deliveries touching any of these nodes in
    either direction. ``actions``: fnmatch patterns over the action name
    (``internal:cluster/*``).
    """

    def __init__(self, src: Optional[Iterable[str]] = None,
                 dst: Optional[Iterable[str]] = None,
                 nodes: Optional[Iterable[str]] = None,
                 actions: Optional[Sequence[str]] = None):
        self.src = set(src) if src else None
        self.dst = set(dst) if dst else None
        self.nodes = set(nodes) if nodes else None
        self.actions = list(actions) if actions else None
        self.hub = None

    # --- lifecycle ----------------------------------------------------

    def apply_to(self, hub) -> "DisruptionScheme":
        hub.add_disruption(self)
        self.hub = hub
        return self

    def remove(self) -> None:
        if self.hub is not None:
            self.hub.remove_disruption(self)
            self.hub = None

    # --- matching + effect --------------------------------------------

    def applies(self, src: str, dst: str, action: str) -> bool:
        if self.src is not None and src not in self.src:
            return False
        if self.dst is not None and dst not in self.dst:
            return False
        if self.nodes is not None and not ({src, dst} & self.nodes):
            return False
        if self.actions is not None and not any(
                # exact match first: ES action names contain [s][r]
                # suffixes that fnmatch would treat as character classes
                action == pat or fnmatch.fnmatch(action, pat)
                for pat in self.actions):
            return False
        return True

    def disrupt(self, src: str, dst: str, action: str) -> None:
        """Effect hook; runs outside the hub lock. May sleep or raise."""
        raise NotImplementedError


class NetworkDelay(DisruptionScheme):
    """Fixed or uniformly-random per-delivery delay
    (NetworkDisruption.NetworkDelay)."""

    def __init__(self, seconds: float, max_seconds: Optional[float] = None,
                 seed: Optional[int] = None, **filters):
        super().__init__(**filters)
        self.seconds = float(seconds)
        self.max_seconds = float(max_seconds) if max_seconds else None
        self._rng = random.Random(seed)
        self._rng_lock = threading.Lock()

    def delay(self) -> float:
        if self.max_seconds is None:
            return self.seconds
        with self._rng_lock:
            return self._rng.uniform(self.seconds, self.max_seconds)

    def disrupt(self, src, dst, action) -> None:
        import time

        time.sleep(self.delay())


class NetworkDrop(DisruptionScheme):
    """Probabilistic request drop: each matching delivery fails with
    probability ``p`` (connection-level error, so retry policies and
    failover engage). ``seed`` makes the drop sequence reproducible."""

    def __init__(self, p: float, seed: Optional[int] = None, **filters):
        super().__init__(**filters)
        if not 0.0 <= p <= 1.0:
            raise ValueError("drop probability must be in [0, 1]")
        self.p = float(p)
        self._rng = random.Random(seed)
        self._rng_lock = threading.Lock()
        self.dropped = 0

    def disrupt(self, src, dst, action) -> None:
        with self._rng_lock:
            hit = self._rng.random() < self.p
        if hit:
            self.dropped += 1
            raise NodeNotConnectedException(
                f"[{dst}] dropped [{action}] from [{src}] (injected)")


class NetworkPartition(DisruptionScheme):
    """Partition between two node sets (NetworkDisruption.Bridge /
    TwoPartitions). ``one_way=True`` drops only side1→side2 traffic —
    the asymmetric-partition case where a deposed master can still hear
    the cluster that can no longer hear it."""

    def __init__(self, side1: Iterable[str], side2: Iterable[str],
                 one_way: bool = False, **filters):
        super().__init__(**filters)
        self.side1 = set(side1)
        self.side2 = set(side2)
        self.one_way = bool(one_way)

    def disrupt(self, src, dst, action) -> None:
        forward = src in self.side1 and dst in self.side2
        backward = src in self.side2 and dst in self.side1
        if forward or (backward and not self.one_way):
            raise NodeNotConnectedException(
                f"[{dst}] partitioned from [{src}] (injected)")


class UnresponsiveNode(DisruptionScheme):
    """The node accepts requests but never answers
    (NetworkDisruption.NetworkUnresponsive): the delivery blocks until
    the caller's request timeout fires (or ``max_block_s`` as a leak
    guard), then fails. ``remove()``/``heal`` unblocks parked deliveries
    immediately."""

    def __init__(self, node: str, max_block_s: float = 60.0, **filters):
        filters.setdefault("nodes", [node])
        super().__init__(**filters)
        self.node = node
        self.max_block_s = float(max_block_s)
        self._healed = threading.Event()

    def remove(self) -> None:
        self._healed.set()
        super().remove()

    def disrupt(self, src, dst, action) -> None:
        self._healed.wait(self.max_block_s)
        raise NodeNotConnectedException(
            f"[{self.node}] unresponsive, [{action}] never answered "
            f"(injected)")


class ActionBlackhole(DisruptionScheme):
    """Requests matching the action patterns vanish: the delivery blocks
    until the caller's deadline (MockTransportService's request
    blackholing by action name). Scope with ``dst=[...]`` to blackhole a
    single replica's writes while the node otherwise stays reachable."""

    def __init__(self, actions: Sequence[str], max_block_s: float = 60.0,
                 **filters):
        super().__init__(actions=list(actions), **filters)
        self.max_block_s = float(max_block_s)
        self._healed = threading.Event()
        self.swallowed = 0

    def remove(self) -> None:
        self._healed.set()
        super().remove()

    def disrupt(self, src, dst, action) -> None:
        self.swallowed += 1
        self._healed.wait(self.max_block_s)
        raise NodeNotConnectedException(
            f"[{dst}] blackholed [{action}] from [{src}] (injected)")
